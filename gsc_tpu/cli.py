"""Command-line interface (reference: root main.py + inference.py +
coordsim/main.py).

Subcommands:
- ``init-configs``: generate an example config set (agent/simulator/service/
  scheduler YAML + Abilene GraphML) — the assets the reference checks in
  under configs/, produced programmatically here.
- ``train``: load the 5 config namespaces, train DDPG, save an orbax
  checkpoint, then roll one greedy test episode on the inference network
  (main.py:16-76 flow).
- ``infer``: restore a checkpoint and run test episodes (inference.py:17-40).
- ``simulate``: standalone simulator smoke-run with a uniform dummy
  schedule, no RL (coordsim/main.py:19-89).
"""
from __future__ import annotations

import json
import os

import click
import jax
import numpy as np
import yaml


@click.group()
def cli():
    """gsc-tpu: TPU-native service coordination framework."""


def _apply_jax_cache(flag_value):
    """Wire the persistent jax compilation cache into this process:
    ``--jax-cache-dir`` wins, else ``GSC_JAX_CACHE_DIR``; unset leaves the
    jax default (off) alone.  Returns the effective directory (or None)
    so run_start obs meta can record what actually applied.  The test
    suite has set this via conftest.py since PR 2 — production entry
    points get the same compile-skipping here."""
    d = flag_value or os.environ.get("GSC_JAX_CACHE_DIR")
    if not d:
        return None
    d = os.path.abspath(d)
    try:
        jax.config.update("jax_compilation_cache_dir", d)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:   # backend declines (e.g. unsupported platform)
        click.echo(f"[jax-cache] not applied ({e})", err=True)
        return None
    return d


# (temperature, floor) — the ONE definition behind the two
# --curriculum-* click defaults AND the flags-without-factory guard in
# train(): a tuned default must keep both in lockstep, or every
# non-factory run would trip the guard
_CURRICULUM_DEFAULTS = (1.0, 0.25)

_JAX_CACHE_HELP = (
    "persistent jax compilation cache directory (XLA executables are "
    "reused across processes — repeat runs skip identical compiles).  "
    "Unset: the GSC_JAX_CACHE_DIR env var; neither = cache off.  The "
    "effective dir is recorded in run_start obs meta")


def _uniform_schedule_action(limits, node_mask):
    """Flat [A] uniform dummy schedule over real nodes (the coordsim
    smoke-run placement, shared by `simulate` and `serve`'s request-pool
    roller)."""
    sched = np.zeros(limits.scheduling_shape, np.float32)
    sched[:, :, :, node_mask] = 1.0 / max(int(node_mask.sum()), 1)
    return sched.reshape(-1)


@cli.command("init-configs")
@click.option("--out", default="configs", show_default=True)
def init_configs(out: str):
    """Write an example config set (agent, simulator, service, scheduler,
    networks)."""
    from .topology.synthetic import (
        abilene,
        bteurope,
        claranet,
        compuserve,
        line,
        triangle,
        write_graphml,
    )

    os.makedirs(f"{out}/networks", exist_ok=True)
    write_graphml(abilene(), f"{out}/networks/abilene-in4.graphml")
    write_graphml(triangle(), f"{out}/networks/triangle.graphml")
    write_graphml(line(3), f"{out}/networks/line3.graphml")
    # ladder rung 3: 24-node/37-edge real topology (BT Europe, Topology Zoo)
    write_graphml(bteurope(node_cap_range=(1, 3)),
                  f"{out}/networks/bteurope-in2-rand-cap1-2.graphml")
    # the reference's other small real scenarios (Topology Zoo shapes)
    write_graphml(claranet(), f"{out}/networks/claranet-in4-cap1.graphml")
    write_graphml(compuserve(),
                  f"{out}/networks/compuserve-in4-cap1.graphml")

    with open(f"{out}/service_abc.yaml", "w") as f:
        yaml.safe_dump({
            "sfc_list": {"sfc_1": ["a", "b", "c"]},
            "sf_list": {n: {"processing_delay_mean": 5.0,
                            "processing_delay_stdev": 0.0}
                        for n in "abc"},
        }, f)
    # rung-3 5-SF chain with heterogeneous delays, a startup delay and a
    # non-identity resource function (reader.py:60-72 pluggable demand)
    with open(f"{out}/service_abcde.yaml", "w") as f:
        yaml.safe_dump({
            "sfc_list": {"sfc_1": ["a", "b", "c", "d", "e"]},
            "sf_list": {
                "a": {"processing_delay_mean": 5.0,
                      "processing_delay_stdev": 0.0},
                "b": {"processing_delay_mean": 2.0,
                      "processing_delay_stdev": 0.0},
                "c": {"processing_delay_mean": 10.0,
                      "processing_delay_stdev": 0.0,
                      "startup_delay": 5.0},
                "d": {"processing_delay_mean": 1.0,
                      "processing_delay_stdev": 0.0},
                "e": {"processing_delay_mean": 4.0,
                      "processing_delay_stdev": 0.0,
                      "resource_function_id": "overhead"},
            },
        }, f)
    with open(f"{out}/simulator.yaml", "w") as f:
        yaml.safe_dump({
            "inter_arrival_mean": 10.0, "deterministic_arrival": True,
            "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
            "flow_size_shape": 0.001, "deterministic_size": True,
            "run_duration": 100, "ttl_choices": [100],
        }, f)
    # MMPP bursty-arrival scenario (rand-mmp-arrival12-8_det-size001_dur100)
    with open(f"{out}/simulator_mmpp.yaml", "w") as f:
        yaml.safe_dump({
            "inter_arrival_mean": 12.0, "deterministic_arrival": False,
            "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
            "flow_size_shape": 0.001, "deterministic_size": True,
            "run_duration": 100, "ttl_choices": [100],
            "use_states": True, "init_state": "state_1",
            "states": {"state_1": {"inter_arr_mean": 12.0, "switch_p": 0.05},
                       "state_2": {"inter_arr_mean": 8.0, "switch_p": 0.05}},
        }, f)
    # trace-driven scenario (configs/traces format: time,node,
    # inter_arrival_mean[,cap] with popN node names, trace_processor.py:23-54)
    with open(f"{out}/trace_rampup.csv", "w") as f:
        f.write("time,node,inter_arrival_mean,cap\n")
        f.write("0,pop0,10.0,\n")
        f.write("500,pop0,5.0,\n")
        f.write("1000,pop0,2.5,4\n")
        f.write("1500,pop1,5.0,\n")
    with open(f"{out}/simulator_trace.yaml", "w") as f:
        yaml.safe_dump({
            "inter_arrival_mean": 10.0, "deterministic_arrival": True,
            "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
            "flow_size_shape": 0.001, "deterministic_size": True,
            "run_duration": 100, "ttl_choices": [100],
            "trace_path": f"{out}/trace_rampup.csv",
        }, f)
    with open(f"{out}/agent.yaml", "w") as f:
        yaml.safe_dump({
            "observation_space": ["ingress_traffic", "node_load", "node_cap"],
            "graph_mode": True, "episode_steps": 200,
            "objective": "prio-flow", "target_success": "auto",
            "GNN_features": 22, "GNN_num_layers": 2, "GNN_num_iter": 2,
            "GNN_aggr": "mean",
            "actor_hidden_layer_nodes": [256],
            "critic_hidden_layer_nodes": [64],
            "mem_limit": 10000, "batch_size": 100,
            "nb_steps_warmup_critic": 200,
            "rand_mu": 0.0, "rand_sigma": 0.3,
            "gamma": 0.99, "target_model_update": 1.0e-4,
            "learning_rate": 1.0e-3,
        }, f)
    with open(f"{out}/scheduler.yaml", "w") as f:
        yaml.safe_dump({
            "training_network_files": [f"{out}/networks/abilene-in4.graphml"],
            "inference_network": f"{out}/networks/abilene-in4.graphml",
            "period": 10,
        }, f)
    click.echo(f"wrote example configs under {out}/")


def _build(agent_config, simulator_config, service, scheduler, seed,
           max_nodes, max_edges, resource_functions_path=None,
           precision=None, substep_impl=None, unroll=None, topo_mix=None):
    from .config.loader import load_agent, load_scheduler, load_service, load_sim
    from .config.schema import EnvLimits
    from .env.driver import EpisodeDriver
    from .env.env import ServiceCoordEnv

    # --precision overrides the agent yaml's (or default f32) policy
    agent = load_agent(agent_config,
                       **({"precision": precision} if precision else {}))
    # --substep-impl / --unroll override the simulator yaml's engine knobs
    # (`is not None`, not truthiness: an explicit --unroll 0 must reach
    # SimConfig validation and ERROR, never silently keep the yaml value)
    sim_overrides = {}
    if substep_impl is not None:
        sim_overrides["substep_impl"] = substep_impl
    if unroll is not None:
        sim_overrides["scan_unroll"] = unroll
    sim_cfg = load_sim(simulator_config, **sim_overrides)
    svc = load_service(service,
                       resource_functions_path=resource_functions_path)
    sched = load_scheduler(scheduler)
    limits = EnvLimits.for_service(svc, max_nodes=max_nodes,
                                   max_edges=max_edges)
    env = ServiceCoordEnv(svc, sim_cfg, agent, limits)
    driver = EpisodeDriver(sched, sim_cfg, svc, agent.episode_steps,
                           max_nodes=max_nodes, max_edges=max_edges,
                           base_seed=seed, topo_mix=topo_mix)
    return env, driver, agent


@cli.command()
@click.argument("agent_config")
@click.argument("simulator_config")
@click.argument("service")
@click.argument("scheduler")
@click.option("--episodes", default=40, show_default=True)
@click.option("--seed", default=0, show_default=True)
@click.option("--result-dir", default="results", show_default=True)
@click.option("--experiment-id", default=None)
@click.option("--max-nodes", default=24, show_default=True)
@click.option("--max-edges", default=37, show_default=True)
@click.option("--tensorboard/--no-tensorboard", default=False)
@click.option("--profile/--no-profile", default=False,
              help="write a jax profiler trace of training")
@click.option("--runs", default=1, show_default=True,
              help="independent seeded runs; the best by mean reward over "
                   "the last 10 episodes is reported (select_best_agent)")
@click.option("--resume", default=None,
              help="checkpoint dir from a previous train run: restores "
                   "params+opt+targets+replay+PRNG and continues exactly "
                   "(total episode count still set by --episodes).  "
                   "'auto' searches --result-dir for the newest checkpoint "
                   "whose content checksum validates (periodic/preemption "
                   "saves and final checkpoints all qualify), falling back "
                   "past corrupted ones")
@click.option("--resource-functions-path", default=None,
              help="dir (or .py file) of user resource-function plugins "
                   "to register before parsing the service catalog "
                   "(reference: reader.py:60-72 dynamic imports)")
@click.option("--replicas", default=1, show_default=True,
              help="vmapped env replicas per episode (>1: the TPU "
                   "data-parallel path with on-device per-episode traffic "
                   "sampling; 1: the reference's single-env loop)")
@click.option("--chunk", default=50, show_default=True,
              help="rollout steps per device call with --replicas > 1 "
                   "(long single-call scans exceed TPU per-call limits)")
@click.option("--mesh", default=None,
              help="pjit device mesh 'DPxMP' (e.g. 8x1, 4x2) for "
                   "--replicas > 1: env replicas/replay/traffic shard "
                   "over the dp*mp device grid and the learner state "
                   "follows --partition-rules.  Replica count must be "
                   "divisible by dp*mp.  The backend must HAVE dp*mp "
                   "devices (for a CPU dry run preset XLA_FLAGS=--xla_"
                   "force_host_platform_device_count=N — train never "
                   "silently re-platforms).  Checkpoints are always "
                   "host-gathered, so a "
                   "--resume may use a DIFFERENT mesh shape than the run "
                   "that wrote them (elastic resume).  Unset: today's "
                   "single-device dispatch")
@click.option("--partition-rules", type=click.Choice(["replicated",
                                                      "sharded", "tp"]),
              default="replicated", show_default=True,
              help="partition rulebook for the learner state under "
                   "--mesh: 'replicated' keeps every parameter on every "
                   "device (bit-identical to 'sharded' on the same mesh; "
                   "a 1x1 mesh is bit-identical to no --mesh at all, a "
                   "multi-device mesh drifts ~1e-7 vs the meshless "
                   "dispatch from fusion-boundary reordering), 'sharded' "
                   "splits "
                   "wide actor/critic/GAT matrices + their Adam moments "
                   "over the mp axis (parallel.partition.sharded_rules) "
                   "— final learner state stays bit-identical across "
                   "mesh carvings of the same device count.  'tp' is "
                   "TRUE tensor-parallel compute "
                   "(parallel.partition.tp_rules): contraction dims "
                   "split over mp with psum-accumulated partial "
                   "products, the state stays resident-sharded THROUGH "
                   "the compiled program (no entry/exit layout moves) — "
                   "results drift ~1e-7/mp per gradient step and are "
                   "accepted by the bench_diff learning-curve envelope "
                   "vs a replicated control, NOT by bit-equality")
@click.option("--topo-mix", default=None,
              help="mixed-topology batched training (--replicas > 1): "
                   "fill the replica axis with a round-robin of this "
                   "comma-separated mix instead of one network per "
                   "episode.  Entries: 'schedule' (expands to the "
                   "scheduler's training topologies) or a scenario-"
                   "registry name (abilene, triangle, bteurope, ..., "
                   "random<N>/star<N>/ring<N>/line<N>), each optionally "
                   "'+<shape>' (bursty|diurnal|flash_crowd traffic), "
                   "'~<site>@<interval>[.<index>]' capacity faults "
                   "(link/node, '&'-joined), ':<seed>' (randomized "
                   "generators only).  Example: "
                   "'schedule,abilene+bursty,random12~link@3.0:7'.  One "
                   "compiled program serves the whole mixture — the "
                   "schedule 'switch' is just per-replica topology data, "
                   "so nothing retraces.  OR the on-device scenario "
                   "factory: 'factory:<fam>[-<fam>...][+shapes][~faults]' "
                   "(families star/ring/line/random, or 'all') samples a "
                   "fresh randomized per-replica (topology, traffic, "
                   "fault plan) INSIDE the compiled program every "
                   "episode — zero host regen, zero retraces, an "
                   "unbounded scenario distribution — with batch "
                   "composition steered by the TD auto-curriculum "
                   "(--curriculum-temperature/--curriculum-floor)")
@click.option("--pipeline/--no-pipeline", default=True, show_default=True,
              help="asynchronous episode pipeline (--replicas 1 path): "
                   "background traffic prefetch, fused rollout+learn "
                   "device step, deferred metric draining — bit-identical "
                   "results, the chip never idles between episodes; "
                   "--no-pipeline runs the serial reference loop")
@click.option("--precision", type=click.Choice(["f32", "bf16"]),
              default=None,
              help="dtype policy override: f32 (default; bit-identical to "
                   "the dtype-unaware stack) or bf16 (mixed-precision "
                   "network compute + replay storage with f32 master "
                   "params/optimizer/TD targets — ~2x MXU throughput, "
                   "half the replay HBM).  Unset = the agent yaml's "
                   "'precision' key (default f32)")
@click.option("--substep-impl", type=click.Choice(["xla", "pallas"]),
              default=None,
              help="simulator substep engine override: xla (default; the "
                   "hand-fused one-hot pipeline) or pallas (the substep "
                   "megakernel, ONE kernel invocation per substep — "
                   "bit-exact vs xla, CPU/interpret-only until its "
                   "Mosaic port).  Unset = the simulator yaml's "
                   "'substep_impl' key (default xla)")
@click.option("--unroll", type=int, default=None,
              help="substep-scan unroll factor override "
                   "(SimConfig.scan_unroll; trades compile time for less "
                   "scan overhead on the op-count-bound substep — sweep "
                   "with tools/lever_sweep.py, then promote the winner "
                   "here).  Unset = the simulator yaml's 'scan_unroll' "
                   "key (default 1)")
@click.option("--obs/--no-obs", "obs_enabled", default=True,
              show_default=True,
              help="unified run telemetry: per-episode events.jsonl "
                   "(SPS, phase timings, losses/grad-norms, drop reasons, "
                   "device memory), atomic metrics.json snapshots, and "
                   "the pipeline watchdog — tools/obs_report.py renders "
                   "the stream")
@click.option("--obs-dir", default=None,
              help="directory for events.jsonl/metrics.json "
                   "(default: the run's result dir)")
@click.option("--obs-interval", default=10, show_default=True,
              help="episodes between atomic metrics.json snapshot "
                   "rewrites")
@click.option("--obs-rotate-mb", default=0.0, show_default=True,
              help="size-based events.jsonl rotation for long exhibits: "
                   "when the live stream exceeds this many MiB it rotates "
                   "to events.jsonl.1..N (readers — obs_report, the trace "
                   "exporter — walk the segments transparently; 0 = no "
                   "rotation)")
@click.option("--obs-series-window", default=1024, show_default=True,
              help="flight recorder: points kept per metric in the hub's "
                   "bounded time-series rings (drop-oldest).  Feeds the "
                   "whole-run series.json, the /series endpoint query, "
                   "the async pipeline trace tracks and the black-box "
                   "post-mortem dumps.  0 disables history entirely — "
                   "the event stream is then byte-identical to a "
                   "recorder-free run")
@click.option("--perf/--no-perf", "perf_enabled", default=True,
              show_default=True,
              help="device-cost ledger: capture compiled FLOPs/bytes/"
                   "fusion counts of the watched entry points at compile "
                   "time, merge the run's phase wall into per-dispatch "
                   "MFU/roofline, and write perf.json next to "
                   "metrics.json (tools/bench_diff.py diffs them across "
                   "runs).  Costs one extra AOT trace per entry point at "
                   "startup; adds nothing to the dispatch path")
@click.option("--learn-obs/--no-learn-obs", "learnobs_enabled",
              default=True, show_default=True,
              help="on-device learning-signal ledger: per-topology "
                   "|TD-error| segments (segment_sum over the replay "
                   "rows' topo_idx), Q-value distribution moments, "
                   "per-layer param/grad norms and replay fill/age — "
                   "computed INSIDE the dispatched programs and drained "
                   "with the deferred metric drain (zero new host "
                   "syncs).  Lands as learn_signal events + tagged "
                   "gauges; RunObserver.close() extracts schema-"
                   "versioned curves.json that tools/bench_diff.py "
                   "gates (final-window return, AUC, episodes-to-"
                   "threshold)")
@click.option("--metrics-port", default=0, show_default=True,
              help="live Prometheus /metrics endpoint over the run's "
                   "MetricsHub (stdlib HTTP server on 127.0.0.1) so a "
                   "long run can be scraped WHILE it executes: curl "
                   "http://127.0.0.1:<port>/metrics.  0 = disabled; the "
                   "bound port is recorded as a metrics_endpoint event")
@click.option("--watchdog-budget", default=300.0, show_default=True,
              help="seconds without a completed episode before the "
                   "pipeline watchdog emits a structured 'stall' event "
                   "(0 disables the watchdog)")
@click.option("--watchdog-escalate", default=3, show_default=True,
              help="after the first stall, this many MORE full "
                   "--watchdog-budget periods of continued silence "
                   "escalate from reporting to acting: the watchdog "
                   "interrupts the prefetcher and the trainer restarts it "
                   "from the episode counter (0 = report-only)")
@click.option("--check-invariants/--no-check-invariants", default=False,
              show_default=True,
              help="run utils.debug.check_invariants on every drained "
                   "episode's final simulator state; violations emit "
                   "structured 'invariant_violation' events")
@click.option("--fault-plan", default=None,
              help="deterministic fault injection for chaos testing "
                   "(resilience.FaultPlan grammar: 'site@key[:arg]' "
                   "joined by ';').  Serial sites key by episode: "
                   "prefetch_die, slow_episode, dispatch_transient, "
                   "nan_grads, ckpt_corrupt.  Async fleet sites "
                   "(--async): actor_die@a<actor>:<episode>, "
                   "ring_poison@<episode>, publish_corrupt@v<version>, "
                   "watcher_stall@a<actor>:<episode>[:sleep_s], "
                   "learner_transient@<burst>.  nan_grads also fires on "
                   "--replicas > 1 (host-verified, rollback-backed).  "
                   "Unset: the GSC_FAULT_PLAN env var; empty = no faults")
@click.option("--rollback/--no-rollback", default=True, show_default=True,
              help="keep a last-good in-memory snapshot of (state, "
                   "replay) and roll back when the on-device all-finite "
                   "guard flags a poisoned learner state (costs ~2 extra "
                   "replay copies in HBM; training math is bit-identical "
                   "until a violation actually triggers)")
@click.option("--ckpt-interval", default=0, show_default=True,
              help="episodes between preemption-safe checkpoints "
                   "(checksummed, written under <run>/ckpts with a "
                   "rotating last-good pointer; 0 disables).  SIGTERM/"
                   "SIGINT always snapshot one on the way out")
@click.option("--ckpt-retain", default=3, show_default=True,
              help="periodic checkpoints kept on disk (the last-good "
                   "pointer target is never pruned)")
@click.option("--hot-swap-dir", default=None,
              help="train-while-serve: publish the actor params as "
                   "versioned, fingerprint-keyed hot-swap artifacts "
                   "(serve.fleet.WeightPublisher) into this directory "
                   "every --publish-interval drained-finite episodes — a "
                   "concurrently running `cli serve --hot-swap-dir` "
                   "fleet swaps each version in between dispatches.  "
                   "--replicas 1 ships the rollback guard's VERIFIED "
                   "snapshot; --replicas > 1 ships the host-gathered, "
                   "finite-verified replica state (mesh-agnostic layout "
                   "under --mesh, like the checkpoints)")
@click.option("--publish-interval", default=1, show_default=True,
              help="episodes between hot-swap weight publishes "
                   "(with --hot-swap-dir)")
@click.option("--async", "async_mode", is_flag=True, default=False,
              help="decoupled actor/learner training (--replicas > 1): "
                   "--async-actors rollout threads run the jitted replica "
                   "rollout continuously and ship device-resident "
                   "transition blocks into the shared replay ring (one "
                   "jitted replay_ingest per block, no host round-trip), "
                   "while the learner runs learn bursts back-to-back and "
                   "publishes actor weights every --publish-bursts bursts "
                   "over an in-process WeightPublisher bus the actors "
                   "adopt between dispatches.  Off-policy staleness is "
                   "bounded (--max-staleness) and measured (policy_lag / "
                   "replay_lag gauges, actor_idle/learner_idle phases).  "
                   "Composes with --mesh over the dp axis: the replay "
                   "ring lives dp-sharded on the learner mesh, ingest is "
                   "an AOT-compiled per-shard donated write (asserted "
                   "collective-free) and learn bursts run under the full "
                   "pjit plan (tp-only meshes, dp=1, are refused).  "
                   "Composes with --fault-plan (async fleet sites; actor "
                   "supervision + poison quarantine + rollback) and with "
                   "--resume auto after a SIGTERM preemption; learning "
                   "curves match the sync control within bench_diff's "
                   "curve bands, not bit-exactly")
@click.option("--async-actors", default=2, show_default=True,
              help="rollout threads for --async (each owns its own env "
                   "replicas batch, PRNG stream and adopted weights; "
                   "episodes are round-robined by global index, so the "
                   "scenario stream is thread-count-independent)")
@click.option("--max-staleness", default=0, show_default=True,
              help="--async backpressure bound: max produced-but-"
                   "uningested env steps the actors may run ahead of the "
                   "learner before the replay channel blocks them "
                   "(0 = two episodes' worth per actor)")
@click.option("--publish-bursts", default=1, show_default=True,
              help="learn bursts between actor-weight publishes on the "
                   "--async path (higher = staler actors, fewer "
                   "publish-time host syncs)")
@click.option("--learn-ratio", default=1.0, show_default=True,
              help="--async learner pacing: gradient-step budget per "
                   "ingested env step, relative to the sync control "
                   "(1.0 = one burst per replicas*episode_steps ingested "
                   "steps — the matched-budget setting the curve bands "
                   "assume)")
@click.option("--curriculum-temperature", default=_CURRICULUM_DEFAULTS[0],
              show_default=True,
              help="TD auto-curriculum softmax temperature over the "
                   "per-family |TD| EWMAs (factory --topo-mix only): "
                   "lower = chase the generalization frontier harder, "
                   "higher = flatter; infinity degenerates to "
                   "round-robin-like uniform sampling")
@click.option("--curriculum-floor", default=_CURRICULUM_DEFAULTS[1],
              show_default=True,
              help="total probability mass the auto-curriculum always "
                   "spreads uniformly over the factory families (0..1): "
                   "no family's sampling probability can fall below "
                   "floor/K, so every family stays alive (forgetting "
                   "stays visible)")
@click.option("--jax-cache-dir", default=None, help=_JAX_CACHE_HELP)
@click.option("--verbose/--quiet", default=True)
def train(agent_config, simulator_config, service, scheduler, episodes, seed,
          result_dir, experiment_id, max_nodes, max_edges, tensorboard,
          profile, runs, resume, resource_functions_path, replicas, chunk,
          mesh, partition_rules, topo_mix, pipeline, precision,
          substep_impl, unroll, obs_enabled, obs_dir, obs_interval,
          obs_rotate_mb, obs_series_window, perf_enabled,
          learnobs_enabled, metrics_port,
          watchdog_budget, watchdog_escalate,
          check_invariants, fault_plan, rollback, ckpt_interval,
          ckpt_retain, hot_swap_dir, publish_interval, async_mode,
          async_actors, max_staleness, publish_bursts, learn_ratio,
          curriculum_temperature, curriculum_floor, jax_cache_dir,
          verbose):
    """Train DDPG, checkpoint, then one greedy test episode
    (main.py:16-76).  With --runs N, trains N seeds and selects the best
    (src/rlsp/agents/main.py:89-113 semantics).  With --replicas B, each
    episode rolls out B vmapped env replicas feeding sharded replay — the
    TPU scale-out the reference lacks; evaluation and the checkpointed
    learner state are identical in shape to the single-env path."""
    import numpy as _np

    from .agents.trainer import Trainer
    from .utils.checkpoint import load_checkpoint, save_checkpoint
    from .utils.experiment import (
        ExperimentResult,
        copy_inputs,
        select_best_agent,
        setup_result_dir,
    )

    jax_cache_dir = _apply_jax_cache(jax_cache_dir)
    if resume and runs != 1:
        raise click.BadParameter("--resume only supports --runs 1")
    if metrics_port < 0:
        raise click.BadParameter("--metrics-port must be >= 0 "
                                 "(0 = disabled)")
    if metrics_port and not obs_enabled:
        # same contract as cli serve: a port that silently never binds
        # would leave a scraper on connection-refused all run long
        raise click.BadParameter("--metrics-port needs the run observer "
                                 "(drop --no-obs)")
    if unroll is not None and unroll < 1:
        # same contract as bench.py's --unroll: fail fast with the flag's
        # name, not a SimConfig traceback from deep inside the run loop
        raise click.BadParameter("--unroll must be a positive integer")
    if publish_interval < 1:
        raise click.BadParameter("--publish-interval must be >= 1")
    if async_mode:
        # fail fast with the flag's name — the trainer raises the same
        # refusals, but from deep inside the run loop after the build
        if replicas <= 1:
            raise click.BadParameter(
                "--async decouples the replica rollout from the learner "
                "— it requires the replica-parallel path (--replicas > 1)")
        if async_actors < 1:
            raise click.BadParameter("--async-actors must be >= 1")
        if max_staleness < 0:
            raise click.BadParameter(
                "--max-staleness must be >= 0 (0 = two episodes' worth "
                "of steps per actor)")
        if publish_bursts < 1:
            raise click.BadParameter("--publish-bursts must be >= 1")
        if learn_ratio <= 0:
            raise click.BadParameter("--learn-ratio must be > 0")
    elif (async_actors, max_staleness, publish_bursts, learn_ratio) != \
            (2, 0, 1, 1.0):
        raise click.BadParameter(
            "--async-actors/--max-staleness/--publish-bursts/"
            "--learn-ratio tune the decoupled actor/learner path — pass "
            "--async or drop the flags")
    plan = None
    if mesh:
        # build the plan BEFORE any other jax work so the mesh binds the
        # backend's first-created devices
        from .parallel import ShardingPlan, parse_mesh_shape
        if replicas <= 1:
            raise click.BadParameter(
                "--mesh shards env replicas over the device grid — it "
                "requires the replica-parallel path (--replicas > 1)")
        try:
            dp_, mp_ = parse_mesh_shape(mesh)
        except ValueError as e:
            raise click.BadParameter(str(e))
        if replicas % (dp_ * mp_) != 0:
            raise click.BadParameter(
                f"--replicas ({replicas}) must be divisible by the mesh "
                f"device count ({dp_ * mp_} = {dp_}x{mp_}) for an even "
                "replica sharding")
        # same contract as bench.py and make_train_mesh's docstring:
        # production entry points check device counts BEFORE building the
        # mesh — otherwise make_train_mesh's virtual-CPU fallback would
        # silently re-platform a TPU training run onto dp*mp virtual CPU
        # devices (the dry-run path must be an explicit choice)
        have = len(jax.devices())
        if have < dp_ * mp_:
            raise click.UsageError(
                f"--mesh {mesh} needs {dp_ * mp_} devices, backend has "
                f"{have}.  For a CPU dry run start the process with "
                f"JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_"
                f"device_count={dp_ * mp_}")
        plan = ShardingPlan.from_spec(mesh, rules=partition_rules)
        if async_mode:
            # dp-sharded replay needs a dp axis — refuse tp-only grids
            # here with the flag's name, not from inside the run loop
            try:
                plan.assert_async_capable()
            except ValueError as e:
                raise click.BadParameter(str(e))
    elif partition_rules != "replicated":
        raise click.BadParameter(
            f"--partition-rules {partition_rules} has no effect without "
            "--mesh — pass --mesh DPxMP (e.g. 4x2) or drop the flag")
    if topo_mix:
        if replicas <= 1:
            raise click.BadParameter(
                "--topo-mix fills the replica axis with the mixture — it "
                "requires the replica-parallel path (--replicas > 1)")
        # grammar + registry-name validation BEFORE any expensive build
        # (factory: entries parse through topology.factory, everything
        # else through the registry); size/fit errors (a 53-node tinet
        # in a 24-node bucket) surface from the driver's compile with
        # the bucket dims in the message
        from .topology.scenarios import validate_mix
        try:
            validate_mix(topo_mix)
        except ValueError as e:
            raise click.BadParameter(f"--topo-mix: {e}")
    from .topology.factory import is_factory_mix
    curriculum_cfg = None
    if is_factory_mix(topo_mix):
        from .env.curriculum import CurriculumConfig
        try:
            curriculum_cfg = CurriculumConfig(
                temperature=curriculum_temperature,
                floor=curriculum_floor)
        except ValueError as e:
            raise click.BadParameter(str(e))
    elif (curriculum_temperature, curriculum_floor) != _CURRICULUM_DEFAULTS:
        raise click.BadParameter(
            "--curriculum-* steers the on-device scenario factory — "
            "pass --topo-mix factory:... or drop the flags")
    if resume == "auto":
        # newest checksummed checkpoint under the result root that still
        # validates — a corrupted newest (half-written at the kill, bit
        # rot) falls back to the previous good one
        from .resilience.ckpt import find_resumable
        found = find_resumable(result_dir)
        if not found:
            raise click.BadParameter(
                "--resume auto: no checkpoint with a validating content "
                f"checksum under {result_dir!r} (periodic --ckpt-interval "
                "saves, preemption snapshots and final checkpoints all "
                "qualify)")
        click.echo(f"[resume auto] {found}", err=True)
        resume = found
    # deterministic chaos schedule (--fault-plan / GSC_FAULT_PLAN env);
    # parse errors must fail the command before any run state exists.
    # Parsed FRESH per run below — FaultPlan specs fire exactly once, so
    # one shared object would leave runs 1..N-1 silently fault-free.
    from .resilience.faults import FaultPlan
    try:
        FaultPlan.from_env(fault_plan)
    except ValueError as e:
        raise click.BadParameter(str(e))
    run_dirs = []
    outputs = {}
    for run in range(runs):
        fplan = FaultPlan.from_env(fault_plan)
        run_seed = seed + run
        if resume:
            # the checkpoint records the precision it was trained under
            # (sidecar meta): silently rebuilding its bf16 replay into an
            # f32 template (or vice versa) would either round the buffer
            # or drop it behind a misleading format-mismatch fallback —
            # adopt the recorded policy, and refuse a contradicting flag
            from .utils.checkpoint import read_checkpoint_meta
            meta = read_checkpoint_meta(resume)
            # a checkpoint without the sidecar predates the precision
            # policy and can only hold f32 state/replay — treating it as
            # anything else would rebuild a mismatched replay template
            # and drop the stored buffer behind the format-fallback path
            ck_prec = meta.get("precision") or "f32"
            if precision and precision != ck_prec:
                raise click.BadParameter(
                    f"--precision {precision} contradicts the checkpoint's "
                    f"{'recorded' if 'precision' in meta else 'implicit pre-meta'} "
                    f"policy ({ck_prec}); resume adopts the checkpoint's "
                    "precision — drop the flag or retrain")
            if not precision and ck_prec != "f32":
                click.echo(f"[resume] adopting checkpoint precision "
                           f"{ck_prec}", err=True)
            precision = ck_prec
        rdir = setup_result_dir(result_dir, experiment_id)
        run_dirs.append(rdir)
        copy_inputs(rdir, [agent_config, simulator_config, service, scheduler])
        result = ExperimentResult(rdir)
        result.env_config = {"agent_config": agent_config,
                             "simulator_config": simulator_config,
                             "service": service, "scheduler": scheduler,
                             "seed": run_seed}
        # console + per-run file log (setup_logging, main.py:307-329)
        from .utils.logging import setup_logging
        setup_logging(verbose=False, logfile=os.path.join(rdir, "run.log"))
        env, driver, agent = _build(agent_config, simulator_config, service,
                                    scheduler, run_seed, max_nodes, max_edges,
                                    resource_functions_path,
                                    precision=precision,
                                    substep_impl=substep_impl,
                                    unroll=unroll, topo_mix=topo_mix)
        # episode-0 topology/traffic memo: mesh_meta and the resume
        # template both need the same deterministic build, and it is
        # real host work — pay it at most once per run
        _ep0 = []

        def _episode0():
            if not _ep0:
                _ep0.append(driver.episode(0, False))
            return _ep0[0]

        mesh_meta = {}
        if plan is not None and obs_enabled:
            # partition-layout record for run_start: the effective mesh
            # shape + per-leaf spec counts (never the full tree) over the
            # eval_shape'd learner state — pure tracing, no device work,
            # and the SAME summary() the tests assert on.  Gated on obs:
            # run_start is its only consumer, and the episode(0) traffic
            # build is real host work a --no-obs run shouldn't pay
            from .agents.ddpg import DDPG as _DDPG
            topo0, traffic0 = _episode0()
            _, obs_shape = jax.eval_shape(
                env.reset, jax.random.PRNGKey(0), topo0, traffic0)
            state_shape = jax.eval_shape(
                _DDPG(env, agent).init, jax.random.PRNGKey(0), obs_shape)
            mesh_meta = {"mesh": plan.describe(),
                         "partition_rules": partition_rules,
                         "partition_specs": plan.summary(state_shape)}
        obs = None
        if obs_enabled:
            from .obs import RunObserver

            # with --runs N and an explicit --obs-dir, each run gets its
            # own subdirectory so the event streams never interleave
            odir = obs_dir or rdir
            if obs_dir and runs > 1:
                odir = os.path.join(obs_dir, f"run{run}")
            obs = RunObserver(odir, snapshot_interval=obs_interval,
                              watchdog_budget_s=watchdog_budget,
                              watchdog_escalate=watchdog_escalate,
                              rotate_mb=obs_rotate_mb, perf=perf_enabled,
                              learn=learnobs_enabled,
                              metrics_port=(metrics_port or None),
                              series_window=obs_series_window,
                              tags={"seed": run_seed})
            obs.start(meta={"episodes": episodes, "replicas": replicas,
                            "pipeline": pipeline, "seed": run_seed,
                            "topo_mix": topo_mix,
                            **({"curriculum": {
                                "temperature": curriculum_temperature,
                                "floor": curriculum_floor}}
                               if curriculum_cfg is not None else {}),
                            "precision": agent.precision,
                            # the EFFECTIVE engine knobs (yaml or flag),
                            # read back from the built sim_cfg so the
                            # recorded values can't drift from what ran
                            "substep_impl": env.sim_cfg.substep_impl,
                            "unroll": env.sim_cfg.scan_unroll,
                            "result_dir": rdir,
                            "ckpt_interval": ckpt_interval,
                            "hot_swap_dir": hot_swap_dir,
                            **({"async": {
                                "actors": async_actors,
                                "max_staleness": max_staleness,
                                "publish_bursts": publish_bursts,
                                "learn_ratio": learn_ratio}}
                               if async_mode else {}),
                            "jax_cache_dir": jax_cache_dir,
                            **mesh_meta,
                            **({"fault_plan": fplan.summary()} if fplan
                               else {})})
        trainer = Trainer(env, driver, agent, seed=run_seed, result_dir=rdir,
                          tensorboard=tensorboard, obs=obs,
                          check_invariants=check_invariants,
                          fault_plan=fplan, rollback=rollback)
        # checksummed rotating checkpoints under the run dir: periodic
        # (--ckpt-interval) and the SIGTERM/SIGINT snapshot both land
        # here, which is exactly the tree --resume auto searches
        from .resilience.ckpt import CheckpointManager
        from .resilience.preempt import PreemptionGuard
        manager = CheckpointManager(os.path.join(rdir, "ckpts"),
                                    retain=ckpt_retain,
                                    meta={"precision": agent.precision},
                                    fault_plan=fplan, obs=obs)
        try:
            # everything from here on runs under the observer: a failed
            # resume restore (or bad --episodes) must still land the
            # run_end status=error tail before propagating
            init_state = init_buffer = None
            start_episode = 0
            if resume:
                from .utils.checkpoint import load_full_or_partial
                topo0, traffic0 = _episode0()
                _, obs0 = env.reset(jax.random.PRNGKey(0), topo0, traffic0)
                example = trainer.ddpg.init(jax.random.PRNGKey(0), obs0)
                if replicas > 1:
                    # replica-sharded replay: [B, capacity, ...] leaves — a
                    # checkpoint from a matching --replicas run restores
                    # fully; anything else falls back to state-only
                    from .parallel import ParallelDDPG
                    example_buffer = ParallelDDPG(
                        env, agent, num_replicas=replicas).init_buffers(obs0)
                else:
                    example_buffer = trainer.ddpg.init_buffer(obs0)
                restored, buffer_ok = load_full_or_partial(
                    resume, example, example_buffer=example_buffer,
                    example_extra={"episode": _np.asarray(0, _np.int32)})
                if buffer_ok:
                    init_buffer = restored["buffer"]
                else:
                    init_buffer = None
                    click.echo("[resume] replay buffer not restorable "
                               "(legacy storage format, or replay config "
                               "such as mem_limit changed since the "
                               "checkpoint) — restored state only, replay "
                               "starts empty", err=True)
                init_state = restored["state"]
                start_episode = int(restored["extra"]["episode"]) \
                    if "extra" in restored else 0
                if start_episode >= episodes:
                    # range(start, episodes) would be empty: no training,
                    # but the checkpoint would be REWRITTEN with the
                    # smaller counter — corrupting exact resume for later
                    # runs
                    raise click.BadParameter(
                        f"--episodes ({episodes}) must exceed the "
                        f"checkpoint's completed episode count "
                        f"({start_episode})")
            result.runtime_start("train")
            # SIGTERM/SIGINT during training stop the loop at the next
            # episode boundary; the snapshot + clean exit happen below
            with PreemptionGuard() as guard:
                publisher = None
                if hot_swap_dir:
                    from .serve.fleet import WeightPublisher
                    publisher = WeightPublisher(
                        hot_swap_dir,
                        hub=(obs.hub if obs is not None else None),
                        fault_plan=fplan)
                if replicas > 1 and async_mode:
                    state, buffer = trainer.train_async(
                        episodes, num_replicas=replicas, chunk=chunk,
                        actor_threads=async_actors,
                        verbose=verbose, profile=profile,
                        init_state=init_state, init_buffers=init_buffer,
                        start_episode=start_episode,
                        ckpt_manager=manager, ckpt_interval=ckpt_interval,
                        preempt=guard, plan=plan, publisher=publisher,
                        publish_bursts=publish_bursts,
                        curriculum=curriculum_cfg,
                        max_staleness=max_staleness,
                        learn_ratio=learn_ratio)
                elif replicas > 1:
                    state, buffer = trainer.train_parallel(
                        episodes, num_replicas=replicas, chunk=chunk,
                        verbose=verbose, profile=profile,
                        init_state=init_state, init_buffers=init_buffer,
                        start_episode=start_episode,
                        ckpt_manager=manager, ckpt_interval=ckpt_interval,
                        preempt=guard, plan=plan, publisher=publisher,
                        publish_interval=(publish_interval
                                          if hot_swap_dir else 0),
                        curriculum=curriculum_cfg)
                else:
                    state, buffer = trainer.train(
                        episodes, verbose=verbose, profile=profile,
                        init_state=init_state, init_buffer=init_buffer,
                        start_episode=start_episode, pipeline=pipeline,
                        ckpt_manager=manager, ckpt_interval=ckpt_interval,
                        preempt=guard, publisher=publisher,
                        publish_interval=(publish_interval
                                          if hot_swap_dir else 0))
            result.runtime_stop("train")

            if trainer.preempted:
                # preemption-safe exit: a checksummed snapshot of the
                # drained state (monotone episode counter), a clean rc=0,
                # and a JSON line saying how to continue — no evaluation,
                # the grace window is for the checkpoint
                done = trainer.completed_episodes
                ckpt = manager.save(state, buffer, episode=done)
                if obs is not None:
                    obs.close(status="preempted")
                result.metrics = {"status": "preempted"}
                result.write()
                payload = {
                    "status": "preempted", "signal": guard.signame,
                    "result_dir": rdir, "checkpoint": ckpt,
                    "episodes_completed": done,
                    "hint": "continue with --resume auto"}
                ainfo = getattr(trainer, "async_info", None)
                if async_mode and ainfo:
                    # the ASYNC_r02 drain proof, attached to the exit
                    # line: a preempted async run must have drained the
                    # channel fully before the snapshot above
                    payload["drain"] = {
                        k: ainfo[k] for k in (
                            "produced_steps", "ingested_steps",
                            "transitions_lost")
                        if k in ainfo}
                click.echo(json.dumps(payload))
                return

            ckpt = save_checkpoint(os.path.join(rdir, "checkpoint"), state,
                                   buffer=buffer,
                                   extra={"episode": _np.asarray(episodes,
                                                                 _np.int32)},
                                   meta={"precision": agent.precision,
                                         "episode": episodes},
                                   checksum=True)
            result.runtime_start("test")
            test = trainer.evaluate(state, episodes=1, test_mode=True,
                                    telemetry=True)
            result.runtime_stop("test")
        except BaseException:
            # the run's final events (run_end status=error + a last
            # snapshot) must land even when training faults — that tail
            # is exactly what post-mortems read.  Best effort: a close
            # that itself fails (e.g. the same full disk that killed the
            # run) must not mask the original traceback.
            if obs is not None:
                try:
                    obs.close(status="error")
                except Exception:
                    pass
            raise
        if obs is not None:
            obs.close(status="ok")
        result.metrics = test
        result.write()
        outputs[rdir] = {"result_dir": rdir, "checkpoint": ckpt, **test}
    best = select_best_agent(run_dirs) if runs > 1 else run_dirs[0]
    click.echo(json.dumps({**outputs[best], "runs": runs,
                           "all_result_dirs": run_dirs}))


@cli.command()
@click.argument("agent_config")
@click.argument("simulator_config")
@click.argument("service")
@click.argument("scheduler")
@click.argument("checkpoint")
@click.option("--episodes", default=1, show_default=True)
@click.option("--seed", default=0, show_default=True)
@click.option("--max-nodes", default=24, show_default=True)
@click.option("--max-edges", default=37, show_default=True)
@click.option("--resource-functions-path", default=None,
              help="dir (or .py file) of user resource-function plugins")
@click.option("--precision", type=click.Choice(["f32", "bf16"]),
              default=None,
              help="dtype policy override; unset = the checkpoint's "
                   "recorded policy (sidecar meta; falls back to the "
                   "agent yaml for pre-meta checkpoints) so the greedy "
                   "episodes evaluate under the compute dtype the "
                   "checkpoint was trained with")
@click.option("--jax-cache-dir", default=None, help=_JAX_CACHE_HELP)
def infer(agent_config, simulator_config, service, scheduler, checkpoint,
          episodes, seed, max_nodes, max_edges, resource_functions_path,
          precision, jax_cache_dir):
    """Restore a checkpoint and run greedy test episodes
    (inference.py:17-40).  The JSON output splits compile+warmup wall
    (``compile_warmup_s``: everything up to the first completed control
    step) from steady-state episode time (``steady_s``) — the cold-start
    cost the serving path (``cli serve``) exists to amortize is visible
    here, not hidden inside the total."""
    from .agents.trainer import Trainer
    from .utils.checkpoint import load_full_or_partial, read_checkpoint_meta

    import numpy as _np

    _apply_jax_cache(jax_cache_dir)
    if precision is None:
        precision = read_checkpoint_meta(checkpoint).get("precision")
    env, driver, agent = _build(agent_config, simulator_config, service,
                                scheduler, seed, max_nodes, max_edges,
                                resource_functions_path,
                                precision=precision)
    trainer = Trainer(env, driver, agent, seed=seed)
    topo, traffic = driver.episode(0, test_mode=True)
    _, obs = env.reset(jax.random.PRNGKey(seed), topo, traffic)
    example = trainer.ddpg.init(jax.random.PRNGKey(0), obs)
    example_buffer = trainer.ddpg.init_buffer(obs)
    # full train checkpoint (state + replay + episode counter), or a
    # state-only / legacy-replay-format checkpoint via partial restore
    state = load_full_or_partial(
        checkpoint, example, example_buffer=example_buffer,
        example_extra={"episode": _np.asarray(0, _np.int32)})[0]["state"]
    out = trainer.evaluate(state, episodes=episodes, test_mode=True)
    click.echo(json.dumps(out))


@cli.command()
@click.argument("agent_config")
@click.argument("simulator_config")
@click.argument("service")
@click.argument("scheduler")
@click.argument("checkpoint", required=False)
@click.option("--requests", default=64, show_default=True,
              help="synthetic coordination requests the built-in load "
                   "driver fires through the server (the programmatic "
                   "surface is PolicyServer.submit)")
@click.option("--concurrency", default=4, show_default=True,
              help="closed-loop client threads submitting concurrently — "
                   "what actually fills the larger batch buckets")
@click.option("--buckets", default="1,4,8", show_default=True,
              help="comma-separated batch-size buckets; each gets its own "
                   "AOT-compiled executable, a request batch runs in the "
                   "smallest bucket that fits it")
@click.option("--deadline-ms", default=5.0, show_default=True,
              help="max wait before a partially-filled batch flushes (the "
                   "latency a lone request pays for batching; with "
                   "--continuous it only bounds SLO deadline-miss "
                   "accounting — continuous batching never waits it out)")
@click.option("--continuous", is_flag=True, default=False,
              help="continuous batching: the next batch is formed while "
                   "the current device call is in flight and dispatches "
                   "the moment the device frees — requests join the next "
                   "dispatch instead of waiting out --deadline-ms.  "
                   "Latency-optimal at low rate (a lone request never "
                   "idles a deadline away), batch-optimal under load "
                   "(the in-flight backlog becomes the next batch).  "
                   "Default: the historic deadline batcher")
@click.option("--workers", default=1, show_default=True,
              help="serving fleet size: N PolicyServer replicas behind "
                   "least-queue-depth dispatch, every serve metric "
                   "tagged worker=w<i>.  A learned-tier fleet also gets "
                   "an SPR brownout tier that absorbs overflow (full "
                   "worker queue, or SLO budget burn past "
                   "--brownout-burn with a backlog) instead of "
                   "rejecting.  1 = the historic single server")
@click.option("--brownout-burn", default=2.0, show_default=True,
              help="error-budget burn rate above which a backlogged "
                   "fleet sheds new load to the SPR tier (needs "
                   "--workers > 1, a checkpoint and --slo-p99-ms; "
                   "0 disables proactive shedding — overflow shedding "
                   "on a full queue stays on)")
@click.option("--hot-swap-dir", default=None,
              help="live weight hot-swap: watch this publish directory "
                   "(serve.fleet.WeightPublisher layout — cli train "
                   "--hot-swap-dir writes it) and swap newly published "
                   "weight versions in BETWEEN device dispatches, zero "
                   "requests dropped, no batch ever mixing versions; "
                   "every serve_flush event/span carries the "
                   "policy_version that answered it")
@click.option("--swap-poll-s", default=0.2, show_default=True,
              help="seconds between hot-swap directory polls")
@click.option("--fire-swaps", default=0, show_default=True,
              help="self-test/bench hook: publish this many weight "
                   "versions into --hot-swap-dir WHILE the synthetic "
                   "load runs (spaced across the request count), so "
                   "hot-swap-under-fire is measurable from one command.  "
                   "The published payload is the serving tier's own "
                   "current weights (learned: the restored actor params; "
                   "SPR: the precomputed schedule action), so answers "
                   "stay bit-stable while the full swap path — publish, "
                   "watch, validate, lock, swap, stamp — executes under "
                   "load")
@click.option("--artifact-cache", default=None,
              help="compiled-policy artifact cache dir (serialized "
                   "jax.export modules keyed by checkpoint fingerprint + "
                   "shapes + precision + jaxlib).  Default: "
                   "<result-dir>/serve_cache — shared across runs, so a "
                   "warm restart skips policy tracing entirely")
@click.option("--pool-steps", default=8, show_default=True,
              help="env steps rolled (uniform schedule) to build the "
                   "synthetic request pool of distinct observations")
@click.option("--stats-interval", default=50, show_default=True,
              help="completed requests between serve_stats events")
@click.option("--request-timeout", default=120.0, show_default=True,
              help="seconds one driver client waits for its answer")
@click.option("--seed", default=0, show_default=True)
@click.option("--max-nodes", default=24, show_default=True)
@click.option("--max-edges", default=37, show_default=True)
@click.option("--resource-functions-path", default=None,
              help="dir (or .py file) of user resource-function plugins")
@click.option("--result-dir", default="results", show_default=True)
@click.option("--obs/--no-obs", "obs_enabled", default=True,
              show_default=True,
              help="serving telemetry through the run observer: "
                   "serve_start/serve_stats events + latency histograms "
                   "in events.jsonl/metrics.json (tools/obs_report.py "
                   "renders the serving section)")
@click.option("--obs-dir", default=None,
              help="directory for events.jsonl/metrics.json "
                   "(default: the run's result dir)")
@click.option("--obs-series-window", default=1024, show_default=True,
              help="flight recorder: points kept per metric in the hub's "
                   "time-series rings (the fleet dispatcher samples "
                   "queue depth, bucket occupancy, burn and pad waste "
                   "into them at the burn-refresh cadence; series.json "
                   "and /series read them back).  0 disables history")
@click.option("--perf/--no-perf", "perf_enabled", default=True,
              show_default=True,
              help="device-cost ledger over the serving buckets: each "
                   "serve_policy_b<B> records compiled FLOPs/bytes/"
                   "fusions at start() and its measured latency merges "
                   "in at close() — perf.json lands next to metrics.json")
@click.option("--metrics-port", default=0, show_default=True,
              help="live Prometheus /metrics endpoint over the serving "
                   "hub (the same endpoint cli train exposes): latency "
                   "histograms, queue depth and bucket occupancy are "
                   "scrapeable while the server runs.  0 = disabled; "
                   "requires --obs")
@click.option("--trace-sample", default=0, show_default=True,
              help="head-sample every Nth request into a "
                   "serve_request_span event (queue-wait / batch-wait / "
                   "device / fan-out split; the trace exporter renders "
                   "them flow-linked to their flush).  0 = request "
                   "spans off; flush-level serve_flush spans and the "
                   "latency-decomposition histograms are always "
                   "recorded under --obs.  Requires --obs")
@click.option("--slo-p99-ms", default=None,
              help="declarative latency objective(s) the SLO engine "
                   "judges rolling attainment + error-budget burn "
                   "against.  Grammar: '<ms>' overall, "
                   "'<bucket>:<ms>' per bucket, comma-separated — e.g. "
                   "'25' or '25,8:60'.  Off by default (deadline-miss "
                   "ratio, pad waste and arrival rate are tracked "
                   "regardless).  Requires --obs")
@click.option("--jax-cache-dir", default=None, help=_JAX_CACHE_HELP)
def serve(agent_config, simulator_config, service, scheduler, checkpoint,
          requests, concurrency, buckets, deadline_ms, continuous,
          workers, brownout_burn, hot_swap_dir, swap_poll_s, fire_swaps,
          artifact_cache, pool_steps, stats_interval, request_timeout,
          seed, max_nodes, max_edges, resource_functions_path, result_dir,
          obs_enabled, obs_dir, obs_series_window, perf_enabled,
          metrics_port, trace_sample, slo_p99_ms, jax_cache_dir):
    """Serve coordination decisions from an AOT-compiled greedy policy.

    With CHECKPOINT: restores the actor, ahead-of-time compiles the
    batched greedy policy for every bucket (artifact-cache backed — a
    warm restart deserializes instead of re-tracing, so startup drops
    from minutes to seconds), then answers micro-batched requests.
    Without CHECKPOINT: the SPR shortest-path heuristic serves as the
    non-learned fallback tier through the same queue and accounting.

    Fleet mode (--workers N) runs N server replicas behind
    least-queue-depth dispatch with an SPR brownout tier;
    --hot-swap-dir makes every worker watch a weight-publish directory
    (written by a concurrent `cli train --hot-swap-dir` run) and swap
    new policy versions in between dispatches — train-while-serve with
    zero dropped requests across a swap.

    This command drives itself with a synthetic closed-loop request load
    (--requests/--concurrency over a pool of real observations) and
    reports requests/s + p50/p99 latency as JSON — the in-process SLA
    measurement loop that tools/serve_bench.py banks as SERVE_*.json."""
    import threading
    import time as _time

    import jax.numpy as jnp
    import numpy as _np

    from .agents.ddpg import DDPG
    from .serve import (ArtifactCache, FleetDispatcher, GreedyServePolicy,
                        PolicyServer, SPRFallbackPolicy)
    from .utils.experiment import setup_result_dir

    try:
        bucket_sizes = tuple(sorted({int(b) for b in buckets.split(",")}))
        if not bucket_sizes or any(b < 1 for b in bucket_sizes):
            raise ValueError
    except ValueError:
        raise click.BadParameter(
            f"--buckets must be comma-separated positive ints, got "
            f"{buckets!r}")
    if requests < 1 or concurrency < 1:
        raise click.BadParameter("--requests and --concurrency must be "
                                 "positive")
    if workers < 1:
        raise click.BadParameter("--workers must be >= 1")
    if fire_swaps < 0:
        raise click.BadParameter("--fire-swaps must be >= 0")
    if fire_swaps and not hot_swap_dir:
        raise click.BadParameter("--fire-swaps publishes into the hot-"
                                 "swap directory — pass --hot-swap-dir")
    if swap_poll_s <= 0:
        raise click.BadParameter("--swap-poll-s must be > 0")
    if metrics_port < 0:
        raise click.BadParameter("--metrics-port must be >= 0 "
                                 "(0 = disabled)")
    if metrics_port and not obs_enabled:
        raise click.BadParameter("--metrics-port needs the run observer "
                                 "(drop --no-obs)")
    if trace_sample < 0:
        raise click.BadParameter("--trace-sample must be >= 0 "
                                 "(0 = request spans off)")
    if (trace_sample or slo_p99_ms) and not obs_enabled:
        raise click.BadParameter("--trace-sample/--slo-p99-ms need the "
                                 "run observer (drop --no-obs)")
    slo_objectives = None
    if slo_p99_ms:
        from .obs import parse_slo_spec
        try:
            slo_objectives = parse_slo_spec(slo_p99_ms)
        except ValueError as e:
            raise click.BadParameter(f"--slo-p99-ms {slo_p99_ms!r}: {e}")
    jax_cache_dir = _apply_jax_cache(jax_cache_dir)

    precision = None
    if checkpoint:
        from .utils.checkpoint import read_checkpoint_meta
        precision = read_checkpoint_meta(checkpoint).get("precision")
    env, driver, agent = _build(agent_config, simulator_config, service,
                                scheduler, seed, max_nodes, max_edges,
                                resource_functions_path,
                                precision=precision)
    ddpg = DDPG(env, agent)
    topo, traffic = driver.episode(0, test_mode=True)
    env_state, obs0 = env.reset(jax.random.PRNGKey(seed), topo, traffic)

    # request pool: distinct real observations from rolling the env under
    # the uniform dummy schedule (works with or without a checkpoint) —
    # collected BEFORE serving starts so pool construction never pollutes
    # the latency measurement
    to_host = lambda tree: jax.tree_util.tree_map(_np.asarray, tree)
    uniform_action = jnp.asarray(_uniform_schedule_action(
        env.limits, _np.asarray(topo.node_mask)))
    pool = [to_host(obs0)]
    ob = obs0
    for _ in range(max(pool_steps, 0)):
        env_state, ob, _, _, _ = env.step(env_state, topo, traffic,
                                          uniform_action)
        pool.append(to_host(ob))

    rdir = setup_result_dir(result_dir, "serve")
    cache_dir = artifact_cache or os.path.join(result_dir, "serve_cache")
    tier = "learned" if checkpoint else "spr"
    obs_rec = None
    if obs_enabled:
        from .obs import RunObserver
        obs_rec = RunObserver(obs_dir or rdir, tags={"seed": seed},
                              perf=perf_enabled,
                              metrics_port=(metrics_port or None),
                              series_window=obs_series_window)
        obs_rec.start(meta={
            "mode": "serve", "tier": tier, "seed": seed,
            "requests": requests, "concurrency": concurrency,
            "buckets": list(bucket_sizes), "deadline_ms": deadline_ms,
            "batch_mode": "continuous" if continuous else "deadline",
            "workers": workers, "hot_swap_dir": hot_swap_dir,
            "fire_swaps": fire_swaps,
            "trace_sample": trace_sample, "slo_p99_ms": slo_p99_ms,
            "precision": agent.precision,
            "substep_impl": env.sim_cfg.substep_impl,
            "unroll": env.sim_cfg.scan_unroll,
            "jax_cache_dir": jax_cache_dir,
            "checkpoint": checkpoint, "result_dir": rdir})
    # the latency/queue series live in the hub, and the command's JSON
    # output is read off them — so --no-obs (no events.jsonl/metrics.json)
    # still gets a private, sink-less hub; otherwise p50/p99 would print
    # as a fake-perfect 0.0 instead of a measurement
    if obs_rec is not None:
        hub = obs_rec.hub
    else:
        from .obs import MetricsHub
        hub = MetricsHub(tags={"seed": seed})
    # request-path tracing + SLO engine ride the observer: flush spans
    # and decomposition always recorded under --obs, request spans
    # head-sampled by --trace-sample, slo.json written at close.  With
    # --no-obs the server runs the historic tracer-free path.  Fleet
    # workers each get their OWN tracer (a tracer binds one SLO engine);
    # they share the hub, so the histograms/events merge fleet-wide.
    slo_path = obs_rec.slo_path if obs_rec is not None else None

    def make_tracer():
        if obs_rec is None:
            return None
        from .obs import ServeTracer
        return ServeTracer(hub=hub, sample=trace_sample)

    mode = "continuous" if continuous else "deadline"
    common = dict(buckets=bucket_sizes, deadline_ms=deadline_ms, hub=hub,
                  stats_interval=stats_interval, mode=mode,
                  hot_swap_dir=hot_swap_dir, swap_poll_s=swap_poll_s,
                  slo=slo_objectives)
    try:
        spr_fallback = lambda: SPRFallbackPolicy(topo, env.limits, obs0)
        swap_payload = None   # what --fire-swaps publishes
        if checkpoint:
            from .utils.checkpoint import (checkpoint_fingerprint,
                                           load_full_or_partial)
            example = ddpg.init(jax.random.PRNGKey(0), obs0)
            example_buffer = ddpg.init_buffer(obs0)
            state = load_full_or_partial(
                checkpoint, example, example_buffer=example_buffer,
                example_extra={"episode": _np.asarray(0, _np.int32)}
            )[0]["state"]
            learned = dict(
                policy=GreedyServePolicy(ddpg, obs0),
                params=state.actor_params,
                cache=ArtifactCache(cache_dir),
                fingerprint=checkpoint_fingerprint(checkpoint),
                precision=agent.precision,
                substep_impl=env.sim_cfg.substep_impl,
                graph_mode=agent.graph_mode)
            swap_payload = jax.device_get(state.actor_params)
            if workers == 1:
                frontend = server = PolicyServer(
                    **common, **learned,
                    perf=(obs_rec.perf if obs_rec is not None else None),
                    tracer=make_tracer(), slo_path=slo_path)
            else:
                # the cost ledger rides worker 0 only: the per-bucket
                # compile capture is identical across workers, and the
                # serve_batch_ms histogram it merges at close is the
                # fleet aggregate already
                fleet = [PolicyServer(
                    **common, **learned, worker=f"w{i}",
                    perf=(obs_rec.perf if obs_rec is not None and i == 0
                          else None),
                    tracer=make_tracer()) for i in range(workers)]
                brownout = PolicyServer(
                    fallback=spr_fallback(), buckets=bucket_sizes,
                    deadline_ms=deadline_ms, hub=hub, worker="spr",
                    mode=mode, stats_interval=stats_interval,
                    tracer=make_tracer(), slo=slo_objectives)
                frontend = FleetDispatcher(
                    fleet, spr=brownout, hub=hub,
                    brownout_burn=(brownout_burn or None))
                server = fleet[0]
        else:
            if workers == 1:
                frontend = server = PolicyServer(
                    **common, fallback=spr_fallback(),
                    tracer=make_tracer(), slo_path=slo_path)
            else:
                # an SPR fleet IS the bottom tier — no brownout target
                # below it; overflow rejects like the single server would
                fleet = [PolicyServer(
                    **common, fallback=spr_fallback(), worker=f"w{i}",
                    tracer=make_tracer()) for i in range(workers)]
                frontend = FleetDispatcher(fleet, hub=hub,
                                           brownout_burn=None)
                server = fleet[0]
            if hot_swap_dir:
                # the SPR tier's "weights" are its precomputed schedule
                # action — what a fired swap republishes
                swap_payload = [_np.asarray(server.fallback.action)]
        frontend.start()

        # --fire-swaps: publish K versions of the CURRENT weights while
        # the load runs, spaced across the request count — the workers'
        # VersionWatchers must pick every one up under fire with zero
        # dropped requests (tools/fleet_smoke.py and serve_bench's
        # SERVE_r02 swap leg assert exactly that)
        fire_stop = threading.Event()
        fire_thread = None
        publisher = None
        if fire_swaps:
            from .serve.fleet import WeightPublisher
            publisher = WeightPublisher(hot_swap_dir, hub=hub)
            targets = [max(1, int(requests * (i + 1) / (fire_swaps + 1)))
                       for i in range(fire_swaps)]
            if workers > 1:
                adopted = lambda: min(w.policy_version for w in fleet)
            else:
                adopted = lambda: server.policy_version

            def _fire():
                # each publish waits for the PREVIOUS version to be
                # adopted by every worker: the watcher (correctly)
                # swaps straight to the newest version, so back-to-back
                # publishes within one poll interval would coalesce
                # into a single swap and undercount the exercised path
                fired = 0
                while fired < len(targets) and not fire_stop.is_set():
                    done = hub.get_counter("serve_requests_total")
                    if done >= targets[fired] \
                            and adopted() >= publisher.version:
                        publisher.publish(swap_payload,
                                          meta={"fired_at": int(done)})
                        fired += 1
                    else:
                        fire_stop.wait(0.003)

            fire_thread = threading.Thread(target=_fire, daemon=True,
                                           name="gsc-swap-firer")
            fire_thread.start()

        # closed-loop load: each client thread submits its share
        # sequentially, so at most --concurrency requests are in flight
        errors = []
        shares = [requests // concurrency + (1 if i < requests % concurrency
                                             else 0)
                  for i in range(concurrency)]

        def client(tid: int, n: int):
            for j in range(n):
                ob_h = pool[(tid + j * concurrency) % len(pool)]
                try:
                    frontend.submit(ob_h).result(request_timeout)
                except Exception as e:  # noqa: BLE001 - surfaced in JSON
                    errors.append(f"client{tid}/{j}: {e}")

        t0 = _time.perf_counter()
        threads = [threading.Thread(target=client, args=(i, n),
                                    name=f"gsc-serve-client-{i}",
                                    daemon=True)
                   for i, n in enumerate(shares) if n]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        wall = _time.perf_counter() - t0
        if fire_thread is not None:
            # let the firer finish its remaining publishes (adoption-
            # gated, so this is at most a few poll periods) before the
            # backstop stop
            fire_thread.join(timeout=10.0)
            fire_stop.set()
            fire_thread.join(timeout=5.0)
            # bounded wait for the watchers to adopt the last published
            # version, so the JSON's swap count is deterministic (the
            # load is done; this costs at most a few poll periods)
            swap_total = (frontend.swap_total if workers > 1
                          else lambda: server.swaps)
            want = publisher.version * (workers if workers > 1 else 1)
            deadline_wait = _time.perf_counter() + 5.0
            while swap_total() < want \
                    and _time.perf_counter() < deadline_wait:
                _time.sleep(swap_poll_s / 4)
        lat = server.latency_summary() or {}
        per_bucket = {}
        for b in bucket_sizes:
            s = server.latency_summary(b)
            if s and s.get("count"):
                per_bucket[str(b)] = {
                    "requests": int(s["count"]),
                    "p50_ms": round(s["p50"], 3),
                    "p99_ms": round(s["p99"], 3)}
        swaps = frontend.swap_total() if workers > 1 else server.swaps
        brownout_counts = None
        if workers > 1:
            brownout_counts = {
                reason: int(hub.get_counter("serve_brownout_total",
                                            reason=reason))
                for reason in ("slo_burn", "overflow")}
        frontend.close()
        # AFTER close: the tracer's final synchronous drain runs inside
        # close(), so the engine has seen every flush — reading earlier
        # under-reports fast runs (the drainer thread ticks at 50 ms)
        slo_block = (frontend.slo_summary() if workers > 1
                     else server.slo_summary())
        if workers > 1 and slo_path is not None \
                and frontend.merged_slo() is not None:
            # the fleet's slo.json: merged engine snapshots + fleet-wide
            # latency percentiles (same schema bench_diff's slo rows
            # ingest; per-worker numbers ride under per_worker)
            from .obs.slo import SLO_SCHEMA_VERSION, write_slo_json
            merged = frontend.merged_slo()
            write_slo_json(slo_path, {
                "schema_version": SLO_SCHEMA_VERSION,
                "ts": round(_time.time(), 3),
                "run": hub.base_tags.get("run"),
                "tier": server.tier,
                "buckets": list(bucket_sizes),
                "requests_completed": frontend.completed,
                "p50_latency_ms": round(lat.get("p50", 0.0), 4),
                "p99_latency_ms": round(lat.get("p99", 0.0), 4),
                **merged})
    except BaseException:
        if obs_rec is not None:
            try:
                obs_rec.close(status="error")
            except Exception:
                pass
        raise
    if obs_rec is not None:
        obs_rec.close(status="ok")
    click.echo(json.dumps({
        "tier": server.tier, "requests": requests,
        "workers": workers, "mode": mode,
        "errors": len(errors), "error_detail": errors[:5],
        "wall_s": round(wall, 3),
        "rps": round(requests / wall, 3) if wall > 0 else 0.0,
        "p50_ms": round(lat.get("p50", 0.0), 3),
        "p99_ms": round(lat.get("p99", 0.0), 3),
        "buckets": per_bucket,
        "slo": slo_block,
        "swaps": swaps,
        "published_versions": (publisher.version if publisher else 0),
        "policy_version": server.policy_version,
        "brownout": brownout_counts,
        "startup": server.startup,
        "artifact_cache": cache_dir if checkpoint else None,
        "jax_cache_dir": jax_cache_dir,
        "result_dir": rdir}))


@cli.command()
@click.option("--duration", "-d", default=1000.0, show_default=True,
              help="simulated ms")
@click.option("--network", "-n", required=True)
@click.option("--service", "-sf", required=True)
@click.option("--config", "-c", required=True)
@click.option("--seed", default=0, show_default=True)
@click.option("--max-nodes", default=24, show_default=True)
@click.option("--max-edges", default=37, show_default=True)
@click.option("--resource-functions-path", default=None,
              help="dir (or .py file) of user resource-function plugins")
@click.option("--per-flow-algo", type=click.Choice(["local", "spr"]),
              default="local", show_default=True,
              help="per-flow decision algorithm when the simulator config "
              "sets controller: per_flow — 'local' processes every flow at "
              "its current node (jitted policy); 'spr' runs the "
              "shortest-path heuristic through the host-side "
              "PerFlowController (the reference's FlowController loop)")
def simulate(duration, network, service, config, seed, max_nodes, max_edges,
             resource_functions_path, per_flow_algo):
    """Standalone simulator run with a uniform schedule over all nodes and
    every SF placed everywhere — the smoke-run mode of coordsim/main.py:19-89
    (which uses hard-coded dummy placement/schedule tables)."""
    import jax.numpy as jnp

    from .config.loader import load_service, load_sim
    from .config.schema import DROP_REASONS, EnvLimits
    from .sim.engine import SimEngine
    from .sim.traffic import generate_traffic
    from .topology.compiler import check_dt_quantization, load_topology

    svc = load_service(service,
                       resource_functions_path=resource_functions_path)
    sim_cfg = load_sim(config)
    if per_flow_algo != "local" and sim_cfg.controller != "per_flow":
        # fail BEFORE the expensive setup (GraphML load, traffic
        # generation, engine init) — the mismatch is knowable right here
        raise click.BadParameter(
            f"--per-flow-algo {per_flow_algo} requires 'controller: "
            "per_flow' in the simulator config (this config runs the "
            "duration controller, which would silently ignore the "
            "algorithm)")
    limits = EnvLimits.for_service(svc, max_nodes=max_nodes,
                                   max_edges=max_edges)
    topo = load_topology(network, max_nodes=max_nodes, max_edges=max_edges,
                         force_link_cap=sim_cfg.force_link_cap,
                         force_node_cap=sim_cfg.force_node_cap, seed=seed)
    check_dt_quantization(topo, sim_cfg.dt, name=network)
    steps = int(np.ceil(duration / sim_cfg.run_duration))
    if steps < 1:
        raise click.BadParameter("duration must cover at least one "
                                 f"run_duration ({sim_cfg.run_duration} ms)")
    traffic = generate_traffic(sim_cfg, svc, topo, steps, seed)
    engine = SimEngine(svc, sim_cfg, limits)

    nm = np.asarray(topo.node_mask)
    state = engine.init(jax.random.PRNGKey(seed), topo)
    if sim_cfg.controller == "per_flow":
        # FlowController granularity (flow_controller.py:21-92): each
        # deciding flow gets an individual destination every substep.
        if per_flow_algo == "spr":
            # host-side external algorithm through PerFlowController —
            # the loop a reference user writes against
            # FlowController.get_init_state/get_next_state
            from .sim.perflow import PerFlowController
            from .sim.spr import run_spr_episode

            ctrl = PerFlowController(engine, topo, traffic)
            state = run_spr_episode(ctrl, state, steps * engine.substeps)
            metrics = state.metrics
        else:
            # jitted local policy: process at the flow's node
            # (place-on-decision installs the SF; idle instances are
            # GC'd after vnf_timeout)
            from .sim.state import PH_DECIDE

            def decide_local(st):
                deciding = st.flows.phase == PH_DECIDE
                return jnp.where(deciding, st.flows.node, -1)

            for _ in range(steps):
                state, metrics = engine.apply_per_flow(state, topo, traffic,
                                                       decide_local)
    else:
        sched = _uniform_schedule_action(limits, nm).reshape(
            limits.scheduling_shape)
        placement = jnp.asarray(np.broadcast_to(nm[:, None],
                                                (max_nodes, limits.sf_pool)))
        for _ in range(steps):
            state, metrics = engine.apply(state, topo, traffic,
                                          jnp.asarray(sched), placement)
    m = metrics
    click.echo(json.dumps({
        "total_flows": int(m.generated), "successful_flows": int(m.processed),
        "dropped_flows": int(m.dropped),
        "drop_reasons": {k: int(v) for k, v in
                         zip(DROP_REASONS, np.asarray(m.drop_reasons))},
        "avg_end2end_delay": float(m.avg_e2e()),
    }))


if __name__ == "__main__":
    cli()
