"""Mesh-shape + partition-rulebook grammar — deliberately jax-free.

The ``"DPxMP"`` mesh grammar and the named-rulebook vocabulary are
spoken by surfaces on BOTH sides of the jax boundary: the CLI and
``parallel/partition.py`` import jax anyway, but ``bench.py``'s
orchestrator must stay jax-free (a parent process that imports jax
claims the TPU alongside its measurement workers).  PR 8 left the regex
copied into bench.py twice for exactly that reason; this module is the
one shared definition both sides import — ``import gsc_tpu.meshspec``
executes only the package docstring, never a jax import.

Canonical spellings, enforced here so cross-artifact grouping never
splits one value into two strings:

- mesh shapes are lowercase ``"dpxmp"`` with a bare ``"N"`` meaning
  ``"Nx1"`` (``canonical_mesh``);
- rulebook names are exactly the :data:`PARTITION_RULEBOOKS` tuple —
  ``replicated`` (bit-identical no-op fallback), ``sharded``
  (output-feature residency sharding, bit-exact by construction), and
  ``tp`` (true tensor-parallel compute, accepted under tolerance bands
  — see ``parallel/partition.py``).
"""
from __future__ import annotations

import re
from typing import Tuple

#: named partition rulebooks every surface (cli/bench/dryrun/partition)
#: accepts, in increasing order of precision-contract spend:
#: replicated == bit-identical fallback, sharded == bit-exact residency
#: sharding, tp == psum-accumulated tensor-parallel compute gated by
#: tolerance bands instead of bit-equality.
PARTITION_RULEBOOKS: Tuple[str, ...] = ("replicated", "sharded", "tp")

_MESH_RE = re.compile(r"(\d+)(?:x(\d+))?")


def parse_mesh_shape(spec) -> Tuple[int, int]:
    """``"DPxMP"`` -> ``(dp, mp)``; a bare ``"N"`` means ``Nx1``.

    Raises ``ValueError`` with the offending text for anything else —
    callers (cli/bench) surface it as a flag error, never a traceback
    from deep inside mesh construction."""
    text = str(spec).strip().lower()
    m = _MESH_RE.fullmatch(text)
    if not m:
        raise ValueError(
            f"mesh shape {spec!r} is not 'DPxMP' (e.g. 8x1, 4x2) or 'N'")
    dp, mp = int(m.group(1)), int(m.group(2) or 1)
    if dp < 1 or mp < 1:
        raise ValueError(f"mesh shape {spec!r} axes must be positive")
    return dp, mp


def canonical_mesh(spec) -> str:
    """The one spelling of a mesh shape every artifact records:
    lowercase ``"dpxmp"``, a bare ``"N"`` canonicalized to ``"Nx1"``.
    Validates via :func:`parse_mesh_shape` (same ``ValueError``
    contract)."""
    dp, mp = parse_mesh_shape(spec)
    return f"{dp}x{mp}"


def validate_partition_rules(name: str) -> str:
    """The canonical rulebook name, or ``ValueError`` naming the
    vocabulary — one message for every surface."""
    text = str(name).strip()
    if text not in PARTITION_RULEBOOKS:
        raise ValueError(
            f"unknown rulebook {text!r} "
            f"({'|'.join(PARTITION_RULEBOOKS)})")
    return text
