"""Shared utilities: checkpointing, experiment bookkeeping, telemetry."""
from .checkpoint import load_checkpoint, save_checkpoint
from .experiment import ExperimentResult, copy_inputs, setup_result_dir
from .telemetry import TestModeWriter

__all__ = ["load_checkpoint", "save_checkpoint", "ExperimentResult",
           "copy_inputs", "setup_result_dir", "TestModeWriter"]
