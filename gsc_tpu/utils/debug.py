"""Debug utilities: simulator-state invariant checks + profiling hooks.

The reference's only runtime safety net is defensive asserts sprinkled
through the simulator (metrics.py:119-158, default_forwarder.py:51,125,
base_processor.py:60,135 — SURVEY.md §4) and SimPy's single-threaded
scheduling in place of race detection (SURVEY.md §5).  The batched-engine
analogue is a host-side invariant checker over the ``SimState`` pytree —
run it between intervals in debug runs or property tests — plus
``jax_debug_nans`` / profiler toggles for the train driver.
"""
from __future__ import annotations

from typing import List

import numpy as np

from ..sim.state import PH_DECIDE, PH_FREE, PH_HOP, PH_PROC, SimState
from ..topology.compiler import Topology


def check_invariants(state: SimState, topo: Topology,
                     chain_len: np.ndarray, tol: float = 1e-3) -> List[str]:
    """Return a list of violated invariants (empty = healthy).

    Checks the conservation laws the reference asserts piecemeal:
    non-negative loads, link usage within capacity
    (default_forwarder.py:95-111), flow phases/positions in range, and
    metrics bookkeeping consistency (generated = processed + dropped +
    active, metrics.py:119-127).
    """
    errs = []
    f = state.flows
    phase = np.asarray(f.phase)
    m = state.metrics

    if (np.asarray(state.node_load) < -tol).any():
        errs.append("negative node_load")
    if (np.asarray(state.edge_used) < -tol).any():
        errs.append("negative edge_used")
    over = np.asarray(state.edge_used) > np.asarray(topo.edge_cap) + tol
    if (over & np.asarray(topo.edge_mask)).any():
        errs.append("edge_used exceeds edge capacity")

    if not np.isin(phase, [PH_FREE, PH_DECIDE, PH_HOP, PH_PROC]).all():
        errs.append("invalid flow phase")
    active = phase != PH_FREE
    pos = np.asarray(f.position)[active]
    cl = chain_len[np.asarray(f.sfc)[active]]
    if (pos < 0).any() or (pos > cl).any():
        errs.append("flow position outside chain")
    nodes = np.asarray(f.node)[active]
    if len(nodes) and (nodes >= topo.max_nodes).any():
        errs.append("flow at out-of-range node")
    if (np.asarray(f.ttl)[active] < -tol).any():
        errs.append("active flow with negative TTL")

    booked = int(m.processed) + int(m.dropped) + int(m.active)
    if int(m.generated) != booked:
        errs.append(
            f"metrics mismatch: generated={int(m.generated)} != "
            f"processed+dropped+active={booked}")
    if int(m.active) != int(active.sum()):
        errs.append(
            f"active count mismatch: metrics={int(m.active)} "
            f"table={int(active.sum())}")
    if int(m.dropped) != int(np.asarray(m.drop_reasons).sum()):
        errs.append("drop_reasons do not sum to dropped")
    # WRR realized-ratio counters round-trip through f32 one-hot dots every
    # decision round (engine._take); exactness requires every count to stay
    # below 2^24 (f32 integer-exact range).  run_flow_counts is the only
    # unbounded integer routed through them — per-run resets keep it tiny
    # today, but a cadence change would corrupt silently without this.
    if int(np.asarray(m.run_flow_counts).max()) >= 2 ** 24:
        errs.append("run_flow_counts >= 2^24 (f32 one-hot dots lose "
                    "integer exactness)")
    trunc = int(np.asarray(state.truncated_arrivals))
    if trunc > 0:
        # not state corruption, but a visible divergence from the
        # reference's unbounded concurrent-flow model: raise max_flows (or
        # _ARRIVALS_PER_SUBSTEP) to restore exact arrival timing
        errs.append(
            f"{trunc} arrivals admitted late (flow-table slot exhaustion)")
    return errs


def assert_invariants(state: SimState, topo: Topology,
                      chain_len: np.ndarray) -> None:
    errs = check_invariants(state, topo, chain_len)
    if errs:
        raise AssertionError("simulator invariants violated: " + "; ".join(errs))


class Profiler:
    """jax.profiler trace wrapper for the train driver (the rebuild's
    answer to the reference's wall/process timers, SURVEY.md §5 tracing)."""

    def __init__(self, log_dir: str):
        self.log_dir = log_dir
        self._active = False

    def __enter__(self):
        import jax

        jax.profiler.start_trace(self.log_dir)
        self._active = True
        return self

    def __exit__(self, *exc):
        import jax

        if self._active:
            jax.profiler.stop_trace()
            self._active = False
        return False
