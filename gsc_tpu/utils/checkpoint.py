"""Checkpoint / resume — orbax-backed, exact-resume semantics.

The reference's persistence is ad hoc: ``th.save(actor)`` + a pickled
AgentHelper after training (main.py:46-50), reloaded by inference.py:19-23;
optimizer and replay state are never saved, so continue-training is broken
(SURVEY.md §5).  Here the *entire* learner state (actor/critic params,
targets, both optimizer states, PRNG key) and optionally the replay buffer
are one orbax checkpoint, so training resumes bit-exactly.
"""
from __future__ import annotations

import inspect
import json
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from ..agents.buffer import ReplayBuffer
from ..agents.ddpg import DDPGState

# ``partial_restore=`` landed in orbax well after the version this image
# bakes in (0.7.0 rejects it with a TypeError) — gate on the actual
# signature rather than a version string so forward/backward installs both
# work.  Older orbax spells the same semantics through the transformations
# API: ``transforms={}`` + ``transforms_default_to_original`` restores
# exactly the keys present in ``item`` and drops extra on-disk entries.
_PARTIAL_RESTORE_KWARG = "partial_restore" in inspect.signature(
    ocp.args.PyTreeRestore.__init__).parameters


def _meta_path(path: str) -> str:
    # SIBLING of the orbax dir, not inside it: orbax owns (and rewrites)
    # the checkpoint directory's contents on every force-save
    return os.path.abspath(path).rstrip(os.sep) + ".meta.json"


def save_checkpoint(path: str, state: DDPGState,
                    buffer: Optional[ReplayBuffer] = None,
                    extra: Optional[dict] = None,
                    meta: Optional[dict] = None) -> str:
    """Write learner state (+ optional replay buffer + metadata).

    ``meta`` is plain-JSON run metadata (e.g. the precision policy name)
    written to a ``<path>.meta.json`` sidecar — config-level facts a
    resume/infer must know BEFORE it can build the restore templates, so
    they cannot live inside the orbax pytree (whose restore already needs
    correctly-dtyped examples)."""
    path = os.path.abspath(path)
    payload = {"state": state}
    if buffer is not None:
        payload["buffer"] = buffer
    if extra is not None:
        payload["extra"] = extra
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    if meta is not None:
        # atomic (temp + rename): a crash mid-write must never leave a
        # truncated sidecar that reads back as "pre-meta f32" against a
        # bf16 checkpoint
        from ..obs.sinks import write_atomic_json
        write_atomic_json(_meta_path(path), meta)
    else:
        # a meta-less re-save to the same path must not leave the PREVIOUS
        # save's sidecar describing the new checkpoint
        try:
            os.unlink(_meta_path(path))
        except OSError:
            pass
    return path


def read_checkpoint_meta(path: str) -> dict:
    """The ``save_checkpoint(meta=...)`` sidecar; {} for checkpoints
    written before the sidecar existed (implicitly f32, full-f32 replay)."""
    try:
        with open(_meta_path(path)) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return {}


def load_checkpoint(path: str, example_state: DDPGState,
                    example_buffer: Optional[ReplayBuffer] = None,
                    example_extra: Optional[dict] = None,
                    partial: bool = False) -> dict:
    """Restore a checkpoint into the shapes/dtypes of the given examples.

    ``partial=True`` restores only the keys present in the target and
    ignores extra on-disk entries — e.g. pulling just the learner state
    out of a full train checkpoint whose replay-buffer storage format
    differs from the current code's."""
    path = os.path.abspath(path)
    target = {"state": example_state}
    if example_buffer is not None:
        target["buffer"] = example_buffer
    if example_extra is not None:
        target["extra"] = example_extra
    if partial:
        ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        kwargs = dict(
            item=target,
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))
        if _PARTIAL_RESTORE_KWARG:
            args = ocp.args.PyTreeRestore(partial_restore=True, **kwargs)
        else:
            args = ocp.args.PyTreeRestore(transforms={}, **kwargs)
        return ckptr.restore(path, args=args)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target)


def load_full_or_partial(path: str, example_state: DDPGState,
                         example_buffer: Optional[ReplayBuffer] = None,
                         example_extra: Optional[dict] = None
                         ) -> tuple[dict, bool]:
    """Full restore, falling back to a buffer-less partial restore when the
    on-disk replay doesn't match ``example_buffer`` (legacy storage format,
    or replay config such as mem_limit changed since the checkpoint).

    Returns ``(restored, buffer_restored)``.  Only the restore itself is
    guarded — build the examples BEFORE calling so unrelated construction
    errors surface instead of being misread as a format mismatch."""
    try:
        return load_checkpoint(path, example_state,
                               example_buffer=example_buffer,
                               example_extra=example_extra), True
    except (ValueError, KeyError):
        pass
    try:
        return load_checkpoint(path, example_state,
                               example_extra=example_extra,
                               partial=True), False
    except (ValueError, KeyError):
        if example_extra is None:
            raise
        # state-only checkpoint without metadata (e.g. a bare actor
        # export): the caller gets no "extra" key and must default
        return load_checkpoint(path, example_state, partial=True), False
