"""Checkpoint / resume — orbax-backed, exact-resume semantics.

The reference's persistence is ad hoc: ``th.save(actor)`` + a pickled
AgentHelper after training (main.py:46-50), reloaded by inference.py:19-23;
optimizer and replay state are never saved, so continue-training is broken
(SURVEY.md §5).  Here the *entire* learner state (actor/critic params,
targets, both optimizer states, PRNG key) and optionally the replay buffer
are one orbax checkpoint, so training resumes bit-exactly.
"""
from __future__ import annotations

import hashlib
import inspect
import json
import logging
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from ..agents.buffer import ReplayBuffer
from ..agents.ddpg import DDPGState

log = logging.getLogger("gsc_tpu.utils.checkpoint")

# ``partial_restore=`` landed in orbax well after the version this image
# bakes in (0.7.0 rejects it with a TypeError) — gate on the actual
# signature rather than a version string so forward/backward installs both
# work.  Older orbax spells the same semantics through the transformations
# API: ``transforms={}`` + ``transforms_default_to_original`` restores
# exactly the keys present in ``item`` and drops extra on-disk entries.
_PARTIAL_RESTORE_KWARG = "partial_restore" in inspect.signature(
    ocp.args.PyTreeRestore.__init__).parameters


def _meta_path(path: str) -> str:
    # SIBLING of the orbax dir, not inside it: orbax owns (and rewrites)
    # the checkpoint directory's contents on every force-save
    return os.path.abspath(path).rstrip(os.sep) + ".meta.json"


def checkpoint_checksum(path: str) -> str:
    """Content checksum of an on-disk checkpoint: sha256 over every file
    under the orbax directory (sorted relative paths + bytes), so a
    truncated array file, a lost rename, or bit rot all change the digest.
    Stored in the ``.meta.json`` sidecar by ``save_checkpoint(...,
    checksum=True)`` and re-derived by :func:`verify_checkpoint`."""
    path = os.path.abspath(path)
    h = hashlib.sha256()
    for root, dirs, files in sorted(os.walk(path)):
        dirs.sort()
        for name in sorted(files):
            fp = os.path.join(root, name)
            h.update(os.path.relpath(fp, path).encode())
            h.update(b"\0")
            with open(fp, "rb") as f:
                for chunk in iter(lambda: f.read(1 << 20), b""):
                    h.update(chunk)
            h.update(b"\0")
    return h.hexdigest()


def save_checkpoint(path: str, state: DDPGState,
                    buffer: Optional[ReplayBuffer] = None,
                    extra: Optional[dict] = None,
                    meta: Optional[dict] = None,
                    checksum: bool = False) -> str:
    """Write learner state (+ optional replay buffer + metadata).

    ``meta`` is plain-JSON run metadata (e.g. the precision policy name)
    written to a ``<path>.meta.json`` sidecar — config-level facts a
    resume/infer must know BEFORE it can build the restore templates, so
    they cannot live inside the orbax pytree (whose restore already needs
    correctly-dtyped examples).

    ``checksum=True`` adds a content checksum of the written checkpoint to
    the sidecar (creating one even for ``meta=None``) so ``--resume auto``
    can prove the checkpoint intact before trusting it — the
    preemption-safe periodic saves always pass it."""
    path = os.path.abspath(path)
    payload = {"state": state}
    if buffer is not None:
        payload["buffer"] = buffer
    if extra is not None:
        payload["extra"] = extra
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    if checksum:
        meta = dict(meta or {})
        meta["checksum"] = checkpoint_checksum(path)
        meta["checksum_algo"] = "sha256-tree"
    if meta is not None:
        # atomic (temp + rename): a crash mid-write must never leave a
        # truncated sidecar that reads back as "pre-meta f32" against a
        # bf16 checkpoint
        from ..obs.sinks import write_atomic_json
        write_atomic_json(_meta_path(path), meta)
    else:
        # a meta-less re-save to the same path must not leave the PREVIOUS
        # save's sidecar describing the new checkpoint
        try:
            os.unlink(_meta_path(path))
        except OSError:
            pass
    return path


def read_checkpoint_meta(path: str) -> dict:
    """The ``save_checkpoint(meta=...)`` sidecar; {} for checkpoints
    written before the sidecar existed (implicitly f32, full-f32 replay).

    A truncated/corrupt sidecar (crash mid-write on a pre-atomic-writer
    install, disk damage, stray edit) degrades to the same {}: resume must
    never be bricked by a half-written METADATA file when the checkpoint
    itself is fine — the caller falls back to the implicit-f32 path and a
    structured warning says why."""
    meta_path = _meta_path(path)
    try:
        with open(meta_path) as f:
            meta = json.load(f)
    except FileNotFoundError:
        return {}
    except (OSError, ValueError, UnicodeDecodeError) as e:
        # ValueError covers json.JSONDecodeError (truncated/garbled JSON)
        log.warning(
            "checkpoint sidecar unreadable — treating as pre-meta "
            "(implicit f32, no checksum): path=%s error=%s:%s",
            meta_path, type(e).__name__, e)
        return {}
    if not isinstance(meta, dict):
        log.warning(
            "checkpoint sidecar is not a JSON object — treating as "
            "pre-meta: path=%s got=%s", meta_path, type(meta).__name__)
        return {}
    return meta


def checkpoint_fingerprint(path: str) -> str:
    """Stable content identity of a checkpoint, for keying derived
    artifacts (the serving stack's compiled-policy cache).  Prefers the
    sidecar's recorded content checksum (free to read; present on every
    ``checksum=True`` save) and falls back to recomputing the sha256 tree
    digest for checkpoints saved without one — either way, retraining or
    touching any array file changes the fingerprint, so a stale compiled
    policy can never be served against new weights."""
    recorded = read_checkpoint_meta(path).get("checksum")
    if recorded:
        return recorded
    return checkpoint_checksum(path)


def verify_checkpoint(path: str) -> bool:
    """True iff ``path`` exists and its recomputed content checksum equals
    the sidecar's recorded one.  False for checkpoints saved without
    ``checksum=True`` — a checkpoint that cannot prove integrity is not a
    valid ``--resume auto`` candidate (explicit ``--resume <path>`` still
    restores it)."""
    if not os.path.isdir(path):
        return False
    recorded = read_checkpoint_meta(path).get("checksum")
    if not recorded:
        return False
    return checkpoint_checksum(path) == recorded


def load_checkpoint(path: str, example_state: DDPGState,
                    example_buffer: Optional[ReplayBuffer] = None,
                    example_extra: Optional[dict] = None,
                    partial: bool = False) -> dict:
    """Restore a checkpoint into the shapes/dtypes of the given examples.

    ``partial=True`` restores only the keys present in the target and
    ignores extra on-disk entries — e.g. pulling just the learner state
    out of a full train checkpoint whose replay-buffer storage format
    differs from the current code's."""
    path = os.path.abspath(path)
    target = {"state": example_state}
    if example_buffer is not None:
        target["buffer"] = example_buffer
    if example_extra is not None:
        target["extra"] = example_extra
    if partial:
        ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        kwargs = dict(
            item=target,
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))
        if _PARTIAL_RESTORE_KWARG:
            args = ocp.args.PyTreeRestore(partial_restore=True, **kwargs)
        else:
            args = ocp.args.PyTreeRestore(transforms={}, **kwargs)
        return ckptr.restore(path, args=args)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target)


def load_full_or_partial(path: str, example_state: DDPGState,
                         example_buffer: Optional[ReplayBuffer] = None,
                         example_extra: Optional[dict] = None
                         ) -> tuple[dict, bool]:
    """Full restore, falling back to a buffer-less partial restore when the
    on-disk replay doesn't match ``example_buffer`` (legacy storage format,
    or replay config such as mem_limit changed since the checkpoint).

    Returns ``(restored, buffer_restored)``.  Only the restore itself is
    guarded — build the examples BEFORE calling so unrelated construction
    errors surface instead of being misread as a format mismatch."""
    try:
        return load_checkpoint(path, example_state,
                               example_buffer=example_buffer,
                               example_extra=example_extra), True
    except (ValueError, KeyError):
        pass
    try:
        return load_checkpoint(path, example_state,
                               example_extra=example_extra,
                               partial=True), False
    except (ValueError, KeyError):
        if example_extra is None:
            raise
        # state-only checkpoint without metadata (e.g. a bare actor
        # export): the caller gets no "extra" key and must default
        return load_checkpoint(path, example_state, partial=True), False
