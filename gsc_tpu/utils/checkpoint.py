"""Checkpoint / resume — orbax-backed, exact-resume semantics.

The reference's persistence is ad hoc: ``th.save(actor)`` + a pickled
AgentHelper after training (main.py:46-50), reloaded by inference.py:19-23;
optimizer and replay state are never saved, so continue-training is broken
(SURVEY.md §5).  Here the *entire* learner state (actor/critic params,
targets, both optimizer states, PRNG key) and optionally the replay buffer
are one orbax checkpoint, so training resumes bit-exactly.
"""
from __future__ import annotations

import inspect
import os
from typing import Any, Optional

import jax
import orbax.checkpoint as ocp

from ..agents.buffer import ReplayBuffer
from ..agents.ddpg import DDPGState

# ``partial_restore=`` landed in orbax well after the version this image
# bakes in (0.7.0 rejects it with a TypeError) — gate on the actual
# signature rather than a version string so forward/backward installs both
# work.  Older orbax spells the same semantics through the transformations
# API: ``transforms={}`` + ``transforms_default_to_original`` restores
# exactly the keys present in ``item`` and drops extra on-disk entries.
_PARTIAL_RESTORE_KWARG = "partial_restore" in inspect.signature(
    ocp.args.PyTreeRestore.__init__).parameters


def save_checkpoint(path: str, state: DDPGState,
                    buffer: Optional[ReplayBuffer] = None,
                    extra: Optional[dict] = None) -> str:
    """Write learner state (+ optional replay buffer + metadata)."""
    path = os.path.abspath(path)
    payload = {"state": state}
    if buffer is not None:
        payload["buffer"] = buffer
    if extra is not None:
        payload["extra"] = extra
    ckptr = ocp.StandardCheckpointer()
    ckptr.save(path, payload, force=True)
    ckptr.wait_until_finished()
    return path


def load_checkpoint(path: str, example_state: DDPGState,
                    example_buffer: Optional[ReplayBuffer] = None,
                    example_extra: Optional[dict] = None,
                    partial: bool = False) -> dict:
    """Restore a checkpoint into the shapes/dtypes of the given examples.

    ``partial=True`` restores only the keys present in the target and
    ignores extra on-disk entries — e.g. pulling just the learner state
    out of a full train checkpoint whose replay-buffer storage format
    differs from the current code's."""
    path = os.path.abspath(path)
    target = {"state": example_state}
    if example_buffer is not None:
        target["buffer"] = example_buffer
    if example_extra is not None:
        target["extra"] = example_extra
    if partial:
        ckptr = ocp.Checkpointer(ocp.PyTreeCheckpointHandler())
        kwargs = dict(
            item=target,
            restore_args=ocp.checkpoint_utils.construct_restore_args(target))
        if _PARTIAL_RESTORE_KWARG:
            args = ocp.args.PyTreeRestore(partial_restore=True, **kwargs)
        else:
            args = ocp.args.PyTreeRestore(transforms={}, **kwargs)
        return ckptr.restore(path, args=args)
    ckptr = ocp.StandardCheckpointer()
    return ckptr.restore(path, target)


def load_full_or_partial(path: str, example_state: DDPGState,
                         example_buffer: Optional[ReplayBuffer] = None,
                         example_extra: Optional[dict] = None
                         ) -> tuple[dict, bool]:
    """Full restore, falling back to a buffer-less partial restore when the
    on-disk replay doesn't match ``example_buffer`` (legacy storage format,
    or replay config such as mem_limit changed since the checkpoint).

    Returns ``(restored, buffer_restored)``.  Only the restore itself is
    guarded — build the examples BEFORE calling so unrelated construction
    errors surface instead of being misread as a format mismatch."""
    try:
        return load_checkpoint(path, example_state,
                               example_buffer=example_buffer,
                               example_extra=example_extra), True
    except (ValueError, KeyError):
        pass
    try:
        return load_checkpoint(path, example_state,
                               example_extra=example_extra,
                               partial=True), False
    except (ValueError, KeyError):
        if example_extra is None:
            raise
        # state-only checkpoint without metadata (e.g. a bare actor
        # export): the caller gets no "extra" key and must default
        return load_checkpoint(path, example_state, partial=True), False
