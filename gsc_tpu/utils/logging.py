"""Logging setup — console + per-run file handler.

The reference configures logging from ``logging.conf`` (console handler,
per-module levels) and ``setup_logging`` attaches a per-run file handler
under the result directory (src/rlsp/agents/main.py:307-329,
logging.conf:1-34).  Here the same policy is code, not an INI file: one
console handler on the root ``gsc_tpu`` logger (INFO, DEBUG with
``verbose``), quieter defaults for the chatty simulator modules, and an
optional per-run ``run.log`` file handler in the experiment's result dir.
"""
from __future__ import annotations

import logging
import os
from typing import Optional

_FORMAT = "%(asctime)s - %(name)s - %(levelname)s - %(message)s"
# per-module default levels (logging.conf's flowsimulator/oldsimulator
# sections keep the simulator quiet unless asked)
_MODULE_LEVELS = {
    "gsc_tpu.sim": logging.WARNING,
    "gsc_tpu.env": logging.WARNING,
}


def setup_logging(verbose: bool = False,
                  logfile: Optional[str] = None) -> logging.Logger:
    """Configure the ``gsc_tpu`` logger tree; returns the root package
    logger.  Idempotent: repeated calls reconfigure rather than stack
    handlers."""
    logger = logging.getLogger("gsc_tpu")
    logger.setLevel(logging.DEBUG)
    logger.propagate = False
    for h in list(logger.handlers):
        logger.removeHandler(h)

    console = logging.StreamHandler()
    console.setLevel(logging.DEBUG if verbose else logging.INFO)
    console.setFormatter(logging.Formatter(_FORMAT))
    logger.addHandler(console)

    for name, level in _MODULE_LEVELS.items():
        logging.getLogger(name).setLevel(
            logging.DEBUG if verbose else level)

    if logfile:
        os.makedirs(os.path.dirname(os.path.realpath(logfile)), exist_ok=True)
        fh = logging.FileHandler(logfile, mode="a")
        fh.setFormatter(logging.Formatter(_FORMAT))
        fh.setLevel(logging.DEBUG if verbose else logging.INFO)
        logger.addHandler(fh)
    return logger
