"""Experiment bookkeeping: result dirs, config copies, result.yaml.

Mirrors the reference's experiment plumbing — ``setup_files`` copies every
input config into the run's result directory for reproducibility
(src/rlsp/agents/main.py:279-306), ``ExperimentResult`` records wall/process
time per phase into result.yaml (src/rlsp/utils/experiment_result.py:29-54).
"""
from __future__ import annotations

import os
import shutil
import time
from datetime import datetime
from typing import Dict, List, Optional

import yaml


class ExperimentResult:
    """Phase-timed experiment record (experiment_result.py semantics)."""

    def __init__(self, result_dir: str):
        self.result_dir = result_dir
        self.env_config: Dict[str, str] = {}
        self.agent_config: Dict[str, object] = {}
        self._timers: Dict[str, Dict[str, float]] = {}
        self.metrics: Dict[str, float] = {}

    def runtime_start(self, phase: str):
        self._timers[phase] = {"wall_start": time.time(),
                               "process_start": time.process_time()}

    def runtime_stop(self, phase: str):
        t = self._timers[phase]
        t["wall_time"] = time.time() - t.pop("wall_start")
        t["process_time"] = time.process_time() - t.pop("process_start")

    def write(self):
        os.makedirs(self.result_dir, exist_ok=True)
        record = {
            "env_config": self.env_config,
            "agent_config": self.agent_config,
            "runtimes": self._timers,
            "metrics": self.metrics,
        }
        with open(os.path.join(self.result_dir, "result.yaml"), "w") as f:
            yaml.safe_dump(record, f, default_flow_style=False)


def setup_result_dir(base: str, experiment_id: Optional[str] = None) -> str:
    """results/<id>/<timestamp>/ (main.py:175-235 layout).  Uniquified with
    a numeric suffix when the second-granularity timestamp collides (e.g.
    multi-run sweeps starting within one second)."""
    ts = datetime.now().strftime("%Y-%m-%d_%H-%M-%S")
    root = os.path.join(base, experiment_id or "default")
    d = os.path.join(root, ts)
    i = 1
    while True:
        try:
            os.makedirs(d)
            return d
        except FileExistsError:
            d = os.path.join(root, f"{ts}_{i}")
            i += 1


def copy_inputs(result_dir: str, paths: List[Optional[str]]):
    """Copy all input config files into the result dir
    (src/rlsp/agents/main.py:279-306)."""
    dst = os.path.join(result_dir, "inputs")
    os.makedirs(dst, exist_ok=True)
    for p in paths:
        if p and os.path.isfile(p):
            shutil.copy(p, dst)


def select_best_agent(result_dirs: List[str], last_k: int = 10) -> str:
    """Pick the run with the best mean reward over its last ``last_k``
    episodes (src/rlsp/agents/main.py:89-113 — which reads a stale
    'episode_reward.csv'/'reward' schema; this reads the live writer's
    rewards.csv with field 'r', simple_ddpg.py:167)."""
    import csv

    best_dir, best = None, -float("inf")
    for d in result_dirs:
        path = os.path.join(d, "rewards.csv")
        if not os.path.isfile(path):
            continue
        with open(path) as f:
            rewards = [float(row["r"]) for row in csv.DictReader(f)]
        if not rewards:
            continue
        mean = sum(rewards[-last_k:]) / len(rewards[-last_k:])
        if mean > best:
            best, best_dir = mean, d
    if best_dir is None:
        raise ValueError("no run with a readable rewards.csv")
    return best_dir
