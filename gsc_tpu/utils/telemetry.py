"""Test-mode CSV telemetry — schema-compatible with the reference writer.

Reference: coordsim/writer/writer.py:16-235.  In test mode the reference
streams per-control-interval CSVs (placements, node_metrics, metrics,
run_flows, drop_reasons, runtimes, rl_state, optional scheduling) from a
SimPy process.  Here the same files with the same headers are written by the
evaluation driver from the metrics pytree after each control step — one
device→host transfer per interval, no process machinery.
"""
from __future__ import annotations

import csv
import os
import time
from contextlib import contextmanager
from typing import Dict, Optional, Sequence

import numpy as np

from ..config.schema import DROP_REASONS


class PhaseTimer:
    """Per-phase host wall timing for the asynchronous episode pipeline.

    The pipeline's win is OVERLAP — host traffic sampling and metric
    draining hidden behind device compute — which a single SPS number
    cannot attribute.  This accumulates host-side wall time per named phase
    (``host_sample``, ``dispatch``, ``drain``, ...): ``dispatch`` is the
    time the loop spends handing work to the device (async, so near-zero
    unless the dispatch queue is full — i.e. the device is the
    bottleneck), ``drain`` is time blocked on device→host metric syncs,
    and ``host_sample`` only appears on the serial path (the prefetch
    thread absorbs it on the pipelined path).  A pipelined run should show
    drain+host_sample collapsing toward zero while dispatch grows to cover
    the device wall.

    Accumulation is lock-protected: the async actor/learner path shares
    ONE ledger across the actor threads and the learner loop (that is
    what makes ``actor_idle`` vs ``learner_idle`` comparable on one
    clock), and an unlocked read-modify-write would drop increments under
    that interleaving."""

    def __init__(self):
        import threading

        self._total: Dict[str, float] = {}
        self._count: Dict[str, int] = {}
        self._lock = threading.Lock()

    @contextmanager
    def phase(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self.add(name, time.perf_counter() - t0)

    def add(self, name: str, seconds: float):
        with self._lock:
            self._total[name] = self._total.get(name, 0.0) + seconds
            self._count[name] = self._count.get(name, 0) + 1

    def summary(self) -> Dict[str, Dict[str, float]]:
        """{phase: {total_s, count, mean_ms}} over everything recorded."""
        with self._lock:
            totals = dict(self._total)
            counts = dict(self._count)
        return {
            name: {"total_s": round(t, 4), "count": counts[name],
                   "mean_ms": round(1e3 * t / max(counts[name], 1), 3)}
            for name, t in sorted(totals.items())
        }


class TestModeWriter:
    """CSV suite with the reference's file names and headers
    (writer.py:26-110).

    ``flush_every`` batches the every-file flush to one in every N
    ``write_step`` calls (default 1 = the reference's flush-per-interval
    behavior, which the parity tests rely on; long evaluation sweeps pass
    ``Trainer.evaluate(telemetry_flush_every=N)`` so 8 file flushes stop
    gating every control interval).
    ``close`` always flushes whatever is buffered and is idempotent; the
    writer is also a context manager (``with TestModeWriter(...) as w:``).
    """

    def __init__(self, test_dir: str, write_schedule: bool = False,
                 write_flow_actions: bool = False,
                 sf_names: Sequence[str] = (), sfc_names: Sequence[str] = (),
                 flush_every: int = 1):
        if flush_every < 1:
            raise ValueError(f"flush_every must be >= 1, got {flush_every}")
        os.makedirs(test_dir, exist_ok=True)
        self.sf_names = list(sf_names)
        self.sfc_names = list(sfc_names)
        self.write_schedule = write_schedule
        self.write_flow_actions = write_flow_actions
        self.flush_every = flush_every
        self._steps_since_flush = 0
        self._closed = False
        self._files = {}
        self._writers = {}

        def w(name, header):
            f = open(os.path.join(test_dir, name), "w", newline="")
            self._files[name] = f
            wr = csv.writer(f)
            wr.writerow(header)
            self._writers[name] = wr
            return wr

        w("placements.csv", ["episode", "time", "node", "sf"])
        w("node_metrics.csv", ["episode", "time", "node", "node_capacity",
                               "used_resources", "ingress_traffic"])
        # trailing truncated_arrivals column is an extension over the
        # reference schema (writer.py:47): nonzero means flow-table slot
        # exhaustion / the per-substep arrival budget delayed arrivals and
        # generated-flow timing no longer matches the reference exactly
        w("metrics.csv", ["episode", "time", "total_flows", "successful_flows",
                          "dropped_flows", "in_network_flows",
                          "avg_end2end_delay", "truncated_arrivals"])
        w("run_flows.csv", ["episode", "time", "successful_flows",
                            "dropped_flows", "total_flows"])
        w("runtimes.csv", ["run", "runtime"])
        w("drop_reasons.csv", ["episode", "time", *DROP_REASONS])
        # rl_state.csv has no header row in the reference (writer.py:233-235)
        f = open(os.path.join(test_dir, "rl_state.csv"), "w", newline="")
        self._files["rl_state.csv"] = f
        self._writers["rl_state.csv"] = csv.writer(f)
        if write_schedule:
            w("scheduling.csv", ["episode", "time", "origin_node", "sfc",
                                 "sf", "schedule_node", "schedule_prob"])
        if write_flow_actions:
            # per-flow decision rows (writer.py:101-110 header)
            w("flow_actions.csv", ["episode", "time", "flow_id",
                                   "flow_rem_ttl", "flow_ttl", "curr_node_id",
                                   "dest_node", "cur_node_rem_cap",
                                   "next_node_rem_cap", "link_cap",
                                   "link_rem_cap"])
        self._run = 0

    def write_flow_action(self, episode: int, time: float, flow_id: int,
                          rem_ttl: float, ttl: float, cur_node, dest_node,
                          cur_node_rem_cap: float, next_node_rem_cap: float,
                          link_cap, link_rem_cap):
        """One per-flow decision row (writer.py:112-140)."""
        if self.write_flow_actions:
            self._writers["flow_actions.csv"].writerow(
                [episode, time, flow_id, rem_ttl, ttl, cur_node, dest_node,
                 cur_node_rem_cap, next_node_rem_cap, link_cap, link_rem_cap])
            if self.flush_every == 1:
                self._files["flow_actions.csv"].flush()

    def write_step(self, episode: int, time: float, metrics, placement,
                   node_cap, node_names: Optional[Sequence[str]] = None,
                   schedule=None, runtime: Optional[float] = None,
                   rl_state: Optional[Sequence[float]] = None,
                   truncated_arrivals: int = 0):
        """Log one control interval from device pytrees."""
        placement = np.asarray(placement)
        node_cap = np.asarray(node_cap)
        n = placement.shape[0]
        names = (list(node_names) if node_names
                 else [f"pop{i}" for i in range(n)])
        sfs = self.sf_names or [f"sf{i}" for i in range(placement.shape[1])]

        for node in range(n):
            for s in range(placement.shape[1]):
                if placement[node, s]:
                    self._writers["placements.csv"].writerow(
                        [episode, time, names[node], sfs[s]])

        # used_resources = peak demanded capacity this run
        # (run_max_node_usage, writer.py:183)
        used = np.asarray(metrics.run_max_node_usage)
        ingress = np.asarray(metrics.run_requested_node)
        for node in range(n):
            if node_cap[node] > 0 or used[node] > 0:
                self._writers["node_metrics.csv"].writerow(
                    [episode, time, names[node], node_cap[node], used[node],
                     ingress[node]])

        self._writers["metrics.csv"].writerow(
            [episode, time, int(metrics.generated), int(metrics.processed),
             int(metrics.dropped), int(metrics.active),
             float(metrics.avg_e2e()), int(truncated_arrivals)])
        self._writers["run_flows.csv"].writerow(
            [episode, time, int(metrics.run_processed),
             int(metrics.run_dropped), int(metrics.run_generated)])
        self._writers["drop_reasons.csv"].writerow(
            [episode, time, *np.asarray(metrics.drop_reasons).tolist()])
        if runtime is not None:
            self._run += 1
            self._writers["runtimes.csv"].writerow([self._run, runtime])
        if rl_state is not None:
            self._writers["rl_state.csv"].writerow(
                [episode, time] + [float(x) for x in rl_state])
        if schedule is not None and self.write_schedule:
            sched = np.asarray(schedule)
            sfcs = self.sfc_names or [f"sfc{i}" for i in range(sched.shape[1])]
            rows = []
            for src in range(n):
                for c in range(sched.shape[1]):
                    for s in range(sched.shape[2]):
                        for dst in range(n):
                            p = sched[src, c, s, dst]
                            if p > 0:
                                rows.append([episode, time, names[src],
                                             sfcs[c], sfs[s], names[dst], p])
            self._writers["scheduling.csv"].writerows(rows)
        self._steps_since_flush += 1
        if self._steps_since_flush >= self.flush_every:
            self._steps_since_flush = 0
            for f in self._files.values():
                f.flush()

    def close(self):
        """Flush and close every file; safe to call more than once (and
        called automatically when used as a context manager)."""
        if self._closed:
            return
        self._closed = True
        for f in self._files.values():
            f.close()   # close() flushes Python-buffered data itself

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        self.close()
        return False
