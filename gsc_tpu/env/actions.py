"""Action post-processing and placement derivation — pure jnp, vmap-able.

The reference post-processes actor outputs on the host per N-destination row
(threshold + renormalize, applied twice — src/rlsp/agents/simple_ddpg.py:374-395
with normalize semantics of common/common_functionalities.py:12-55) and derives
the placement by recursively following nonzero schedule weights from every
active ingress (src/rlsp/envs/simulator_wrapper.py:90-120, 161-167).  Both are
reimplemented as fixed-shape tensor ops that jit/vmap.
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np


def post_process_action(action: jnp.ndarray, num_dst: int,
                        threshold: float = 0.1) -> jnp.ndarray:
    """Threshold low probabilities to zero and renormalize each destination
    row to sum 1, twice (simple_ddpg.py:381-388).

    An all-zero row becomes the uniform distribution over all ``num_dst``
    (padded) destinations, matching normalize_scheduling_probabilities'
    zero-sum branch (common_functionalities.py:30-32) — the second threshold
    pass then zeroes 1/num_dst again whenever 1/num_dst < threshold, so the
    fixed point is uniform, exactly as in the reference.

    action: [..., R * num_dst] flat scheduling tensor in [0, 1].
    """
    shape = action.shape
    rows = action.reshape(shape[:-1] + (-1, num_dst))
    for _ in range(2):
        kept = jnp.where(rows >= threshold, rows, 0.0)
        total = kept.sum(-1, keepdims=True)
        rows = jnp.where(total > 0, kept / jnp.maximum(total, 1e-30),
                         1.0 / num_dst)
    return rows.reshape(shape)


def action_to_schedule(action: jnp.ndarray, scheduling_shape) -> jnp.ndarray:
    """Flat action [A] -> dense schedule [N, C, S, N] (the reference's
    reshape at simulator_wrapper.py:145-146; no dict explosion needed)."""
    return action.reshape(scheduling_shape)


def derive_placement(schedule: jnp.ndarray, chain_sf: np.ndarray,
                     chain_len: np.ndarray, active_ingress: jnp.ndarray,
                     num_sfs: int) -> jnp.ndarray:
    """Reachability-based placement [N, S] from schedule weights.

    The tensor equivalent of add_placement_recursive
    (simulator_wrapper.py:90-120): starting from every active ingress, a node
    hosts SF ``chain_sf[c, s]`` iff any reachable source schedules nonzero
    weight to it at chain position ``s``; reachability then advances to those
    targets.  The recursion depth is the (static) chain length, so this is a
    short unrolled loop of [N]x[N,N] reductions.

    schedule:       [N, C, S, N] scheduling weights
    chain_sf:       [C, S] static np array of SF indices (-1 pad)
    chain_len:      [C] static np array
    active_ingress: [N] bool (get_active_ingress_nodes,
                    siminterface/simulator.py:261-263)
    """
    n = schedule.shape[0]
    placed = jnp.zeros((n, num_sfs), bool)
    for c in range(chain_sf.shape[0]):
        reach = active_ingress
        for s in range(int(chain_len[c])):
            targets = ((schedule[:, c, s, :] > 0) & reach[:, None]).any(axis=0)
            placed = placed.at[:, int(chain_sf[c, s])].max(targets)
            reach = targets
    return placed


def action_mask(node_mask: jnp.ndarray, num_sfcs: int,
                max_sfs: int) -> jnp.ndarray:
    """Flattened [N*C*S*N] 0/1 mask selecting (real src, *, *, real dst)
    entries (the wrapper's mask at simulator_wrapper.py:139-143; also the
    ``mask`` attached to graph observations, simulator_wrapper.py:300-305)."""
    m = node_mask.astype(jnp.float32)
    mask4 = m[:, None, None, None] * m[None, None, None, :]
    return jnp.broadcast_to(
        mask4, (m.shape[0], num_sfcs, max_sfs, m.shape[0])).reshape(-1)
