"""TD-weighted auto-curriculum over scenario-factory families.

PR 11's learn ledger already mints the signal a curriculum needs: the
learn burst folds per-transition |TD-error| into per-``topo_idx``
segment sums inside the compiled program
(:mod:`gsc_tpu.obs.learning`), and under a factory mix the segment axis
IS the family axis (``topo_id = family index``).  This module closes
the loop on the host side of the drain — zero new device syncs:

- :class:`Curriculum` keeps one |TD| EWMA per family, updated from each
  drained episode's segment sums;
- :meth:`Curriculum.weights` turns the EWMAs into sampling logits
  (``softmax(ewma / temperature)``) mixed with a uniform floor, so
  batch composition chases the families that still carry learning
  signal while the floor keeps EVERY family alive (a family whose TD
  collapsed must keep being revisited, or forgetting is invisible);
- the resulting ``[K]`` probability vector feeds the next episode's
  ``ScenarioFactory.sample_batch`` as plain traced data — curriculum
  moves never retrace.

Cold start: families never observed yet borrow the LARGEST seen EWMA
(optimism under uncertainty — an unexplored arm should be tried, not
starved because its estimate initializes at zero); with no observations
at all the distribution is uniform.

Knobs (``cli train --curriculum-temperature/--curriculum-floor``):
``temperature`` flattens (high) or sharpens (low) the TD-driven skew;
``floor`` is the total probability mass always spread uniformly, so no
family's probability can fall below ``floor / K``.  Round-robin — the
PR 9 registry behavior — is the ``temperature -> inf`` limit; it still
wins when the mixture members are so different that per-family replay
imbalance hurts more than frontier-chasing helps (see README).
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class CurriculumConfig:
    """Host-side curriculum knobs (all pure-python; the device only ever
    sees the resulting probability vector)."""

    temperature: float = 1.0   # softmax temperature over the |TD| EWMAs
    floor: float = 0.25        # total uniform probability mass (0..1)
    alpha: float = 0.3         # EWMA step toward an episode's |TD| mean

    def __post_init__(self):
        if not 0.0 <= self.floor <= 1.0:
            raise ValueError(f"curriculum floor must be in [0, 1]: "
                             f"{self.floor}")
        if self.temperature <= 0.0:
            raise ValueError(f"curriculum temperature must be > 0: "
                             f"{self.temperature}")
        if not 0.0 < self.alpha <= 1.0:
            raise ValueError(f"curriculum alpha must be in (0, 1]: "
                             f"{self.alpha}")


class Curriculum:
    """Per-family |TD| EWMAs -> sampling weights (math in the module
    docstring).  Pure numpy on purpose: the update runs at drain cadence
    on already-synced values, and hand-computed unit tests can pin the
    arithmetic exactly."""

    def __init__(self, names: Sequence[str],
                 cfg: Optional[CurriculumConfig] = None):
        if not names:
            raise ValueError("curriculum needs at least one family name")
        self.names: List[str] = [str(n) for n in names]
        self.cfg = cfg or CurriculumConfig()
        k = len(self.names)
        self.ewma = np.zeros(k, np.float64)
        self.seen = np.zeros(k, bool)
        self.updates = 0

    @property
    def num_families(self) -> int:
        return len(self.names)

    def fold_td(self, td_abs_sum, td_count) -> np.ndarray:
        """Fold one drained episode's per-family |TD| segment sums into
        the EWMAs.  Families with zero transitions this episode keep
        their EWMA (no observation != zero TD); a family's FIRST
        observation initializes its EWMA to the observed mean instead of
        stepping from 0 (cold-start bias toward under-sampling).
        Non-finite segments are DROPPED like unobserved ones: the
        replica path deliberately continues past a poisoned learner
        state (no rollback guard — checkpoints/publishes skip, the loop
        runs on), and one NaN burst folded here would make EVERY
        family's weight NaN forever, silently killing the curriculum
        for the run's remainder.  Returns the updated EWMA vector (a
        copy)."""
        sums = np.asarray(td_abs_sum, np.float64).reshape(-1)
        counts = np.asarray(td_count, np.float64).reshape(-1)
        if sums.shape[0] != self.num_families \
                or counts.shape[0] != self.num_families:
            raise ValueError(
                f"TD segments have {sums.shape[0]} families, curriculum "
                f"tracks {self.num_families} ({self.names})")
        observed = (counts > 0) & np.isfinite(sums) & np.isfinite(counts)
        means = np.where(observed, sums / np.maximum(counts, 1.0), 0.0)
        a = self.cfg.alpha
        stepped = (1.0 - a) * self.ewma + a * means
        self.ewma = np.where(
            observed, np.where(self.seen, stepped, means), self.ewma)
        self.seen |= observed
        self.updates += 1
        return self.ewma.copy()

    def weights(self) -> np.ndarray:
        """The ``[K]`` family-sampling distribution for the NEXT episode:
        ``(1 - floor) * softmax(ewma / temperature) + floor / K``.
        Unseen families borrow the max seen EWMA (optimism); all-unseen
        is exactly uniform.  Always sums to 1 with every entry >=
        ``floor / K > 0`` (for ``floor > 0``)."""
        k = self.num_families
        if not self.seen.any():
            return np.full(k, 1.0 / k)
        logits = np.where(self.seen, self.ewma, self.ewma[self.seen].max())
        z = logits / self.cfg.temperature
        z = z - z.max()
        p = np.exp(z)
        p = p / p.sum()
        floor = self.cfg.floor
        return (1.0 - floor) * p + floor / k

    # ------------------------------------------------------------- emit
    def emit_weights(self, hub, episode: int) -> Optional[Dict]:
        """``curriculum_weight{family=...}`` gauges + one ``curriculum``
        event per drained episode (same hub pathway as the learn
        ledger's gauges; no-op without a hub)."""
        if hub is None:
            return None
        w = self.weights()
        for name, v in zip(self.names, w):
            hub.gauge("curriculum_weight", round(float(v), 6), family=name)
        return hub.event(
            "curriculum", episode=episode,
            weights={n: round(float(v), 6)
                     for n, v in zip(self.names, w)},
            td_ewma={n: round(float(e), 6)
                     for n, e in zip(self.names, self.ewma)},
            updates=self.updates)
