"""Host-side episode driver: topology scheduling + per-episode traffic.

The reference swaps the training topology every ``period`` episodes (cycling
the scheduler's ``training_network_files``) and always uses the inference
network in test mode (src/rlsp/envs/gym_env.py:103-128, configs/config/
scheduler.yaml:1-11), regenerating pre-sampled flow lists each episode
(siminterface/simulator.py:115-117).  Here both become cheap host-side array
selection: topologies are compiled once into padded ``Topology`` pytrees, and
each episode gets a freshly sampled ``TrafficSchedule``.
"""
from __future__ import annotations

from typing import List, Optional, Sequence

import numpy as np

from ..config.schema import SchedulerConfig, ServiceConfig, SimConfig
from ..sim.state import TrafficSchedule
from ..sim.traffic import TraceEvents, generate_traffic, traffic_capacity
from ..topology.compiler import (Topology, check_dt_quantization,
                                 load_topology)


def _node_index(name: str) -> int:
    """Trace 'node' column -> node index; accepts the reference's 'popN'
    spelling (configs/traces/*.csv) and bare integers."""
    s = str(name)
    return int(s[3:]) if s.startswith("pop") else int(s)


class EpisodeDriver:
    """Yields (topology, traffic) per episode following the scheduler config."""

    def __init__(self, scheduler: SchedulerConfig, sim_cfg: SimConfig,
                 service: ServiceConfig, episode_steps: int,
                 max_nodes: int = 24, max_edges: int = 37,
                 base_seed: int = 0,
                 topologies: Optional[Sequence[Topology]] = None,
                 inference_topology: Optional[Topology] = None):
        self.scheduler = scheduler
        self.sim_cfg = sim_cfg
        self.service = service
        self.episode_steps = episode_steps
        self.base_seed = base_seed
        if topologies is None:
            topologies = [
                load_topology(p, max_nodes=max_nodes, max_edges=max_edges,
                              force_link_cap=sim_cfg.force_link_cap,
                              force_node_cap=sim_cfg.force_node_cap,
                              seed=base_seed)
                for p in scheduler.training_network_files
            ]
        self.topologies: List[Topology] = list(topologies)
        if inference_topology is None:
            inference_topology = load_topology(
                scheduler.inference_network, max_nodes=max_nodes,
                max_edges=max_edges, force_link_cap=sim_cfg.force_link_cap,
                force_node_cap=sim_cfg.force_node_cap, seed=base_seed)
        self.inference_topology = inference_topology
        for i, t in enumerate(self.topologies + [self.inference_topology]):
            check_dt_quantization(t, sim_cfg.dt, name=f"topology[{i}]")
        self.trace = (TraceEvents.from_csv(sim_cfg.trace_path, _node_index)
                      if sim_cfg.trace_path else None)
        # fixed traffic capacity across episodes -> no recompiles
        max_ing = max(int(np.asarray(t.is_ingress).sum()) for t in
                      self.topologies + [self.inference_topology])
        self.capacity = traffic_capacity(sim_cfg, max_ing, episode_steps)

    def topology_for(self, episode: int, test_mode: bool = False) -> Topology:
        """Topology schedule (gym_env.py:103-128): switch every ``period``
        episodes, cycling the training list; inference net in test mode."""
        if test_mode:
            return self.inference_topology
        index = (episode // self.scheduler.period) % len(self.topologies)
        return self.topologies[index]

    def traffic_for(self, episode: int, topo: Topology,
                    seed: Optional[int] = None) -> TrafficSchedule:
        seed = self.base_seed + episode if seed is None else seed
        return generate_traffic(self.sim_cfg, self.service, topo,
                                self.episode_steps, seed, trace=self.trace,
                                capacity=self.capacity)

    def episode(self, episode: int, test_mode: bool = False,
                seed: Optional[int] = None):
        topo = self.topology_for(episode, test_mode)
        return topo, self.traffic_for(episode, topo, seed)
