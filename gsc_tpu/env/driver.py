"""Host-side episode driver: topology scheduling + per-episode traffic.

The reference swaps the training topology every ``period`` episodes (cycling
the scheduler's ``training_network_files``) and always uses the inference
network in test mode (src/rlsp/envs/gym_env.py:103-128, configs/config/
scheduler.yaml:1-11), regenerating pre-sampled flow lists each episode
(siminterface/simulator.py:115-117).  Here both become cheap host-side array
selection: topologies are compiled once into padded ``Topology`` pytrees, and
each episode gets a freshly sampled ``TrafficSchedule``.
"""
from __future__ import annotations

import os
import queue
import threading
from typing import Callable, List, Optional, Sequence

import numpy as np

from ..config.schema import SchedulerConfig, ServiceConfig, SimConfig
from ..sim.state import TrafficSchedule
from ..sim.traffic import TraceEvents, generate_traffic, traffic_capacity
from ..topology import scenarios
from ..topology.compiler import (Topology, TopologyBucket,
                                 check_dt_quantization,
                                 load_topology_cached)


def _node_index(name: str) -> int:
    """Trace 'node' column -> node index; accepts the reference's 'popN'
    spelling (configs/traces/*.csv) and bare integers."""
    s = str(name)
    return int(s[3:]) if s.startswith("pop") else int(s)


class EpisodeDriver:
    """Yields (topology, traffic) per episode following the scheduler config."""

    def __init__(self, scheduler: SchedulerConfig, sim_cfg: SimConfig,
                 service: ServiceConfig, episode_steps: int,
                 max_nodes: int = 24, max_edges: int = 37,
                 base_seed: int = 0,
                 topologies: Optional[Sequence[Topology]] = None,
                 inference_topology: Optional[Topology] = None,
                 topo_mix: Optional[str] = None,
                 registry: Optional["scenarios.ScenarioRegistry"] = None):
        self.scheduler = scheduler
        self.sim_cfg = sim_cfg
        self.service = service
        self.episode_steps = episode_steps
        self.base_seed = base_seed
        if topologies is None:
            # memoized per (file, mtime, dims, cap overrides, seed):
            # schedule rebuilds and --runs legs reuse the compiled pytree
            # instead of re-parsing + re-shortest-pathing every network
            # topo_id = schedule position, stamped inside the memo so a
            # rebuilt driver (--runs legs, schedule switches) gets the
            # SAME object back for every position
            topologies = [
                load_topology_cached(
                    p, max_nodes=max_nodes, max_edges=max_edges,
                    force_link_cap=sim_cfg.force_link_cap,
                    force_node_cap=sim_cfg.force_node_cap,
                    seed=base_seed, topo_id=i)
                for i, p in enumerate(scheduler.training_network_files)
            ]
        # schedule topologies carry their schedule position as topo_id so
        # replay transitions record which network they were collected on
        # (mixed batches re-stamp per mix-entry position instead); loaded
        # topologies arrive pre-stamped, caller-passed lists get stamped
        # here
        import jax.numpy as jnp
        self.topologies: List[Topology] = [
            t if int(np.asarray(t.topo_id)) == i
            else t.replace(topo_id=jnp.asarray(i, jnp.int32))
            for i, t in enumerate(topologies)]
        if inference_topology is None:
            inference_topology = load_topology_cached(
                scheduler.inference_network, max_nodes=max_nodes,
                max_edges=max_edges, force_link_cap=sim_cfg.force_link_cap,
                force_node_cap=sim_cfg.force_node_cap, seed=base_seed)
        self.inference_topology = inference_topology
        for i, t in enumerate(self.topologies + [self.inference_topology]):
            check_dt_quantization(t, sim_cfg.dt, name=f"topology[{i}]")
        self.trace = (TraceEvents.from_csv(sim_cfg.trace_path, _node_index)
                      if sim_cfg.trace_path else None)
        # fixed traffic capacity across episodes -> no recompiles
        max_ing = max(int(np.asarray(t.is_ingress).sum()) for t in
                      self.topologies + [self.inference_topology])
        self.capacity = traffic_capacity(sim_cfg, max_ing, episode_steps)
        # ---- mixed-topology batch mode (topology.scenarios) -------------
        # ``topo_mix`` turns the schedule-of-topologies into PER-BATCH
        # diversity: mix_plan(B) fills the replica axis round-robin over
        # the expanded entry list (schedule networks + registry
        # scenarios), all padded into one shape bucket — a single vmapped
        # dispatch then trains every mixture member side by side with ONE
        # compiled program.
        self.topo_mix = topo_mix
        self.registry = registry or scenarios.DEFAULT_REGISTRY
        self.bucket = TopologyBucket(max_nodes, max_edges)
        self._mix_entries = None
        self._mix_plans = {}
        # ``factory:`` mixes select the on-device scenario factory
        # (topology.factory): no host mix entries exist — every episode
        # SAMPLES fresh per-replica scenarios inside the compiled
        # program, with batch composition steered by the TD curriculum
        # (env.curriculum).  The spec parses here (fail fast on grammar)
        # but the ScenarioFactory builds lazily: constructing it touches
        # jax device constants, which drivers built only for validation
        # should never pay.
        self.factory_spec = None
        self._factory = None
        from ..topology.factory import is_factory_mix, parse_factory
        if topo_mix and is_factory_mix(topo_mix):
            self.factory_spec = parse_factory(topo_mix)
        elif topo_mix:
            sched_names = [os.path.basename(p) for p in
                           scheduler.training_network_files]
            self._mix_entries = scenarios.build_mix_entries(
                topo_mix, self.registry, self.bucket,
                schedule_topos=self.topologies,
                schedule_names=sched_names, dt=sim_cfg.dt)

    @property
    def scenario_factory(self):
        """The driver's :class:`~gsc_tpu.topology.factory.
        ScenarioFactory` (built on first access; None without a
        ``factory:`` mix)."""
        if self.factory_spec is None:
            return None
        if self._factory is None:
            from ..topology.factory import ScenarioFactory
            self._factory = ScenarioFactory(
                self.factory_spec, self.sim_cfg, self.service,
                self.episode_steps, max_nodes=self.bucket.max_nodes,
                max_edges=self.bucket.max_edges)
        return self._factory

    # ------------------------------------------------------------ mix mode
    def mix_plan(self, num_replicas: int) -> "scenarios.MixPlan":
        """Round-robin MixPlan for ``num_replicas`` (memoized per B —
        the stacked topology is the SAME object every episode, so the
        vmapped dispatch never re-places or retraces it)."""
        if not self.topo_mix:
            raise ValueError("driver has no topo_mix configured")
        if self.factory_spec is not None:
            raise ValueError(
                "a factory mix samples scenarios on device per episode — "
                "no host MixPlan exists (use driver.scenario_factory)")
        plan = self._mix_plans.get(num_replicas)
        if plan is None:
            plan = scenarios.plan_mix(self._mix_entries, num_replicas,
                                      self.bucket, self.sim_cfg,
                                      self.episode_steps)
            self._mix_plans[num_replicas] = plan
        return plan

    def mix_traffic(self, episode: int,
                    plan: "scenarios.MixPlan") -> TrafficSchedule:
        """[B]-stacked host traffic for one mixed episode (per-replica
        seeds follow the replica-parallel trainer's convention)."""
        return scenarios.mix_traffic_host(
            plan, self.sim_cfg, self.service, self.episode_steps,
            seed_for=lambda r: self.base_seed + 1000 * episode + r,
            default_trace=self.trace)

    def topology_for(self, episode: int, test_mode: bool = False) -> Topology:
        """Topology schedule (gym_env.py:103-128): switch every ``period``
        episodes, cycling the training list; inference net in test mode."""
        if test_mode:
            return self.inference_topology
        index = (episode // self.scheduler.period) % len(self.topologies)
        return self.topologies[index]

    # ------------------------------------------------- topology identity
    # (the obs layer's attribution surface: replay rows store topo_id,
    # and these map ids back to names so single-replica runs land in the
    # same per-topology report tables as mixed batches)
    @property
    def num_topo_ids(self) -> int:
        """How many distinct ``topo_id`` values this driver's episodes
        can stamp into replay rows: mix-entry count for mixed runs,
        schedule length otherwise (the learn ledger's segment axis).
        ``getattr`` tolerates stub drivers built via ``__new__`` (the
        test suite's single-topology fakes).  Factory mixes segment per
        FAMILY (``topo_id`` = family index)."""
        spec = getattr(self, "factory_spec", None)
        if spec is not None:
            return spec.num_families
        entries = getattr(self, "_mix_entries", None)
        if entries is not None:
            return len(entries)
        return len(self.topologies)

    def _schedule_names(self) -> List[str]:
        """Schedule-position -> name (file basenames; drivers built from
        explicit topology lists fall back to positional names).  The ONE
        naming rule behind :attr:`topo_id_names` and
        :meth:`topology_name_for`, so the learn ledger's segment names
        and the episode-event topology stamps can never disagree."""
        files = list(self.scheduler.training_network_files or [])
        if len(files) == len(self.topologies):
            return [os.path.basename(p) for p in files]
        return [f"topology{i}" for i in range(len(self.topologies))]

    @property
    def topo_id_names(self) -> List[str]:
        """``topo_id`` -> human-readable name, aligned with
        :attr:`num_topo_ids` (factory family names, mix-entry names,
        else the schedule names)."""
        spec = getattr(self, "factory_spec", None)
        if spec is not None:
            return list(spec.families)
        entries = getattr(self, "_mix_entries", None)
        if entries is not None:
            return [e.name for e in entries]
        return self._schedule_names()

    def topology_name_for(self, episode: int,
                          test_mode: bool = False) -> str:
        """Name of the topology :meth:`topology_for` yields — the serial
        trainer stamps it on episode events / ``topology_return`` gauges
        so single-replica runs appear in the same per-topology tables as
        mixed batches.  (Schedule names, NOT :attr:`topo_id_names`: a
        mixed driver's id axis is mix entries, but this method describes
        the schedule pick the non-mixed paths dispatch.)"""
        if test_mode:
            return os.path.basename(self.scheduler.inference_network or
                                    "inference")
        index = (episode // self.scheduler.period) % len(self.topologies)
        return self._schedule_names()[index]

    def traffic_for(self, episode: int, topo: Topology,
                    seed: Optional[int] = None) -> TrafficSchedule:
        seed = self.base_seed + episode if seed is None else seed
        return generate_traffic(self.sim_cfg, self.service, topo,
                                self.episode_steps, seed, trace=self.trace,
                                capacity=self.capacity)

    def episode(self, episode: int, test_mode: bool = False,
                seed: Optional[int] = None):
        topo = self.topology_for(episode, test_mode)
        return topo, self.traffic_for(episode, topo, seed)

    def prefetcher(self, start: int, stop: int, test_mode: bool = False,
                   depth: int = 2, stage: Optional[Callable] = None,
                   heartbeat: Optional[Callable] = None,
                   before_episode: Optional[Callable] = None
                   ) -> "EpisodePrefetcher":
        """Background double buffer over ``episode``: episode k+1's traffic
        is sampled (and optionally staged to device via ``stage``) while
        episode k's rollout runs on the accelerator.  ``heartbeat`` (e.g.
        the obs hub's prefetcher beat) is called from the producer thread
        after every staged episode so a watchdog can tell a dead producer
        from one blocked on a full queue.  ``before_episode(ep,
        stop_event)`` runs in the producer before each episode's sampling
        — the resilience fault-injection hook (prefetcher death, slow
        episodes)."""
        return EpisodePrefetcher(self, start, stop, test_mode=test_mode,
                                 depth=depth, stage=stage,
                                 heartbeat=heartbeat,
                                 before_episode=before_episode)


class PrefetchInterrupted(RuntimeError):
    """The prefetcher was deliberately interrupted (watchdog escalation) —
    the consumer should restart it from the current episode counter."""


class EpisodePrefetcher:
    """Host-side episode pipeline: a daemon thread runs the driver's
    per-episode sampling (topology selection + host traffic generation)
    ``depth`` episodes ahead of the training loop, through a bounded queue.

    The sequence is IDENTICAL to serial ``driver.episode(ep, test_mode)``
    calls — traffic is seeded purely by the episode index
    (``base_seed + episode``), so look-ahead cannot perturb it, and the
    topology objects are the driver's own cached ``Topology`` pytrees (the
    same Python objects the serial path yields, preserving ``id(topo)``
    keyed caches downstream).

    ``stage(topo, traffic) -> (topo, traffic)`` runs IN the producer thread
    — pass a ``jax.device_put`` wrapper to overlap the host→device transfer
    with the running episode as well (transfers are thread-safe and async).
    """

    _DONE = "done"
    _ERROR = "error"

    def __init__(self, driver: EpisodeDriver, start: int, stop: int,
                 test_mode: bool = False, depth: int = 2,
                 stage: Optional[Callable] = None,
                 heartbeat: Optional[Callable] = None,
                 before_episode: Optional[Callable] = None):
        if depth < 1:
            raise ValueError(f"prefetch depth must be >= 1, got {depth}")
        self.driver = driver
        self._queue: queue.Queue = queue.Queue(maxsize=depth)
        self._stop_flag = threading.Event()
        self._interrupted: Optional[str] = None
        self._args = (start, stop, test_mode, stage, heartbeat,
                      before_episode)
        self._thread = threading.Thread(
            target=self._produce, name="gsc-episode-prefetch", daemon=True)
        self._thread.start()

    @property
    def queue_depth(self) -> int:
        """Episodes currently staged (approximate — the producer races)."""
        return self._queue.qsize()

    def is_alive(self) -> bool:
        """Producer-thread liveness (watchdog stall-event probe)."""
        return self._thread.is_alive()

    def _produce(self):
        start, stop, test_mode, stage, heartbeat, before_episode = self._args
        try:
            for ep in range(start, stop):
                if before_episode is not None:
                    # fault-injection hook; receives the stop flag so an
                    # injected slow-stage sleep aborts the moment close()
                    # abandons this producer
                    before_episode(ep, self._stop_flag)
                if self._stop_flag.is_set():
                    return
                item = self.driver.episode(ep, test_mode)
                if stage is not None:
                    item = stage(*item)
                if heartbeat is not None:
                    heartbeat()
                # bounded put, polled so close() can abandon a full queue
                while not self._stop_flag.is_set():
                    try:
                        self._queue.put((ep, item), timeout=0.1)
                        break
                    except queue.Full:
                        continue
                if self._stop_flag.is_set():
                    return
        except BaseException as e:  # surfaced on the consumer's next get()
            self._queue.put((self._ERROR, e))
        else:
            self._queue.put((self._DONE, None))

    def interrupt(self, reason: str):
        """Fail the consumer's next (or currently-blocked) ``get`` with a
        :class:`PrefetchInterrupted` — the watchdog's escalation path:
        called from the watchdog thread when the pipeline has been quiet
        past its escalation budget, so the trainer wakes out of a blocked
        ``get`` and restarts the prefetcher.  The producer itself is left
        to ``close()``."""
        self._interrupted = reason
        try:   # wake a consumer blocked on an empty queue; a full queue
            # means the consumer isn't blocked here and the flag check in
            # get() suffices
            self._queue.put_nowait((self._ERROR,
                                    PrefetchInterrupted(reason)))
        except queue.Full:
            pass

    def get(self, episode: int):
        """(topo, traffic) for ``episode`` — episodes must be consumed in
        the order the prefetcher was built for."""
        if self._interrupted is not None:
            raise PrefetchInterrupted(self._interrupted)
        tag, item = self._queue.get()
        if tag == self._ERROR:
            if isinstance(item, PrefetchInterrupted):
                raise item
            raise RuntimeError(
                "episode prefetch thread failed") from item
        if tag == self._DONE:
            raise RuntimeError(
                f"prefetcher exhausted before episode {episode}")
        if tag != episode:
            raise RuntimeError(
                f"out-of-order prefetch consumption: asked for episode "
                f"{episode}, next staged is {tag}")
        return item

    def close(self):
        """Stop the producer; safe to call at any point (including after an
        exception mid-epoch)."""
        self._stop_flag.set()
        try:
            while True:  # unblock a producer waiting on a full queue
                self._queue.get_nowait()
        except queue.Empty:
            pass
        self._thread.join(timeout=5.0)
