"""Node-permutation augmentation — pure jnp, vmap-able.

Reference: src/rlsp/envs/simulator_wrapper.py:310-369 (enabled by the
``shuffle_nodes`` agent flag, off by default, src/rlsp/agents/main.py:254):
each step the observation's node order is shuffled by a fresh random
permutation and the agent's action — produced in the shuffled frame — is
mapped back through the inverse permutation (both source and destination
axes) before the simulator sees it.

The reference implementation only handles the flat 2-component state via
Python list slicing; here both observation modes are supported with
fixed-shape gathers (padded nodes permute like any other — the action mask
travels with the permutation, so the agent still sees which entries are
real).
"""
from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp

from .observations import GraphObs


def random_permutation(key, n: int) -> jnp.ndarray:
    """Fresh node permutation (simulator_wrapper.py:318-319)."""
    return jax.random.permutation(key, n)


def inverse_permutation(perm: jnp.ndarray) -> jnp.ndarray:
    """inverse[perm[j]] = j (simulator_wrapper.py:327-332)."""
    return jnp.argsort(perm)


def permute_flat_obs(obs: jnp.ndarray, perm: jnp.ndarray) -> jnp.ndarray:
    """Apply the same node order to every stacked component vector
    (simulator_wrapper.py:323-325).  obs: [..., F*N] with F stacked
    node-vectors."""
    n = perm.shape[0]
    lead = obs.shape[:-1]
    v = obs.reshape(lead + (-1, n))
    return v[..., perm].reshape(obs.shape)


def permute_graph_obs(obs: GraphObs, perm: jnp.ndarray,
                      num_sfcs: int, max_sfs: int) -> GraphObs:
    """Permute node rows, relabel edges, and permute the action mask
    consistently with ``permute_action_mask`` below."""
    inv = inverse_permutation(perm)
    n = perm.shape[0]
    mask4 = obs.mask.reshape(obs.mask.shape[:-1] + (n, num_sfcs, max_sfs, n))
    mask4 = mask4[..., perm, :, :, :][..., perm]
    return GraphObs(
        nodes=obs.nodes[..., perm, :],
        node_mask=obs.node_mask[..., perm],
        # new node id of old node u is inv[u]
        edge_index=inv[obs.edge_index],
        edge_mask=obs.edge_mask,
        mask=mask4.reshape(obs.mask.shape),
    )


def reverse_action_permutation(action: jnp.ndarray, perm: jnp.ndarray,
                               scheduling_shape: Tuple[int, int, int, int]
                               ) -> jnp.ndarray:
    """Map an action produced in the permuted frame back to the original
    node order on both source and destination axes
    (simulator_wrapper.py:334-369)."""
    inv = inverse_permutation(perm)
    a = action.reshape(action.shape[:-1] + scheduling_shape)
    a = a[..., inv, :, :, :][..., inv]
    return a.reshape(action.shape)


class ShuffleOps:
    """The per-step shuffle_nodes protocol, shared by the single-env and
    data-parallel rollouts (gym_env.py:164-206 flow): observations live in a
    per-step permuted frame, actions map back through the inverse before the
    env sees them.  With ``shuffle_nodes`` off every method is the identity,
    so rollout bodies call these unconditionally."""

    def __init__(self, agent, limits):
        self.agent = agent
        self.limits = limits
        self.on = agent.shuffle_nodes
        self.n = limits.max_nodes

    def init_perm(self, key) -> jnp.ndarray:
        if not self.on:
            return jnp.arange(self.n)
        return random_permutation(key, self.n)

    def permute_obs(self, obs, perm):
        if not self.on:
            return obs
        if self.agent.graph_mode:
            return permute_graph_obs(obs, perm, self.limits.num_sfcs,
                                     self.limits.max_sfs)
        return permute_flat_obs(obs, perm)

    def step_mask(self, obs, mask, perm):
        """Action mask in the current (possibly permuted) frame."""
        if self.agent.graph_mode:
            return obs.mask          # travels with the permuted obs
        if not self.on:
            return mask
        m4 = mask.reshape(self.limits.scheduling_shape)
        return m4[perm][..., perm].reshape(-1)

    def env_action(self, action, perm):
        """Action back in the simulator's frame (gym_env.py:193-196)."""
        if not self.on:
            return action
        return reverse_action_permutation(action, perm,
                                          self.limits.scheduling_shape)

    def advance(self, key, next_obs, perm):
        """Fresh permutation + permuted next obs (gym_env.py:202-206)."""
        if not self.on:
            return next_obs, perm
        next_perm = random_permutation(key, self.n)
        return self.permute_obs(next_obs, next_perm), next_perm
