"""The RL environment: pure functional reset/step over the batched simulator.

The TPU-native replacement for the reference's GymEnv + SimulatorWrapper stack
(src/rlsp/envs/gym_env.py:24-211, src/rlsp/envs/simulator_wrapper.py:22-176):
instead of a stateful gym.Env mutating a SimPy simulator, ``ServiceCoordEnv``
is a factory of pure ``reset``/``step`` functions over ``EnvState`` pytrees —
they jit, vmap over env replicas, and shard over device meshes.  Episode
control (topology scheduling, per-episode traffic generation) lives in the
host-side ``EpisodeDriver``.
"""
from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
from flax import struct

from ..config.schema import AgentConfig, EnvLimits, ServiceConfig, SimConfig
from ..sim.engine import SimEngine
from ..sim.state import SimState, TrafficSchedule
from ..topology.compiler import Topology
from .actions import action_mask, action_to_schedule, derive_placement, post_process_action
from .observations import GraphObs, flat_obs, graph_obs
from .rewards import compute_reward, reward_constants


@struct.dataclass
class EnvState:
    """Per-replica environment state (the analogue of GymEnv's mutable
    attributes: run_count, ewma_flows — gym_env.py:47-51, 80-82)."""

    sim: SimState
    step: jnp.ndarray        # [] i32 steps taken this episode
    ewma_flows: jnp.ndarray  # [] f32 EWMA of flow success (gym_env.py:80-91)


class ServiceCoordEnv:
    """Factory closing over static configuration.

    ``reset(rng, topo, traffic)``  -> (EnvState, obs)
    ``step(state, topo, traffic, action)`` -> (EnvState, obs, reward, done, info)

    ``action`` is the flat [A] scheduling tensor in [0, 1] *after* agent-side
    post-processing (``process_action``), matching the reference's split where
    SimpleDDPG post-processes and GymEnv.step consumes
    (simple_ddpg.py:248-249, gym_env.py:171-211).
    """

    def __init__(self, service: ServiceConfig, sim_cfg: SimConfig,
                 agent: AgentConfig, limits: EnvLimits,
                 engine: Optional[SimEngine] = None):
        self.service = service
        self.sim_cfg = sim_cfg
        self.agent = agent
        self.limits = limits
        # injectable engine: pass sim.dummy.DummyEngine to exercise the RL
        # stack without the simulator (the reference's dummy_env pattern)
        self.engine = engine if engine is not None else SimEngine(
            service, sim_cfg, limits)
        self.tables = self.engine.tables
        self.min_delay, self.diameter = reward_constants(
            agent, [service.sf_list[n].processing_delay_mean
                    for n in service.sf_names])

    # ------------------------------------------------------------- helpers
    def process_action(self, action: jnp.ndarray) -> jnp.ndarray:
        """Agent-side action post-processing (simple_ddpg.py:374-395)."""
        return post_process_action(action, self.limits.max_nodes,
                                   self.agent.schedule_threshold)

    def _masked_schedule(self, action: jnp.ndarray, topo: Topology) -> jnp.ndarray:
        """Flat action -> [N,C,S,N] schedule with padded src/dst entries
        zeroed (the wrapper's mask selection, simulator_wrapper.py:139-146:
        padded destinations never receive weight, so WRR ignores them)."""
        sched = action_to_schedule(action, self.limits.scheduling_shape)
        m = topo.node_mask.astype(sched.dtype)
        return sched * m[:, None, None, None] * m[None, None, None, :]

    def _obs(self, state: SimState, topo: Topology, traffic: TrafficSchedule):
        t_steps = traffic.node_cap.shape[0]
        cap_now = traffic.node_cap[jnp.clip(state.run_idx, 0, t_steps - 1)]
        override = None
        if self.sim_cfg.prediction:
            # show upcoming ingress traffic instead of observed (the traffic
            # predictor subsystem, traffic_predictor.py:22-56)
            from ..sim.predictor import predict_ingress_traffic
            override = predict_ingress_traffic(
                traffic, state.run_idx, self.sim_cfg.run_duration,
                self.limits.max_nodes)
        if self.agent.graph_mode:
            return graph_obs(state.metrics, topo, cap_now, self.tables.chain_sf,
                             self.agent.observation_space,
                             self.limits.num_sfcs, self.limits.max_sfs,
                             ingress_override=override)
        return flat_obs(state.metrics, topo, cap_now, self.tables.chain_sf,
                        self.agent.observation_space,
                        ingress_override=override)

    def obs_dim(self) -> int:
        """Flat observation length (len(observation_space) stacked node
        vectors, padded to MAX_NODES)."""
        return self.limits.max_nodes * len(self.agent.observation_space)

    # --------------------------------------------------------------- reset
    @partial(jax.jit, static_argnums=0)
    def reset(self, rng, topo: Topology, traffic: TrafficSchedule):
        """New episode: fresh simulator state, observation of the empty
        network (the reference's wrapper.init runs only the t=0 bookkeeping
        event before producing the first obs, duration_controller.py:20-33)."""
        sim = self.engine.init(rng, topo)
        state = EnvState(sim=sim, step=jnp.zeros((), jnp.int32),
                         ewma_flows=jnp.ones((), jnp.float32))  # gym_env.py:81
        return state, self._obs(sim, topo, traffic)

    # ---------------------------------------------------------------- step
    @partial(jax.jit, static_argnums=0)
    def step(self, state: EnvState, topo: Topology, traffic: TrafficSchedule,
             action: jnp.ndarray):
        schedule = self._masked_schedule(action, topo)
        t_steps = traffic.ingress_active.shape[0]
        active_ing = (topo.is_ingress & topo.node_mask
                      & traffic.ingress_active[
                          jnp.clip(state.sim.run_idx, 0, t_steps - 1)])
        placement = derive_placement(
            schedule, self.tables.chain_sf, self.tables.chain_len,
            active_ing, self.limits.sf_pool)
        sim, metrics = self.engine.apply(state.sim, topo, traffic, schedule,
                                         placement)
        reward, ewma, info = compute_reward(
            self.agent, metrics, placement, topo.node_mask,
            self.limits.sf_pool, self.min_delay, self.diameter,
            state.ewma_flows)
        step = state.step + 1
        done = step >= self.agent.episode_steps
        info["run_generated"] = metrics.run_generated
        info["run_processed"] = metrics.run_processed
        info["run_dropped"] = metrics.run_dropped
        # surface what was actually applied so telemetry doesn't recompute it
        info["placement"] = placement
        info["schedule"] = schedule
        state = EnvState(sim=sim, step=step, ewma_flows=ewma)
        return state, self._obs(sim, topo, traffic), reward, done, info
