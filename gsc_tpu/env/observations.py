"""Observation builders — flat and graph modes, pure jnp.

Reference: src/rlsp/envs/simulator_wrapper.py:178-308.  Three node-feature
vectors, each max-normalized as ``clip(x / (max(x) + 1e-3), 0, 1)``:

- ``ingress_traffic``: per-node requested traffic of each chain's *first* SF
  (simulator_wrapper.py:205-212, 255-266).  The reference iterates SFCs and
  lets the last one win the dict write; we sum across SFCs (identical for the
  default single-SFC catalog; documented divergence for multi-SFC).
- ``node_load``: processed-traffic / node-capacity utilization, 1 where the
  node has zero capacity (simulator_wrapper.py:196-203, 268-281).
- ``node_cap``: max-normalized raw capacity (simulator_wrapper.py:216-221,
  283-292).

Flat mode concatenates the selected vectors (simulator_wrapper.py:223-230);
the reference sizes them by the *real* node count — here they are padded to
MAX_NODES with zeros so shapes stay static.  Graph mode returns the node
feature matrix + directed edge index + the flattened action mask, the pytree
analogue of the torch-geometric ``Data`` (simulator_wrapper.py:294-308).
"""
from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp
import numpy as np
from flax import struct

from ..topology.compiler import Topology
from .actions import action_mask


@struct.dataclass
class GraphObs:
    """Graph observation (reference: torch-geometric Data with x, edge_index,
    mask — simulator_wrapper.py:294-308)."""

    nodes: jnp.ndarray       # [N, F] node features
    node_mask: jnp.ndarray   # [N] bool (padding made explicit)
    edge_index: jnp.ndarray  # [2, 2E] directed (both ways per undirected edge)
    edge_mask: jnp.ndarray   # [2E] bool
    mask: jnp.ndarray        # [A] flattened action mask


def _maxnorm(x: jnp.ndarray) -> jnp.ndarray:
    return jnp.clip(x / (jnp.max(x) + 1e-3), 0.0, 1.0)


def node_features(metrics, topo: Topology, node_cap_now: jnp.ndarray,
                  chain_sf: np.ndarray, observation_space: Tuple[str, ...],
                  ingress_override: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N, F] feature matrix with F = len(observation_space), columns in the
    configured order (sample_agent.yaml:6-9).  ``ingress_override`` replaces
    the observed ingress traffic (the traffic predictor overwriting the
    requested-traffic metric, traffic_predictor.py:28-56)."""
    cols = []
    for comp in observation_space:
        if comp == "ingress_traffic":
            if ingress_override is not None:
                ing = ingress_override
            else:
                ing = jnp.zeros_like(node_cap_now)
                for c in range(chain_sf.shape[0]):
                    # run_requested is position-indexed; chain entry point
                    # is position 0
                    ing = ing + metrics.run_requested[:, c, 0]
            cols.append(_maxnorm(ing))
        elif comp == "node_load":
            usage = metrics.run_processed_traffic.sum(axis=-1)
            util = jnp.where(node_cap_now > 0, usage / jnp.maximum(node_cap_now, 1e-30), 1.0)
            util = jnp.where(topo.node_mask, util, 0.0)
            cols.append(_maxnorm(util))
        elif comp == "node_cap":
            cols.append(_maxnorm(jnp.where(topo.node_mask, node_cap_now, 0.0)))
        else:  # validated at config load; defensive
            raise ValueError(f"Unknown observation component {comp!r}")
    return jnp.stack(cols, axis=-1)


def flat_obs(metrics, topo: Topology, node_cap_now: jnp.ndarray,
             chain_sf: np.ndarray, observation_space: Tuple[str, ...],
             ingress_override: jnp.ndarray | None = None) -> jnp.ndarray:
    """[N * F] concatenation of the selected vectors
    (simulator_wrapper.py:223-230)."""
    feats = node_features(metrics, topo, node_cap_now, chain_sf,
                          observation_space, ingress_override)
    return feats.T.reshape(-1)


def graph_obs(metrics, topo: Topology, node_cap_now: jnp.ndarray,
              chain_sf: np.ndarray, observation_space: Tuple[str, ...],
              num_sfcs: int, max_sfs: int,
              ingress_override: jnp.ndarray | None = None) -> GraphObs:
    feats = node_features(metrics, topo, node_cap_now, chain_sf,
                          observation_space, ingress_override)
    edge_index, edge_mask = topo.directed_edge_index()
    return GraphObs(
        nodes=jnp.where(topo.node_mask[:, None], feats, 0.0),
        node_mask=topo.node_mask,
        edge_index=edge_index,
        edge_mask=edge_mask,
        mask=action_mask(topo.node_mask, num_sfcs, max_sfs),
    )
