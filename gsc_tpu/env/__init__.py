"""RL environment layer (reference: src/rlsp/envs/)."""
from .actions import (
    action_mask,
    action_to_schedule,
    derive_placement,
    post_process_action,
)
from .driver import EpisodeDriver
from .env import EnvState, ServiceCoordEnv
from .observations import GraphObs, flat_obs, graph_obs
from .rewards import compute_reward, reward_constants

__all__ = [
    "action_mask", "action_to_schedule", "derive_placement",
    "post_process_action", "EpisodeDriver", "EnvState", "ServiceCoordEnv",
    "GraphObs", "flat_obs", "graph_obs", "compute_reward", "reward_constants",
]
