"""Reward objectives — pure jnp, vmap-able.

Reference: src/rlsp/envs/gym_env.py:223-380.  Four objectives
(src/rlsp/utils/constants.py:3):

- ``prio-flow``: flow reward first; delay only counts once the success ratio
  meets the target (or 0.9x the EWMA of past success when target='auto',
  gym_env.py:310-323 with EWMA update at gym_env.py:83-91).
- ``soft-deadline``: meet the delay deadline first, then optimize flow
  success with the delay term frozen (gym_env.py:325-334).
- ``soft-deadline-exp``: utility U = succ_ratio * U_d(delay) with
  log-exponential dropoff past the deadline (gym_env.py:336-355).
- ``weighted``: configured linear combination of all four components
  (gym_env.py:357-362).

Components (all in [-1, 1]):
- flow reward (succ - drop)/(succ + drop) over the last control interval
  (gym_env.py:223-234)
- delay reward 1 + (min_delay - delay)/diameter, clipped; -1 when no flow
  succeeded (gym_env.py:236-250); min_delay = sum of VNF processing means
  (gym_env.py:93-101); diameter hard-coded 15 (gym_env.py:56)
- shaped node reward counting a node as 0.5..1 used by its placed-SF count
  (gym_env.py:268-285)
- instance reward by total placed instances (gym_env.py:287-298)
"""
from __future__ import annotations

from typing import Dict, Tuple

import jax.numpy as jnp

from ..config.schema import AgentConfig


def reward_constants(agent: AgentConfig, proc_delay_means) -> Tuple[float, float]:
    """(min_delay, network_diameter).  min_delay = sum of VNF delay means
    (gym_env.py:93-101); the diameter is the reference's hard-coded 15
    (gym_env.py:56)."""
    return float(sum(proc_delay_means)), 15.0


def compute_reward(agent: AgentConfig, metrics, placement: jnp.ndarray,
                   node_mask: jnp.ndarray, num_sfs: int, min_delay: float,
                   diameter: float, ewma_flows: jnp.ndarray
                   ) -> Tuple[jnp.ndarray, jnp.ndarray, Dict[str, jnp.ndarray]]:
    """-> (total_reward, new_ewma_flows, info).

    placement: the *derived* [N, S] placement (only SFs reachable by traffic),
    which is what the reference's simulator state reports back
    (simulator_wrapper.py:161-167 -> siminterface/simulator.py sf_placement).
    """
    succ = metrics.run_processed.astype(jnp.float32)
    drop = metrics.run_dropped.astype(jnp.float32)
    total = succ + drop
    succ_ratio = jnp.where(total > 0, succ / jnp.maximum(total, 1.0), 0.0)
    flow_reward = jnp.where(total > 0, (succ - drop) / jnp.maximum(total, 1.0), 0.0)

    delay = jnp.maximum(metrics.run_avg_e2e(), min_delay)
    delay_reward = jnp.clip((min_delay - delay) / diameter + 1.0, -1.0, 1.0)
    delay_reward = jnp.where(succ_ratio == 0, -1.0, delay_reward)

    # shaped node usage: 0.5 + 0.5 * (k-1)/(num_sfs-1) per node with k>=1
    # placed SFs (gym_env.py:268-285)
    num_nodes = node_mask.sum().astype(jnp.float32)
    k = placement.astype(jnp.float32).sum(axis=-1)
    frac = jnp.where(
        k > 0, 0.5 + 0.5 * (k - 1.0) / jnp.maximum(num_sfs - 1.0, 1.0), 0.0)
    nodes_used = jnp.where(node_mask, frac, 0.0).sum()
    nodes_reward = 2.0 * (-nodes_used / jnp.maximum(num_nodes, 1.0)) + 1.0

    num_instances = placement.astype(jnp.float32).sum()
    instance_reward = 2.0 * (-num_instances / jnp.maximum(num_nodes * num_sfs, 1.0)) + 1.0

    new_ewma = ewma_flows
    if agent.objective == "prio-flow":
        nodes_reward = jnp.zeros(())
        instance_reward = jnp.zeros(())
        if agent.target_success == "auto":
            target = 0.9 * ewma_flows
            new_ewma = 0.5 * succ_ratio + 0.5 * ewma_flows  # gym_env.py:83-91
        else:
            target = jnp.asarray(float(agent.target_success))
        delay_reward = jnp.where(succ_ratio < target, -1.0, delay_reward)
    elif agent.objective == "soft-deadline":
        nodes_reward = jnp.zeros(())
        instance_reward = jnp.zeros(())
        met = delay <= agent.soft_deadline
        flow_reward = jnp.where(met, flow_reward, -1.0)
        delay_reward = jnp.where(
            met, jnp.clip(-agent.soft_deadline / diameter, -1.0, 1.0),
            delay_reward)
    elif agent.objective == "soft-deadline-exp":
        flow_reward = jnp.zeros(())
        nodes_reward = jnp.zeros(())
        instance_reward = jnp.zeros(())
        over = jnp.maximum(delay - agent.soft_deadline, 1e-30)
        delay_utility = jnp.where(
            delay > agent.soft_deadline,
            jnp.clip(-jnp.log10(over / agent.dropoff), 0.0, 1.0), 1.0)
        delay_reward = succ_ratio * delay_utility
    elif agent.objective == "weighted":
        flow_reward = flow_reward * agent.flow_weight
        delay_reward = delay_reward * agent.delay_weight
        nodes_reward = nodes_reward * agent.node_weight
        instance_reward = instance_reward * agent.instance_weight
    # objective validity enforced at config load (schema.py)

    total_reward = flow_reward + delay_reward + nodes_reward + instance_reward
    info = {
        "succ_ratio": succ_ratio,
        "avg_e2e_delay": delay,
        "flow_reward": flow_reward,
        "delay_reward": delay_reward,
        "nodes_reward": nodes_reward,
        "instance_reward": instance_reward,
    }
    return total_reward, new_ewma, info
