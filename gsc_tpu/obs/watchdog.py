"""Pipeline watchdog: a heartbeat monitor over the asynchronous episode
pipeline.

The pipelined trainer can hang in ways a log file never shows: the
prefetcher thread deadlocks on a full queue, a device call faults and the
drain blocks forever, host sampling livelocks.  The watchdog polls the
hub's ``episode`` heartbeat (beaten after every drained episode) and, when
no episode completes within the wall budget, emits ONE structured
``stall`` event carrying the last pipeline phase entered/completed, the
dispatch→drain lag, every component's heartbeat age, and any registered
probes (prefetch queue depth, thread liveness).  It re-arms after the next
completed episode, so an intermittent stall produces one event per
occurrence rather than a flood.

The thread is a daemon and holds no JAX state — it can never wedge the
device or outlive the process.
"""
from __future__ import annotations

import threading
from typing import Callable, Dict, Optional

from .hub import MetricsHub


class PipelineWatchdog:
    """Emits ``stall`` events when the ``episode`` heartbeat goes quiet.

    ``start_paused=True`` (the trainer wiring) keeps the monitor disarmed
    until :meth:`resume` — evaluation, checkpointing and other between-loop
    work must not count against the episode wall budget.
    """

    def __init__(self, hub: MetricsHub, budget_s: float,
                 beat_name: str = "episode",
                 poll_s: Optional[float] = None,
                 start_paused: bool = False,
                 escalate_after: int = 0,
                 on_escalate: Optional[Callable[[float], None]] = None,
                 on_blackbox: Optional[Callable[[str, float],
                                               None]] = None):
        if budget_s <= 0:
            raise ValueError(f"watchdog budget must be > 0, got {budget_s}")
        self.hub = hub
        self.budget_s = float(budget_s)
        self.beat_name = beat_name
        # escalation (resilience): after the first stall, ``escalate_after``
        # MORE full budget periods of continued silence move the watchdog
        # from reporting to acting — ``on_escalate(age_s)`` fires ONCE per
        # stall episode (re-armed with the stall flag by the next
        # heartbeat).  The trainer wires a prefetcher interrupt/restart
        # into it; 0 disables escalation (report-only, the PR 2 behavior).
        self.escalate_after = max(int(escalate_after), 0)
        self.on_escalate = on_escalate
        # black-box hook (flight recorder): ``on_blackbox(thread, age_s)``
        # fires once per escalation — the RunObserver wires its
        # post-mortem dump here so a wedged fleet leaves blackbox.json,
        # not just a stall line in a stream nobody can read back
        self.on_blackbox = on_blackbox
        self._escalated = False
        # fleet coverage: extra per-thread heartbeats (actor0..N, the
        # learner) watched alongside the main beat.  Each carries its own
        # stall/escalation state so one wedged actor re-arms
        # independently of a healthy learner.  name -> state dict
        self._watched: Dict[str, Dict] = {}   # guarded-by: self._watched_lock
        self._watched_lock = threading.Lock()
        # poll fast enough to flag a stall well inside one extra budget
        # interval, but never busier than 4 Hz
        self.poll_s = poll_s if poll_s is not None else max(
            min(self.budget_s / 4.0, 1.0), 0.25)
        self._probes: Dict[str, Callable[[], object]] = {}
        self._stop = threading.Event()
        self._paused = threading.Event()
        if start_paused:
            self._paused.set()
        self._stalled = False
        self._stalled_at_beat: Optional[float] = None
        self.stall_count = 0
        self._thread = threading.Thread(target=self._run,
                                        name="gsc-pipeline-watchdog",
                                        daemon=True)

    # ------------------------------------------------------------ control
    def register_probe(self, name: str, fn: Callable[[], object]):
        """Attach a diagnostic callable whose value is included in stall
        events (e.g. prefetch queue depth)."""
        self._probes[name] = fn

    def watch_thread(self, name: str, budget_s: Optional[float] = None):
        """Watch one more per-thread heartbeat (fleet coverage: actors,
        the learner).  The thread must ``hub.beat(name)`` at its own
        cadence; when it goes quiet past ``budget_s`` (default: the main
        budget) ONE ``stall`` event fires naming the thread and the
        phase ``hub.note_thread_phase`` last recorded for it — so a
        wedged actor reads as ``actor1 stuck in blocked_put``, not as an
        anonymous missed episode.  Re-arms on the thread's next beat."""
        self.hub.beat(name)   # arm from registration, like start()
        with self._watched_lock:
            self._watched[name] = {
                "budget_s": float(budget_s) if budget_s else self.budget_s,
                "stalled": False, "stalled_at_beat": None,
                "escalated": False}

    def unwatch_thread(self, name: str):
        with self._watched_lock:
            self._watched.pop(name, None)

    def unwatch_all_threads(self):
        with self._watched_lock:
            self._watched.clear()

    def start(self):
        self.hub.beat(self.beat_name)   # arm: age measured from start
        self._thread.start()
        return self

    def resume(self):
        """Arm the monitor (trainer entering its episode loop).  Beats once
        so paused time never counts toward the budget."""
        self.hub.beat(self.beat_name)
        self._stalled = False
        self._escalated = False
        self._stalled_at_beat = None
        with self._watched_lock:
            for name, st in self._watched.items():
                # paused time never counts toward any thread's budget
                self.hub.beat(name)
                st["stalled"] = False
                st["escalated"] = False
                st["stalled_at_beat"] = None
        self._paused.clear()

    def pause(self):
        """Disarm (trainer left the episode loop)."""
        self._paused.set()

    def stop(self):
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5.0)

    # --------------------------------------------------------------- loop
    def _run(self):
        while not self._stop.wait(self.poll_s):
            if self._paused.is_set():
                continue
            age = self.hub.beat_age(self.beat_name)
            if age is None:
                continue
            # re-arm on any heartbeat NEWER than the one the last stall
            # was declared against — comparing timestamps (not current
            # age) means a short recovery between two stalls re-arms even
            # when no poll tick happens to land inside it
            if self._stalled and \
                    self.hub.beat_time(self.beat_name) != self._stalled_at_beat:
                self._stalled = False
                self._escalated = False
            if age > self.budget_s and not self._stalled:
                self._stalled = True
                self._stalled_at_beat = self.hub.beat_time(self.beat_name)
                self.stall_count += 1
                self._emit_stall(age)
            if (self._stalled and not self._escalated
                    and self.escalate_after > 0
                    and age > self.budget_s * (1 + self.escalate_after)):
                self._escalated = True
                self._escalate(age)
            self._poll_watched()

    def _poll_watched(self):
        """One pass over the fleet's per-thread heartbeats: stall events
        name the quiet thread + its last phase; continued silence past
        the escalation horizon triggers the black-box dump (once per
        stall episode, per thread)."""
        with self._watched_lock:
            watched = list(self._watched.items())
        for name, st in watched:
            age = self.hub.beat_age(name)
            if age is None:
                continue
            beat = self.hub.beat_time(name)
            if st["stalled"] and beat != st["stalled_at_beat"]:
                st["stalled"] = False
                st["escalated"] = False
            if age > st["budget_s"] and not st["stalled"]:
                st["stalled"] = True
                st["stalled_at_beat"] = beat
                self.stall_count += 1
                self._emit_thread_stall(name, age, st["budget_s"])
            if (st["stalled"] and not st["escalated"]
                    and age > st["budget_s"] * (1 + max(
                        self.escalate_after, 1))):
                st["escalated"] = True
                self._blackbox(name, age)

    def _emit_thread_stall(self, name: str, age: float, budget_s: float):
        fields: Dict[str, object] = {
            "thread": name,
            "age_s": round(age, 3),
            "budget_s": budget_s,
            "last_phase": self.hub.thread_phase(name),
            "heartbeats": self.hub.beat_ages(),
            "thread_phases": self.hub.thread_phases(),
        }
        for pname, fn in self._probes.items():
            try:
                fields[pname] = fn()
            except Exception as e:
                fields[pname] = f"probe-error: {e!r}"
        self.hub.counter("stalls")
        self.hub.counter("thread_stalls", thread=name)
        self.hub.event("stall", **fields)

    def _blackbox(self, thread: str, age: float):
        cb = self.on_blackbox
        self.hub.counter("blackbox_dumps")
        if cb is not None:
            try:
                cb(thread, age)
            except Exception as e:   # the dump failing must not kill the
                # monitor — the stall evidence is already in the stream
                self.hub.event("blackbox_error", thread=thread,
                               error=repr(e))

    def _escalate(self, age: float):
        """The stall outlived ``escalate_after`` extra budget periods: act.
        The callback runs on this (watchdog) thread and must only poke
        thread-safe handles — the trainer's hook interrupts the prefetcher
        queue, and the training loop does the actual restart."""
        cb = self.on_escalate
        self.hub.counter("watchdog_escalations")
        self.hub.event(
            "escalation", age_s=round(age, 3), budget_s=self.budget_s,
            quiet_periods=self.escalate_after + 1,
            action="callback" if cb is not None else "none")
        if cb is not None:
            try:
                cb(age)
            except Exception as e:   # an escalation that faults must not
                # kill the monitor thread — the stall evidence survives
                self.hub.event("escalation_error", error=repr(e))
        # the main pipeline going quiet past its escalation horizon is a
        # post-mortem moment too — same dump the wedged-thread path gets
        self._blackbox(self.beat_name, age)

    def _emit_stall(self, age: float):
        phase, done = self.hub.last_phase
        fields: Dict[str, object] = {
            "age_s": round(age, 3),
            "budget_s": self.budget_s,
            "last_phase": phase,
            "last_phase_state": "completed" if done else "running",
            "episodes_dispatched": self.hub.get_counter(
                "episodes_dispatched"),
            "episodes_drained": self.hub.get_counter("episodes_drained"),
            "heartbeats": self.hub.beat_ages(),
        }
        fields["dispatch_drain_lag"] = (
            fields["episodes_dispatched"] - fields["episodes_drained"])
        if fields["episodes_drained"] == 0:
            # a genuinely overdue FIRST episode still deserves the event
            # (that hang is invisible otherwise), but on a cold compile
            # cache the first fused dispatch's XLA compile can dominate
            # this interval — say so instead of crying wolf
            fields["note"] = ("no episode has completed yet — a cold "
                              "first-dispatch compile can dominate this "
                              "interval")
        for name, fn in self._probes.items():
            try:
                fields[name] = fn()
            except Exception as e:   # a dead probe is itself a diagnostic
                fields[name] = f"probe-error: {e!r}"
        self.hub.counter("stalls")
        self.hub.event("stall", **fields)
