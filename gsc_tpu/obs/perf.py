"""Device-cost ledger — compile-time FLOPs/bytes/fusions per entry point.

The round-5 MFU/roofline table that proved the substep regime (op-count
bound, ~100x above the HBM roof) was assembled BY HAND from one-off
scripts and went stale the moment it landed in BENCH_NOTES.  This module
makes that evidence a per-run artifact: every watched jitted entry point
(``episode_step``, ``chunk_step``, ``learn_burst``,
``serve_policy_b<B>``) is AOT-lowered once at setup time and its
``Compiled`` object mined for

- XLA's own cost model (``compiled.cost_analysis()``): FLOPs and bytes
  accessed per call;
- HLO structure (:mod:`gsc_tpu.analysis.hlo`): fusion count — the
  op-count perf proxy the megakernel campaign gates on — plus a small
  op histogram (while/dot/scatter/gather) and the collective-op stats
  (all-reduce/all-gather/reduce-scatter count + payload bytes) that
  make the ``tp``-vs-``sharded`` interconnect comparison machine-read
  (on a sharded dispatch the trainer additionally captures the
  PARTITIONED executable as ``<entry>_sharded`` — the plain entry stays
  the carving-comparable number);
- executable memory residency (``compiled.memory_analysis()``).

Wall timings arrive separately via :meth:`CostLedger.note_timing` — fed
from the trainer's **existing deferred drains** (PhaseTimer totals) and
the serve latency histograms, so the ledger adds ZERO host syncs to the
dispatch path (the ``no_host_sync`` sentinel contract: everything here
happens before the episode loop or after it, never inside a dispatch).

Combining the two yields per-dispatch achieved FLOP/s, MFU against a
per-backend peak envelope, and the roofline position (arithmetic
intensity vs the ridge point, attainable-roof multiple).  The whole
ledger serializes as a schema-versioned ``perf.json`` next to
``metrics.json`` (``RunObserver.close`` writes it), each capture also
emitting one structured ``compile_cost`` event into events.jsonl.

CPU-backend caveat: XLA's CPU cost model still reports flops/bytes, but
the peak envelope is an order-of-magnitude placeholder — MFU numbers on
CPU are for run-over-run comparison (tools/bench_diff.py tolerance
bands), not absolute utilization claims.  Rows record the backend so a
reader can never mistake one for the other.
"""
from __future__ import annotations

import functools
import logging
import time
from typing import Dict, Optional

from ..analysis.hlo import collective_stats, count_fusions, op_histogram

log = logging.getLogger("gsc_tpu.obs.perf")

# bump on any breaking change to the perf.json layout; readers
# (tools/obs_report.py, tools/bench_diff.py) key on it
PERF_SCHEMA_VERSION = 1

# peak envelopes per backend platform for MFU/roofline.  TPU row is the
# v4 datasheet (275 TFLOP/s bf16 MXU, 1.2 TB/s HBM); GPU a generic A100
# class; CPU an honest single-core order-of-magnitude placeholder (this
# box) — see the module docstring's caveat.  Override per-run with
# ``CostLedger(peak_flops=..., peak_bytes_per_s=...)`` when the hardware
# is known more precisely.
PEAK_ENVELOPES = {
    "tpu": {"flops_per_s": 275e12, "bytes_per_s": 1.2e12},
    "gpu": {"flops_per_s": 312e12, "bytes_per_s": 2.0e12},
    "cpu": {"flops_per_s": 5e10, "bytes_per_s": 2e10},
}

# ops worth a per-entry histogram next to the fusion count: `while` is
# the serial-scatter tell on CPU, `dot` the MXU share, scatter/gather
# the layout-sensitive movers (analysis/hlo.py docstrings)
_OP_HISTOGRAM = ("while", "dot", "scatter", "gather")


def _unwrap_partial(fn, args, kwargs):
    """Peel ``functools.partial`` layers (the ``donated_jit`` wrapper
    shape: ``partial(jit(fn, ...), bound_self)``) down to the jit object,
    folding the partial's bound arguments in front of the caller's."""
    while isinstance(fn, functools.partial):
        args = tuple(fn.args) + tuple(args)
        kwargs = {**fn.keywords, **kwargs}
        fn = fn.func
    return fn, args, kwargs


def resolve_lowerable(owner, name: str):
    """(fn, prefix_args) for capturing entry point ``name`` on ``owner``
    (a DDPG/ParallelDDPG): the instance attribute when it unwraps to a
    lowerable jit — the ``donated_jit`` partial, i.e. the EXECUTABLE
    actually dispatched, whose backend compile seeds the persistent
    cache for the first real dispatch — else the class-level jit with
    the owner passed explicitly (``donate=False``, where the class jit
    IS the dispatched program, and the sharded-plan wrappers, where the
    unsharded class jit is the carving-comparable stand-in).  The single
    resolver behind Trainer and bench.py capture sites, so the
    donated-wrapper shape is interpreted in exactly one place."""
    fn = owner.__dict__.get(name)
    inner = fn
    while isinstance(inner, functools.partial):
        inner = inner.func
    if fn is not None and hasattr(inner, "lower"):
        return fn, ()
    return getattr(type(owner), name), (owner,)


def _cost_dict(compiled) -> Dict[str, float]:
    """Flatten ``compiled.cost_analysis()`` (dict, or list-of-dict on
    older jaxlibs) to one ``{metric: value}`` dict."""
    cost = compiled.cost_analysis()
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    return dict(cost or {})


class CostLedger:
    """Per-run compile-time cost ledger + wall-timing merge.

    ``hub`` (a :class:`~gsc_tpu.obs.MetricsHub`) is optional; with one,
    every capture emits a ``compile_cost`` event.  Capture failures are
    recorded (``{"available": False, "error": ...}``) and logged, never
    raised — a missing cost model must not fail a training run.
    """

    def __init__(self, hub=None, backend: Optional[str] = None,
                 peak_flops: Optional[float] = None,
                 peak_bytes_per_s: Optional[float] = None):
        self.hub = hub
        self._backend = backend          # resolved lazily (needs jax)
        self._peak_flops = peak_flops
        self._peak_bw = peak_bytes_per_s
        self._entries: Dict[str, Dict] = {}
        self._timings: Dict[str, Dict[str, float]] = {}
        self._phases: Dict[str, Dict[str, float]] = {}

    # ------------------------------------------------------------- backend
    def backend(self) -> str:
        if self._backend is None:
            try:
                import jax
                self._backend = jax.default_backend()
            except Exception:
                self._backend = "unknown"
        return self._backend

    def peaks(self) -> Dict[str, float]:
        env = PEAK_ENVELOPES.get(self.backend(), PEAK_ENVELOPES["cpu"])
        return {"flops_per_s": self._peak_flops or env["flops_per_s"],
                "bytes_per_s": self._peak_bw or env["bytes_per_s"]}

    # ------------------------------------------------------------- capture
    def has(self, name: str) -> bool:
        return name in self._entries

    def capture(self, name: str, fn, args=(), kwargs=None,
                recapture: bool = False) -> Optional[Dict]:
        """AOT-lower ``fn`` (a jit object, possibly wrapped in
        ``functools.partial``) on ``args``/``kwargs`` and record its
        static cost.  Arguments may be live arrays OR
        ``jax.ShapeDtypeStruct``s — lowering never executes the program,
        so donated buffers are safe to pass.  Idempotent per name unless
        ``recapture``."""
        if self.has(name) and not recapture:
            return self._entries[name]
        kwargs = dict(kwargs or {})
        t0 = time.perf_counter()
        try:
            fn, args, kwargs = _unwrap_partial(fn, args, kwargs)
            compiled = fn.lower(*args, **kwargs).compile()
            entry = self.capture_compiled(name, compiled)
            entry["capture_s"] = round(time.perf_counter() - t0, 3)
            return entry
        except Exception as e:  # noqa: BLE001 - observability must not kill
            log.warning("cost-ledger capture of %r failed: %s: %s",
                        name, type(e).__name__, e)
            self._entries[name] = {"available": False,
                                   "error": f"{type(e).__name__}: {e}"}
            return self._entries[name]

    def capture_compiled(self, name: str, compiled) -> Dict:
        """Record an already-compiled ``jax.stages.Compiled`` (the serve
        path holds one per bucket after warmup)."""
        cost = _cost_dict(compiled)
        hlo = ""
        try:
            hlo = compiled.as_text()
        except Exception:   # backends without HLO text access
            pass
        entry: Dict = {
            "available": True,
            "flops": float(cost.get("flops", 0.0)),
            "bytes_accessed": float(cost.get("bytes accessed", 0.0)),
            "fusions": count_fusions(hlo) if hlo else None,
            "ops": op_histogram(hlo, _OP_HISTOGRAM) if hlo else {},
            # cross-device movers (all-reduce/all-gather/reduce-scatter
            # ... count + payload bytes per call): 0/{} on single-device
            # programs; on a partitioned executable this is the
            # machine-read side of the tp-vs-sharded interconnect claim
            "collectives": (collective_stats(hlo) if hlo
                            else {"ops": {}, "count": 0, "bytes": 0}),
        }
        if entry["flops"] and entry["bytes_accessed"]:
            entry["arithmetic_intensity"] = round(
                entry["flops"] / entry["bytes_accessed"], 4)
        try:
            mem = compiled.memory_analysis()
            entry["memory"] = {
                "argument_bytes": int(mem.argument_size_in_bytes),
                "output_bytes": int(mem.output_size_in_bytes),
                "temp_bytes": int(mem.temp_size_in_bytes),
            }
        except Exception:
            pass
        self._entries[name] = entry
        if self.hub is not None:
            self.hub.event("compile_cost", fn=name,
                           flops=entry["flops"],
                           bytes_accessed=entry["bytes_accessed"],
                           fusions=entry["fusions"],
                           ops=entry["ops"],
                           collectives=entry["collectives"])
            if entry["fusions"] is not None:
                self.hub.gauge("compile_fusions", entry["fusions"], fn=name)
        return entry

    # ------------------------------------------------------------- timings
    def note_timing(self, name: str, total_s: float, count: int):
        """Merge host-wall attribution for ``name``'s dispatches —
        sourced from the trainer's PhaseTimer totals / the serve latency
        histograms AFTER the run, never from inside the dispatch path."""
        if count <= 0:
            return
        self._timings[name] = {"total_s": round(float(total_s), 6),
                               "count": int(count)}

    def note_phases(self, phases: Dict[str, Dict[str, float]]):
        """Attach the run's cumulative PhaseTimer summary (the
        device-vs-host time split obs_report renders)."""
        self._phases = dict(phases or {})

    # ------------------------------------------------------------- summary
    def _derived(self, entry: Dict, timing: Optional[Dict]) -> Dict:
        """MFU + roofline position from static cost x measured wall."""
        out = dict(entry)
        if timing:
            out["dispatches"] = timing["count"]
            out["wall_s_total"] = timing["total_s"]
            mean_s = timing["total_s"] / max(timing["count"], 1)
            out["wall_s_mean"] = round(mean_s, 6)
            peaks = self.peaks()
            if entry.get("available") and entry.get("flops") and mean_s > 0:
                achieved = entry["flops"] / mean_s
                out["achieved_flops_per_s"] = round(achieved, 1)
                out["mfu"] = round(achieved / peaks["flops_per_s"], 6)
                bytes_a = entry.get("bytes_accessed") or 0.0
                if bytes_a:
                    bw = bytes_a / mean_s
                    out["achieved_bytes_per_s"] = round(bw, 1)
                    out["bw_util"] = round(bw / peaks["bytes_per_s"], 6)
                    intensity = entry["flops"] / bytes_a
                    ridge = peaks["flops_per_s"] / peaks["bytes_per_s"]
                    attainable = min(peaks["flops_per_s"],
                                     intensity * peaks["bytes_per_s"])
                    out["roofline"] = {
                        "intensity": round(intensity, 4),
                        "ridge": round(ridge, 4),
                        "regime": ("memory_bound" if intensity < ridge
                                   else "compute_bound"),
                        # how far BELOW the attainable roof the measured
                        # rate sits (>=1; the round-5 table's "~100x
                        # above the HBM roof" phrasing, inverted to a
                        # stable ratio)
                        "roof_multiple": round(
                            attainable / max(achieved, 1e-30), 1),
                    }
        return out

    def entry(self, name: str) -> Optional[Dict]:
        e = self._entries.get(name)
        if e is None:
            return None
        return self._derived(e, self._timings.get(name))

    def summary(self) -> Dict:
        """The full schema-versioned perf document."""
        return {
            "schema_version": PERF_SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "backend": self.backend(),
            "peaks": self.peaks(),
            "run": (self.hub.base_tags.get("run")
                    if self.hub is not None else None),
            "entries": {name: self._derived(e, self._timings.get(name))
                        for name, e in self._entries.items()},
            "phases": self._phases,
        }

    def write_json(self, path: str) -> str:
        """Atomic ``perf.json`` write (same contract as metrics.json).
        Named ``write_json`` rather than ``write`` on purpose: traced
        code paths call file ``.write()`` constantly, and gsc-lint's
        name-graph would fuse a method named ``write`` into the jit
        cone."""
        from .sinks import write_atomic_json
        return write_atomic_json(path, self.summary())
