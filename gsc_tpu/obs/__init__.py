"""Run observability: metrics hub, event stream, device gauges, watchdog.

The training loop's only window used to be a ``PhaseTimer`` dict printed at
loop end — no way to see a stall, a leaking HBM buffer, or a collapsing
reward curve *while* a long run is in flight, and no machine-readable
record to compare runs afterward.  Podracer (arXiv:2104.06272) and
MindSpeed RL (arXiv:2507.19017) both treat per-component throughput /
utilization telemetry as a first-class requirement for keeping accelerator
pipelines honest; this package is that substrate:

- :class:`MetricsHub` — process-wide counters / gauges / histograms,
  tagged by run/replica, thread-safe (the prefetcher and watchdog threads
  write into it concurrently with the training loop).
- :class:`JsonlSink` — per-run ``events.jsonl``: one structured record per
  episode (SPS, per-phase host timings, learner losses/grad-norms, sim
  drop-reason totals, truncated-arrival counts, replay-buffer bytes,
  device memory) plus ``run_start`` / ``stall`` / ``invariant_violation``
  / ``run_end`` records.
- :func:`write_atomic_json` — ``metrics.json`` snapshot exposition,
  rewritten atomically every N episodes with Prometheus-text-style flat
  names so external scrapers/tail tools can poll a live run.
- :mod:`~gsc_tpu.obs.device` — HBM gauges from
  ``jax.local_devices()[*].memory_stats()`` sampled each drain.
- :class:`PipelineWatchdog` — heartbeats the prefetcher thread and the
  dispatch→drain lag; emits a structured ``stall`` event when no episode
  finishes within a wall budget.
- :mod:`~gsc_tpu.obs.trace` — ``jax.profiler`` annotations so ``--profile``
  traces attribute device time to pipeline phases.
- :class:`CostLedger` (:mod:`~gsc_tpu.obs.perf`) — compile-time
  FLOPs/bytes/fusion counts per watched entry point merged with the
  drained wall timings into per-dispatch MFU and roofline position;
  serialized as the schema-versioned per-run ``perf.json``
  (``tools/bench_diff.py`` diffs them across runs).
- :class:`LearnLedger` (:mod:`~gsc_tpu.obs.learning`) — the on-device
  learning-signal ledger: per-topology |TD-error| segments, Q-value
  distribution moments, per-layer param/grad norms and replay fill/age
  computed INSIDE the dispatched programs and drained with the deferred
  metric drain (zero new host syncs), landing as ``learn_signal`` events
  + tagged gauges.
- :class:`MetricsEndpoint` (:mod:`~gsc_tpu.obs.endpoint`) — live
  ``/metrics`` HTTP endpoint (stdlib, Prometheus text exposition) over
  the hub snapshot, so long runs are scrapeable while they execute.
- :class:`SLOEngine` / :class:`ServeTracer` (:mod:`~gsc_tpu.obs.slo`) —
  the serving-tier currency: per-request span tracing (queue-wait /
  batch-wait / device / fan-out decomposition of ``serve_latency_ms``,
  head-sampled ``serve_request_span`` events, deferred off the flush
  path) and declarative latency SLOs (rolling attainment, error-budget
  burn rate, deadline-miss ratio, arrival-rate EWMA, pad waste) folded
  into ``serve_stats``, ``/metrics`` and the per-run ``slo.json``.
- :mod:`~gsc_tpu.obs.curves` — per-run learning-curve extraction:
  events.jsonl -> schema-versioned ``curves.json`` whose summary metrics
  (final-window return, AUC, episodes-to-threshold)
  ``tools/bench_diff.py`` gates under tolerance bands.
- :class:`RunObserver` — the facade the trainer/CLI wire through.  It
  also owns a per-run retrace sentinel
  (:class:`gsc_tpu.analysis.sentinels.CompileMonitor`): jit traces / XLA
  compilations of watched entry points land as ``compile`` events in the
  same stream, so a retrace storm is attributable from run telemetry.

All later perf PRs report through this subsystem.
"""
from .curves import CURVES_SCHEMA_VERSION, extract_curves, write_curves
from .device import device_memory_snapshot, record_device_gauges
from .endpoint import MetricsEndpoint, prometheus_text
from .hub import MetricsHub
from .learning import LearnLedger, LearnLedgerSpec, emit_learn_signal
from .perf import PERF_SCHEMA_VERSION, CostLedger
from .run import RunObserver
from .series import (BLACKBOX_SCHEMA_VERSION, SERIES_SCHEMA_VERSION,
                     SeriesStore, write_blackbox, write_series)
from .sinks import (JsonlSink, ListSink, TailSink, rotated_paths,
                    write_atomic_json)
from .slo import (SLO_SCHEMA_VERSION, ServeTracer, SLOEngine,
                  SLOObjectives, parse_slo_spec, write_slo_json)
from .watchdog import PipelineWatchdog

__all__ = [
    "MetricsHub", "JsonlSink", "ListSink", "TailSink", "SeriesStore",
    "SERIES_SCHEMA_VERSION", "BLACKBOX_SCHEMA_VERSION", "write_series",
    "write_blackbox", "write_atomic_json",
    "rotated_paths", "device_memory_snapshot", "record_device_gauges",
    "PipelineWatchdog", "RunObserver", "CostLedger",
    "PERF_SCHEMA_VERSION", "LearnLedger", "LearnLedgerSpec",
    "emit_learn_signal", "MetricsEndpoint", "prometheus_text",
    "CURVES_SCHEMA_VERSION", "extract_curves", "write_curves",
    "SLO_SCHEMA_VERSION", "SLOEngine", "SLOObjectives", "ServeTracer",
    "parse_slo_spec", "write_slo_json",
]
