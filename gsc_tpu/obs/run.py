"""RunObserver — the facade one training run wires through.

Owns the hub, the ``events.jsonl`` sink, the ``metrics.json`` snapshot
cadence and the watchdog for a single run directory.  The trainer calls
:meth:`episode_dispatched` / :meth:`episode_end`; everything else (device
gauges, snapshot rewrites, heartbeats, stall monitoring) happens here so
the training loop stays readable.
"""
from __future__ import annotations

import logging
import os
from typing import Dict, List, Optional

from .device import record_device_gauges
from .hub import MetricsHub
from .sinks import JsonlSink, TailSink, write_atomic_json
from .watchdog import PipelineWatchdog

log = logging.getLogger("gsc_tpu.obs.run")

# phases whose per-episode wall deltas are worth percentile tracking
# (the last four are the async actor/learner ledger: actor-side rollout
# dispatch + backpressure wait, learner-side ingest + data wait)
_PHASE_HIST = ("host_sample", "host_sample_wait", "dispatch", "drain",
               "actor_dispatch", "actor_idle", "replay_ingest",
               "learner_idle")


class RunObserver:
    """Per-run observability: hub + JSONL events + atomic snapshots +
    watchdog, all rooted in one output directory."""

    def __init__(self, out_dir: str, run_id: Optional[str] = None,
                 snapshot_interval: int = 10,
                 watchdog_budget_s: float = 0.0,
                 tags: Optional[Dict[str, object]] = None,
                 compile_events: bool = True,
                 watchdog_escalate: int = 0,
                 rotate_mb: float = 0.0,
                 perf: bool = False,
                 learn: bool = False,
                 metrics_port: Optional[int] = None,
                 series_window: int = 0,
                 blackbox_window_s: float = 30.0):
        self.out_dir = os.path.abspath(out_dir)
        os.makedirs(self.out_dir, exist_ok=True)
        run_id = run_id or os.path.basename(self.out_dir.rstrip(os.sep))
        # flight recorder (``--obs-series-window``): bounded per-metric
        # time-series rings in the hub.  0 = off — series() no-ops, no
        # tail sink is attached, and the event stream stays byte-
        # identical to the history-free observer
        self.hub = MetricsHub(tags={"run": run_id, **(tags or {})},
                              series_window=series_window)
        self.events_path = os.path.join(self.out_dir, "events.jsonl")
        self.snapshot_path = os.path.join(self.out_dir, "metrics.json")
        self.perf_path = os.path.join(self.out_dir, "perf.json")
        self.curves_path = os.path.join(self.out_dir, "curves.json")
        self.series_path = os.path.join(self.out_dir, "series.json")
        self.blackbox_path = os.path.join(self.out_dir, "blackbox.json")
        # serving SLO summary (obs.slo): PolicyServer.close() writes it
        # when `cli serve` hands the server this path
        self.slo_path = os.path.join(self.out_dir, "slo.json")
        # size-based rotation for 100+-episode exhibits (``--obs-rotate-mb``)
        # — readers walk the rotated segments via sinks.rotated_paths
        self.hub.add_sink(JsonlSink(self.events_path, rotate_mb=rotate_mb))
        # black-box event tail: the last-N pending events a post-mortem
        # dump flushes when the fleet dies mid-write
        self.blackbox_window_s = float(blackbox_window_s)
        self._tail_sink = None
        if self.hub.series_store is not None:
            self._tail_sink = TailSink()
            self.hub.add_sink(self._tail_sink)
        # device-cost ledger (obs.perf.CostLedger): opt-in because each
        # captured entry point costs one extra AOT trace at setup time —
        # the CLI enables it by default (--perf), bare test observers
        # don't pay for it.  The trainer/server capture into it; close()
        # writes perf.json next to metrics.json.
        self.perf = None
        if perf:
            from .perf import CostLedger
            self.perf = CostLedger(hub=self.hub)
        # learning-signal ledger (obs.learning.LearnLedger): opt-in like
        # the cost ledger — the trainer reads the facade's static spec
        # into the jitted agents, drains per-episode signals through it,
        # and close() extracts curves.json from the event stream.  Bare
        # test observers stay ledger-free (historic traces untouched).
        self.learn = None
        if learn:
            from .learning import LearnLedger
            self.learn = LearnLedger(hub=self.hub)
        # live /metrics endpoint (obs.endpoint.MetricsEndpoint): None =
        # off; 0 = ephemeral port (tests); bound lazily in start()
        self._metrics_port = metrics_port
        self.endpoint = None
        self.snapshot_interval = max(int(snapshot_interval), 1)
        self.watchdog: Optional[PipelineWatchdog] = None
        if watchdog_budget_s and watchdog_budget_s > 0:
            # paused until the trainer enters its episode loop — eval /
            # checkpoint time between loops must not read as a pipeline
            # stall.  (First-dispatch jit compile happens INSIDE the loop
            # and does count: a stall with episodes_drained=0 carries a
            # note saying compile may dominate it.)
            # escalation (``watchdog_escalate`` extra quiet periods before
            # acting) stays report-only until the trainer installs its
            # ``on_escalate`` hook for the duration of the episode loop
            self.watchdog = PipelineWatchdog(
                self.hub, watchdog_budget_s, start_paused=True,
                escalate_after=watchdog_escalate,
                # a stall that outlives the escalation horizon flushes
                # the black-box dump — a dead fleet leaves a post-mortem
                on_blackbox=lambda thread, age: self.write_blackbox(
                    reason=f"watchdog_escalation:{thread}",
                    extra={"age_s": round(age, 3)}))
        # retrace sentinel (analysis.sentinels.CompileMonitor): counts jit
        # traces / XLA compiles per watched entry point and emits one
        # `compile` event per occurrence into events.jsonl — a retrace
        # storm shows up in run telemetry, not just in wall time.  Created
        # lazily in start() so constructing an observer never touches jax
        # logging config.
        self._compile_events = compile_events
        self.compile_monitor = None
        self._drained = 0
        self._prev_phase_totals: Dict[str, float] = {}
        self._started = False
        self._closed = False

    # ---------------------------------------------------------- lifecycle
    def start(self, meta: Optional[Dict] = None) -> "RunObserver":
        if self._started:
            return self
        self._started = True
        self.hub.event("run_start", **(meta or {}))
        if self._compile_events:
            from ..analysis.sentinels import CompileMonitor
            self.compile_monitor = CompileMonitor(hub=self.hub).start()
        if self.watchdog is not None:
            self.watchdog.start()
        if self._metrics_port is not None:
            # best effort: a taken port must not kill a training run —
            # the run keeps its on-disk snapshots either way
            from .endpoint import MetricsEndpoint
            try:
                self.endpoint = MetricsEndpoint(
                    self.hub, port=self._metrics_port).start()
                self.hub.event("metrics_endpoint",
                               port=self.endpoint.port,
                               url=self.endpoint.url)
            except OSError as e:
                log.warning("metrics endpoint not started on port %s: %s",
                            self._metrics_port, e)
                self.endpoint = None
        return self

    def close(self, status: str = "ok"):
        """Final snapshot + ``run_end`` event; idempotent."""
        if self._closed:
            return
        self._closed = True
        if self.watchdog is not None:
            self.watchdog.stop()
        if self.compile_monitor is not None:
            self.compile_monitor.stop()
        if self.endpoint is not None:
            self.endpoint.stop()
            self.endpoint = None
        try:
            if self.learn is not None:
                # learning-curve extraction from the run's own event
                # stream (rotation-aware) into schema-versioned
                # curves.json — best effort, like the perf ledger
                try:
                    from .curves import write_curves
                    from .trace import read_events
                    events = read_events(self.events_path)
                    if any(e.get("event") in ("episode", "harness_episode")
                           for e in events):
                        write_curves(self.curves_path, events)
                except Exception:
                    pass
            if self.perf is not None and self.perf.summary()["entries"]:
                # the per-run cost ledger lands next to metrics.json —
                # best effort, a cost-model failure must not mask the
                # run's own teardown
                try:
                    self.perf.write_json(self.perf_path)
                except Exception:
                    pass
            self.hub.event("run_end", status=status,
                           episodes=self._drained,
                           stalls=self.hub.get_counter("stalls"),
                           recoveries=self.hub.get_counter(
                               "recoveries_total"))
            if self.hub.series_store is not None:
                # whole-run history next to the snapshot — best effort,
                # like the perf/curves writers
                try:
                    from .series import write_series
                    write_series(self.series_path, self.hub.series_store,
                                 run=self.hub.base_tags.get("run"))
                except Exception:
                    pass
            if status not in ("ok", "preempted"):
                # a run dying on an exception leaves the same post-mortem
                # a wedged fleet does (the preempted path writes its own,
                # tagged with the signal, before the trainer returns)
                try:
                    self.write_blackbox(reason=f"run_end:{status}")
                except Exception:
                    pass
            self.write_snapshot()
        finally:
            self.hub.close()

    def __enter__(self):
        return self.start()

    def __exit__(self, exc_type, *exc):
        self.close(status="error" if exc_type else "ok")
        return False

    # ------------------------------------------------------------ plumbing
    def resume_watchdog(self):
        if self.watchdog is not None:
            self.watchdog.resume()

    def pause_watchdog(self):
        if self.watchdog is not None:
            self.watchdog.pause()

    def watch_fleet(self, names, budget_s: Optional[float] = None):
        """Register per-thread heartbeats (actors + learner) with the
        watchdog for the duration of an async run — a wedged thread's
        stall event names it and the phase it is stuck in."""
        if self.watchdog is not None:
            for name in names:
                self.watchdog.watch_thread(name, budget_s=budget_s)

    def unwatch_fleet(self):
        if self.watchdog is not None:
            self.watchdog.unwatch_all_threads()

    def write_blackbox(self, reason: str,
                       extra: Optional[Dict] = None) -> Optional[str]:
        """Flush the post-mortem: last ``blackbox_window_s`` seconds of
        every series ring + the pending event tail + heartbeat ages and
        per-thread phases, atomically to ``blackbox.json``.  Called from
        the watchdog's escalation hook, the SIGTERM path and the
        error-status close; safe (and useful) even with the series store
        disabled — the event tail is empty then, but the heartbeat/phase
        picture still lands."""
        from .series import write_blackbox
        return write_blackbox(
            self.blackbox_path, reason,
            store=self.hub.series_store,
            events=(self._tail_sink.tail() if self._tail_sink is not None
                    else []),
            window_s=self.blackbox_window_s,
            heartbeats=self.hub.beat_ages(),
            thread_phases=self.hub.thread_phases(),
            run=self.hub.base_tags.get("run"),
            extra=extra)

    def record_precision(self, policy):
        """Dtype-policy gauges + one ``precision`` event (policy is a
        ``config.schema.PrecisionPolicy``).  Gauges carry the bit width per
        role so ``metrics.json`` diffs show a dtype change numerically;
        the event carries the dtype names for the per-run report header
        (tools/obs_report.py)."""
        bits = {"float32": 32, "bfloat16": 16, "float16": 16}
        for role, dt in (("param", policy.param_dtype),
                         ("gnn_compute", policy.gnn_compute),
                         ("mlp_compute", policy.mlp_compute),
                         ("replay", policy.replay_dtype)):
            self.hub.gauge("dtype_bits", bits.get(dt, 0), role=role)
        self.hub.event("precision", name=policy.name,
                       param_dtype=policy.param_dtype,
                       gnn_compute=policy.gnn_compute,
                       mlp_compute=policy.mlp_compute,
                       replay_dtype=policy.replay_dtype)

    def prefetcher_heartbeat(self):
        """Bound callable handed to ``EpisodeDriver.prefetcher`` — beats
        from the producer thread after every staged episode."""
        return lambda: self.hub.beat("prefetcher")

    def attach_prefetcher(self, prefetcher):
        """Register stall-event probes over a live prefetcher: queue depth
        and producer-thread liveness."""
        if self.watchdog is not None:
            self.watchdog.register_probe(
                "prefetch_queue_depth", lambda: prefetcher.queue_depth)
            self.watchdog.register_probe(
                "prefetcher_alive", lambda: prefetcher.is_alive())

    # ------------------------------------------------------------- episodes
    def episode_dispatched(self, episode: int):
        self.hub.counter("episodes_dispatched")
        self.hub.beat("dispatch")

    def episode_end(self, episode: int, global_step: int,
                    metrics: Dict[str, float], sps: float,
                    phases: Dict[str, Dict[str, float]],
                    drop_reasons: Optional[Dict[str, int]] = None,
                    truncated_arrivals: int = 0,
                    replay_bytes: Optional[int] = None,
                    extra: Optional[Dict] = None) -> Dict:
        """One drained episode: update hub series, sample device memory,
        emit the ``episode`` event, heartbeat the watchdog, and rewrite
        the snapshot every ``snapshot_interval`` episodes.

        ``phases`` is the cumulative ``PhaseTimer.summary()``; per-episode
        deltas are derived here and fed to the phase histograms."""
        self._drained += 1
        self.hub.counter("episodes_drained")
        self.hub.gauge("sps", sps)
        self.hub.gauge("episode", episode)
        # flight-recorder history rides the SAME values the gauges get,
        # at the same instant — the last ring point of every fed metric
        # always equals the final metrics.json snapshot (series() no-ops
        # when the recorder is off)
        self.hub.series("sps", sps)
        self.hub.series("episode", episode)
        for k, v in metrics.items():
            try:
                fv = float(v)
            except (TypeError, ValueError):
                continue   # non-scalar stat (kept in the event record only)
            self.hub.gauge(k, fv)
            self.hub.series(k, fv)
        if replay_bytes is not None:
            self.hub.gauge("replay_bytes", replay_bytes)
            self.hub.series("replay_bytes", replay_bytes)
        if truncated_arrivals:
            self.hub.counter("truncated_arrivals_total", truncated_arrivals)
        for reason, n in (drop_reasons or {}).items():
            if n:
                self.hub.counter("sim_drops_total", n, reason=reason)
        for name in _PHASE_HIST:
            total = phases.get(name, {}).get("total_s")
            if total is None:
                continue
            delta = total - self._prev_phase_totals.get(name, 0.0)
            self._prev_phase_totals[name] = total
            self.hub.observe("phase_s", delta, phase=name)
        device_memory = record_device_gauges(self.hub)
        record = self.hub.event(
            "episode", episode=episode, global_step=global_step,
            sps=round(sps, 3), **metrics,
            drop_reasons=drop_reasons or {},
            truncated_arrivals=truncated_arrivals,
            replay_bytes=replay_bytes,
            phases=phases, device_memory=device_memory,
            **(extra or {}))
        self.hub.beat("episode")
        if self._drained % self.snapshot_interval == 0:
            self.write_snapshot()
        return record

    def recovery(self, episode: int, site: str, action: str,
                 fault: Optional[str] = None,
                 attempt: Optional[int] = None,
                 detail: Optional[str] = None) -> Dict:
        """One self-healing action (resilience subsystem): a monotonic
        total plus a per-(site, action) counter for metrics.json diffs,
        and one structured ``recovery`` event in events.jsonl —
        ``tools/obs_report.py`` renders them as the recovery timeline.

        The degradation ladder's actions: ``retry`` (dispatch backoff),
        ``restart`` (prefetcher), ``pipeline_off`` (degrade to serial
        sampling), ``rollback`` (restore last-good state), ``resave``
        (checkpoint failed validation), ``preempt_snapshot`` (SIGTERM)."""
        self.hub.counter("recoveries_total")
        self.hub.counter("recoveries", site=site, action=action)
        return self.hub.event(
            "recovery", episode=episode, site=site, action=action,
            **{k: v for k, v in (("fault", fault), ("attempt", attempt),
                                 ("detail", detail)) if v is not None})

    def invariant_violation(self, episode: int, violations: List[str]):
        """Route a simulator-invariant failure through the same structured
        pathway as the compile sentinel: a monotonic counter for
        metrics.json diffs plus one event per occurrence in events.jsonl
        (tools/obs_report.py lists both families)."""
        self.hub.counter("invariant_violations_total", len(violations))
        self.hub.event("invariant_violation", episode=episode,
                       violations=violations)

    def eval_episode(self, episode: int, episodic_return: float,
                     succ_ratio: float, runtime_s: float):
        self.hub.counter("eval_episodes")
        device_memory = record_device_gauges(self.hub)
        self.hub.event("eval_episode", episode=episode,
                       episodic_return=episodic_return,
                       succ_ratio=succ_ratio,
                       runtime_s=round(runtime_s, 4),
                       device_memory=device_memory)

    # ------------------------------------------------------------ snapshot
    def write_snapshot(self) -> str:
        import time

        return write_atomic_json(self.snapshot_path, {
            "ts": round(time.time(), 3),
            "run": self.hub.base_tags.get("run"),
            "metrics": self.hub.snapshot(),
        })
