"""Live /metrics endpoint — a stdlib-only HTTP server over the MetricsHub.

Long runs used to be observable only post-hoc (events.jsonl archaeology)
or by polling the atomic ``metrics.json`` snapshot off disk.  This module
exposes the SAME hub snapshot over HTTP while the run executes, in
Prometheus text exposition format, so a 100+-episode exhibit can be
scraped/watched live (``curl`` or a real Prometheus scraper — the flat
series names ``gsc_<name>{tag="v",...}`` are already exposition-shaped).

Deliberately jax-free and read-only: the handler thread only ever calls
``hub.snapshot()`` (one lock acquisition, O(series)), never touches the
training loop, and serves on a daemon thread — a wedged scraper cannot
stall a dispatch.  Gauges registered via ``hub.live_gauge`` (e.g. the
serving queue depth) are re-probed inside every snapshot, so a scrape
mid-run reads the CURRENT value, not the last event-writer sample.  Wired via ``RunObserver(metrics_port=...)`` /
``cli train --metrics-port`` (default off); ``cli serve`` reuses it for
the serving hub.

Routes: ``/metrics`` (Prometheus text), ``/healthz`` (JSON liveness),
``/series`` (read-only JSON time-series query over the hub's flight-
recorder rings: ``/series?name=<bare metric>&since=<unix ts>`` — both
parameters optional; 404 when the hub runs without a series window).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict
from urllib.parse import parse_qs, urlparse

# the exposition version Prometheus scrapers negotiate on
PROM_CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def prometheus_text(snapshot: Dict[str, float]) -> str:
    """Hub snapshot -> Prometheus text exposition (one series per line;
    names from ``hub.flat_name`` are already ``name{label="v"}``)."""
    lines = []
    for name, value in sorted(snapshot.items()):
        try:
            lines.append(f"{name} {float(value)}")
        except (TypeError, ValueError):
            continue
    return "\n".join(lines) + "\n"


class _Handler(BaseHTTPRequestHandler):
    # one hub read per request; the server object carries the hub ref
    def do_GET(self):  # noqa: N802 - BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        if path in ("/metrics", "/"):
            body = prometheus_text(self.server.hub.snapshot()).encode()
            self._reply(200, PROM_CONTENT_TYPE, body)
        elif path == "/healthz":
            body = json.dumps({"status": "ok",
                               "series": len(self.server.hub.snapshot()),
                               }).encode()
            self._reply(200, "application/json", body)
        elif path == "/series":
            self._reply_series()
        else:
            self._reply(404, "text/plain",
                        b"not found (routes: /metrics, /healthz, "
                        b"/series)\n")

    def _reply_series(self):
        """Read-only JSON history query — the autoscaler-shaped consumer
        interface (same payload shape as the on-disk ``series.json``).
        One store read per request; never touches the training loop."""
        store = getattr(self.server.hub, "series_store", None)
        if store is None:
            self._reply(404, "application/json", json.dumps(
                {"error": "series history disabled "
                          "(hub has no series window)"}).encode())
            return
        query = parse_qs(urlparse(self.path).query)
        name = (query.get("name") or [None])[0] or None
        since = None
        raw = (query.get("since") or [None])[0]
        if raw:
            try:
                since = float(raw)
            except ValueError:
                self._reply(400, "application/json", json.dumps(
                    {"error": f"bad since={raw!r} (want a unix "
                              "timestamp)"}).encode())
                return
        doc = store.document(run=self.server.hub.base_tags.get("run"))
        if name is not None or since is not None:
            doc["series"] = store.query(name=name, since=since)
        self._reply(200, "application/json", json.dumps(doc).encode())

    def _reply(self, code: int, ctype: str, body: bytes):
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, *args):   # scrapes must not spam the run log
        pass


class MetricsEndpoint:
    """Background HTTP server exposing one hub.  ``port=0`` binds an
    ephemeral port (tests; the bound port is read back from ``.port``
    after :meth:`start`)."""

    def __init__(self, hub, port: int = 0, host: str = "127.0.0.1"):
        self.hub = hub
        self.host = host
        self.port = int(port)
        self._server = None
        self._thread = None

    def start(self) -> "MetricsEndpoint":
        server = ThreadingHTTPServer((self.host, self.port), _Handler)
        server.daemon_threads = True
        server.hub = self.hub
        self.port = server.server_address[1]
        self._server = server
        self._thread = threading.Thread(target=server.serve_forever,
                                        name="gsc-metrics-endpoint",
                                        daemon=True)
        self._thread.start()
        return self

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def stop(self):
        server, self._server = self._server, None
        if server is not None:
            server.shutdown()
            server.server_close()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None
