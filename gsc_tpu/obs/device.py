"""Device/HBM gauges from ``jax.local_devices()[*].memory_stats()``.

On TPU/GPU backends ``memory_stats()`` reports allocator state
(``bytes_in_use``, ``peak_bytes_in_use``, ``bytes_limit``, ...); the CPU
backend returns ``None``.  Records keep one entry per local device either
way, with ``available`` flagging whether the backend exposes the stats —
the events.jsonl schema is stable across backends, so a report written
against a CPU smoke run reads a TPU run unchanged.
"""
from __future__ import annotations

from typing import Dict, List, Optional

# the allocator keys worth streaming; other backend-specific entries
# (num_allocs, largest_alloc_size, ...) stay out of the per-episode record
_KEYS = ("bytes_in_use", "peak_bytes_in_use", "bytes_limit")


def device_memory_snapshot() -> List[Dict]:
    """One record per local device: ``{"device", "available", "backend",
    and (when the backend exposes allocator stats) bytes_in_use/
    peak_bytes_in_use/bytes_limit}``.

    ``available: false`` records carry the backend name (``memory_stats()``
    is ``None`` on CPU) so downstream readers — obs_report's memory
    section, the bench rows — can distinguish "this backend has no HBM
    data" from "usage was flat" instead of silently skipping the device."""
    import jax

    records = []
    for d in jax.local_devices():
        try:
            stats = d.memory_stats()
        except Exception:   # backends without the API raise rather than
            stats = None    # return None (older plugin versions)
        rec: Dict = {"device": str(d), "available": bool(stats),
                     "backend": getattr(d, "platform", "unknown")}
        if stats:
            for k in _KEYS:
                if k in stats:
                    rec[k] = int(stats[k])
        records.append(rec)
    return records


def record_device_gauges(hub, records: Optional[List[Dict]] = None
                         ) -> List[Dict]:
    """Sample (or reuse) a memory snapshot and mirror it into hub gauges
    tagged by device — ``gsc_device_bytes_in_use{device="TPU_0"}`` etc. in
    the metrics.json exposition."""
    if records is None:
        records = device_memory_snapshot()
    for rec in records:
        for k in _KEYS:
            if k in rec:
                hub.gauge(f"device_{k}", rec[k], device=rec["device"])
    return records
