"""Time-series rings + the black-box post-mortem writer — the flight
recorder's storage layer.

Every hub series used to be a LAST-VALUE cell: ``metrics.json`` is a
point-in-time snapshot, so the autoscaler-shaped consumers ROADMAP item 3
needs (burn trends, queue-depth ramps, policy-lag creep) had no history
to read, and a dead fleet left nothing but a truncated events file.  This
module keeps bounded ``(ts, value)`` rings per metric:

- :class:`SeriesStore` — thread-safe drop-oldest rings keyed exactly like
  the hub's flat series names (``gsc_<name>{tag="v",...}``).  Appends are
  O(1) host-float deque pushes under one lock — nothing on the dispatch
  path ever syncs a device value to feed a ring; every feed site is a
  host site that already held the value (drain, learner loop, dispatcher).
- ``series.json`` — the schema-versioned whole-run dump
  :meth:`SeriesStore.document` produces and ``RunObserver.close()``
  writes, so history survives the process.
- :func:`write_blackbox` — the crash/stall post-mortem: the last N
  seconds of every ring plus the pending event tail, flushed to
  ``blackbox.json`` when the watchdog escalates, the run dies, or a
  SIGTERM lands (the PR 5 recovery path).

The module is deliberately jax-free and import-light: the hub imports it
lazily, tools read its documents with nothing but stdlib json.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional, Tuple

from .sinks import write_atomic_json

# bump on any series.json / /series payload shape change
SERIES_SCHEMA_VERSION = 1
# bump on any blackbox.json shape change
BLACKBOX_SCHEMA_VERSION = 1

# a ring key is (name, sorted tag items) — the hub's own key shape
_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _flat(name: str, tags: Tuple[Tuple[str, str], ...]) -> str:
    # local copy of hub.flat_name (hub imports THIS module lazily; a
    # top-level import back into hub would be a cycle)
    label = ",".join(f'{k}="{v}"' for k, v in tags)
    return f"gsc_{name}{{{label}}}" if label else f"gsc_{name}"


class SeriesStore:
    """Bounded per-metric ``(ts, value)`` rings, drop-oldest.

    ``window`` caps POINTS per ring, not seconds — a 1 Hz feed with the
    default CLI window holds ~17 minutes, matching the hub histogram
    window's live-tail horizon.  All methods are thread-safe; appends
    from the learner loop, the serve dispatcher and the drain never
    contend for more than one dict lookup + deque push."""

    def __init__(self, window: int = 1024,
                 base_tags: Optional[Dict[str, str]] = None):
        if window < 1:
            raise ValueError(f"series window must be >= 1, got {window}")
        self.window = int(window)
        self.base_tags: Dict[str, str] = dict(base_tags or {})
        self._lock = threading.Lock()
        self._rings: Dict[_Key, deque] = {}   # guarded-by: self._lock

    # ------------------------------------------------------------- writes
    def add_point(self, name: str, value: float,
                  ts: Optional[float] = None,
               **tags):
        """Push one point (drop-oldest past the window).  ``ts`` defaults
        to now; callers replaying deferred records pass their own."""
        key = (name, tuple(sorted((k, str(v)) for k, v in tags.items())))
        point = (round(float(ts if ts is not None else time.time()), 3),
                 float(value))
        with self._lock:
            ring = self._rings.get(key)
            if ring is None:
                ring = self._rings[key] = deque(maxlen=self.window)
            ring.append(point)

    # -------------------------------------------------------------- reads
    def names(self) -> List[str]:
        with self._lock:
            keys = list(self._rings)
        base = tuple(sorted(self.base_tags.items()))
        return sorted(_flat(n, tuple(sorted(base + t))) for n, t in keys)

    def query(self, name: Optional[str] = None,
              since: Optional[float] = None) -> Dict[str, List[List[float]]]:
        """``{flat_name: [[ts, value], ...]}``, oldest first.  ``name``
        filters on the BARE metric name (tags ignored — one bare name can
        fan out to many tagged rings); ``since`` keeps points with
        ``ts >= since``."""
        base = tuple(sorted(self.base_tags.items()))
        with self._lock:
            items = [(k, list(ring)) for k, ring in self._rings.items()]
        out: Dict[str, List[List[float]]] = {}
        for (n, tags), points in items:
            if name and n != name:
                continue
            if since is not None:
                points = [p for p in points if p[0] >= since]
            if not points:
                continue
            out[_flat(n, tuple(sorted(base + tags)))] = \
                [[p[0], p[1]] for p in points]
        return out

    def tail(self, seconds: float) -> Dict[str, List[List[float]]]:
        """Every ring's points from the last ``seconds`` — the black-box
        dump's series window."""
        return self.query(since=time.time() - float(seconds))

    def last(self, name: str, **tags) -> Optional[float]:
        key = (name, tuple(sorted((k, str(v)) for k, v in tags.items())))
        with self._lock:
            ring = self._rings.get(key)
            return ring[-1][1] if ring else None

    def point_count(self) -> int:
        with self._lock:
            return sum(len(r) for r in self._rings.values())

    # ---------------------------------------------------------- documents
    def document(self, run: Optional[str] = None,
                 since: Optional[float] = None) -> Dict:
        """The schema-versioned payload both ``series.json`` and the
        ``/series`` endpoint serve."""
        return {
            "schema_version": SERIES_SCHEMA_VERSION,
            "ts": round(time.time(), 3),
            "run": run,
            "window": self.window,
            "series": self.query(since=since),
        }


def write_series(path: str, store: SeriesStore,
                 run: Optional[str] = None) -> str:
    """Atomic whole-run ``series.json`` dump."""
    return write_atomic_json(path, store.document(run=run))


def write_blackbox(path: str, reason: str,
                   store: Optional[SeriesStore] = None,
                   events: Optional[List[Dict]] = None,
                   window_s: float = 30.0,
                   heartbeats: Optional[Dict[str, float]] = None,
                   thread_phases: Optional[Dict[str, str]] = None,
                   run: Optional[str] = None,
                   extra: Optional[Dict] = None) -> str:
    """The post-mortem dump: last ``window_s`` of every series ring plus
    the pending event tail, written atomically so a dying process leaves
    a complete document or none.  Every field is optional — a run with
    the series store disabled still gets its event tail and heartbeat
    ages on a crash."""
    doc = {
        "schema_version": BLACKBOX_SCHEMA_VERSION,
        "ts": round(time.time(), 3),
        "run": run,
        "reason": reason,
        "window_s": float(window_s),
        "series": store.tail(window_s) if store is not None else {},
        "events": list(events or []),
        "heartbeats": dict(heartbeats or {}),
        "thread_phases": dict(thread_phases or {}),
    }
    if extra:
        doc.update(extra)
    return write_atomic_json(path, doc)
