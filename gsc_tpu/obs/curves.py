"""Learning-curve extraction: events.jsonl -> schema-versioned curves.json.

ROADMAP item 2 trades bit-exactness for true tensor parallelism "when
learning curves stay inside the banded envelope" — which needs the curve
to BE an artifact, not a rewards.csv a human eyeballs.  This module
extracts the per-episode learning series a run's event stream already
carries (``episode`` / ``harness_episode`` events for returns and losses,
``learn_signal`` events for TD-error and Q moments — the on-device learn
ledger, :mod:`~gsc_tpu.obs.learning`) into one ``curves.json`` per run:

- ``series``: aligned per-episode lists (episode, episodic_return,
  critic_loss, actor_loss, sps, td_abs_mean, q_mean) — non-finite values
  sanitized to null so the document stays strict JSON;
- ``per_topology``: per-network return and |TD| series (mixed-topology
  runs, plus the serial path's stamped topology);
- ``summary``: the envelope metrics ``tools/bench_diff.py`` gates under
  tolerance bands — ``final_window_return`` (mean over the last W
  episodes), ``auc_return`` (per-episode-normalized area under the
  return curve), ``episodes_to_threshold`` (first episode whose trailing
  W-mean reaches ``first + 0.9 * (final - first)``; null when the curve
  never rose), and ``final_window_td_abs``.

``RunObserver.close()`` writes it next to metrics.json; append-mode
streams are partitioned on ``run_start`` and the LAST run wins (the same
rule as tools/obs_report.py).  The reader side is plain JSON — bench_diff
stays stdlib-only.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional

CURVES_SCHEMA_VERSION = 1
# envelope window: the "mean reward over the last 10 episodes" the repo's
# select_best_agent discipline already uses
FINAL_WINDOW = 10
THRESHOLD_FRACTION = 0.9


def _finite(v) -> Optional[float]:
    if isinstance(v, bool) or not isinstance(v, (int, float)):
        return None
    return float(v) if math.isfinite(v) else None


def _mean(vals: List[float]) -> Optional[float]:
    vals = [v for v in vals if v is not None]
    return round(sum(vals) / len(vals), 6) if vals else None


def _last_run(events: List[Dict]) -> List[Dict]:
    starts = [i for i, e in enumerate(events)
              if isinstance(e, dict) and e.get("event") == "run_start"]
    return events[starts[-1]:] if starts else events


def extract_curves(events: List[Dict], window: int = FINAL_WINDOW,
                   threshold_fraction: float = THRESHOLD_FRACTION) -> Dict:
    """Build the curves document from a (ts-sorted) event stream."""
    events = _last_run([e for e in events if isinstance(e, dict)])
    run = next((e.get("run") for e in events if e.get("run")), None)

    # per-episode rows keyed by episode index; 'episode' events are the
    # trainer's drained rows (both paths); harness_episode fills gaps for
    # harness-only drivers (tools/learning_curve.py)
    rows: Dict[int, Dict] = {}
    for ev in events:
        kind = ev.get("event")
        ep = ev.get("episode")
        if not isinstance(ep, int):
            continue
        if kind == "episode":
            row = rows.setdefault(ep, {})
            for src, dst in (("episodic_return", "episodic_return"),
                             ("critic_loss", "critic_loss"),
                             ("actor_loss", "actor_loss"), ("sps", "sps")):
                if src in ev:
                    row[dst] = _finite(ev.get(src))
            if ev.get("topology"):
                row["topology"] = str(ev["topology"])
        elif kind == "harness_episode":
            row = rows.setdefault(ep, {})
            row.setdefault("episodic_return",
                           _finite(ev.get("episodic_return")))
            for name, v in (ev.get("per_topology_return") or {}).items():
                row.setdefault("per_topology_return", {})[str(name)] = \
                    _finite(v)
        elif kind == "learn_signal":
            row = rows.setdefault(ep, {})
            row["td_abs_mean"] = _finite(ev.get("td_abs_mean"))
            row["q_mean"] = _finite(ev.get("q_mean"))
            for name, v in (ev.get("per_topology_td") or {}).items():
                row.setdefault("per_topology_td", {})[str(name)] = \
                    _finite(v)

    episodes = sorted(rows)
    series = {"episode": episodes}
    for key in ("episodic_return", "critic_loss", "actor_loss", "sps",
                "td_abs_mean", "q_mean"):
        col = [rows[ep].get(key) for ep in episodes]
        if any(v is not None for v in col):
            series[key] = col

    per_topology: Dict[str, Dict[str, list]] = {}

    def topo_row(name: str) -> Dict[str, list]:
        return per_topology.setdefault(
            name, {"episode": [], "return": [], "td_abs_mean": []})

    for ep in episodes:
        row = rows[ep]
        names = set(row.get("per_topology_return") or {}) \
            | set(row.get("per_topology_td") or {})
        if row.get("topology"):
            names.add(row["topology"])
        for name in names:
            t = topo_row(name)
            t["episode"].append(ep)
            ret = (row.get("per_topology_return") or {}).get(name)
            if ret is None and row.get("topology") == name:
                ret = row.get("episodic_return")
            t["return"].append(ret)
            t["td_abs_mean"].append(
                (row.get("per_topology_td") or {}).get(name))

    returns = [rows[ep].get("episodic_return") for ep in episodes]
    tds = [rows[ep].get("td_abs_mean") for ep in episodes]
    w = max(min(window, len(episodes)), 1)
    summary: Dict = {"window": window,
                     "threshold_fraction": threshold_fraction}
    finite_returns = [r for r in returns if r is not None]
    if finite_returns:
        first_w = _mean(returns[:w])
        final_w = _mean(returns[-w:])
        summary["first_window_return"] = first_w
        summary["final_window_return"] = final_w
        summary["auc_return"] = _mean(returns)
        # episodes-to-threshold: first episode whose TRAILING w-mean
        # reaches 90% of the first->final rise; null when the curve
        # never rose (a flat/declining run has no "time to learn")
        ett = None
        if first_w is not None and final_w is not None \
                and final_w > first_w:
            threshold = first_w + threshold_fraction * (final_w - first_w)
            summary["threshold_return"] = round(threshold, 6)
            for i in range(len(episodes)):
                trail = _mean(returns[max(0, i - w + 1):i + 1])
                if trail is not None and trail >= threshold:
                    ett = episodes[i]
                    break
        summary["episodes_to_threshold"] = ett
    if any(t is not None for t in tds):
        summary["final_window_td_abs"] = _mean(tds[-w:])

    return {
        "schema_version": CURVES_SCHEMA_VERSION,
        "run": run,
        "episodes": len(episodes),
        "series": series,
        "per_topology": per_topology,
        "summary": summary,
    }


def write_curves(path: str, events: List[Dict],
                 window: int = FINAL_WINDOW) -> str:
    """Atomic curves.json write (same contract as metrics.json)."""
    from .sinks import write_atomic_json

    return write_atomic_json(path, extract_curves(events, window=window))
