"""On-device learning-signal ledger — the learning-quality counterpart of
the :mod:`~gsc_tpu.obs.perf` CostLedger.

PR 10 made *performance* a per-run artifact (FLOPs/MFU/roofline); training
QUALITY was still archaeology: losses and a mean Q rode the episode
events, but nothing said WHICH topology's transitions still carry TD
error, whether a layer's gradients are exploding, or how spread the Q
distribution is — the per-scenario signal the auto-curriculum item needs
and the banded learning-curve envelopes item 2 trades bit-exactness
against.  Podracer (arXiv:2104.06272) keeps learner statistics resident
on-device and drains them with the existing dispatch cadence; Jumanji
(arXiv:2306.09884) computes the per-scenario signal inside the compiled
program.  Both patterns apply directly here:

**Device half** (traced inside the agents' jitted programs, keyed on a
static :class:`LearnLedgerSpec` so the no-ledger trace stays byte-identical
to the pre-ledger stack):

- :func:`learn_signal` — per-transition |TD-error| aggregated per
  ``topo_idx`` via ``segment_sum`` (replay rows already carry the
  topology id), Q-value distribution moments (mean/std/min/max — not
  just the mean the loss logs), and per-layer param/grad norm tree
  summaries (grouped by top-level module, e.g. ``actor/GNNEmbedder_0``).
- :func:`replay_stats` — replay fill/age folded into the rollout stats.

Everything folds into the EXISTING dispatch outputs and drains with the
deferred metric drain — zero new host syncs on the dispatch path (the
same ``no_host_sync`` contract the CostLedger is tested under).

**Host half** (after the deferred drain has already synced the values):

- :func:`emit_learn_signal` — one structured ``learn_signal`` event per
  episode into events.jsonl plus hub gauges (``td_abs_mean`` overall and
  tagged ``topology=<name>``, ``q_mean``/``q_std``/``q_min``/``q_max``,
  ``grad_norm{layer=...}``, ``param_norm{layer=...}``, ``replay_fill``).
- :class:`LearnLedger` — the RunObserver-owned facade that remembers the
  topo-id -> name mapping and hands the trainer its static spec.

``RunObserver.close()`` then extracts the per-run learning curves from
the event stream into schema-versioned ``curves.json``
(:mod:`~gsc_tpu.obs.curves`), which ``tools/bench_diff.py`` gates.
"""
from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence

import numpy as np


@dataclass(frozen=True)
class LearnLedgerSpec:
    """Static ledger config threaded into the jitted agents.

    Hashable/frozen on purpose: it rides on the agent instance, which is
    a static argnum of every dispatch entry point — two agents that
    differ only in spec share no trace, and ``None`` (no ledger) traces
    the historic program byte for byte.

    ``num_topos`` sizes the TD-error segment axis: topo ids are the
    schedule position (plain runs) or the mix-entry index (mixed-topology
    batches), clipped into ``[0, num_topos)`` on device.
    """

    num_topos: int = 1


def _key_str(entry) -> str:
    """One pytree path entry -> readable component (DictKey / GetAttrKey /
    SequenceKey across jax versions)."""
    for attr in ("key", "name", "idx"):
        if hasattr(entry, attr):
            return str(getattr(entry, attr))
    return str(entry)


def _layer_groups(tree) -> Dict[str, list]:
    """Group a (params-like) pytree's leaves by top-level module:
    ``{'actor': {'params': {'Dense_0': {'kernel': ...}}}}`` groups under
    ``actor/Dense_0``.  Grouping is purely structural (static at trace
    time), so the signal pytree has a fixed shape the fori-loop carry can
    hold."""
    import jax

    groups: Dict[str, list] = {}
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        keys = [_key_str(p) for p in path if _key_str(p) != "params"]
        if len(keys) > 1:
            keys = keys[:-1]     # drop the leaf name (kernel/bias/...)
        name = "/".join(keys[:2]) or "leaf"
        groups.setdefault(name, []).append(leaf)
    return groups


def layer_norms(tree) -> Dict[str, "object"]:
    """Per-layer global norms of a params/grads pytree (device scalars)."""
    import jax.numpy as jnp

    return {name: jnp.sqrt(sum(jnp.sum(jnp.square(l)) for l in leaves))
            for name, leaves in _layer_groups(tree).items()}


def learn_signal(spec: LearnLedgerSpec, topo_idx, td, q, params, grads
                 ) -> Dict:
    """One gradient step's learning signal (traced inside the learn
    burst).  ``td`` is the critic residual ``q - stop_grad(target)`` the
    loss already computes; ``params``/``grads`` are the post-update trees
    — everything here CONSUMES tensors the update path materialized, so
    the update math is untouched and ledger-on runs stay bit-identical
    to ledger-off runs."""
    import jax
    import jax.numpy as jnp

    # num_topos is a static Python int (frozen spec) — no cast, so the
    # R1 host-sync scan never mistakes it for a traced value
    k = max(spec.num_topos, 1)
    seg = jnp.clip(jnp.asarray(topo_idx).astype(jnp.int32), 0, k - 1)
    td_abs = jnp.abs(td)
    return {
        # accumulated across the burst by _learn_burst's carry
        "td_abs_sum": jax.ops.segment_sum(td_abs, seg, num_segments=k),
        "td_count": jax.ops.segment_sum(jnp.ones_like(td_abs), seg,
                                        num_segments=k),
        # distribution moments, not just the mean the loss logs — a
        # collapsing critic shows as q_std -> 0 long before the loss does
        "q_mean": q.mean(), "q_std": q.std(),
        "q_min": q.min(), "q_max": q.max(),
        "param_norms": layer_norms(params),
        "grad_norms": layer_norms(grads),
    }


def zero_learn_signal(spec: LearnLedgerSpec, state) -> Dict:
    """The fori-loop carry template matching :func:`learn_signal`'s
    structure (layer names derive from the state's static tree, so the
    two always agree)."""
    import jax.numpy as jnp

    k = max(spec.num_topos, 1)
    trees = {"actor": state.actor_params, "critic": state.critic_params}
    zeros = {name: jnp.zeros(()) for name in _layer_groups(trees)}
    return {
        "td_abs_sum": jnp.zeros((k,)), "td_count": jnp.zeros((k,)),
        "q_mean": jnp.zeros(()), "q_std": jnp.zeros(()),
        "q_min": jnp.zeros(()), "q_max": jnp.zeros(()),
        "param_norms": dict(zeros), "grad_norms": dict(zeros),
    }


def accumulate_signal(acc: Dict, sig: Dict) -> Dict:
    """Fold one gradient step's signal into the burst carry: TD segments
    ACCUMULATE over the whole burst (the per-topology learning pressure),
    moments and norms keep the last step's values (the same last-write
    semantics as the existing loss metrics)."""
    return {**sig,
            "td_abs_sum": acc["td_abs_sum"] + sig["td_abs_sum"],
            "td_count": acc["td_count"] + sig["td_count"]}


def replay_stats(buffer) -> Dict:
    """Replay fill/age stats from the live buffer, on device (reading
    ``buffer.size`` host-side would sync the dispatch head).  Handles the
    single-agent ``[capacity, ...]`` layout and the replica-sharded
    ``[B, capacity, ...]`` layout (``size`` is then ``[B]``)."""
    import jax
    import jax.numpy as jnp

    leaf = jax.tree_util.tree_leaves(buffer.data)[0]
    size = buffer.size
    cap = leaf.shape[1] if jnp.ndim(size) else leaf.shape[0]
    s = size.astype(jnp.float32)
    return {
        "size": size,
        # cap is a static Python int off the leaf shape — plain division,
        # no float() cast for the R1 scan to misread
        "fill": s / max(cap, 1),
        # ring semantics: entries age 0..size-1 until the ring wraps, so
        # mean insertion-age in env steps is (size-1)/2
        "age_mean_steps": jnp.maximum(s - 1.0, 0.0) / 2.0,
    }


# ----------------------------------------------------------------- host
def _scalar(v) -> Optional[float]:
    try:
        return round(float(np.asarray(v)), 6)
    except (TypeError, ValueError):
        return None


def emit_learn_signal(hub, episode: int, signal: Optional[Dict] = None,
                      replay: Optional[Dict] = None,
                      segment_names: Optional[Sequence[str]] = None
                      ) -> Optional[Dict]:
    """Drain one episode's learn signal into the hub: gauges + one
    ``learn_signal`` event.  Called AFTER the deferred drain has blocked
    on the episode's device work, so every ``np.asarray`` here reads an
    already-synced value — the dispatch path never waits on this."""
    if hub is None or (signal is None and replay is None):
        return None
    fields: Dict = {"episode": episode}
    if signal is not None:
        sums = np.asarray(signal["td_abs_sum"], dtype=np.float64)
        counts = np.asarray(signal["td_count"], dtype=np.float64)
        total = counts.sum()
        td_mean = (round(float(sums.sum() / total), 6) if total > 0
                   else None)
        per_topo = {}
        for i in range(sums.shape[0]):
            if counts[i] > 0:
                name = (str(segment_names[i]) if segment_names is not None
                        and i < len(segment_names) else f"topo{i}")
                per_topo[name] = round(float(sums[i] / counts[i]), 6)
        q = {k: _scalar(signal[k])
             for k in ("q_mean", "q_std", "q_min", "q_max")}
        grad_norms = {k: _scalar(v)
                      for k, v in (signal.get("grad_norms") or {}).items()}
        param_norms = {k: _scalar(v)
                       for k, v in (signal.get("param_norms") or {}).items()}
        fields.update(td_abs_mean=td_mean, per_topology_td=per_topo, **q,
                      grad_norms=grad_norms, param_norms=param_norms)
        if td_mean is not None:
            hub.gauge("td_abs_mean", td_mean)
        for name, v in per_topo.items():
            hub.gauge("td_abs_mean", v, topology=name)
        for k, v in q.items():
            if v is not None:
                hub.gauge(k, v)
        for name, v in grad_norms.items():
            if v is not None:
                hub.gauge("grad_norm", v, layer=name)
        for name, v in param_norms.items():
            if v is not None:
                hub.gauge("param_norm", v, layer=name)
    if replay is not None:
        fill = np.asarray(replay["fill"], dtype=np.float64)
        fields["replay"] = {
            "size": np.asarray(replay["size"]).tolist(),
            "fill": round(float(fill.mean()), 6),
            "age_mean_steps": round(float(
                np.asarray(replay["age_mean_steps"]).mean()), 3),
        }
        hub.gauge("replay_fill", float(fill.mean()))
    return hub.event("learn_signal", **fields)


class LearnLedger:
    """Host-side facade the :class:`~gsc_tpu.obs.run.RunObserver` owns
    when constructed with ``learn=True``: hands the trainer the static
    device spec (:meth:`spec`), remembers the topo-id -> name mapping,
    and drains per-episode signals through :func:`emit_learn_signal`."""

    def __init__(self, hub):
        self.hub = hub
        self.segment_names: Optional[List[str]] = None
        self.episodes = 0

    def spec(self, num_topos: int,
             names: Optional[Sequence[str]] = None) -> LearnLedgerSpec:
        if names:
            self.segment_names = [str(n) for n in names]
        return LearnLedgerSpec(num_topos=max(int(num_topos or 1), 1))

    def episode(self, episode: int, signal: Optional[Dict] = None,
                replay: Optional[Dict] = None) -> Optional[Dict]:
        self.episodes += 1
        return emit_learn_signal(self.hub, episode, signal=signal,
                                 replay=replay,
                                 segment_names=self.segment_names)
