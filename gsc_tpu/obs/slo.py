"""Serving SLO engine + request-path span tracer.

The serving tier used to report one number per request —
``serve_latency_ms`` from enqueue to fan-out — with no visibility into
*where* the time went (queue wait vs batch formation vs device wall) and
no objective to judge it against.  ROADMAP item 3's fleet (continuous
batching, hot-swap with zero dropped requests, SLA-driven bucket
autoscaling) is undrivable without exactly that decomposition plus SLO
accounting; this module mints both currencies:

- :class:`ServeTracer` — per-request spans.  The batcher stamps
  timestamps only (enqueue -> batch admission -> device dispatch ->
  completion/fan-out; ``time.perf_counter`` calls and a deque append,
  nothing else) and hands each flush's compact record here; a background
  drainer thread turns the records into latency-decomposition
  histograms, ``serve_flush`` events (always) and head-sampled
  ``serve_request_span`` events (every Nth trace id) — so the flush
  path itself does zero blocking emission work.  The span events carry
  their *original* wall timestamps, so the trace exporter
  (:mod:`~gsc_tpu.obs.trace`) renders them with faithful geometry and
  links each sampled request to its flush with a flow arrow.
- :class:`SLOEngine` — declarative latency objectives
  (:func:`parse_slo_spec` grammar: ``"25"`` = overall p-latency target
  in ms, ``"25,8:60"`` adds a per-bucket override), rolling-window
  attainment against them, error-budget burn rate
  (``(1 - attainment) / (1 - target)``), cumulative deadline-miss ratio
  (latency > the batcher's ``deadline_ms``), arrival-rate EWMA over
  inter-arrival gaps, and per-flush pad-waste fraction
  (``1 - n_real/bucket``).  The engine's snapshot folds into
  ``serve_stats`` events, the live ``/metrics`` endpoint (as
  ``slo_*`` gauges) and the ``slo.json`` document
  :meth:`~gsc_tpu.serve.server.PolicyServer.close` writes.

Deliberately jax-free (stdlib + the hub): every value it touches is a
host float the batcher already owned — the no-host-sync contract of the
flush path is preserved by construction and re-asserted by test.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, List, Optional

SLO_SCHEMA_VERSION = 1

# rolling attainment window: enough requests for a stable fraction
# without unbounded memory (matches the hub histogram window scale)
_SLO_WINDOW = 512
# arrival-rate EWMA smoothing over inter-arrival gaps
_ARRIVAL_ALPHA = 0.2


def _ratio(num: float, den: float) -> Optional[float]:
    return round(num / den, 6) if den else None


class SLOObjectives:
    """Declarative latency objectives: an overall target plus optional
    per-bucket overrides, judged at ``target_attainment`` (the SRE error
    budget is ``1 - target_attainment``)."""

    def __init__(self, p99_ms: Optional[float] = None,
                 per_bucket: Optional[Dict[int, float]] = None,
                 target_attainment: float = 0.99):
        if not 0.0 < target_attainment < 1.0:
            raise ValueError(f"target_attainment must be in (0, 1): "
                             f"{target_attainment!r}")
        self.p99_ms = float(p99_ms) if p99_ms is not None else None
        self.per_bucket = {int(b): float(v)
                           for b, v in (per_bucket or {}).items()}
        self.target_attainment = float(target_attainment)

    def objective_for(self, bucket) -> Optional[float]:
        """The target a request in ``bucket`` is judged against: the
        bucket override when one exists, else the overall objective."""
        try:
            return self.per_bucket.get(int(bucket), self.p99_ms)
        except (TypeError, ValueError):
            return self.p99_ms

    def declared(self) -> bool:
        return self.p99_ms is not None or bool(self.per_bucket)

    def to_doc(self) -> Dict:
        return {"p99_ms": self.p99_ms,
                "per_bucket": {str(b): v
                               for b, v in sorted(self.per_bucket.items())},
                "target_attainment": self.target_attainment}


def parse_slo_spec(spec: str,
                   target_attainment: float = 0.99) -> SLOObjectives:
    """``--slo-p99-ms`` grammar -> :class:`SLOObjectives`.

    ``entry := <ms> | <bucket>:<ms>``, comma-separated; at most one bare
    ``<ms>`` (the overall objective), any number of per-bucket overrides.
    Examples: ``"25"``, ``"25,8:60"``, ``"4:40,8:60"``.  Raises
    ``ValueError`` on malformed/duplicate/non-positive entries."""
    overall: Optional[float] = None
    per_bucket: Dict[int, float] = {}
    for raw in str(spec).split(","):
        entry = raw.strip()
        if not entry:
            raise ValueError(f"empty entry in SLO spec {spec!r}")
        if ":" in entry:
            b_txt, v_txt = entry.split(":", 1)
            try:
                b, v = int(b_txt), float(v_txt)
            except ValueError:
                raise ValueError(f"bad per-bucket SLO entry {entry!r} "
                                 f"(want <bucket>:<ms>)")
            if b < 1 or v <= 0:
                raise ValueError(f"per-bucket SLO entry {entry!r} must "
                                 "have bucket >= 1 and ms > 0")
            if b in per_bucket:
                raise ValueError(f"duplicate bucket {b} in SLO spec "
                                 f"{spec!r}")
            per_bucket[b] = v
        else:
            try:
                v = float(entry)
            except ValueError:
                raise ValueError(f"bad SLO entry {entry!r} (want <ms> or "
                                 "<bucket>:<ms>)")
            if v <= 0:
                raise ValueError(f"overall SLO must be > 0 ms: {entry!r}")
            if overall is not None:
                raise ValueError(f"more than one overall objective in "
                                 f"SLO spec {spec!r}")
            overall = v
    return SLOObjectives(p99_ms=overall, per_bucket=per_bucket,
                         target_attainment=target_attainment)


class SLOEngine:
    """Rolling SLO accounting for one serving process.

    Fed exclusively from the :class:`ServeTracer` drain (and the
    batcher's rejection path) — never from the flush path directly.
    Thread-safe: the drainer thread writes while ``serve_stats``
    emission and ``close()`` read."""

    def __init__(self, deadline_ms: float,
                 objectives: Optional[SLOObjectives] = None,
                 hub=None, window: int = _SLO_WINDOW,
                 alpha: float = _ARRIVAL_ALPHA,
                 tags: Optional[Dict[str, str]] = None):
        self.deadline_ms = float(deadline_ms)
        self.objectives = objectives or SLOObjectives()
        self.hub = hub
        # extra gauge/counter tags (e.g. {"worker": "w0"} in a fleet, so
        # N engines sharing one hub never fight over the slo_* series);
        # empty = the historic untagged series
        self.tags = dict(tags or {})
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        # (latency_ms, bucket) rolling window for attainment
        self._window = deque(maxlen=max(int(window), 1))   # guarded-by: self._lock
        self._requests = 0                  # guarded-by: self._lock
        self._deadline_misses = 0           # guarded-by: self._lock
        self._errored = 0                   # guarded-by: self._lock
        self._lat_sum = 0.0                 # guarded-by: self._lock
        self._queue_wait_sum = 0.0          # guarded-by: self._lock
        self._flushes = 0                   # guarded-by: self._lock
        self._pad_sum = 0.0                 # guarded-by: self._lock
        self._per_bucket: Dict[int, Dict[str, float]] = {}   # guarded-by: self._lock
        self._rejected: Dict[str, int] = {}   # guarded-by: self._lock
        self._last_arrival: Optional[float] = None   # guarded-by: self._lock
        self._ia_ewma: Optional[float] = None        # guarded-by: self._lock
        self._published_misses = 0          # guarded-by: self._lock

    # ------------------------------------------------------------ feeding
    def note_arrival(self, wall_ts: float):
        """One request arrival (accepted OR rejected) — drives the
        arrival-rate EWMA over inter-arrival gaps.  Gaps are floored at
        1 ns: a coarse wall clock stamping a burst with identical times
        must read as "very fast", never poison the EWMA with a 0 that
        makes the rate unreportable."""
        with self._lock:
            if self._last_arrival is not None:
                gap = max(wall_ts - self._last_arrival, 1e-9)
                self._ia_ewma = gap if self._ia_ewma is None else \
                    self.alpha * gap + (1.0 - self.alpha) * self._ia_ewma
            self._last_arrival = wall_ts

    def record_request(self, latency_ms: float, bucket: int,
                       queue_wait_ms: float = 0.0) -> bool:
        """One completed request; returns whether it missed the
        deadline (latency > the batcher's ``deadline_ms``)."""
        miss = latency_ms > self.deadline_ms
        with self._lock:
            self._requests += 1
            self._lat_sum += latency_ms
            self._queue_wait_sum += max(queue_wait_ms, 0.0)
            if miss:
                self._deadline_misses += 1
            self._window.append((float(latency_ms), int(bucket)))
            b = self._per_bucket.setdefault(
                int(bucket), {"requests": 0, "deadline_misses": 0,
                              "flushes": 0, "pad_sum": 0.0})
            b["requests"] += 1
            if miss:
                b["deadline_misses"] += 1
        return miss

    def record_failed_request(self, bucket: int):
        """A request whose device call ERRORED: it was never answered,
        so it burns the budget as both a deadline miss and an objective
        violation (an infinite latency fails any target) — a failing
        server must not report perfect attainment."""
        with self._lock:
            self._requests += 1
            self._errored += 1
            self._deadline_misses += 1
            self._window.append((float("inf"), int(bucket)))
            b = self._per_bucket.setdefault(
                int(bucket), {"requests": 0, "deadline_misses": 0,
                              "flushes": 0, "pad_sum": 0.0})
            b["requests"] += 1
            b["deadline_misses"] += 1

    def record_flush(self, n_real: int, bucket: int):
        pad = 1.0 - (n_real / bucket) if bucket else 0.0
        with self._lock:
            self._flushes += 1
            self._pad_sum += pad
            b = self._per_bucket.setdefault(
                int(bucket), {"requests": 0, "deadline_misses": 0,
                              "flushes": 0, "pad_sum": 0.0})
            b["flushes"] += 1
            b["pad_sum"] += pad

    def record_rejection(self, reason: str, wall_ts: Optional[float] = None):
        with self._lock:
            self._rejected[reason] = self._rejected.get(reason, 0) + 1
        if wall_ts is not None:
            self.note_arrival(wall_ts)

    # ----------------------------------------------------------- reading
    def _window_attainment(self, bucket: Optional[int] = None) \
            -> Optional[float]:  # requires-lock: self._lock
        """Fraction of rolling-window requests meeting their applicable
        objective (bucket override else overall); None when no objective
        applies to any window entry.  Caller holds the lock."""
        hits = total = 0
        for lat, b in self._window:
            if bucket is not None and b != bucket:
                continue
            target = self.objectives.objective_for(b)
            if target is None:
                continue
            total += 1
            if lat <= target:
                hits += 1
        return _ratio(hits, total)

    def snapshot(self) -> Dict:
        """The SLO state as one JSON-able dict (the ``serve_stats`` /
        ``slo.json`` payload core)."""
        with self._lock:
            attainment = self._window_attainment()
            burn = None
            if attainment is not None:
                budget = 1.0 - self.objectives.target_attainment
                burn = round((1.0 - attainment) / budget, 4)
            per_bucket = {}
            for b, rec in sorted(self._per_bucket.items()):
                per_bucket[str(b)] = {
                    "requests": int(rec["requests"]),
                    "deadline_misses": int(rec["deadline_misses"]),
                    "deadline_miss_ratio": _ratio(rec["deadline_misses"],
                                                  rec["requests"]),
                    "pad_waste": _ratio(rec["pad_sum"], rec["flushes"]),
                    "objective_ms": self.objectives.objective_for(b),
                    "attainment": self._window_attainment(b),
                }
            rate = None
            if self._ia_ewma is not None:   # floored > 0 in note_arrival
                rate = round(1.0 / self._ia_ewma, 3)
            return {
                "deadline_ms": self.deadline_ms,
                "objectives": self.objectives.to_doc(),
                "requests": self._requests,
                "errored_requests": self._errored,
                "deadline_misses": self._deadline_misses,
                "deadline_miss_ratio": _ratio(self._deadline_misses,
                                              self._requests),
                "attainment": attainment,
                "burn_rate": burn,
                "arrival_rate_rps": rate,
                "flushes": self._flushes,
                "pad_waste": _ratio(self._pad_sum, self._flushes),
                "queue_wait_frac": _ratio(self._queue_wait_sum,
                                          self._lat_sum),
                "rejected": dict(self._rejected),
                "window": {"size": len(self._window),
                           "capacity": self._window.maxlen},
                "per_bucket": per_bucket,
            }

    def publish_gauges(self):
        """Refresh the hub's ``slo_*`` gauges + deadline-miss counter
        from the current state (drainer cadence, never the flush path)."""
        if self.hub is None:
            return
        snap = self.snapshot()
        for name, key in (("slo_deadline_miss_ratio", "deadline_miss_ratio"),
                          ("slo_attainment", "attainment"),
                          ("slo_burn_rate", "burn_rate"),
                          ("slo_arrival_rate_rps", "arrival_rate_rps"),
                          ("slo_pad_waste", "pad_waste"),
                          ("slo_queue_wait_frac", "queue_wait_frac")):
            if snap.get(key) is not None:
                self.hub.gauge(name, snap[key], **self.tags)
        with self._lock:
            delta = self._deadline_misses - self._published_misses
            self._published_misses = self._deadline_misses
        if delta:
            self.hub.counter("serve_deadline_miss_total", delta,
                             **self.tags)


class ServeTracer:
    """Deferred span pipeline between the batcher's flush path and the
    observability stream.

    The batcher calls :meth:`record_flush` (a deque append of plain
    floats) and :meth:`note_rejection`; a daemon drainer thread converts
    pending records into

    - decomposition histograms (``serve_queue_wait_ms`` /
      ``serve_batch_wait_ms`` / ``serve_fanout_ms``, overall + per
      bucket; the device wall already lives in ``serve_batch_ms``),
    - one ``serve_flush`` event per device call (always recorded),
    - one ``serve_request_span`` event per head-sampled request
      (``sample`` = record every Nth trace id; 0 disables request
      spans), and
    - the :class:`SLOEngine` updates + ``slo_*`` gauge refresh.

    The pending queue is bounded; overflow drops the OLDEST record and
    counts it (``spans_dropped`` in the snapshot and a hub counter) —
    telemetry degrades loudly, the serve path never blocks on it."""

    def __init__(self, hub=None, sample: int = 0,
                 drain_interval_s: float = 0.05, max_pending: int = 8192):
        self.hub = hub
        self.sample = max(int(sample), 0)
        self.drain_interval_s = float(drain_interval_s)
        self.max_pending = int(max_pending)
        self.engine: Optional[SLOEngine] = None
        self._pending: deque = deque()   # guarded-by: self._append_lock
        self._dropped = 0                # guarded-by: self._append_lock
        self._published_dropped = 0      # guarded-by: self._append_lock
        self._flush_seq = 0              # guarded-by: self._drain_lock
        self._drain_lock = threading.Lock()
        self._append_lock = threading.Lock()
        self._stop_event = threading.Event()
        self._thread: Optional[threading.Thread] = None

    def bind_engine(self, engine: SLOEngine) -> "ServeTracer":
        self.engine = engine
        return self

    # ---------------------------------------------------------- lifecycle
    def start(self) -> "ServeTracer":
        if self._thread is None:
            self._stop_event.clear()
            self._thread = threading.Thread(target=self._run,
                                            name="gsc-serve-tracer",
                                            daemon=True)
            self._thread.start()
        return self

    def stop(self):
        """Stop the drainer and drain everything still pending."""
        self._stop_event.set()
        thread, self._thread = self._thread, None
        if thread is not None:
            thread.join(timeout=10.0)
        self.drain_pending()

    def _run(self):
        while not self._stop_event.wait(self.drain_interval_s):
            self.drain_pending()

    # ------------------------------------------------- batcher-side hooks
    def record_flush(self, rec: Dict):
        """Called from the batcher thread right after a flush: ``rec``
        holds timestamps + per-request tuples, nothing derived.  O(1),
        no I/O, no locks shared with the drain's emission work."""
        with self._append_lock:
            if len(self._pending) >= self.max_pending:
                self._pending.popleft()
                self._dropped += 1
            self._pending.append(("flush", rec))

    def note_rejection(self, reason: str, wall_ts: float):
        with self._append_lock:
            if len(self._pending) >= self.max_pending:
                self._pending.popleft()
                self._dropped += 1
            self._pending.append(("reject", reason, wall_ts))

    # --------------------------------------------------------------- drain
    def drain_pending(self):
        """Process every pending record (drainer thread, ``stop()`` and
        tests); serialized so records are handled in arrival order."""
        with self._drain_lock:
            batch: List = []
            with self._append_lock:
                while self._pending:
                    batch.append(self._pending.popleft())
            for item in batch:
                if item[0] == "flush":
                    self._drain_flush(item[1])
                else:
                    _, reason, wall_ts = item
                    if self.engine is not None:
                        self.engine.record_rejection(reason, wall_ts)
            if batch:
                if self.engine is not None:
                    self.engine.publish_gauges()
                self._publish_dropped()

    def _publish_dropped(self):
        if self.hub is None:
            return
        with self._append_lock:
            delta = self._dropped - self._published_dropped
            self._published_dropped = self._dropped
        if delta:
            self.hub.counter("serve_spans_dropped_total", delta)

    @property
    def spans_dropped(self) -> int:
        with self._append_lock:
            return self._dropped

    def _drain_flush(self, rec: Dict):  # requires-lock: self._drain_lock
        bucket = rec["bucket"]
        n_real = rec["n_real"]
        t_dispatch = rec["t_dispatch"]
        t_device_done = rec["t_device_done"]
        device_ms = (t_device_done - t_dispatch) * 1e3
        pad_fraction = round(1.0 - n_real / bucket, 6) if bucket else 0.0
        flush_id = self._flush_seq
        self._flush_seq += 1
        # fleet/hot-swap context: the policy version the device call ran
        # under (stamped under the batcher's flush lock, so it is exact)
        # and the worker id — both ride every serve_flush event and span
        # when the batcher declares them (None/absent otherwise)
        extra = {}
        if rec.get("policy_version") is not None:
            extra["policy_version"] = rec["policy_version"]
        if rec.get("worker"):
            extra["worker"] = rec["worker"]
        if self.engine is not None:
            self.engine.record_flush(n_real, bucket)
        if rec.get("error") is not None:
            # failed device call: the requests were never answered —
            # count them against the budget (misses + objective
            # violations), record the flush slice with its error, and
            # skip the per-request decomposition (there is none)
            if self.engine is not None:
                for (trace_id, wall_enq, _t_enq, _t_admit, _t) \
                        in rec["requests"]:
                    self.engine.record_failed_request(bucket)
                    self.engine.note_arrival(wall_enq)
            if self.hub is not None:
                self.hub.event("serve_flush",
                               ts=round(rec["wall_dispatch"], 6),
                               flush_id=flush_id, bucket=bucket,
                               n_real=n_real, pad_fraction=pad_fraction,
                               device_ms=round(device_ms, 4),
                               queue_depth=rec.get("queue_depth"),
                               error=rec["error"], **extra)
            return
        spans = []
        for (trace_id, wall_enq, t_enq, t_admit, t_done) in rec["requests"]:
            queue_wait_ms = (t_admit - t_enq) * 1e3
            batch_wait_ms = (t_dispatch - t_admit) * 1e3
            fanout_ms = (t_done - t_device_done) * 1e3
            # end-to-end to device-result availability — the exact value
            # the batcher recorded as serve_latency_ms for this request,
            # so queue + batch + device == latency by construction
            latency_ms = (t_device_done - t_enq) * 1e3
            miss = None
            if self.engine is not None:
                miss = self.engine.record_request(
                    latency_ms, bucket, queue_wait_ms=queue_wait_ms)
                self.engine.note_arrival(wall_enq)
            if self.hub is not None:
                for name, v in (("serve_queue_wait_ms", queue_wait_ms),
                                ("serve_batch_wait_ms", batch_wait_ms),
                                ("serve_fanout_ms", fanout_ms)):
                    self.hub.observe(name, v)
                    self.hub.observe(name, v, bucket=bucket)
            if self.sample and trace_id % self.sample == 0:
                spans.append({
                    "trace_id": trace_id, "flush_id": flush_id,
                    "bucket": bucket,
                    "ts": round(wall_enq, 6),
                    "queue_wait_ms": round(queue_wait_ms, 4),
                    "batch_wait_ms": round(batch_wait_ms, 4),
                    "device_ms": round(device_ms, 4),
                    "fanout_ms": round(fanout_ms, 4),
                    "latency_ms": round(latency_ms, 4),
                    "deadline_miss": miss,
                    **extra,
                })
        if self.hub is not None:
            # flush-level span: ALWAYS recorded (one per device call);
            # ts pinned to the dispatch wall time so the trace exporter
            # gets faithful geometry despite the deferred emission
            self.hub.event("serve_flush", ts=round(rec["wall_dispatch"], 6),
                           flush_id=flush_id, bucket=bucket, n_real=n_real,
                           pad_fraction=pad_fraction,
                           device_ms=round(device_ms, 4),
                           queue_depth=rec.get("queue_depth"), **extra)
            for span in spans:
                self.hub.event("serve_request_span", **span)


def write_slo_json(path: str, doc: Dict) -> str:
    """Atomic ``slo.json`` write (same contract as metrics.json)."""
    from .sinks import write_atomic_json

    return write_atomic_json(path, doc)
