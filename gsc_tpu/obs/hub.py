"""Process-wide metrics hub: counters, gauges, histograms, heartbeats.

One :class:`MetricsHub` per run.  Writers are the training loop, the
prefetcher thread (heartbeats) and the watchdog thread (stall events), so
every mutation takes the hub lock — all operations are O(1) dict updates
plus a bounded-deque append, cheap enough for per-episode cadence.

Names follow Prometheus conventions: ``snapshot()`` flattens every series
to ``gsc_<name>{tag="value",...}`` text-exposition keys (histograms expand
to ``_count``/``_sum``/``_min``/``_max``/``_p50``/``_p90``/``_p99``), so a
``metrics.json`` written from it can be tailed or scraped without knowing
the hub's internal structure.
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Dict, Iterable, List, Optional, Tuple

# percentile window: enough samples to make p99 meaningful over a long run
# without unbounded memory; a run logging 1 episode/s holds ~17 min of
# history, which is the window a live-tail debugging session cares about
_HIST_WINDOW = 1024
_PCTS = ((0.50, "p50"), (0.90, "p90"), (0.99, "p99"))


def _percentile(sorted_vals: List[float], q: float) -> float:
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return 0.0
    idx = min(int(q * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[idx]


class _Histogram:
    __slots__ = ("count", "total", "vmin", "vmax", "window")

    def __init__(self):
        self.count = 0
        self.total = 0.0
        self.vmin = float("inf")
        self.vmax = float("-inf")
        self.window = deque(maxlen=_HIST_WINDOW)

    def observe(self, value: float):
        self.count += 1
        self.total += value
        self.vmin = min(self.vmin, value)
        self.vmax = max(self.vmax, value)
        self.window.append(value)

    def summary(self) -> Dict[str, float]:
        vals = sorted(self.window)
        out = {"count": float(self.count), "sum": self.total,
               "min": self.vmin if self.count else 0.0,
               "max": self.vmax if self.count else 0.0,
               "mean": self.total / self.count if self.count else 0.0}
        for q, label in _PCTS:
            out[label] = _percentile(vals, q)
        return out


# a series key is (name, sorted tag items) — hashable and order-insensitive
_Key = Tuple[str, Tuple[Tuple[str, str], ...]]


def _key(name: str, tags: Dict[str, object]) -> _Key:
    return name, tuple(sorted((k, str(v)) for k, v in tags.items()))


def flat_name(name: str, tags: Iterable[Tuple[str, str]]) -> str:
    """Prometheus-text-style series name: ``gsc_name{k="v",...}``."""
    label = ",".join(f'{k}="{v}"' for k, v in tags)
    return f"gsc_{name}{{{label}}}" if label else f"gsc_{name}"


class MetricsHub:
    """Counters, gauges and histograms tagged by run/replica, plus the
    heartbeat registry the :class:`~gsc_tpu.obs.watchdog.PipelineWatchdog`
    polls and the event fan-out the JSONL stream hangs off."""

    def __init__(self, tags: Optional[Dict[str, object]] = None,
                 series_window: int = 0):
        self._lock = threading.RLock()
        self.base_tags: Dict[str, str] = {
            k: str(v) for k, v in (tags or {}).items()}
        self._counters: Dict[_Key, float] = {}        # guarded-by: self._lock
        self._gauges: Dict[_Key, float] = {}          # guarded-by: self._lock
        self._live_gauges: Dict[_Key, object] = {}    # guarded-by: self._lock
        self._hists: Dict[_Key, _Histogram] = {}      # guarded-by: self._lock
        self._beats: Dict[str, float] = {}            # guarded-by: self._lock
        self._last_phase: Optional[str] = None        # guarded-by: self._lock
        self._last_phase_done = False                 # guarded-by: self._lock
        # per-thread pipeline phase (fleet watchdog coverage): a wedged
        # actor's stall event names the phase IT was in, not the main
        # loop's
        self._thread_phases: Dict[str, str] = {}      # guarded-by: self._lock
        self._sinks: list = []                        # guarded-by: self._lock
        # time-series rings (the flight recorder; ``--obs-series-window``):
        # None = history off, series() is a no-op and every snapshot /
        # event byte stays identical to the history-free hub
        self.series_store = None
        if series_window and series_window > 0:
            from .series import SeriesStore
            self.series_store = SeriesStore(window=series_window,
                                            base_tags=self.base_tags)

    # ------------------------------------------------------------- series
    def counter(self, name: str, inc: float = 1.0, **tags) -> float:
        """Monotonic counter; returns the new value."""
        k = _key(name, tags)
        with self._lock:
            val = self._counters.get(k, 0.0) + inc
            self._counters[k] = val
            return val

    def get_counter(self, name: str, **tags) -> float:
        with self._lock:
            return self._counters.get(_key(name, tags), 0.0)

    def gauge(self, name: str, value: float, **tags):
        """Point-in-time value (last write wins)."""
        with self._lock:
            self._gauges[_key(name, tags)] = float(value)

    def get_gauge(self, name: str, **tags) -> Optional[float]:
        with self._lock:
            return self._gauges.get(_key(name, tags))

    def live_gauge(self, name: str, probe, **tags):
        """Register a zero-arg probe sampled at every :meth:`snapshot` —
        the /metrics endpoint scrapes through snapshot, so a live probe
        (e.g. the serve queue depth) stays current between the event
        writers' explicit samples.  The probe runs under the hub lock:
        keep it O(1) and lock-free (a ``qsize()``, a counter read)."""
        with self._lock:
            self._live_gauges[_key(name, tags)] = probe

    def drop_live_gauge(self, name: str, **tags):
        with self._lock:
            self._live_gauges.pop(_key(name, tags), None)

    def series(self, name: str, value: float, ts: Optional[float] = None,
               **tags):
        """Append one ``(ts, value)`` point to the metric's bounded ring
        (drop-oldest; the flight recorder's history).  A no-op when the
        hub was built without a series window, so feed sites never need
        to gate themselves.  The store has its own lock — a series feed
        never contends with snapshot scrapes on the hub lock."""
        if self.series_store is not None:
            self.series_store.add_point(name, value, ts=ts, **tags)

    def observe(self, name: str, value: float, **tags):
        """Histogram sample (count/sum/min/max + windowed percentiles)."""
        k = _key(name, tags)
        with self._lock:
            hist = self._hists.get(k)
            if hist is None:
                hist = self._hists[k] = _Histogram()
            hist.observe(float(value))

    def histogram_summary(self, name: str, **tags) -> Optional[Dict[str, float]]:
        with self._lock:
            h = self._hists.get(_key(name, tags))
            return h.summary() if h else None

    # --------------------------------------------------------- heartbeats
    def beat(self, name: str):
        """Record liveness of a component (trainer loop, prefetcher, ...)."""
        with self._lock:
            self._beats[name] = time.monotonic()

    def beat_age(self, name: str) -> Optional[float]:
        """Seconds since ``name`` last beat; None if it never has."""
        with self._lock:
            t = self._beats.get(name)
        return None if t is None else time.monotonic() - t

    def beat_time(self, name: str) -> Optional[float]:
        """Raw monotonic timestamp of the last beat (watchdog re-arm key)."""
        with self._lock:
            return self._beats.get(name)

    def beat_ages(self) -> Dict[str, float]:
        now = time.monotonic()
        with self._lock:
            return {n: round(now - t, 3) for n, t in self._beats.items()}

    # ---------------------------------------------------- phase bookkeeping
    def note_phase(self, name: str, done: bool = False):
        """Track the pipeline phase currently executing (``done=False``) or
        just finished (``done=True``) — a stall event reports both so a hang
        points at the phase it is stuck *in*."""
        with self._lock:
            self._last_phase = name
            self._last_phase_done = done

    @property
    def last_phase(self) -> Tuple[Optional[str], bool]:
        with self._lock:
            return self._last_phase, self._last_phase_done

    def note_thread_phase(self, thread: str, phase: str):
        """Track the phase one named pipeline thread (actor0, learner,
        ...) is currently in — the fleet watchdog reports it when THAT
        thread's heartbeat goes quiet, so a stall says ``blocked_put``
        vs ``dispatch`` vs ``adopt`` instead of pointing at the main
        loop."""
        with self._lock:
            self._thread_phases[thread] = phase

    def thread_phase(self, thread: str) -> Optional[str]:
        with self._lock:
            return self._thread_phases.get(thread)

    def thread_phases(self) -> Dict[str, str]:
        with self._lock:
            return dict(self._thread_phases)

    # -------------------------------------------------------------- events
    def add_sink(self, sink):
        with self._lock:
            self._sinks.append(sink)

    def event(self, kind: str, **fields) -> Dict[str, object]:
        """Emit one structured record to every sink; returns the record.
        Base tags (run id, ...) merge in under the caller's fields."""
        record = {"event": kind, "ts": round(time.time(), 3),
                  **self.base_tags, **fields}
        with self._lock:
            sinks = list(self._sinks)
        for sink in sinks:
            sink.emit(record)
        return record

    # ------------------------------------------------------------ snapshot
    def snapshot(self) -> Dict[str, float]:
        """Flat ``{prometheus_name: value}`` view of every live series.
        Live-gauge probes are sampled first (their latest value also
        lands in the plain gauge table, so ``get_gauge`` and later
        snapshots agree with what was served)."""
        with self._lock:
            for k, probe in list(self._live_gauges.items()):
                try:
                    self._gauges[k] = float(probe())
                except Exception:   # a dead probe must not break scrapes
                    pass
            base = tuple(self.base_tags.items())
            merge = lambda tags: tuple(sorted(base + tags))
            out: Dict[str, float] = {}
            for (name, tags), v in self._counters.items():
                out[flat_name(name, merge(tags))] = v
            for (name, tags), v in self._gauges.items():
                out[flat_name(name, merge(tags))] = v
            for (name, tags), h in self._hists.items():
                s = h.summary()
                for suffix in ("count", "sum", "min", "max", "p50", "p90",
                               "p99"):
                    out[flat_name(f"{name}_{suffix}", merge(tags))] = s[suffix]
            return out

    def close(self):
        with self._lock:
            sinks, self._sinks = list(self._sinks), []
        for sink in sinks:
            try:
                sink.close()
            except Exception:  # a failing sink must not mask run teardown
                pass
