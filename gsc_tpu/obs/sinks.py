"""Event sinks + atomic snapshot writer for the metrics hub.

``events.jsonl`` is append-only (one JSON object per line — safe to tail
while a run is in flight); ``metrics.json`` is a whole-file snapshot
rewritten atomically (temp file + ``os.replace``) so a poller never reads
a half-written document.
"""
from __future__ import annotations

import json
import os
import threading
from collections import deque
from typing import Dict, List

import numpy as np


def jsonable(obj):
    """Best-effort conversion of event-record leaves to JSON types —
    device scalars and numpy arrays show up in episode stats."""
    if isinstance(obj, np.generic):
        return obj.item()
    if isinstance(obj, np.ndarray):
        return obj.tolist()
    if hasattr(obj, "item") and getattr(obj, "ndim", None) == 0:
        return obj.item()   # 0-d jax arrays without importing jax here
    if hasattr(obj, "tolist"):
        return obj.tolist()
    return str(obj)


def rotated_paths(path: str) -> List[str]:
    """Every on-disk segment of a (possibly rotated) JSONL stream, oldest
    first: ``events.jsonl.N .. events.jsonl.1, events.jsonl``.  Readers
    (tools/obs_report.py, the trace exporter) concatenate them to see one
    continuous stream; a never-rotated run yields just ``[path]``."""
    n = 1
    older = []
    while os.path.exists(f"{path}.{n}"):
        older.append(f"{path}.{n}")
        n += 1
    return list(reversed(older)) + [path]


class JsonlSink:
    """Append-only JSONL event stream; every record flushed so a live run
    can be tailed.  ``emit`` is called from the training loop AND the
    watchdog thread — serialized by a lock.

    ``rotate_mb > 0`` enables size-based rotation for the 100+-episode
    exhibits: when the live file exceeds the budget it is renamed to
    ``<path>.1`` (existing ``.k`` segments shift to ``.k+1``) and a fresh
    file opened — the stream stays tail-able and :func:`rotated_paths`
    reassembles the full history."""

    def __init__(self, path: str, rotate_mb: float = 0.0):
        os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
        self.path = path
        self.rotate_bytes = int(max(rotate_mb, 0.0) * 2 ** 20)
        self._lock = threading.Lock()
        self._file = open(path, "a")   # guarded-by: self._lock

    def _rotate(self):  # requires-lock: self._lock
        """Shift <path>.k -> <path>.k+1 (highest first), live -> .1,
        reopen fresh.  Caller holds the lock (the ``requires-lock``
        annotation above tells R7 so — emit() is the only caller).

        The live handle is retired via ``contextlib.closing`` rather
        than a direct ``.close()`` call: ``emit`` shares its name with a
        device-side scan body, so gsc-lint's name-graph walks this
        host-only path as if it were traced — a bare ``.close()`` edge
        here would fuse every ``close`` method in the repo into the jit
        cone and flag their host clocks/casts as trace-time syncs."""
        import contextlib
        with contextlib.closing(self._file):
            self._file.flush()
        n = 1
        while os.path.exists(f"{self.path}.{n}"):
            n += 1
        for k in range(n, 1, -1):
            os.replace(f"{self.path}.{k - 1}", f"{self.path}.{k}")
        os.replace(self.path, f"{self.path}.1")
        self._file = open(self.path, "a")

    def emit(self, record: Dict):
        line = json.dumps(record, default=jsonable)
        with self._lock:
            if self._file is None:
                return   # late event after close (e.g. watchdog teardown)
            self._file.write(line + "\n")
            self._file.flush()
            if self.rotate_bytes and self._file.tell() >= self.rotate_bytes:
                self._rotate()

    def close(self):
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None


class TailSink:
    """Bounded in-memory tail of the event stream — the black-box dump's
    "pending events" source.  Keeps the last ``maxlen`` records (already
    JSON-round-tripped, so the dump writes exactly what the JSONL reader
    would have seen); drop-oldest, thread-safe, O(1) per emit."""

    def __init__(self, maxlen: int = 256):
        self._records = deque(maxlen=int(maxlen))   # guarded-by: self._lock
        self._lock = threading.Lock()

    def emit(self, record: Dict):
        line = json.dumps(record, default=jsonable)
        with self._lock:
            self._records.append(json.loads(line))

    def tail(self) -> List[Dict]:
        with self._lock:
            return list(self._records)

    def close(self):
        pass


class ListSink:
    """In-memory sink for tests and the report selftest."""

    def __init__(self):
        self.records: List[Dict] = []
        self._lock = threading.Lock()

    def emit(self, record: Dict):
        with self._lock:
            # round-trip through JSON so tests see exactly what a JSONL
            # reader would — schema drift fails here, not in production
            self.records.append(json.loads(json.dumps(record,
                                                      default=jsonable)))

    def of_kind(self, kind: str) -> List[Dict]:
        with self._lock:
            return [r for r in self.records if r.get("event") == kind]

    def close(self):
        pass


def write_atomic_json(path: str, obj) -> str:
    """Write ``obj`` as JSON via temp-file + ``os.replace`` so concurrent
    readers always see a complete document."""
    path = os.path.abspath(path)
    os.makedirs(os.path.dirname(path), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(obj, f, default=jsonable, indent=0, sort_keys=True)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
    return path
