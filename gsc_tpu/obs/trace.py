"""Profiler annotations + the events.jsonl -> Perfetto trace exporter.

Two halves, one module (both are "how a run becomes a timeline"):

**Live annotations** — ``--profile`` traces of the pipelined trainer used
to be one opaque blob: the fused rollout+learn program, the prefetch
waits and the metric drains all interleave with nothing attributing
device time to pipeline phases.  :func:`phase_span` wraps the host-side
phases in ``jax.profiler.TraceAnnotation`` and :func:`episode_span` marks
each episode dispatch with ``jax.profiler.StepTraceAnnotation``.
Annotation names are stable API — tooling and docs reference them:
``host_sample``, ``host_sample_wait``, ``dispatch``, ``drain`` (phase
ranges) and ``episode_step`` (the per-episode step marker).

**Post-hoc export** — a run's ``events.jsonl`` already carries everything
a timeline needs (episode boundaries, cumulative PhaseTimer totals,
stalls, recovery ladders, compile events, serve stats), but reading a
stall out of log-line timestamp deltas is archaeology.
:func:`build_trace` renders the stream into Chrome trace-event JSON
(the format Perfetto / ``chrome://tracing`` open directly): one track
per logical thread — episode loop, prefetcher, serve, serve_request,
watchdog, compile — with watchdog stalls as instant events,
recovery/rollback ladders chained by flow arrows, batcher flushes as
complete slices on the serve track, and head-sampled
``serve_request_span`` events as slices on the serve_request track
whose flow arrows link each request through its batcher flush to the
device call that answered it.  The async flight-recorder records
(``async_actor_ep`` / ``async_learner_spans``, emitted deferred at run
end by ``run_async`` when the hub keeps series history) reconstruct the
decoupled fleet: one track per actor (rollout slices, backpressure-wait
``put`` slices, ``adopt`` marks), a channel track (each block's queued
put->pop residency), and a learner track (``replay_ingest`` /
``learn_burst`` slices, ``publish`` marks) — with put->pop flow arrows
carrying block size + staleness wait and publish->adopt arrows linking
every weight version to each actor that adopted it.
Phase sub-spans are RECONSTRUCTED from the
cumulative per-episode deltas (laid back-to-back inside each episode's
span and clamped to it), so they show relative share faithfully but not
exact start times.  :func:`validate_trace` is the strict schema check
(monotone ts per track, matched B/E pairs, pid/tid present) that CI and
the exporter gate on; ``tools/trace_export.py`` is the CLI.

The export half is deliberately jax-free (stdlib + the sibling sinks
reader) — it must run anywhere the events stream can be copied to.
"""
from __future__ import annotations

import json
import os
from contextlib import contextmanager
from typing import Dict, List, Optional


@contextmanager
def phase_span(name: str, timer=None, hub=None):
    """One pipeline phase: profiler range + optional
    :class:`~gsc_tpu.utils.telemetry.PhaseTimer` accumulation + hub
    last-phase bookkeeping (what a stall event reports being stuck in)."""
    import jax

    if hub is not None:
        hub.note_phase(name, done=False)
    with jax.profiler.TraceAnnotation(name):
        try:
            if timer is not None:
                with timer.phase(name):
                    yield
            else:
                yield
        finally:
            if hub is not None:
                hub.note_phase(name, done=True)


@contextmanager
def episode_span(step: int, name: str = "episode_step"):
    """Step marker around one episode's device dispatch, so profiler UIs
    attribute device time per episode instead of one run-length blob."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=int(step)):
        yield


# --------------------------------------------------------------- exporter
# one pid per run stream; fixed tids = the logical threads of a run.
# Stable API: tools and tests reference these names.
TRACE_PID = 1
TRACE_TRACKS = {
    "episode": 1,        # training loop: episode spans + phase sub-spans
    "prefetcher": 2,     # producer-thread restarts
    "serve": 3,          # serve_start/serve_stats counters + flush slices
    "watchdog": 4,       # stalls, escalations, invariant violations
    "compile": 5,        # jit trace/XLA compile spans + compile_cost marks
    "recovery": 6,       # self-healing ladder, chained by flow arrows
    "serve_request": 7,  # head-sampled request spans, flow-linked to the
                         # batcher flush that answered them
    "channel": 8,        # async actor->learner conduit: one slice per
                         # block's queued residency (put -> pop)
    "learner": 9,        # async learner: ingest + learn_burst slices,
                         # publish marks (flow-linked to actor adopts)
}
# per-actor async tracks start here: actor a renders on tid BASE + a
ACTOR_TRACK_BASE = 16
# phase sub-span layout order inside an episode slice (the obs schema's
# cumulative PhaseTimer names)
_TRACE_PHASES = ("host_sample", "host_sample_wait", "dispatch", "drain")


def _event_ts(e) -> float:
    ts = e.get("ts") if isinstance(e, dict) else None
    return float(ts) if isinstance(ts, (int, float)) \
        and not isinstance(ts, bool) else float("-inf")


def sort_events(events: List[Dict]) -> List[Dict]:
    """Stable ts-sort WITHIN each run's slice of an (append-mode) stream.
    Runs are delimited by ``run_start`` in file order — a later run whose
    wall clock stepped backwards (NTP, VM resume) must never interleave
    into the previous run's tail, so the sort is per-run, not global.
    Within one run the reorder window is the emit race (ts stamped
    before the sink lock), which is same-run by construction."""
    out: List[Dict] = []
    seg: List[Dict] = []
    for e in events:
        if isinstance(e, dict) and e.get("event") == "run_start" and seg:
            seg.sort(key=_event_ts)
            out.extend(seg)
            seg = []
        seg.append(e)
    seg.sort(key=_event_ts)
    out.extend(seg)
    return out


def read_events(path: str) -> List[Dict]:
    """Load a run's event stream: accepts the run dir or the events.jsonl
    itself, walks rotated segments (``events.jsonl.N .. .1`` then the
    live file — the ``--obs-rotate-mb`` layout), skips torn tail lines.

    Events come back SORTED by ``ts`` within each run (stable — same-ts
    records keep file order; see :func:`sort_events`): the hub stamps
    ``ts`` before taking the sink lock, so concurrently-emitting threads
    (watchdog, prefetcher, main loop) can land out of order in the file,
    and a rotation can split an interleaving across segments.  Every
    consumer of this reader (trace builder, curves extraction) assumes
    one monotone stream per run — sorting here is what makes that
    assumption true, and keeping it per-run means appended runs never
    interleave even when the wall clock stepped backwards between
    them."""
    from .sinks import rotated_paths

    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    segments = [p for p in rotated_paths(path) if os.path.exists(p)]
    if not segments:
        raise FileNotFoundError(f"no events stream at {path}")
    events = []
    for seg in segments:
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue   # torn final line of a live segment
    return sort_events(events)


def _us(ts: float, t0: float) -> float:
    return round((ts - t0) * 1e6, 1)


def build_trace(events: List[Dict]) -> Dict:
    """Chrome trace-event JSON from an obs event stream.

    Episode slices sit back-to-back on the episode track (each ends at
    its event's wall ts); phase sub-spans are reconstructed from the
    per-episode deltas of the cumulative PhaseTimer totals, laid
    sequentially inside the episode slice and scaled down if they would
    overflow it — faithful shares, synthetic start times.  Stalls /
    escalations / invariant violations are instants on the watchdog
    track; consecutive ``recovery`` events chain with flow arrows so a
    retry -> restart -> rollback ladder reads as one connected story."""
    events = [e for e in events if isinstance(e, dict) and "ts" in e]
    if not events:
        return {"traceEvents": [], "displayTimeUnit": "ms"}
    # read_events already sorts, but the builder also accepts raw lists
    # (tests, in-memory sinks) — re-apply the SAME per-run sort so a
    # later run whose clock stepped backwards is never woven into the
    # previous run's slices here either.  (The trace is one timeline, so
    # the final output sort below still orders such streams globally —
    # a Chrome-format requirement; multi-run streams with non-monotone
    # clocks render best-effort.)  Stable: same-ts events keep caller
    # order.
    events = sort_events(events)
    # the async flight-recorder records (``async_actor_ep`` /
    # ``async_learner_spans``) are emitted DEFERRED at run end but carry
    # their own wall timestamps from mid-run — the trace origin must
    # include those payload times or every reconstructed span would land
    # at a negative offset and fail the strict validator
    t_min = [float(e["ts"]) for e in events]
    for e in events:
        k = e.get("event")
        if k == "async_actor_ep":
            t_min.extend(float(r[0]) for r in (e.get("chunks") or []))
            t_min.extend(float(r[0]) - float(r[1])
                         for r in (e.get("puts") or []))
            t_min.extend(float(r[0]) for r in (e.get("adopts") or []))
        elif k == "async_learner_spans":
            for field in ("ingests", "bursts", "publishes"):
                t_min.extend(float(r[0]) for r in (e.get(field) or []))
    t0 = min(t_min)
    run = next((e.get("run") for e in events if e.get("run")), "run")
    out: List[Dict] = []

    # named `push`, not `emit`: a device-side scan body already owns
    # that name, and gsc-lint's name-graph would treat this host-only
    # helper as traced
    def push(ph, name, tid, ts_us, dur=None, args=None, **extra):
        ev = {"ph": ph, "name": name, "pid": TRACE_PID, "tid": tid,
              "ts": ts_us, "cat": "gsc"}
        if dur is not None:
            ev["dur"] = dur
        if args:
            ev["args"] = args
        ev.update(extra)
        out.append(ev)

    # track metadata (ph "M"): process + thread names
    out.append({"ph": "M", "name": "process_name", "pid": TRACE_PID,
                "tid": 0, "ts": 0.0, "args": {"name": f"gsc_tpu {run}"}})
    for label, tid in TRACE_TRACKS.items():
        out.append({"ph": "M", "name": "thread_name", "pid": TRACE_PID,
                    "tid": tid, "ts": 0.0, "args": {"name": label}})

    ep_tid = TRACE_TRACKS["episode"]
    prev_phase_totals: Dict[str, float] = {}
    prev_end = 0.0            # episode-track cursor (monotone)
    compile_end = 0.0         # compile-track cursor
    recoveries = [e for e in events if e.get("event") == "recovery"]
    rec_index = {id(e): i for i, e in enumerate(recoveries)}
    flow_id = 0
    # serving flushes index ((run-segment, flush_id) -> dispatch ts_us):
    # sampled request spans flow-arrow into the flush slice that
    # answered them; built up front because span events carry their
    # ENQUEUE wall time, which always precedes the flush's dispatch time
    # in the sorted stream.  Keyed per run_start segment, not by
    # flush_id alone — appended runs in a reused --obs-dir each restart
    # their flush ids at 0, and a run-1 span must never arrow into a
    # run-2 flush slice
    seg_of: Dict[int, int] = {}
    seg = 0
    for e in events:
        if e.get("event") == "run_start":
            seg += 1
        seg_of[id(e)] = seg
    flush_ts = {(seg_of[id(e)], e.get("flush_id")): _us(float(e["ts"]), t0)
                for e in events
                if e.get("event") == "serve_flush"
                and e.get("flush_id") is not None}
    # async flight-recorder indices (same per-segment keying): put->pop
    # flows need each block's ingest start by seq, publish->adopt flows
    # need each version's publish time; both live in deferred learner
    # records that can sort before OR after the actor records
    async_ingest: Dict[tuple, List] = {}
    async_pub: Dict[tuple, float] = {}
    actor_ids = set()
    for e in events:
        k = e.get("event")
        if k == "async_learner_spans":
            s = seg_of[id(e)]
            for row in (e.get("ingests") or []):
                async_ingest[(s, int(row[5]))] = row
            for p_ts, ver in (e.get("publishes") or []):
                async_pub.setdefault((s, int(ver)), float(p_ts))
        elif k == "async_actor_ep":
            actor_ids.add(int(e.get("actor") or 0))
    for a in sorted(actor_ids):
        out.append({"ph": "M", "name": "thread_name", "pid": TRACE_PID,
                    "tid": ACTOR_TRACK_BASE + a, "ts": 0.0,
                    "args": {"name": f"actor{a}"}})

    for ev in events:
        kind = ev.get("event")
        ts_us = _us(float(ev["ts"]), t0)
        if kind == "run_start":
            prev_phase_totals = {}
            prev_end = max(prev_end, ts_us)
            push("i", "run_start", ep_tid, ts_us, s="t",
                 args={k: v for k, v in ev.items()
                       if k in ("run", "episodes", "replicas", "pipeline",
                                "precision", "substep_impl", "mesh")})
        elif kind == "episode":
            start = max(prev_end, 0.0)
            end = max(ts_us, start)
            push("B", f"episode {ev.get('episode')}", ep_tid, start,
                 args={"episode": ev.get("episode"), "sps": ev.get("sps"),
                       "return": ev.get("episodic_return")})
            totals = {n: i.get("total_s", 0.0)
                      for n, i in (ev.get("phases") or {}).items()}
            deltas = {n: max(t - prev_phase_totals.get(n, 0.0), 0.0)
                      for n, t in totals.items()}
            prev_phase_totals = totals
            order = [p for p in _TRACE_PHASES if deltas.get(p, 0) > 0] + \
                sorted(set(deltas) - set(_TRACE_PHASES))
            total_us = sum(deltas.get(p, 0.0) for p in order) * 1e6
            span = end - start
            scale = (span / total_us) if total_us > span else 1.0
            cursor = start
            for p in order:
                d = round(deltas.get(p, 0.0) * 1e6 * scale, 1)
                if d <= 0:
                    continue
                push("B", p, ep_tid, cursor,
                     args={"delta_ms": round(deltas[p] * 1e3, 3)})
                cursor = round(min(cursor + d, end), 1)
                push("E", p, ep_tid, cursor)
            push("E", f"episode {ev.get('episode')}", ep_tid, end)
            prev_end = end
        elif kind == "eval_episode":
            start = max(prev_end,
                        ts_us - round(float(ev.get("runtime_s") or 0.0)
                                      * 1e6, 1))
            end = max(ts_us, start)
            push("B", f"eval {ev.get('episode')}", ep_tid, start,
                 args={"return": ev.get("episodic_return"),
                       "succ_ratio": ev.get("succ_ratio")})
            push("E", f"eval {ev.get('episode')}", ep_tid, end)
            prev_end = end
        elif kind == "run_end":
            push("i", f"run_end ({ev.get('status')})", ep_tid,
                 max(ts_us, prev_end), s="t")
            prev_end = max(ts_us, prev_end)
        elif kind == "stall":
            push("i", "stall", TRACE_TRACKS["watchdog"], ts_us, s="g",
                 args={"age_s": ev.get("age_s"),
                       "budget_s": ev.get("budget_s"),
                       "last_phase": ev.get("last_phase"),
                       "dispatch_drain_lag": ev.get("dispatch_drain_lag")})
        elif kind == "escalation":
            push("i", "escalation", TRACE_TRACKS["watchdog"], ts_us,
                 s="g", args={"age_s": ev.get("age_s"),
                              "action": ev.get("action")})
        elif kind == "invariant_violation":
            push("i", "invariant_violation", TRACE_TRACKS["watchdog"],
                 ts_us, s="t",
                 args={"episode": ev.get("episode"),
                       "violations": len(ev.get("violations") or [])})
        elif kind == "recovery":
            name = f"{ev.get('site')}/{ev.get('action')}"
            i = rec_index[id(ev)]
            nxt = (_us(float(recoveries[i + 1]["ts"]), t0)
                   if i + 1 < len(recoveries) else ts_us + 1000.0)
            dur = round(max(min(1000.0, nxt - ts_us), 0.0), 1)
            tid = TRACE_TRACKS["recovery"]
            push("B", name, tid, ts_us,
                 args={"episode": ev.get("episode"),
                       "fault": ev.get("fault"),
                       "detail": ev.get("detail")})
            # flow arrows chain the ladder: this action -> the next one
            if i + 1 < len(recoveries):
                flow_id += 1
                push("s", "ladder", tid, ts_us, id=flow_id)
                push("f", "ladder", tid, nxt, id=flow_id, bp="e")
            push("E", name, tid, round(ts_us + dur, 1))
            if ev.get("site") == "prefetcher":
                push("i", ev.get("action") or "restart",
                     TRACE_TRACKS["prefetcher"], ts_us, s="t",
                     args={"episode": ev.get("episode")})
        elif kind == "compile":
            dur = round(float(ev.get("duration_s") or 0.0) * 1e6, 1)
            start = max(compile_end, ts_us - dur)
            end = max(ts_us, start)
            push("B", f"{ev.get('fn')} [{ev.get('stage')}]",
                 TRACE_TRACKS["compile"], start,
                 args={"count": ev.get("count")})
            push("E", f"{ev.get('fn')} [{ev.get('stage')}]",
                 TRACE_TRACKS["compile"], end)
            compile_end = end
        elif kind == "compile_cost":
            push("i", f"cost {ev.get('fn')}", TRACE_TRACKS["compile"],
                 max(ts_us, compile_end), s="t",
                 args={"flops": ev.get("flops"),
                       "bytes_accessed": ev.get("bytes_accessed"),
                       "fusions": ev.get("fusions")})
            compile_end = max(ts_us, compile_end)
        elif kind == "serve_start":
            push("i", "serve_start", TRACE_TRACKS["serve"], ts_us, s="t",
                 args={"tier": ev.get("tier"),
                       "startup_s": ev.get("startup_s")})
        elif kind == "serve_stats":
            push("C", "serve", TRACE_TRACKS["serve"], ts_us,
                 args={"rps": float(ev.get("rps") or 0.0),
                       "p99_ms": float(ev.get("p99_ms") or 0.0),
                       "queue_depth": float(ev.get("queue_depth") or 0)})
        elif kind == "serve_flush":
            # one complete slice per device call ("X": self-contained
            # duration, so overlapping flushes never unbalance a B/E
            # stack); ts is the dispatch wall time the tracer pinned
            dur = round(max(float(ev.get("device_ms") or 0.0), 0.0)
                        * 1e3, 1)
            push("X", f"flush b{ev.get('bucket')}", TRACE_TRACKS["serve"],
                 ts_us, dur=dur,
                 args={"flush_id": ev.get("flush_id"),
                       "n_real": ev.get("n_real"),
                       "pad_fraction": ev.get("pad_fraction")})
        elif kind == "serve_request_span":
            # sampled request: enqueue -> fan-out as one slice, with the
            # queue/batch/device/fan-out split in args; a flow arrow
            # links it to its flush's slice on the serve track
            total_ms = (float(ev.get("latency_ms") or 0.0)
                        + max(float(ev.get("fanout_ms") or 0.0), 0.0))
            push("X", f"req {ev.get('trace_id')}",
                 TRACE_TRACKS["serve_request"], ts_us,
                 dur=round(max(total_ms, 0.0) * 1e3, 1),
                 args={k: ev.get(k) for k in
                       ("trace_id", "flush_id", "bucket", "queue_wait_ms",
                        "batch_wait_ms", "device_ms", "fanout_ms",
                        "latency_ms", "deadline_miss")})
            f_ts = flush_ts.get((seg_of[id(ev)], ev.get("flush_id")))
            if f_ts is not None and f_ts >= ts_us:
                flow_id += 1
                push("s", "serve_req", TRACE_TRACKS["serve_request"],
                     ts_us, id=flow_id)
                push("f", "serve_req", TRACE_TRACKS["serve"], f_ts,
                     id=flow_id, bp="e")
        elif kind == "async_actor_ep":
            # one deferred record per actor-episode; every span below
            # uses the PAYLOAD wall times, not this record's emit ts.
            # All complete slices ("X") — reconstructed spans from three
            # concurrent threads must never share a B/E stack.
            aid = int(ev.get("actor") or 0)
            tid = ACTOR_TRACK_BASE + aid
            s = seg_of[id(ev)]
            ep = ev.get("ep")
            for c0, c1, ver in (ev.get("chunks") or []):
                push("X", f"rollout ep{ep}", tid, _us(float(c0), t0),
                     dur=round(max(float(c1) - float(c0), 0.0) * 1e6, 1),
                     args={"episode": ep, "version": int(ver)})
            for t_enq, wait_s, steps, ver, seq in (ev.get("puts") or []):
                t_enq, wait_s = float(t_enq), max(float(wait_s), 0.0)
                enq_us = _us(t_enq, t0)
                # the backpressure wait the put paid, on the actor track
                push("X", "put", tid, _us(t_enq - wait_s, t0),
                     dur=round(wait_s * 1e6, 1),
                     args={"seq": int(seq), "steps": int(steps),
                           "staleness_wait_s": round(wait_s, 6),
                           "version": int(ver)})
                ing = async_ingest.get((s, int(seq)))
                ing_us = _us(float(ing[0]), t0) if ing else None
                # queued residency on the channel track: put -> pop
                push("X", f"block s{seq}", TRACE_TRACKS["channel"],
                     enq_us,
                     dur=(round(max(ing_us - enq_us, 0.0), 1)
                          if ing_us is not None else 0.0),
                     args={"seq": int(seq), "steps": int(steps),
                           "staleness_wait_s": round(wait_s, 6),
                           "version": int(ver)})
                if ing_us is not None and ing_us >= enq_us:
                    flow_id += 1
                    push("s", "chan", tid, enq_us, id=flow_id,
                         args={"steps": int(steps),
                               "staleness_wait_s": round(wait_s, 6)})
                    push("f", "chan", TRACE_TRACKS["learner"], ing_us,
                         id=flow_id, bp="e")
            for a_ts, ver in (ev.get("adopts") or []):
                a_us = _us(float(a_ts), t0)
                push("i", f"adopt v{int(ver)}", tid, a_us, s="t",
                     args={"version": int(ver)})
                # publish -> adopt: one arrow per adopting actor (the
                # validator balances s/f per flow id, so a version
                # adopted by N actors gets N independent arrows)
                p_ts = async_pub.get((s, int(ver)))
                if p_ts is not None and _us(p_ts, t0) <= a_us:
                    flow_id += 1
                    push("s", f"publish v{int(ver)}",
                         TRACE_TRACKS["learner"], _us(p_ts, t0),
                         id=flow_id)
                    push("f", f"publish v{int(ver)}", tid, a_us,
                         id=flow_id, bp="e")
        elif kind == "async_learner_spans":
            ltid = TRACE_TRACKS["learner"]
            for row in (ev.get("ingests") or []):
                # rows grew a trailing dp-shard id (producer's stable
                # assignment) with the sharded async ring; pre-shard
                # recordings carry 6 elements — unpack tolerantly
                i0, i1, steps, ver, lag, seq = row[:6]
                args = {"seq": int(seq), "steps": int(steps),
                        "version": int(ver), "policy_lag": int(lag)}
                if len(row) > 6:
                    args["replay_shard"] = int(row[6])
                push("X", "replay_ingest", ltid, _us(float(i0), t0),
                     dur=round(max(float(i1) - float(i0), 0.0) * 1e6, 1),
                     args=args)
            for b0, b1, n in (ev.get("bursts") or []):
                push("X", f"learn_burst {int(n)}", ltid,
                     _us(float(b0), t0),
                     dur=round(max(float(b1) - float(b0), 0.0) * 1e6, 1),
                     args={"burst": int(n)})
            for p_ts, ver in (ev.get("publishes") or []):
                push("i", f"publish v{int(ver)}", ltid,
                     _us(float(p_ts), t0), s="t",
                     args={"version": int(ver)})
        # other event kinds (precision, harness_episode, ...) carry no
        # timeline geometry — the report renders them, the trace skips them

    # flows ride INSIDE slices; keep pairs adjacent under the stable sort
    order_key = {"M": 0}
    out.sort(key=lambda e: (e.get("ts", 0.0),
                            order_key.get(e.get("ph"), 1)))
    return {"traceEvents": out, "displayTimeUnit": "ms",
            "metadata": {"run": run, "exporter": "gsc_tpu.obs.trace",
                         "t0_unix_s": t0}}


def validate_trace(trace: Dict) -> List[str]:
    """Strict schema check; returns a list of problems (empty = valid).

    Rules: every event carries ph/name/pid/tid and a numeric ts >= 0;
    events are globally sorted by ts; per (pid, tid) the B/E events form
    a properly nested stack (names match, nothing left open); "X" events
    need dur >= 0; every flow start ("s") has a matching finish ("f")."""
    errors: List[str] = []
    events = trace.get("traceEvents")
    if not isinstance(events, list):
        return ["traceEvents missing or not a list"]
    stacks: Dict[tuple, List[str]] = {}
    flows_open: Dict[object, int] = {}
    last_ts = None
    for i, ev in enumerate(events):
        for field in ("ph", "name", "pid", "tid"):
            if field not in ev:
                errors.append(f"event {i}: missing {field!r}")
        ph = ev.get("ph")
        ts = ev.get("ts")
        if not isinstance(ts, (int, float)) or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if ph != "M":
            if last_ts is not None and ts < last_ts:
                errors.append(f"event {i}: ts {ts} < previous {last_ts} "
                              "(stream not monotone)")
            last_ts = ts
        key = (ev.get("pid"), ev.get("tid"))
        if ph == "B":
            stacks.setdefault(key, []).append(ev.get("name"))
        elif ph == "E":
            stack = stacks.get(key) or []
            if not stack:
                errors.append(f"event {i}: E with empty stack on {key}")
            else:
                top = stack.pop()
                if ev.get("name") and ev["name"] != top:
                    errors.append(f"event {i}: E {ev['name']!r} does not "
                                  f"match open B {top!r} on {key}")
        elif ph == "X":
            if not isinstance(ev.get("dur"), (int, float)) \
                    or ev["dur"] < 0:
                errors.append(f"event {i}: X with bad dur {ev.get('dur')!r}")
        elif ph == "s":
            flows_open[ev.get("id")] = flows_open.get(ev.get("id"), 0) + 1
        elif ph == "f":
            if flows_open.get(ev.get("id"), 0) <= 0:
                errors.append(f"event {i}: flow finish without start "
                              f"(id {ev.get('id')!r})")
            else:
                flows_open[ev["id"]] -= 1
    for key, stack in stacks.items():
        if stack:
            errors.append(f"unclosed B events on {key}: {stack}")
    for fid, n in flows_open.items():
        if n:
            errors.append(f"flow start without finish (id {fid!r})")
    return errors


def export_trace(src: str, out_path: Optional[str] = None):
    """events.jsonl (or run dir) -> validated trace dict; optionally
    written to ``out_path``.  Returns ``(trace, errors)`` — the caller
    decides whether a non-empty error list is fatal."""
    trace = build_trace(read_events(src))
    errors = validate_trace(trace)
    if out_path is not None:
        with open(out_path, "w") as f:
            json.dump(trace, f)
    return trace, errors
