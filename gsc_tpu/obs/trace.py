"""Profiler trace annotations for the episode pipeline.

A ``--profile`` trace of the pipelined trainer used to be one opaque blob:
the fused rollout+learn program, the prefetch waits and the metric drains
all interleave with nothing attributing device time to pipeline phases.
These helpers wrap the host-side phases in ``jax.profiler.TraceAnnotation``
(named ranges on the host timeline that the trace viewer correlates with
the device stream) and each episode dispatch in
``jax.profiler.StepTraceAnnotation`` (the step marker TensorBoard's
profiler uses for per-step device attribution).

Annotation names are stable API — tooling and docs reference them:
``host_sample``, ``host_sample_wait``, ``dispatch``, ``drain`` (phase
ranges) and ``episode_step`` (the per-episode step marker).
"""
from __future__ import annotations

from contextlib import contextmanager


@contextmanager
def phase_span(name: str, timer=None, hub=None):
    """One pipeline phase: profiler range + optional
    :class:`~gsc_tpu.utils.telemetry.PhaseTimer` accumulation + hub
    last-phase bookkeeping (what a stall event reports being stuck in)."""
    import jax

    if hub is not None:
        hub.note_phase(name, done=False)
    with jax.profiler.TraceAnnotation(name):
        try:
            if timer is not None:
                with timer.phase(name):
                    yield
            else:
                yield
        finally:
            if hub is not None:
                hub.note_phase(name, done=True)


@contextmanager
def episode_span(step: int, name: str = "episode_step"):
    """Step marker around one episode's device dispatch, so profiler UIs
    attribute device time per episode instead of one run-length blob."""
    import jax

    with jax.profiler.StepTraceAnnotation(name, step_num=int(step)):
        yield
