"""Benchmark: env-steps/sec/chip on the Abilene flagship scenario.

Measures the full training loop — vmapped env-replica rollout (simulator
physics + obs + reward on device) and the end-of-episode DDPG learn burst —
on one chip, and prints ONE JSON line:

    {"metric": "env_steps_per_sec_per_chip", "value": ..., "unit": ...,
     "vs_baseline": ...}

Baseline: the reference publishes no numbers (BASELINE.md); its training loop
is a single SimPy env + torch-geometric DDPG on one CPU core, whose
steps/sec it logs to TensorBoard but never reports.  We use
REFERENCE_CPU_SPS = 100 env-steps/sec as a generous order-of-magnitude
estimate of that loop (each step simulates ~1000 SimPy events plus a GNN
forward; the paper's training runs are hours for ~40k steps).
``vs_baseline`` is measured_value / REFERENCE_CPU_SPS.
"""
from __future__ import annotations

import json
import time

import jax
import jax.numpy as jnp

REFERENCE_CPU_SPS = 100.0
REPLICAS = 256
EPISODE_STEPS = 200
EPISODES_MEASURED = 3


def main():
    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG

    env, agent, topo, _ = _flagship(episode_steps=EPISODE_STEPS)
    from gsc_tpu.sim.traffic import generate_traffic

    B = REPLICAS
    traffic = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[generate_traffic(env.sim_cfg, env.service, topo, EPISODE_STEPS,
                           seed=s) for s in range(B)])
    pddpg = ParallelDDPG(env, agent, num_replicas=B)

    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    def episode(state, buffers, env_states, obs, start_step):
        state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
            state, buffers, env_states, obs, topo, traffic,
            jnp.int32(start_step))
        state, metrics = pddpg.learn_burst(state, buffers)
        return state, buffers, env_states, obs, stats, metrics

    # warmup/compile
    out = episode(state, buffers, env_states, obs, 0)
    jax.block_until_ready(out)
    state, buffers, env_states, obs = out[:4]

    t0 = time.time()
    for ep in range(1, 1 + EPISODES_MEASURED):
        out = episode(state, buffers, env_states, obs, ep * EPISODE_STEPS)
        jax.block_until_ready(out)
        state, buffers, env_states, obs = out[:4]
    dt = time.time() - t0

    env_steps = EPISODES_MEASURED * EPISODE_STEPS * B
    sps = env_steps / dt
    print(json.dumps({
        "metric": "env_steps_per_sec_per_chip",
        "value": round(sps, 1),
        "unit": "env-steps/s",
        "vs_baseline": round(sps / REFERENCE_CPU_SPS, 2),
    }))


if __name__ == "__main__":
    main()
