"""Benchmark: env-steps/sec/chip on the Abilene flagship scenario.

Measures the full training loop — vmapped env-replica rollout (simulator
physics + obs + reward on device) and the end-of-episode DDPG learn burst —
on one chip, and prints ONE JSON line:

    {"metric": "env_steps_per_sec_per_chip", "status": "ok", "value": ...,
     "unit": ..., "vs_baseline": ..., "pipeline": ..., "precision": ...}

On failure (unreachable backend, every rung faulted) the line is instead
``{"metric": ..., "status": "failed", "reason": ...}`` with NO ``value`` —
readers must key on ``status``, never assume a number is present.

Structure: a stdlib-only ORCHESTRATOR (this process) runs every JAX step in
a child subprocess with a hard timeout, because a faulted TPU call wedges
the shared chip and the *next* process then hangs at backend init.  The
orchestrator (1) probes backend health with a bounded-time child, (2) runs
the measurement worker (``--worker``) over an escalation ladder of
(replicas, chunk) configs, and (3) keeps the best successful number.  A
fault at one rung never poisons the artifact: the previous rung's number is
already banked.

Episodes run CHUNKED: the 200-step episode executes as several shorter
device calls (carrying env state/obs/replay across calls).  Single 200-step
scan calls (200 x 100 fused engine substeps) fault the TPU runtime;
25-50-step chunks are the validated operating range.  By default the
ASYNC PIPELINE path runs: every chunk is a fused ``chunk_step`` (the final
one carrying the learn burst in the same program) and episode k's metric
sync is deferred until after episode k+1's dispatch.  ``--pipeline off``
(or GSC_BENCH_PIPELINE=0) restores the seed's two-call-per-episode shape
so a pair of runs attributes the pipeline's share of the throughput.
``--precision bf16`` (or GSC_BENCH_PRECISION) measures the mixed-precision
policy (bf16 network compute + replay, f32 master state); every row
records its ``precision`` so run-to-run comparisons attribute the dtype
share.  ``--substep-impl pallas`` (GSC_BENCH_SUBSTEP_IMPL) measures the
substep megakernel engine and ``--unroll N`` (GSC_BENCH_SCAN_UNROLL) the
substep-scan unroll factor — the two op-count levers of the >=20x
campaign; every row records ``substep_impl`` and ``unroll`` next to
``pipeline``/``precision`` so the lever_sweep winner can be promoted and
attributed per rung.  A failed probe/run emits a structured
``{"status": "failed", "reason": ...}`` row — never a fake 0.0
measurement — so artifacts distinguish "slow" from "never ran".

Baseline: the reference publishes no numbers (BASELINE.md); its training
loop is a single SimPy env + torch DDPG on one CPU core
(simple_ddpg.py:271 logs SPS to TensorBoard, never reported).  The
denominator here is MEASURED by ``tools/measure_baseline.py`` running the
reference's own simulator step loop on this machine's CPU and stored in
``BASELINE_MEASURED.json``; ``vs_baseline`` = measured_value / that.
"""
from __future__ import annotations

import dataclasses
import json
import os
import subprocess
import sys
import time

EPISODE_STEPS = 200          # reference sample_agent.yaml:23
EPISODES_MEASURED = 2
PROBE_TIMEOUT = 240          # backend init is normally ~10 s; wedged = hang
PROBE_RETRIES = 3
PROBE_RETRY_SLEEP = 60
# transient-rung retry (resilience layer): a worker that crashed/timed out
# while the backend still answers a probe gets ONE bounded-backoff retry
# of the same rung before the ladder falls through — a single tunnel
# hiccup must not demote the artifact to a lower rung's number.  Rows
# record "retries" so a retried-then-succeeded run banks status:ok with
# the retry visible, never a silent second attempt.
RUNG_RETRIES = 1
RUNG_RETRY_SLEEP = 10
# (replicas, chunk_steps, worker_timeout_s).  With the one-hot engine
# (gathers/scatters as MXU contractions) the measured substep wall is
# ~0.9 ms at B=64 and ~3.5 ms at B=512, so 50-step chunk calls stay well
# under the tunnel's per-call deadline (faults appeared near ~60-120 s
# calls).  B=256 is the measured sweet spot (1853 env-steps/s, round 3) so
# it runs FIRST with a fresh-compile-sized timeout — the peak must be
# banked before anything can go wrong; B=64 is the quick fallback, B=512
# the escalation.  A persistent XLA compilation cache (see worker())
# amortizes compiles across worker subprocesses and across bench runs.
LADDER = [
    (256, 50, 2400),
    (64, 50, 900),
    (512, 50, 1500),
]
# total wall budget: never start a rung that could overshoot this with a
# number already banked (the driver's artifact must land with rc=0 —
# worst case is B=256 eating its full 2400 s then the B=64 fallback:
# 3300 s, leaving headroom under any plausible driver deadline; B=512
# only runs when B=256 finished fast, and it measured slightly BELOW
# B=256 after the r3 layout fix anyway)
TOTAL_BUDGET_S = 3600
_FALLBACK_BASELINE_SPS = 100.0  # order-of-magnitude estimate, only used if
                                # BASELINE_MEASURED.json is absent


def _repo(*parts):
    return os.path.join(os.path.dirname(os.path.abspath(__file__)), *parts)


def _env_int(name: str, default: int) -> int:
    """Opt-in integer knob; a malformed value must fail FAST with its name
    (a bare int() crash in every ladder rung reads as a wedged chip)."""
    raw = os.environ.get(name, "").strip()
    if not raw:
        return default
    try:
        return int(raw)
    except ValueError:
        raise SystemExit(f"{name}={raw!r} is not an integer")


def _pipeline_enabled() -> bool:
    """Fused rollout+learn dispatch with deferred metric banking
    (ParallelDDPG.chunk_step).  Default ON — it is the product training
    loop; GSC_BENCH_PIPELINE=0 restores the two-call-per-episode path so a
    row can attribute the pipeline's share of the throughput."""
    return _env_int("GSC_BENCH_PIPELINE", 1) != 0


def _precision() -> str:
    """Dtype policy of the measured stack (config.schema.PRECISION_POLICIES):
    'f32' (default; bit-identical to the dtype-unaware stack) or 'bf16'
    (mixed-precision compute + replay, f32 master state).  Set by
    ``--precision`` / GSC_BENCH_PRECISION; recorded in every row so a pair
    of runs attributes the precision share of the throughput."""
    prec = os.environ.get("GSC_BENCH_PRECISION", "f32").strip() or "f32"
    if prec not in ("f32", "bf16"):
        raise SystemExit(f"GSC_BENCH_PRECISION={prec!r} (expected f32|bf16)")
    return prec


def _substep_impl() -> str:
    """Substep engine of the measured stack (SimConfig.substep_impl):
    'xla' (default; the hand-fused one-hot pipeline) or 'pallas' (the
    substep megakernel — CPU/interpret-only until its Mosaic port, see
    ops/pallas_substep.py).  Set by ``--substep-impl`` /
    GSC_BENCH_SUBSTEP_IMPL; recorded in every row next to pipeline/
    precision so a pair of runs attributes the engine share."""
    impl = os.environ.get("GSC_BENCH_SUBSTEP_IMPL", "xla").strip() or "xla"
    if impl not in ("xla", "pallas"):
        raise SystemExit(
            f"GSC_BENCH_SUBSTEP_IMPL={impl!r} (expected xla|pallas)")
    return impl


def _unroll() -> int:
    """Substep-scan unroll factor (SimConfig.scan_unroll, default 1 =
    the plain scan).  Set by ``--unroll`` / GSC_BENCH_SCAN_UNROLL;
    recorded in every row — this is the sweep knob tools/lever_sweep.py
    measures, surfaced here so a swept winner can be promoted per rung
    without a code edit."""
    unroll = _env_int("GSC_BENCH_SCAN_UNROLL", 1)
    if unroll < 1:
        raise SystemExit(f"GSC_BENCH_SCAN_UNROLL={unroll} must be >= 1")
    return unroll


def _mesh():
    """pjit mesh shape 'DPxMP' of the measured stack (``--mesh`` /
    GSC_BENCH_MESH; parallel.partition.parse_mesh_shape grammar), or None
    for the single-device dispatch every earlier round measured.  Each
    row records the EFFECTIVE value next to pipeline/precision/
    substep_impl — a multi-chip number without its mesh shape is not
    attributable.  Validation here is format-only; the worker checks the
    backend actually HAS dp*mp devices (bench never falls back to a
    virtual CPU mesh — that would bank a CPU number as a chip rate)."""
    raw = os.environ.get("GSC_BENCH_MESH", "").strip()
    if not raw:
        return None
    # the ONE grammar definition (gsc_tpu.meshspec) — jax-free on
    # purpose, so the orchestrator still never claims the TPU alongside
    # its workers; canonical 'dpxmp' spelling (bare 'N' -> 'Nx1') keeps
    # cross-artifact grouping from splitting one shape into two strings
    from gsc_tpu.meshspec import canonical_mesh
    try:
        return canonical_mesh(raw)
    except ValueError as e:
        raise SystemExit(f"GSC_BENCH_MESH={raw!r}: {e}")


def _topo_mix():
    """Mixed-topology batch spec of the measured stack (``--topo-mix`` /
    GSC_BENCH_TOPO_MIX; topology.scenarios mix grammar, registry names
    only — bench has no scheduler to expand 'schedule' from), or None for
    the homogeneous batch every earlier round measured.  Validation here
    is presence-only — the orchestrator stays jax-free, so the grammar/
    registry check happens in the worker (a bad mix fails the rung with
    its parse error, never banks a mislabeled row)."""
    raw = os.environ.get("GSC_BENCH_TOPO_MIX", "").strip()
    return raw or None


def _partition_rules() -> str:
    """Partition rulebook under ``--mesh`` (``--partition-rules`` /
    GSC_BENCH_PARTITION_RULES): 'replicated' (default — params on every
    device, the bit-identical fallback), 'sharded' (wide matrices +
    Adam moments split over mp, bit-exact by construction) or 'tp'
    (true tensor-parallel compute — resident-sharded state, psum
    partial products; rows gate under the bench_diff tolerance bands
    vs a replicated control, never by digest).  Vocabulary lives in
    gsc_tpu.meshspec (jax-free).  Recorded on rows only when a mesh is
    set — without one the knob has nothing to partition."""
    from gsc_tpu.meshspec import validate_partition_rules
    rules = (os.environ.get("GSC_BENCH_PARTITION_RULES", "replicated")
             .strip() or "replicated")
    try:
        return validate_partition_rules(rules)
    except ValueError as e:
        raise SystemExit(f"GSC_BENCH_PARTITION_RULES: {e}")


def _async_actors() -> int:
    """Decoupled actor/learner dispatch (``--async-actors`` /
    GSC_BENCH_ASYNC_ACTORS): 0 (default) measures the synchronous episode
    loop every earlier round banked; N>0 routes the measured window
    through parallel.async_rl.run_async with N rollout threads feeding
    the device-resident replay ring while the learner runs bursts
    back-to-back.  Rows record ``async_actors`` (plus the learner-idle
    fraction on the final row) so async rates never mix with sync ones in
    trajectory tooling — tools/async_bench.py owns the gated sync-vs-
    async comparison artifact; this knob lets the official ladder bank an
    async chip rate without a code edit once that gate is green."""
    n = _env_int("GSC_BENCH_ASYNC_ACTORS", 0)
    if n < 0:
        raise SystemExit(f"GSC_BENCH_ASYNC_ACTORS={n} must be >= 0")
    return n


def ladder():
    """The (replicas, chunk, timeout) escalation ladder.  GSC_BENCH_LADDER
    ("B,chunk,timeout[;B,chunk,timeout...]") overrides it — the CPU smoke
    path (interpret-mode Pallas, 1-core CI boxes) needs a tiny rung, and a
    lever-sweep winner can be measured without a code edit."""
    raw = os.environ.get("GSC_BENCH_LADDER", "").strip()
    if not raw:
        return LADDER
    rungs = []
    for cell in raw.split(";"):
        parts = [p.strip() for p in cell.split(",")]
        if len(parts) != 3:
            raise SystemExit(
                f"GSC_BENCH_LADDER cell {cell!r} is not 'B,chunk,timeout'")
        try:
            rungs.append(tuple(int(p) for p in parts))
        except ValueError:
            raise SystemExit(f"GSC_BENCH_LADDER cell {cell!r} has a "
                             "non-integer field")
    return rungs


def baseline_sps() -> float:
    try:
        with open(_repo("BASELINE_MEASURED.json")) as f:
            return float(json.load(f)["reference_cpu_sps"])
    except Exception:
        print("[bench] BASELINE_MEASURED.json missing/unreadable — "
              f"vs_baseline uses the {_FALLBACK_BASELINE_SPS} ESTIMATE",
              file=sys.stderr)
        return _FALLBACK_BASELINE_SPS


# --------------------------------------------------------------- orchestrator
def probe(timeout=PROBE_TIMEOUT) -> bool:
    """Bounded-time backend health check in a fresh process."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; d=jax.devices(); print('PROBE_OK', len(d))"],
            timeout=timeout, capture_output=True, text=True)
        return r.returncode == 0 and "PROBE_OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def probe_with_retry() -> bool:
    for i in range(PROBE_RETRIES):
        if probe():
            return True
        print(f"[bench] probe {i + 1}/{PROBE_RETRIES} failed; backend "
              f"wedged or tunnel down — sleeping {PROBE_RETRY_SLEEP}s",
              file=sys.stderr)
        time.sleep(PROBE_RETRY_SLEEP)
    return False


def _parse_worker_stdout(stdout):
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            out = json.loads(line)
            if "value" in out:
                return out
        except json.JSONDecodeError:
            continue
    return None


def run_worker(replicas, chunk, timeout):
    """-> (result_or_None, clean).  ``clean`` is False for a timeout or a
    nonzero exit even when a partial result was recovered — the caller
    must re-probe backend health before trusting the chip again."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker",
           str(replicas), str(chunk), str(EPISODES_MEASURED)]
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True)
    except subprocess.TimeoutExpired as e:
        # the worker prints a measurement line after EVERY measured
        # episode, so a worker that hung on a later episode (or never
        # finished its last block) still banks its partial rate
        out = _parse_worker_stdout(
            e.stdout.decode() if isinstance(e.stdout, bytes) else e.stdout)
        print(f"[bench] worker B={replicas} chunk={chunk}: timeout "
              f"({timeout}s)"
              + (f" — partial result {out['value']}" if out else ""),
              file=sys.stderr)
        return out, False
    sys.stderr.write(r.stderr[-2000:])
    if r.returncode != 0:
        print(f"[bench] worker B={replicas} chunk={chunk}: rc="
              f"{r.returncode}", file=sys.stderr)
        # a fault mid-run does not erase episodes already measured
        return _parse_worker_stdout(r.stdout), False
    return _parse_worker_stdout(r.stdout), True


def orchestrate():
    t_start = time.time()   # budget includes probe time: the artifact JSON
                            # must print before any external driver deadline
    if not probe_with_retry():
        # structured FAILED row, not a 0.0 "measurement": trajectory
        # tooling reading BENCH_*.json must be able to distinguish "slow"
        # from "never ran" (the round-5 wedged-tunnel failure mode banked
        # a 0.0 that looked like a rate)
        print(json.dumps({
            "metric": "env_steps_per_sec_per_chip",
            "status": "failed",
            "reason": "TPU backend unreachable (init probe timed out after "
                      f"{PROBE_RETRIES} attempts)",
            "unit": "env-steps/s", "retries": 0,
            "pipeline": _pipeline_enabled(), "precision": _precision(),
            "substep_impl": _substep_impl(), "unroll": _unroll(),
            "mesh": _mesh(), "topo_mix": _topo_mix(),
            # same rides-along-with-mesh rule as ok artifacts: a failed
            # sharded round must not read as a failed replicated one
            **({"partition_rules": _partition_rules()} if _mesh()
               else {})}))
        sys.exit(1)
    best = None
    denom = baseline_sps()

    def artifact(b):
        return json.dumps({
            "metric": "env_steps_per_sec_per_chip",
            "status": "ok",
            "value": b["value"],
            "unit": "env-steps/s",
            "vs_baseline": round(b["value"] / denom, 2),
            # honest-denominator caveat (VERDICT r4): the reference's
            # torch/gym agent stack is not installable here, so the
            # denominator is its env-physics step rate — which OVERSTATES
            # the reference's end-to-end training rate; vs_baseline is
            # therefore conservative
            "baseline_sps": denom,
            "baseline_scope": "reference env-physics only (no torch agent)",
            "pipeline": b.get("pipeline", True),
            "precision": b.get("precision", "f32"),
            # engine knobs from the WORKER's banked row (same derived-
            # from-what-ran rule as `knobs`): the substep implementation
            # and the scan-unroll factor actually built into the stack
            "substep_impl": b.get("substep_impl", "xla"),
            "unroll": b.get("unroll", 1),
            # mesh shape from the worker's banked row (None = the
            # single-device dispatch); partition_rules rides along only
            # when a mesh was actually in play
            "mesh": b.get("mesh"),
            # mixed-topology batch spec from the worker's banked row
            # (None = homogeneous): a mixed-batch rate without its mix is
            # not comparable to the homogeneous rows around it
            "topo_mix": b.get("topo_mix"),
            **({"jit_traces": b["jit_traces"]} if b.get("jit_traces")
               else {}),
            **({"partition_rules": b["partition_rules"]}
               if b.get("partition_rules") else {}),
            # transparent retry accounting: 0 for a first-try number
            "retries": b.get("retries", 0),
            # knobs come from the WORKER's banked row — derived from the
            # values it actually passed to its stack builder (ADVICE r5:
            # the old env-var echo tagged rung4/rung5/interroute rows with
            # a max_flows knob those stacks hardcode away)
            **({"knobs": b["knobs"]} if b.get("knobs") else {}),
        })

    best_clean = False   # a PARTIAL (timed-out/faulted) result must not
    # budget-gate away the cheap clean fallback rung: partial rates are
    # systematically low (fewer episodes amortizing fixed costs).  But the
    # budget must still BIND when rungs keep timing out, so exactly ONE
    # over-budget grace rung is allowed to upgrade a partial/absent result
    # — without it, three partial rungs would run ~2x the budget and the
    # driver would kill the process (rc != 0).
    grace_used = False
    total_retries = 0
    backend_dead = False
    for replicas, chunk, timeout in ladder():
        if time.time() - t_start + timeout > TOTAL_BUDGET_S:
            if best_clean or grace_used:
                print("[bench] wall budget reached — stopping escalation",
                      file=sys.stderr)
                break
            grace_used = True
            print("[bench] over budget with no clean number — one grace "
                  "rung", file=sys.stderr)
        attempts = 0
        while True:
            out, clean = run_worker(replicas, chunk, timeout)
            if out is not None:
                # rows carry their retry count: a transient-failure rung
                # that succeeded on re-attempt banks an honest status:ok
                # row with retries > 0, not a silently-clean number
                out["retries"] = attempts
                if best is None or out["value"] > best["value"]:
                    best = out
                best_clean = best_clean or clean
                print(f"[bench] rung B={replicas} chunk={chunk}: "
                      f"{out['value']:.1f} env-steps/s"
                      + ("" if clean else " (partial)")
                      + (f" (retries={attempts})" if attempts else ""),
                      file=sys.stderr)
                # bank incrementally: the LAST JSON line on stdout is the
                # artifact, so re-printing best-so-far after every rung
                # means even an externally-killed run has the peak in its
                # tail
                print(artifact(best))
            if clean:
                break
            # a timed-out/faulted rung may have wedged the chip — even
            # when it yielded a partial result.  Another attempt (retry or
            # a later rung) is only worth it if the backend still answers
            # a bounded probe.
            if not probe_with_retry():
                backend_dead = True
                break
            if attempts >= RUNG_RETRIES or \
                    time.time() - t_start + timeout > TOTAL_BUDGET_S:
                break   # fall down the ladder, the seed behavior
            attempts += 1
            total_retries += 1
            print(f"[bench] worker B={replicas} chunk={chunk}: transient "
                  f"failure — retry {attempts}/{RUNG_RETRIES} after "
                  f"{RUNG_RETRY_SLEEP}s backoff", file=sys.stderr)
            time.sleep(RUNG_RETRY_SLEEP)
        if backend_dead:
            print("[bench] backend unhealthy after failed rung — "
                  "stopping", file=sys.stderr)
            break
    if best is None:
        # no fake 0.0 measurement — see the probe-failure row above
        print(json.dumps({
            "metric": "env_steps_per_sec_per_chip",
            "status": "failed", "reason": "all ladder rungs failed",
            "unit": "env-steps/s", "retries": total_retries,
            "pipeline": _pipeline_enabled(), "precision": _precision(),
            "substep_impl": _substep_impl(), "unroll": _unroll(),
            "mesh": _mesh(), "topo_mix": _topo_mix(),
            **({"partition_rules": _partition_rules()} if _mesh()
               else {})}))
        sys.exit(1)
    print(artifact(best))


# --------------------------------------------------------------------- worker
def _rung4_stack(episode_steps):
    """BASELINE ladder rung 4 entry: a 64-node random gen_networks-style
    topology (fixed seed for comparable runs), 512 flow slots
    (BASELINE.md:32) — same service/agent/sim config as the flagship."""
    from __graft_entry__ import _flagship
    from gsc_tpu.topology.synthetic import random_network

    env, agent, topo, _ = _flagship(
        max_nodes=64, max_edges=128, episode_steps=episode_steps,
        max_flows=512, spec=random_network(64, seed=7), gen_traffic=False)
    return env, agent, topo


def _interroute_stack(episode_steps):
    """Interoute (Topology Zoo, 110 nodes / 146 edges — the reference's
    largest REAL scenario, configs/networks/interroute/), 1024 flow slots.
    Note this is NOT BASELINE config 5 (200+-node synthetic + mixed SFC
    catalog, covered by tests/test_rung5.py) — it benchmarks the biggest
    network the reference actually ships."""
    from __graft_entry__ import _flagship
    from gsc_tpu.topology.synthetic import interroute

    env, agent, topo, _ = _flagship(
        max_nodes=128, max_edges=192, episode_steps=episode_steps,
        max_flows=1024, spec=interroute(), gen_traffic=False)
    # at 128 max nodes the action/mask dim is 128*1*3*128 = 49k floats per
    # transition, and the flagship mem_limit=10000 OOMs one chip's HBM at
    # B=32 (312 transitions/replica, measured RESOURCE_EXHAUSTED in the
    # learn burst).  2048 total transitions (~mem_limit // B per replica,
    # ParallelDDPG.init_buffers) fit; the r3 run banked 99 env-steps/s
    # with an equivalent budget.
    agent = dataclasses.replace(agent, mem_limit=2048)
    return env, agent, topo


def _rung5_stack(episode_steps):
    """BASELINE ladder rung 5 (BASELINE.md config 5): 200-node synthetic
    multi-cloud topology + the ``mixed_service`` catalog, 1024 flow
    slots.  Replay capped like the interroute stack (the action/mask dim
    is 256*2*3*256 = 393k floats per transition)."""
    from gsc_tpu.config.catalog import mixed_service
    from gsc_tpu.config.schema import AgentConfig, EnvLimits, SimConfig
    from gsc_tpu.env.env import ServiceCoordEnv
    from gsc_tpu.topology.compiler import compile_topology
    from gsc_tpu.topology.synthetic import random_network

    service = mixed_service()
    limits = EnvLimits.for_service(service, max_nodes=256, max_edges=384)
    # FLAGSHIP architecture hyperparameters (default 256/64 hidden, batch
    # 100): the factored action head auto-enables at this action dim
    # (models/nets.py), so the r3 blocker — a 100M-param monolithic output
    # matrix that OOMed the learn burst even at B=4 — no longer exists and
    # the network config ports up the ladder unchanged.  Only the replay
    # BUDGET stays scenario-sized: a rung-5 transition carries ~1.2M f32
    # (two 393k masks + a 393k action), so the flagship's 10000-transition
    # replay would be ~47 GB; mem_limit=1024 keeps TOTAL replay at 1024
    # transitions ~ 5 GB at every B (init_buffers splits mem_limit over
    # replicas with no per-shard floor).
    agent = AgentConfig(graph_mode=True, episode_steps=episode_steps,
                        objective="prio-flow", mem_limit=1024)
    sim_cfg = SimConfig(ttl_choices=(100.0,), max_flows=1024)
    env = ServiceCoordEnv(service, sim_cfg, agent, limits)
    topo = compile_topology(random_network(200, num_ingress=8, seed=11),
                            max_nodes=256, max_edges=384)
    return env, agent, topo


# scenario name -> stack builder; 'flagship' is handled inline in worker()
STACKS = {"rung4": _rung4_stack, "interroute": _interroute_stack,
          "rung5": _rung5_stack}


def _enable_compile_cache():
    """Persistent XLA compilation cache: compiles amortize across worker
    subprocesses (one per ladder rung) and across bench runs — the driver's
    end-of-round run hits the cache this session populated, so a slow fresh
    compile can no longer eat a rung's timeout."""
    import jax
    cache = os.environ.get("GSC_TPU_JIT_CACHE", _repo(".jax_cache"))
    try:
        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception as e:  # cache is an optimization, never a requirement
        print(f"[worker] compile cache unavailable: {e}", file=sys.stderr)


def worker(replicas: int, chunk: int, episodes: int,
           scenario: str = "flagship"):
    import jax
    import jax.numpy as jnp

    _enable_compile_cache()

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    if scenario != "flagship" and scenario not in STACKS:
        raise SystemExit(f"unknown scenario {scenario!r} (expected "
                         f"'flagship' or one of {sorted(STACKS)})")
    assert EPISODE_STEPS % chunk == 0, (EPISODE_STEPS, chunk)
    chunks_per_ep = EPISODE_STEPS // chunk
    t_start = time.time()
    # knobs are derived from the values ACTUALLY passed to the stack
    # builder below (ADVICE r5): max_flows only reaches the flagship
    # builder — rung4/rung5/interroute hardcode their own flow tables, so
    # tagging their rows with the env var would be a lie
    knobs = {}
    pipeline = _pipeline_enabled()   # every row carries "pipeline" at top
    # level — not duplicated into knobs
    precision = _precision()         # likewise "precision"
    if scenario in STACKS:
        env, agent, topo = STACKS[scenario](EPISODE_STEPS)
    else:
        # lever-sweep winner knobs (tools/lever_sweep.py): opt-in via env
        # vars so the official artifact path can adopt a measured winner
        # without a code edit; unset = exact previous behavior
        mf = _env_int("GSC_BENCH_MAX_FLOWS", 128)
        if mf != 128:
            knobs["max_flows"] = mf
        env, agent, topo, _ = _flagship(
            episode_steps=EPISODE_STEPS, max_flows=mf, gen_traffic=False)
    if precision != "f32":
        # the dtype policy rides on the agent config, so every scenario's
        # stack (flagship and hardcoded rungs alike) honors it — models,
        # replay shards and the learn burst all read agent.precision
        agent = dataclasses.replace(agent, precision=precision)
    # engine knobs (substep impl + scan unroll) rebuild the env's sim_cfg
    # for EVERY scenario, so they legitimately tag all rows — top-level
    # fields next to pipeline/precision, not `knobs` entries
    substep_impl = _substep_impl()
    unroll = _unroll()
    if unroll != 1 or substep_impl != "xla":
        from gsc_tpu.env.env import ServiceCoordEnv
        env = ServiceCoordEnv(
            env.service,
            dataclasses.replace(env.sim_cfg, scan_unroll=unroll,
                                substep_impl=substep_impl),
            agent, env.limits)
    B = replicas
    # pjit mesh (--mesh): the sharded dispatch over a dp x mp device grid.
    # The backend must genuinely HAVE the devices — make_train_mesh's
    # virtual-CPU fallback is for dry runs, and a bench row that silently
    # measured 8 virtual CPU "chips" would bank a lie (the make_mesh
    # docstring's contract: production entry points check counts first).
    mesh_spec = _mesh()
    plan = None
    partition_rules = None
    if mesh_spec:
        from gsc_tpu.parallel import ShardingPlan, parse_mesh_shape
        dp_, mp_ = parse_mesh_shape(mesh_spec)
        have = len(jax.devices())
        if have < dp_ * mp_:
            raise SystemExit(
                f"--mesh {mesh_spec} needs {dp_ * mp_} devices, backend "
                f"has {have} — bench never falls back to a virtual mesh "
                "(for a CPU smoke set "
                "XLA_FLAGS=--xla_force_host_platform_device_count=N)")
        if B % (dp_ * mp_) != 0:
            raise SystemExit(
                f"rung replicas ({B}) not divisible by mesh device count "
                f"({dp_ * mp_}) — pick a GSC_BENCH_LADDER whose B fits "
                "the mesh")
        partition_rules = _partition_rules()
        plan = ShardingPlan.from_spec(mesh_spec, rules=partition_rules)
    # mixed-topology batch (--topo-mix): the B axis carries a round-robin
    # of registry scenarios padded into the measured stack's bucket — ONE
    # vmapped program serves the whole mixture, which is exactly the claim
    # the MIXTOPO artifact quantifies against the homogeneous rows
    topo_mix = _topo_mix()
    mix_plan = None
    mix_samplers = None
    factory = None
    factory_probs = None
    if topo_mix:
        from gsc_tpu.topology.factory import is_factory_mix
        if is_factory_mix(topo_mix):
            # on-device scenario factory: fresh per-replica scenarios
            # SAMPLED per episode inside the measured loop (uniform
            # family weights — bench has no curriculum; the trainer owns
            # that loop) — the row measures the factory-inclusive
            # steady-state rate
            from gsc_tpu.topology.factory import (ScenarioFactory,
                                                  parse_factory)
            factory = ScenarioFactory(
                parse_factory(topo_mix), env.sim_cfg, env.service,
                EPISODE_STEPS, max_nodes=env.limits.max_nodes,
                max_edges=env.limits.max_edges)
            factory_probs = jnp.full(
                (factory.spec.num_families,),
                1.0 / factory.spec.num_families)
    if topo_mix and factory is None:
        from gsc_tpu.topology import DEFAULT_REGISTRY, TopologyBucket
        from gsc_tpu.topology.scenarios import (build_mix_entries,
                                                mix_device_samplers,
                                                plan_mix,
                                                sample_mix_device)
        bucket = TopologyBucket(env.limits.max_nodes, env.limits.max_edges)
        entries = build_mix_entries(topo_mix, DEFAULT_REGISTRY, bucket,
                                    dt=env.sim_cfg.dt)
        mix_plan = plan_mix(entries, B, bucket, env.sim_cfg, EPISODE_STEPS)
        topo = mix_plan.topo
    # retrace accounting for the banked rows: mixed vs homogeneous rows
    # must show the SAME trace counts for the dispatch entry points — the
    # mixture is batch data, not a compile axis
    from gsc_tpu.analysis.sentinels import CompileMonitor
    monitor = CompileMonitor().start()
    # traffic sampled ON DEVICE: at B=256 the old host-stacked schedule was
    # ~90 MB through the tunnel before the first measurement
    if factory is not None:
        topo, traffic = factory.sample_batch(jax.random.PRNGKey(42),
                                             factory_probs, B)
    elif mix_plan is not None:
        mix_samplers = mix_device_samplers(mix_plan, env.sim_cfg,
                                           env.service, EPISODE_STEPS)
        traffic = jax.jit(
            lambda k: sample_mix_device(mix_plan, mix_samplers, k))(
            jax.random.PRNGKey(42))
    else:
        dt_sampler = DeviceTraffic(env.sim_cfg, env.service, topo,
                                   EPISODE_STEPS)
        traffic = jax.jit(lambda k: dt_sampler.sample_batch(k, B))(
            jax.random.PRNGKey(42))
    jax.block_until_ready(traffic)
    async_actors = _async_actors()
    if async_actors:
        # same refusals as cli train --async, failing fast with the knob's
        # name: the sharded dispatch memoizes device placements the actor
        # threads would race, and the cost capture assumes the sync
        # dispatch entry points
        if mesh_spec:
            raise SystemExit("GSC_BENCH_ASYNC_ACTORS does not compose with "
                             "GSC_BENCH_MESH yet — drop one of the two")
        if _env_int("GSC_BENCH_PERF", 0):
            raise SystemExit("GSC_BENCH_ASYNC_ACTORS does not compose with "
                             "GSC_BENCH_PERF (the cost capture lowers the "
                             "sync dispatch entry point)")
        # the async path has no fused chunk_step — actors dispatch
        # rollout_episodes, the learner dispatches learn_burst; rows
        # record pipeline=False so they never read as fused-dispatch rates
        pipeline = False
    # donate=False on the async path: actors hand scratch blocks to the
    # learner BY REFERENCE between threads — the one donated call is the
    # learner-owned replay_ingest inside run_async
    pddpg = ParallelDDPG(env, agent, num_replicas=B,
                         donate=(async_actors == 0), plan=plan,
                         per_replica_topology=(mix_plan is not None
                                               or factory is not None))

    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    if async_actors:
        # decoupled actor/learner measurement: N rollout threads feed the
        # device-resident ring through run_async while the learner bursts
        # back-to-back.  Warmup = one episode per actor (compiles every
        # entry point: reset_all / rollout_episodes actor-side,
        # replay_ingest / learn_burst learner-side); the measured window
        # then banks a running rate per drained episode — same
        # partial-credit-on-timeout contract as the sync loop.
        from gsc_tpu.obs.device import device_memory_snapshot
        from gsc_tpu.parallel.async_rl import AsyncConfig, run_async
        from gsc_tpu.utils.telemetry import PhaseTimer

        def scenario_fn(ep):
            if factory is not None:
                # per-episode resample, same steady state the sync
                # factory rows measure
                return factory.sample_batch(
                    jax.random.fold_in(jax.random.PRNGKey(42), ep),
                    factory_probs, B)
            # fixed scenario, same as the sync loop's reuse of the one
            # sampled schedule
            return topo, traffic

        cfg = AsyncConfig(actor_threads=async_actors)
        res = run_async(pddpg, scenario_fn, state, buffers,
                        episodes=async_actors,
                        episode_steps=EPISODE_STEPS, chunk=chunk, seed=0,
                        cfg=cfg)
        state, buffers = res.state, res.buffers
        print(f"[worker] compile+warmup: {time.time() - t_start:.1f}s",
              file=sys.stderr)

        timer = PhaseTimer()   # fresh ledger: warmup wall excluded
        t0 = time.time()
        row = {
            "metric": "env_steps_per_sec_per_chip",
            "unit": "env-steps/s",
            "replicas": B, "chunk": chunk, "scenario": scenario,
            "pipeline": False, "precision": precision,
            "substep_impl": substep_impl, "unroll": unroll,
            "mesh": None, "topo_mix": topo_mix,
            "async_actors": async_actors,
            **({"knobs": knobs} if knobs else {}),
        }
        drained_n = [0]

        def on_episode(rec, ring):
            drained_n[0] += 1
            dt = time.time() - t0
            print(json.dumps({
                **row,
                "value": round(drained_n[0] * EPISODE_STEPS * B / dt, 1),
                "jit_traces": {fn: t for fn, (t, _c)
                               in monitor.snapshot().items() if t and fn in
                               ("rollout_episodes", "learn_burst",
                                "reset_all", "factory_sample",
                                "replay_ingest")},
                "episodes_measured": drained_n[0],
                "measure_wall_s": round(dt, 1),
                "phases": timer.summary(),
            }), flush=True)

        res = run_async(pddpg, scenario_fn, state, buffers,
                        episodes=async_actors + episodes,
                        episode_steps=EPISODE_STEPS, chunk=chunk, seed=0,
                        cfg=cfg, timer=timer, on_episode=on_episode,
                        start_episode=async_actors)
        dt = time.time() - t0
        mem = device_memory_snapshot()
        # final row = the banked one (the orchestrator parses the LAST
        # line with a value): full-window rate + the drain-proved learner
        # accounting the async claim rests on
        print(json.dumps({
            **row,
            "value": round(episodes * EPISODE_STEPS * B / dt, 1),
            "jit_traces": {fn: t for fn, (t, _c)
                           in monitor.snapshot().items() if t and fn in
                           ("rollout_episodes", "learn_burst",
                            "reset_all", "factory_sample",
                            "replay_ingest")},
            "episodes_measured": episodes,
            "measure_wall_s": round(dt, 1),
            "phases": timer.summary(),
            "device_mem": [m for m in mem if m.get("available")],
            "learner_idle_frac": res.info.get("learner_idle_frac"),
            "bursts": res.info.get("bursts"),
            "produced_steps": res.info.get("produced_steps"),
            "ingested_steps": res.info.get("ingested_steps"),
            "policy_lag_max": res.info.get("policy_lag_max"),
        }), flush=True)
        print(f"[worker] phase timings: {json.dumps(timer.summary())}",
              file=sys.stderr)
        return

    # opt-in device-cost ledger (--perf / GSC_BENCH_PERF=1): compile-time
    # FLOPs / bytes / fusion counts of the measured dispatch kernel ride
    # every banked row, so tools/bench_diff.py can diff op-count structure
    # across rounds without a separate profiling run.  Off by default —
    # the capture is one extra AOT trace before warmup, and the official
    # chip artifact must measure exactly the historic startup sequence.
    cost_entry = None
    if _env_int("GSC_BENCH_PERF", 0):
        from gsc_tpu.obs.perf import CostLedger, resolve_lowerable
        ledger = CostLedger()
        cost_name = "chunk_step" if pipeline else "rollout_episodes"
        # the dispatched-executable resolver shared with the Trainer:
        # the donated instance partial when present (its backend compile
        # seeds the persistent cache the warmup then hits), else the
        # unsharded class jit (the sharded-plan wrappers are plain
        # closures with no .lower)
        cost_fn, cost_pre = resolve_lowerable(pddpg, cost_name)
        cost_args = (*cost_pre, state, buffers, env_states, obs, topo,
                     traffic, jnp.int32(0))
        cost_kw = ({"num_steps": chunk, "learn": True} if pipeline
                   else {"num_steps": chunk})
        # banked jit_traces stay comparable to non---perf rounds.
        # Meshless: the AOT lower and the first dispatch SHARE the pjit
        # trace cache (measured), so capture+dispatch trace the
        # learn=True variant exactly once either way — do NOT pause the
        # monitor (that would LOSE the one count).  Under a mesh the
        # sharded dispatch jits a separate copy of the function, so the
        # class-jit capture WOULD add a spurious +1 under the same name
        # — pause the monitor for exactly that case.
        if plan is not None:
            monitor.stop()
            try:
                ledger.capture(cost_name, cost_fn, cost_args, cost_kw)
            finally:
                monitor.start()
        else:
            ledger.capture(cost_name, cost_fn, cost_args, cost_kw)
        cost_entry = {cost_name: ledger.entry(cost_name)}

    from gsc_tpu.obs.device import device_memory_snapshot
    from gsc_tpu.utils.telemetry import PhaseTimer
    timer = PhaseTimer()

    def episode(state, buffers, env_states, obs, ep):
        """Dispatch one full episode's device work (async).  Pipelined:
        every chunk goes through the fused chunk_step, the LAST one with
        learn=True — rollout tail and learn burst in one program.  Off:
        the seed's two-call shape (per-chunk rollout + separate learn).
        Factory mixes RESAMPLE the per-replica scenario per episode
        inside the measured phase (that is the factory's steady state —
        a fixed-scenario factory row would measure the wrong thing)."""
        tpo, tfc = topo, traffic
        with timer.phase("dispatch"):
            if factory is not None:
                tpo, tfc = factory.sample_batch(
                    jax.random.fold_in(jax.random.PRNGKey(42), ep),
                    factory_probs, B)
                # fresh scenario => fresh env state: stepping carries
                # evolved on the PREVIOUS topology against the new one
                # would measure incoherent transitions and skip the
                # per-episode reset the real factory train loop pays
                env_states, obs = pddpg.reset_all(
                    jax.random.fold_in(jax.random.PRNGKey(7), ep), tpo,
                    tfc)
            for c in range(chunks_per_ep):
                start = jnp.int32(ep * EPISODE_STEPS + c * chunk)
                if pipeline:
                    state, buffers, env_states, obs, stats, metrics = \
                        pddpg.chunk_step(state, buffers, env_states, obs,
                                         tpo, tfc, start, chunk,
                                         learn=(c == chunks_per_ep - 1))
                else:
                    state, buffers, env_states, obs, stats = \
                        pddpg.rollout_episodes(state, buffers, env_states,
                                               obs, tpo, tfc, start,
                                               chunk)
            if not pipeline:
                state, metrics = pddpg.learn_burst(state, buffers)
        return state, buffers, env_states, obs, stats, metrics

    def bank(ep, out, t0):
        """Sync one episode's metrics and print its running rate: if a
        later episode faults or outlives the rung timeout, the
        orchestrator still parses the best partial line.  Only the stats/
        learn-metrics leaves are blocked on — the carries may already have
        been DONATED into the next episode's dispatch (the pipeline's
        whole point), and they finish in the same program anyway."""
        with timer.phase("drain"):
            jax.block_until_ready(out[4:])
        dt = time.time() - t0
        sps = ep * EPISODE_STEPS * B / dt
        # obs-subsystem columns, same sources as a train run's
        # events.jsonl: per-phase host wall so a slow row is attributable
        # (dispatch-bound vs drain-bound), and HBM readings so
        # replay/working-set growth across rungs is visible in the banked
        # artifacts (empty list on backends without memory_stats, e.g.
        # CPU dry runs)
        mem = device_memory_snapshot()
        print(json.dumps({
            "metric": "env_steps_per_sec_per_chip",
            "value": round(sps, 1),
            "unit": "env-steps/s",
            "replicas": B, "chunk": chunk, "scenario": scenario,
            "pipeline": pipeline, "precision": precision,
            "substep_impl": substep_impl, "unroll": unroll,
            "mesh": mesh_spec, "topo_mix": topo_mix,
            **({"partition_rules": partition_rules}
               if partition_rules else {}),
            # traces per dispatch entry point since process start
            # (analysis.sentinels.CompileMonitor): the compile-count half
            # of the MIXTOPO mixed-vs-homogeneous comparison.  Only the
            # episode-loop entry points — the monitor also counts every
            # jitted helper (hundreds of one-shot build-time traces),
            # which would bloat the row without informing the comparison.
            "jit_traces": {fn: t for fn, (t, _c)
                           in monitor.snapshot().items() if t and fn in
                           ("chunk_step", "rollout_episodes",
                            "learn_burst", "reset_all",
                            "factory_sample")},
            "episodes_measured": ep,
            "measure_wall_s": round(dt, 1),
            "phases": timer.summary(),
            "device_mem": [m for m in mem if m.get("available")],
            **({"cost": cost_entry} if cost_entry else {}),
            **({"knobs": knobs} if knobs else {}),
        }), flush=True)

    # warmup/compile (episode 0 is also the agent's random-action warmup)
    out = episode(state, buffers, env_states, obs, 0)
    jax.block_until_ready(out)
    state, buffers, env_states, obs = out[:4]
    print(f"[worker] compile+warmup: {time.time() - t_start:.1f}s",
          file=sys.stderr)

    t0 = time.time()
    prev = None   # pipelined: episode k's metric sync happens AFTER
    # episode k+1's dispatch, so the chip rolls straight into the next
    # episode while the host banks the previous rate
    try:
        for ep in range(1, 1 + episodes):
            out = episode(state, buffers, env_states, obs, ep)
            state, buffers, env_states, obs = out[:4]
            if pipeline:
                if prev is not None:
                    bank(*prev, t0)
                prev = (ep, out)
            else:
                bank(ep, out, t0)
    finally:
        # a fault during episode k's dispatch must not drop episode k-1's
        # already-earned measurement line — the orchestrator's recovered
        # partial rate is parsed from the banked tail.  Best effort: a
        # bank that itself fails (wedged backend) must not mask the
        # original fault's traceback or hang past it.
        if prev is not None:
            try:
                bank(*prev, t0)
            except Exception as e:
                print(f"[worker] could not bank episode {prev[0]} after "
                      f"fault: {e!r}", file=sys.stderr)
        print(f"[worker] phase timings: {json.dumps(timer.summary())}",
              file=sys.stderr)


if __name__ == "__main__":
    argv = list(sys.argv[1:])
    if "--pipeline" in argv:
        # orchestrator-level knob: forwarded to worker subprocesses via the
        # environment so every ladder rung measures the same dispatch shape
        i = argv.index("--pipeline")
        mode = argv[i + 1] if i + 1 < len(argv) else "on"
        if mode not in ("on", "off"):
            raise SystemExit(f"--pipeline expects on|off, got {mode!r}")
        os.environ["GSC_BENCH_PIPELINE"] = "1" if mode == "on" else "0"
        del argv[i:i + 2]
    if "--precision" in argv:
        # forwarded the same way so every rung measures one dtype policy;
        # a missing value must ERROR — silently defaulting would bank a
        # mislabeled f32 number for a user who meant to measure bf16
        i = argv.index("--precision")
        prec = argv[i + 1] if i + 1 < len(argv) else None
        if prec not in ("f32", "bf16"):
            raise SystemExit(f"--precision expects f32|bf16, got {prec!r}")
        os.environ["GSC_BENCH_PRECISION"] = prec
        del argv[i:i + 2]
    if "--substep-impl" in argv:
        # same missing-value contract: a silently-defaulted xla row would
        # mislabel a run meant to measure the megakernel
        i = argv.index("--substep-impl")
        impl = argv[i + 1] if i + 1 < len(argv) else None
        if impl not in ("xla", "pallas"):
            raise SystemExit(f"--substep-impl expects xla|pallas, "
                             f"got {impl!r}")
        os.environ["GSC_BENCH_SUBSTEP_IMPL"] = impl
        del argv[i:i + 2]
    if "--unroll" in argv:
        i = argv.index("--unroll")
        val = argv[i + 1] if i + 1 < len(argv) else None
        try:
            unroll = int(val)
        except (TypeError, ValueError):
            raise SystemExit(f"--unroll expects a positive integer, "
                             f"got {val!r}")
        if unroll < 1:
            raise SystemExit(f"--unroll expects a positive integer, "
                             f"got {val!r}")
        os.environ["GSC_BENCH_SCAN_UNROLL"] = str(unroll)
        del argv[i:i + 2]
    if "--async-actors" in argv:
        # forwarded like --unroll; a missing/garbled value must ERROR —
        # a silently-sync row would mislabel a run meant to measure the
        # decoupled actor/learner path
        i = argv.index("--async-actors")
        val = argv[i + 1] if i + 1 < len(argv) else None
        try:
            n_act = int(val)
        except (TypeError, ValueError):
            raise SystemExit(f"--async-actors expects a non-negative "
                             f"integer, got {val!r}")
        if n_act < 0:
            raise SystemExit(f"--async-actors expects a non-negative "
                             f"integer, got {val!r}")
        os.environ["GSC_BENCH_ASYNC_ACTORS"] = str(n_act)
        del argv[i:i + 2]
    if "--mesh" in argv:
        # forwarded to worker subprocesses via the environment like
        # --precision; a missing/garbled value must ERROR — a silently
        # meshless row would mislabel a run meant to measure multi-chip.
        # Grammar + canonical 'Nx1' spelling come from gsc_tpu.meshspec
        # (jax-free), the same definition _mesh() reads back
        from gsc_tpu.meshspec import canonical_mesh
        i = argv.index("--mesh")
        mesh = argv[i + 1] if i + 1 < len(argv) else None
        try:
            os.environ["GSC_BENCH_MESH"] = canonical_mesh(mesh)
        except ValueError:
            raise SystemExit(f"--mesh expects 'DPxMP' with positive axes "
                             f"(e.g. 8x1, 4x2), got {mesh!r}")
        del argv[i:i + 2]
    if "--partition-rules" in argv:
        from gsc_tpu.meshspec import (PARTITION_RULEBOOKS,
                                      validate_partition_rules)
        i = argv.index("--partition-rules")
        rules = argv[i + 1] if i + 1 < len(argv) else None
        try:
            validate_partition_rules(rules)
        except ValueError:
            raise SystemExit(f"--partition-rules expects "
                             f"{'|'.join(PARTITION_RULEBOOKS)}, "
                             f"got {rules!r}")
        os.environ["GSC_BENCH_PARTITION_RULES"] = rules
        del argv[i:i + 2]
    if "--perf" in argv:
        # boolean knob (no value): forwarded to worker subprocesses via
        # the environment like the others — every rung then banks its
        # dispatch kernel's compile-time cost next to the rate
        i = argv.index("--perf")
        os.environ["GSC_BENCH_PERF"] = "1"
        del argv[i:i + 1]
    if "--topo-mix" in argv:
        # forwarded via the environment like --precision; a missing value
        # must ERROR — a silently-homogeneous row would mislabel a run
        # meant to measure the mixture.  Full grammar/registry validation
        # happens in the worker (the parent stays jax-free).
        i = argv.index("--topo-mix")
        mix = argv[i + 1] if i + 1 < len(argv) else None
        if not mix or mix.startswith("--"):
            raise SystemExit(f"--topo-mix expects a mix spec (topology."
                             f"scenarios grammar), got {mix!r}")
        os.environ["GSC_BENCH_TOPO_MIX"] = mix
        del argv[i:i + 2]
    if argv and argv[0] == "--worker":
        worker(int(argv[1]), int(argv[2]), int(argv[3]),
               argv[4] if len(argv) > 4 else "flagship")
    else:
        orchestrate()
