#!/usr/bin/env python
"""gsc-lint CLI — JAX-aware static analysis for this repo.

Usage:
    python tools/gsc_lint.py [paths...]            # default: gsc_tpu/ tools/ bench.py
    python tools/gsc_lint.py --json [paths...]
    python tools/gsc_lint.py --rules R1,R4 [paths...]
    python tools/gsc_lint.py --changed [REF]       # only files in git diff REF
    python tools/gsc_lint.py --write-baseline      # accept current findings
    python tools/gsc_lint.py --prune-stale         # drop baseline entries
                                                   # that match nothing
    python tools/gsc_lint.py --no-baseline         # raw findings, no suppressions

Rules (gsc_tpu/analysis/astlint.py + concur.py):
    R1  host-sync calls (.item(), float()/int() on arrays, np.asarray,
        block_until_ready, device_get) reachable from jitted/scanned code
    R2  use of a variable after it was passed as a donated argument
    R3  time.time()/Python RNG/global mutation inside traced code
    R4  dot/einsum in bf16-policy modules (ops/, models/) missing
        preferred_element_type
    R5  bare Python scalars passed to jitted entry points (weak-type
        retrace risk)
    R6  lock-order cycle: two functions nest the same locks in opposite
        orders (ABBA deadlock)
    R7  field annotated ``# guarded-by: <lock>`` read/written without
        holding that lock (``# requires-lock:`` on a def asserts callers
        hold it)
    R8  multi-device dispatch (chunk_step / rollout_episodes /
        learn_burst / replay_ingest) in a thread-spawning module outside
        ``with dispatch_lock:`` — the PR 18 partition-rendezvous deadlock
    R9  blocking call (untimed get/wait/join/result, nested acquire,
        device call) while holding a lock
    R10 threading.Thread(...) without name=/daemon= (unnamed threads
        break watchdog stall events and black-box post-mortems)

Exit status: 0 when every finding is suppressed (baseline or inline
``gsc-lint: disable=R<k>`` marker), 1 when new findings exist, 2 on usage
errors.  The baseline lives at tools/gsc_lint_baseline.json; every entry
carries a one-line reason.  ``--write-baseline`` rewrites it from the
current findings, preserving existing reasons; entries it has to stamp
with a TODO reason make the write exit 1 until a human replaces them —
an unreviewed suppression must not pass the gate.

Fingerprints hash (rule, path, function, source-line text), not line
numbers, so code motion does not invalidate suppressions; two identical
lines in one function share a fingerprint (suppressing one suppresses
both).  Stale baseline entries (matching nothing) are reported but never
fatal.  Stdlib-only: runs without jax / device init.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from gsc_tpu.analysis import (  # noqa: E402
    RULE_IDS, RULE_TITLES, load_baseline, save_baseline)
from gsc_tpu.analysis.astlint import _iter_py_files, lint_files  # noqa: E402
from gsc_tpu.analysis.baseline import build_result  # noqa: E402

DEFAULT_PATHS = ("gsc_tpu/", "tools/", "bench.py")
DEFAULT_BASELINE = os.path.join(REPO_ROOT, "tools",
                                "gsc_lint_baseline.json")


def _rel(path: str) -> str:
    return os.path.relpath(os.path.abspath(path),
                           REPO_ROOT).replace(os.sep, "/")


def _git_changed_files(ref: str) -> Optional[List[str]]:
    """Repo-relative paths changed vs ``ref`` (staged + unstaged), or
    None when git is unavailable / this is not a work tree — the caller
    falls back to a full scan rather than silently linting nothing."""
    import subprocess
    try:
        proc = subprocess.run(
            ["git", "diff", "--name-only", ref],
            cwd=REPO_ROOT, capture_output=True, text=True, timeout=30)
    except (OSError, subprocess.TimeoutExpired):
        return None
    if proc.returncode != 0:
        return None
    return [ln.strip().replace(os.sep, "/")
            for ln in proc.stdout.splitlines() if ln.strip()]


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter,
        epilog="\n".join(f"  {r}  {RULE_TITLES[r]}" for r in RULE_IDS))
    ap.add_argument("paths", nargs="*",
                    help=f"files/dirs to lint [default: {DEFAULT_PATHS}]")
    ap.add_argument("--baseline", default=DEFAULT_BASELINE,
                    help="suppression baseline JSON "
                         "[default: tools/gsc_lint_baseline.json]")
    ap.add_argument("--no-baseline", action="store_true",
                    help="ignore the baseline (report every finding)")
    ap.add_argument("--write-baseline", action="store_true",
                    help="rewrite the baseline from current findings "
                         "(existing reasons preserved; new entries get a "
                         "TODO reason)")
    ap.add_argument("--prune-stale", action="store_true",
                    help="rewrite the baseline with stale entries "
                         "(matching nothing in the linted scope) removed")
    ap.add_argument("--changed", nargs="?", const="HEAD", default=None,
                    metavar="REF",
                    help="lint only .py files in `git diff --name-only "
                         "REF` [REF default: HEAD]; falls back to a full "
                         "scan when git is unavailable")
    ap.add_argument("--rules", default=None,
                    help="comma-separated subset, e.g. R1,R4")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable output")
    ap.add_argument("-q", "--quiet", action="store_true",
                    help="findings only, no summary lines")
    args = ap.parse_args(argv)

    rules = None
    if args.rules:
        rules = {r.strip().upper() for r in args.rules.split(",")}
        bad = rules - set(RULE_IDS)
        if bad:
            ap.error(f"unknown rule(s): {sorted(bad)}")

    paths = args.paths or [os.path.join(REPO_ROOT, p)
                           for p in DEFAULT_PATHS]
    for p in paths:
        if not os.path.exists(p):
            ap.error(f"no such path: {p}")
        if os.path.isfile(p) and not p.endswith(".py"):
            # _iter_py_files would silently drop it and report a clean
            # "0 files" run — an explicit unlintable file is a usage error
            ap.error(f"not a Python file: {p}")

    if args.write_baseline:
        from gsc_tpu.analysis import inline_suppression

        files = _iter_py_files(paths)
        raw, _ = lint_files(files, rules=rules, root=REPO_ROOT)
        # inline-marked findings are already suppressed at their source
        # line; a baseline entry for one would match nothing on the next
        # run and report as stale
        raw = [f for f in raw
               if not inline_suppression(f.line_text, f.rule)]
        existing = (load_baseline(args.baseline)
                    if os.path.exists(args.baseline) else [])
        # a scoped rewrite (--rules subset / explicit path subset) only
        # re-checked part of the tree: entries outside that scope are
        # preserved verbatim, never silently dropped
        linted_rel = {
            os.path.relpath(os.path.abspath(f),
                            REPO_ROOT).replace(os.sep, "/")
            for f in files}
        preserved = [
            e for e in existing
            if (rules is not None and e.get("rule") not in rules)
            or e.get("path") not in linted_rel]
        n = save_baseline(args.baseline, raw, existing=existing,
                          preserve=preserved)
        print(f"gsc-lint: baseline rewritten with {n} suppression(s) -> "
              f"{args.baseline}")
        todo = sum(1 for e in load_baseline(args.baseline)
                   if e["reason"].startswith("TODO"))
        if todo:
            # exit non-zero: an unreviewed TODO reason must not slip
            # through the CI gate as an accepted suppression
            print(f"gsc-lint: {todo} entries need a written reason "
                  "(search for TODO) before the baseline is reviewable")
            return 1
        return 0

    files = _iter_py_files(paths)
    if args.changed is not None:
        changed = _git_changed_files(args.changed)
        if changed is None:
            if not args.quiet:
                print("gsc-lint: --changed: git unavailable, falling "
                      "back to a full scan", file=sys.stderr)
        else:
            changed_set = set(changed)
            files = [f for f in files if _rel(f) in changed_set]
            if not files:
                if args.as_json:
                    json.dump({"files": 0, "findings": [],
                               "suppressed": [], "stale_suppressions": [],
                               "by_rule": {}, "ok": True},
                              sys.stdout, indent=1)
                    sys.stdout.write("\n")
                elif not args.quiet:
                    print("gsc-lint: no lintable files changed vs "
                          f"{args.changed}")
                return 0

    all_entries = [] if args.no_baseline else load_baseline(args.baseline)
    entries = all_entries
    if rules:
        entries = [e for e in entries
                   if e.get("rule") in rules or not e.get("rule")]
    raw, nfiles = lint_files(files, rules=rules, root=REPO_ROOT)
    result = build_result(raw, entries, nfiles)

    # an entry can only be called stale if this run actually re-checked
    # its file — a scoped run (--changed, an explicit path subset) must
    # not report (or prune) suppressions it never looked at
    linted_rel = {_rel(f) for f in files}
    stale = [e for e in result.stale_suppressions
             if e.get("path") in linted_rel]

    if args.prune_stale:
        if args.no_baseline:
            ap.error("--prune-stale needs the baseline "
                     "(drop --no-baseline)")
        prune = {e["fingerprint"] for e in stale}
        if prune:
            keep = [e for e in all_entries
                    if e["fingerprint"] not in prune]
            save_baseline(args.baseline, [], preserve=keep)
        print(f"gsc-lint: pruned {len(prune)} stale suppression(s) -> "
              f"{args.baseline}")
        stale = []

    if args.as_json:
        json.dump({
            "files": result.files,
            "findings": [f.to_json() for f in result.findings],
            "suppressed": [f.to_json() for f in result.suppressed],
            "stale_suppressions": stale,
            "by_rule": result.by_rule(),
            "ok": result.ok,
        }, sys.stdout, indent=1)
        sys.stdout.write("\n")
        return 0 if result.ok else 1

    for f in result.findings:
        print(f.format())
    if not args.quiet:
        by_rule = result.by_rule()
        detail = ", ".join(f"{r}={n}" for r, n in sorted(by_rule.items()))
        print(f"gsc-lint: {result.files} files, "
              f"{len(result.findings)} finding(s)"
              + (f" ({detail})" if detail else "")
              + f", {len(result.suppressed)} suppressed"
              + (f", {len(stale)} stale" if stale else ""))
        for e in stale:
            print(f"gsc-lint: stale suppression (matched nothing): "
                  f"{e['fingerprint']} {e.get('path', '?')} — run "
                  "--prune-stale to drop it")
    return 0 if result.ok else 1


if __name__ == "__main__":
    sys.exit(main())
