"""Latency-SLA serving bench — banks SERVE_r*.json next to BENCH_*.json.

Measures the serving subsystem end to end, with each leg in a FRESH
subprocess so the startup numbers mean what they claim:

- **cold leg** (empty artifact cache, concurrency 1): ``cold_start_s`` =
  trace + lower + backend-compile of every bucket; request latencies land
  in the smallest bucket;
- **warm leg** (same artifact cache, concurrency = largest bucket):
  ``cache_hit_start_s`` = deserialize + warm only — the number that must
  be seconds, not minutes; every bucket must report a cache hit or the
  bench fails; the concurrent closed-loop load fills the large bucket;
- **sustained trio** (unless ``--no-sustained``): one closed-loop load
  shape (concurrency >= 8, largest bucket > concurrency so the deadline
  batcher pays its wait every flush) through the deadline batcher, then
  continuous batching, then continuous batching with ``--swaps`` live
  weight hot-swaps fired mid-load.  Bank-time gates: zero errors on
  every leg, all fired swaps completed, continuous rps >= deadline rps,
  and the swap leg inside the bench_diff p99/slo_* bands vs the no-swap
  control — SERVE_r02's acceptance criteria, enforced by the tool.

Output artifact (``--out``, default SERVE_r01.json): requests/s and
p50/p99 per leg and per batch bucket, the two startup walls, each leg's
SLO summary (deadline-miss ratio, pad waste, queue-wait fraction,
error-budget burn rate and attainment against ``--slo-p99-ms`` — the
``slo_*`` axes ``tools/bench_diff.py`` gates), and the
scenario/platform provenance.  Usage:

    JAX_PLATFORMS=cpu python tools/serve_bench.py --out SERVE_r01.json

Scenario: the tiny triangle stack (chaos_smoke configs) by default so the
bench runs anywhere; pass --configs agent.yaml,sim.yaml,svc.yaml,sched.yaml
plus --ckpt to bench a real checkpoint/scenario instead.
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _env():
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    # both caches on: the artifact cache is the subject under test, the
    # persistent XLA cache is what makes the deserialized module's backend
    # compile skippable across processes too
    env.setdefault("GSC_JAX_CACHE_DIR", os.path.join(REPO, ".jax_cache"))
    return env


def _train_tiny(tmp: str):
    from chaos_smoke import write_tiny_configs
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, ["train", *args, "--episodes", "2",
                                 "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        raise SystemExit(f"tiny train failed rc={r.exit_code}")
    ckpt = json.loads(r.output.strip().splitlines()[-1])["checkpoint"]
    configs = args[:4]
    extra = [a for a in args[4:] if a != "--quiet"]
    return configs, ckpt, extra


def _serve_leg(configs, ckpt, extra, *, requests, concurrency, buckets,
               deadline_ms, cache_dir, result_dir, slo_p99_ms=None,
               timeout_s=900, flags=()):
    cmd = [sys.executable, "-m", "gsc_tpu.cli", "serve", *configs, ckpt,
           *extra, "--requests", str(requests),
           "--concurrency", str(concurrency), "--buckets", buckets,
           "--deadline-ms", str(deadline_ms),
           "--artifact-cache", cache_dir, "--result-dir", result_dir,
           *flags]
    if slo_p99_ms is not None:
        cmd += ["--slo-p99-ms", str(slo_p99_ms)]
    t0 = time.perf_counter()
    proc = subprocess.run(cmd, cwd=REPO, env=_env(), capture_output=True,
                          text=True, timeout=timeout_s)
    wall = time.perf_counter() - t0
    if proc.returncode != 0:
        sys.stderr.write(proc.stdout + proc.stderr)
        raise SystemExit(f"serve leg failed rc={proc.returncode}")
    out = json.loads(proc.stdout.strip().splitlines()[-1])
    if out["errors"]:
        raise SystemExit(f"serve leg answered with errors: "
                         f"{out['error_detail']}")
    out["process_wall_s"] = round(wall, 3)
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--out", default="SERVE_r01.json")
    ap.add_argument("--requests", type=int, default=200,
                    help="requests per leg [default 200]")
    ap.add_argument("--buckets", default="1,8")
    ap.add_argument("--deadline-ms", type=float, default=5.0)
    ap.add_argument("--slo-p99-ms", type=float, default=250.0,
                    help="latency objective handed to each leg's SLO "
                         "engine — generous by default so attainment/"
                         "burn reflect real trouble, not CPU jitter; "
                         "the banked per-leg `slo` block (deadline-miss "
                         "ratio, pad waste, queue-wait fraction, burn "
                         "rate, attainment) is what bench_diff gates "
                         "under the slo_* bands [default 250]")
    ap.add_argument("--configs", default=None,
                    help="agent,sim,service,scheduler yaml paths (comma-"
                         "separated) for a non-tiny scenario")
    ap.add_argument("--ckpt", default=None,
                    help="existing checkpoint to serve (with --configs)")
    ap.add_argument("--scenario", default=None,
                    help="scenario label recorded in the artifact")
    ap.add_argument("--no-sustained", action="store_true",
                    help="skip the sustained-load trio (deadline "
                         "reference, continuous control, continuous + "
                         "hot-swaps under fire) and bank only the "
                         "historic cold/warm legs")
    ap.add_argument("--sustained-requests", type=int, default=240,
                    help="requests per sustained leg [default 240]")
    ap.add_argument("--sustained-concurrency", type=int, default=8,
                    help="closed-loop clients per sustained leg — the "
                         "acceptance floor is 8 [default 8]")
    ap.add_argument("--sustained-buckets", default="1,8,16",
                    help="buckets for the sustained legs: the largest "
                         "deliberately exceeds the concurrency, so the "
                         "deadline batcher pays its full wait per flush "
                         "while continuous mode never does — the regime "
                         "continuous batching exists for [default 1,8,16]")
    ap.add_argument("--swaps", type=int, default=3,
                    help="hot-swaps fired during the swap leg "
                         "(acceptance floor: 3) [default 3]")
    args = ap.parse_args(argv)

    import jax
    import jaxlib

    tmp = tempfile.mkdtemp(prefix="gsc_serve_bench_")
    if args.configs:
        configs = args.configs.split(",")
        if len(configs) != 4 or not args.ckpt:
            raise SystemExit("--configs wants 4 comma-separated yamls "
                             "plus --ckpt")
        ckpt, extra = args.ckpt, []
        scenario = args.scenario or "custom"
    else:
        configs, ckpt, extra = _train_tiny(tmp)
        scenario = args.scenario or \
            "triangle-3node tiny (chaos_smoke configs), graph-mode GNN actor"

    cache_dir = os.path.join(tmp, "artifact_cache")
    bucket_list = [int(b) for b in args.buckets.split(",")]
    legs = {}
    # cold: empty artifact cache, serial clients -> smallest bucket
    legs["cold"] = _serve_leg(
        configs, ckpt, extra, requests=args.requests, concurrency=1,
        buckets=args.buckets, deadline_ms=args.deadline_ms,
        cache_dir=cache_dir, result_dir=os.path.join(tmp, "serve_cold"),
        slo_p99_ms=args.slo_p99_ms)
    # warm: same cache, fresh process, concurrent clients -> large bucket
    legs["warm"] = _serve_leg(
        configs, ckpt, extra, requests=args.requests,
        concurrency=max(bucket_list), buckets=args.buckets,
        deadline_ms=args.deadline_ms, cache_dir=cache_dir,
        result_dir=os.path.join(tmp, "serve_warm"),
        slo_p99_ms=args.slo_p99_ms)

    hits = {b: rec["cache_hit"]
            for b, rec in legs["warm"]["startup"]["buckets"].items()}
    if not all(hits.values()):
        raise SystemExit(f"warm leg missed the artifact cache: {hits}")
    if any(rec["cache_hit"]
           for rec in legs["cold"]["startup"]["buckets"].values()):
        raise SystemExit("cold leg unexpectedly hit a pre-existing cache "
                         f"— stale --artifact-cache dir? {cache_dir}")

    # sustained trio (the hot-swap-under-fire acceptance legs): the same
    # closed-loop load through (a) the deadline batcher, (b) continuous
    # batching, (c) continuous batching with --swaps live weight swaps
    # fired mid-load.  Every leg must answer with zero errors; the swap
    # leg must stay inside the bench_diff p99/slo_* bands vs the no-swap
    # control, and continuous throughput must meet the deadline
    # batcher's — the fleet claims, machine-checked at bank time.
    sustained = None
    if not args.no_sustained:
        sus = dict(requests=args.sustained_requests,
                   concurrency=args.sustained_concurrency,
                   buckets=args.sustained_buckets,
                   deadline_ms=args.deadline_ms, cache_dir=cache_dir,
                   slo_p99_ms=args.slo_p99_ms)
        legs["sustained_deadline"] = _serve_leg(
            configs, ckpt, extra,
            result_dir=os.path.join(tmp, "serve_sus_deadline"), **sus)
        legs["sustained_control"] = _serve_leg(
            configs, ckpt, extra, flags=["--continuous"],
            result_dir=os.path.join(tmp, "serve_sus_control"), **sus)
        swap_dir = os.path.join(tmp, "hot_swap")
        legs["sustained_swap"] = _serve_leg(
            configs, ckpt, extra,
            flags=["--continuous", "--hot-swap-dir", swap_dir,
                   "--swap-poll-s", "0.02",
                   "--fire-swaps", str(args.swaps)],
            result_dir=os.path.join(tmp, "serve_sus_swap"), **sus)

        swap_leg = legs["sustained_swap"]
        if swap_leg["swaps"] < args.swaps:
            raise SystemExit(
                f"swap leg completed {swap_leg['swaps']} swaps < "
                f"{args.swaps} fired — hot-swap-under-fire not proven")
        dl_rps = legs["sustained_deadline"]["rps"]
        for name in ("sustained_control", "sustained_swap"):
            if legs[name]["rps"] < dl_rps:
                raise SystemExit(
                    f"continuous leg {name} rps {legs[name]['rps']} < "
                    f"deadline batcher {dl_rps} — continuous batching "
                    "must not cost throughput")

        # swap-vs-control through the real bench_diff bands: p99 plus
        # every slo_* axis — the acceptance gate, applied at bank time
        # so a red artifact can never be committed green.  p50/rps stay
        # recorded context rather than gates on this comparison: on a
        # single-core host the publisher + watcher threads legitimately
        # steal cycles from the serve path (the throughput floor is
        # enforced separately against the deadline batcher above)
        import bench_diff

        def _row(name, leg):
            metrics = {"p99_ms": leg["p99_ms"]}
            for k in ("deadline_miss_ratio", "pad_waste",
                      "queue_wait_frac", "burn_rate", "attainment"):
                v = (leg.get("slo") or {}).get(k)
                if isinstance(v, (int, float)):
                    metrics[f"slo_{k}"] = float(v)
            return {"name": name, "status": "ok", "metrics": metrics}

        verdict = bench_diff.diff_rows(
            _row("sustained_swap", legs["sustained_swap"]),
            _row("sustained_control", legs["sustained_control"]))
        if verdict["verdict"] == "regression":
            raise SystemExit(
                "hot-swap leg regressed out of the bench_diff bands vs "
                f"the no-swap control: {verdict['regressions']}")
        sustained = {
            "concurrency": args.sustained_concurrency,
            "buckets": [int(b)
                        for b in args.sustained_buckets.split(",")],
            "requests_per_leg": args.sustained_requests,
            "swaps_fired": args.swaps,
            "swaps_completed": swap_leg["swaps"],
            "published_versions": swap_leg["published_versions"],
            "continuous_vs_deadline_rps": round(
                legs["sustained_control"]["rps"] / dl_rps, 3),
            "swap_vs_control": {
                "verdict": verdict["verdict"],
                "gated_metrics": verdict["gated_metrics"],
                "regressions": verdict["regressions"]},
        }

    bucket_stats = {}
    for leg in legs.values():
        for b, rec in leg["buckets"].items():
            agg = bucket_stats.setdefault(b, {"requests": 0})
            agg["requests"] += rec["requests"]
            # per-bucket latency: keep the leg that actually exercised the
            # bucket hardest (most requests)
            if rec["requests"] >= agg.get("_n", 0):
                agg.update({"p50_ms": rec["p50_ms"],
                            "p99_ms": rec["p99_ms"], "_n": rec["requests"]})
    for agg in bucket_stats.values():
        agg.pop("_n", None)

    artifact = {
        "artifact": os.path.splitext(os.path.basename(args.out))[0],
        "metric": "serve_requests_per_sec",
        "scenario": scenario,
        "platform": jax.default_backend(),
        "jax": jax.__version__, "jaxlib": jaxlib.__version__,
        "tier": legs["cold"]["tier"],
        "buckets": bucket_list,
        "deadline_ms": args.deadline_ms,
        "requests_per_leg": args.requests,
        "slo_p99_ms": args.slo_p99_ms,
        "cold_start_s": legs["cold"]["startup"]["startup_s"],
        "cache_hit_start_s": legs["warm"]["startup"]["startup_s"],
        "sustained": sustained,
        "legs": {
            name: {"concurrency": (
                       1 if name == "cold"
                       else args.sustained_concurrency
                       if name.startswith("sustained")
                       else max(bucket_list)),
                   "mode": leg.get("mode", "deadline"),
                   "rps": leg["rps"], "p50_ms": leg["p50_ms"],
                   "p99_ms": leg["p99_ms"],
                   "process_wall_s": leg["process_wall_s"],
                   # the leg's SLO verdict (deadline-miss ratio, pad
                   # waste, queue-wait fraction, burn rate, attainment)
                   # — bench_diff gates these under the slo_* bands
                   "slo": leg.get("slo"),
                   # hot-swap provenance on the swap leg
                   **({"swaps": leg["swaps"]} if leg.get("swaps")
                      else {}),
                   "startup": leg["startup"],
                   "buckets": leg["buckets"]}
            for name, leg in legs.items()},
        "bucket_stats": bucket_stats,
        "notes": ("closed-loop client threads; latency = submit->answer "
                  "including queue+padding+device call; each leg is a "
                  "fresh process, so cache_hit_start_s is a true process "
                  "restart against the persisted artifacts; sustained_* "
                  "legs share one load shape — deadline batcher vs "
                  "continuous batching vs continuous with live weight "
                  "hot-swaps fired mid-load (swap leg gated against the "
                  "control through the bench_diff p99/slo_* bands at "
                  "bank time)"),
    }
    with open(args.out, "w") as f:
        json.dump(artifact, f, indent=1)
        f.write("\n")
    summary = {"out": args.out,
               "cold_start_s": artifact["cold_start_s"],
               "cache_hit_start_s": artifact["cache_hit_start_s"],
               "cold_rps": legs["cold"]["rps"],
               "warm_rps": legs["warm"]["rps"]}
    if sustained is not None:
        summary.update({
            "deadline_rps": legs["sustained_deadline"]["rps"],
            "continuous_rps": legs["sustained_control"]["rps"],
            "swap_rps": legs["sustained_swap"]["rps"],
            "swaps": sustained["swaps_completed"],
            "swap_vs_control": sustained["swap_vs_control"]["verdict"]})
    print(json.dumps(summary))
    return 0


if __name__ == "__main__":
    sys.exit(main())
