"""20x-push lever sweep — rollout DEVICE rate across the engine knobs
that the r4 profile work identified but never measured on chip:

- ``scan_unroll``: the substep loop is a chain of small fusions, so scan
  loop machinery is a visible wall fraction (engine.py:283-286);
- ``substep_impl``: the XLA one-hot engine vs the pallas substep
  megakernel (SimConfig.substep_impl; CPU/interpret-only until the
  Mosaic port, so chip grids stay xla while the smoke grid carries a
  pallas cell).  Every cell also records ``hlo_fusions``
  (gsc_tpu.analysis.hlo.count_fusions — the op-count proxy that gates
  substep changes; ``--no-fusions`` skips the extra AOT compile);
- ``max_flows``: every [M,*] one-hot contraction scales with the flow
  table; the flagship's M=128 has headroom over its ~64-flow peak
  occupancy (arrival budget right-sizing, VERDICT r4 item 2);
- replicas x chunk: the throughput-vs-per-call-wall trade under the
  tunnel's per-call deadline.

Each cell times ``--calls`` chunked rollout calls (compile + 1 warm call
excluded) and prints a JSON row; the last line is the winner.  Run it in
a dedicated chip window (single process group — never concurrent with
bench):

    python tools/lever_sweep.py                       # default grid
    python tools/lever_sweep.py --cpu --grid smoke    # CPU smoke

Every cell runs as a BOUNDED SUBPROCESS (bench.py's orchestrator model):
a cell that wedges the TPU backend hangs alone and is killed at
``--cell-timeout``, instead of silently burning the whole chip-window
stage timeout and dropping the cells after it; after any unclean cell the
backend is re-probed (bench.probe) before the next one is trusted to the
chip.  ``--in-process`` restores the single-process mode (CI/CPU smoke).
"""
from __future__ import annotations

import argparse
import itertools
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

GRIDS = {
    # (replicas, chunk, max_flows, scan_unroll, substep_impl).  The chip
    # grids sweep the XLA engine's unroll knob (the never-swept r4 lever);
    # the pallas megakernel joins them once its Mosaic lowering lands
    # (ops/pallas_substep.py docstring) — today it is CPU/interpret-only,
    # so only the smoke grid carries a pallas cell.
    "default": list(itertools.product((256, 512), (50,), (96, 128),
                                      (1, 2, 4), ("xla",))),
    "wide": list(itertools.product((256, 512), (25, 50, 100), (96, 128),
                                   (1, 2, 4), ("xla",))),
    "smoke": [(2, 5, 32, 1, "xla"), (2, 5, 32, 2, "xla"),
              (2, 5, 32, 1, "pallas")],
}


def measure(B, chunk, max_flows, unroll, impl, calls, episode_steps,
            fusions=True):
    import dataclasses

    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _flagship
    from gsc_tpu.analysis.hlo import count_fusions
    from gsc_tpu.env.env import ServiceCoordEnv
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    env0, agent, topo, _ = _flagship(episode_steps=episode_steps,
                                     max_flows=max_flows,
                                     gen_traffic=False)
    if unroll != 1 or impl != "xla":
        env0 = ServiceCoordEnv(
            env0.service, dataclasses.replace(env0.sim_cfg,
                                              scan_unroll=unroll,
                                              substep_impl=impl),
            agent, env0.limits)
    dt = DeviceTraffic(env0.sim_cfg, env0.service, topo, episode_steps)
    traffic = jax.jit(lambda k: dt.sample_batch(k, B))(jax.random.PRNGKey(0))
    pddpg = ParallelDDPG(env0, agent, num_replicas=B, donate=True)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    def call(carry, start):
        state, buffers, env_states, obs = carry
        out = pddpg.rollout_episodes(state, buffers, env_states, obs,
                                     topo, traffic, jnp.int32(start), chunk)
        return out[:4]

    t_c = time.time()
    carry = call((state, buffers, env_states, obs), jnp.int32(0))
    jax.block_until_ready(carry)
    compile_s = time.time() - t_c
    carry = call(carry, jnp.int32(chunk))   # warm (donation steady state)
    jax.block_until_ready(carry)
    t0 = time.time()
    for c in range(calls):
        carry = call(carry, jnp.int32((c + 2) * chunk))
    jax.block_until_ready(carry)
    wall = time.time() - t0
    row = {"replicas": B, "chunk": chunk, "max_flows": max_flows,
           "scan_unroll": unroll, "substep_impl": impl,
           "env_steps_per_sec": round(calls * chunk * B / wall, 1),
           "per_call_s": round(wall / calls, 3),
           "compile_s": round(compile_s, 1)}
    if fusions:
        # the op-count proxy next to every rate (analysis.hlo — the gate
        # that caught the bit-exact 281->294 scatter-merge).  AOT-lowers
        # a wrapper program; the persistent cache absorbs the inner
        # executable, --no-fusions skips it on tightly budgeted windows.
        row["hlo_fusions"] = count_fusions(
            jax.jit(call).lower(carry,
                                jnp.int32((calls + 2) * chunk)).compile())
    return row


def _cell_in_process(cell, args):
    """Measure one grid cell in THIS process (the subprocess entry, and
    the --in-process fallback)."""
    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    try:  # same persistent compile cache bench.py uses
        from bench import _enable_compile_cache
        _enable_compile_cache()
    except Exception:
        pass
    B, chunk, mf, unroll, impl = cell
    try:
        row = measure(B, chunk, mf, unroll, impl, args.calls,
                      args.episode_steps, fusions=not args.no_fusions)
    except Exception as e:  # one faulted cell must not kill the sweep
        row = {"replicas": B, "chunk": chunk, "max_flows": mf,
               "scan_unroll": unroll, "substep_impl": impl,
               "error": repr(e)[:200]}
    jax.clear_caches()  # cap live executables/HBM across cells
    return row


def _cell_subprocess(cell, args):
    """Run one grid cell as a bounded child: a wedged-backend hang is
    killed at --cell-timeout instead of eating the stage budget, and the
    parent process never touches the chip (so it cannot be wedged)."""
    B, chunk, mf, unroll, impl = cell
    cmd = [sys.executable, os.path.abspath(__file__),
           "--cell", f"{B},{chunk},{mf},{unroll},{impl}",
           "--calls", str(args.calls),
           "--episode-steps", str(args.episode_steps)]
    if args.cpu:
        cmd.append("--cpu")
    if args.no_fusions:
        cmd.append("--no-fusions")
    tag = {"replicas": B, "chunk": chunk, "max_flows": mf,
           "scan_unroll": unroll, "substep_impl": impl}
    try:
        r = subprocess.run(cmd, timeout=args.cell_timeout,
                           capture_output=True, text=True)
    except subprocess.TimeoutExpired:
        return {**tag, "error": f"cell timeout ({args.cell_timeout}s) — "
                "backend hang killed"}, False
    sys.stderr.write((r.stderr or "")[-1000:])
    for line in reversed((r.stdout or "").strip().splitlines()):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if "env_steps_per_sec" in row or "error" in row:
            return row, r.returncode == 0 and "error" not in row
    return {**tag, "error": f"cell produced no row (rc={r.returncode})"}, \
        False


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--grid", choices=sorted(GRIDS), default="default")
    ap.add_argument("--calls", type=int, default=3)
    ap.add_argument("--episode-steps", type=int, default=200)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--cell-timeout", type=int, default=900,
                    help="hard wall per grid cell (subprocess kill)")
    ap.add_argument("--in-process", action="store_true",
                    help="run cells in this process (no per-cell bound) — "
                         "CI/CPU smoke mode")
    ap.add_argument("--no-fusions", action="store_true",
                    help="skip the per-cell hlo_fusions count (saves one "
                         "AOT wrapper compile per cell on tight windows)")
    ap.add_argument("--cell", default=None,
                    help="internal: measure one 'B,chunk,mf,unroll[,impl]' "
                         "cell and print its row")
    args = ap.parse_args()

    if args.cell:
        parts = args.cell.split(",")
        impl = parts[4] if len(parts) > 4 else "xla"
        cell = tuple(int(x) for x in parts[:4]) + (impl,)
        print(json.dumps(_cell_in_process(cell, args)), flush=True)
        return

    from bench import probe  # bounded-time backend health check
    rows = []
    for cell in GRIDS[args.grid]:
        if args.in_process:
            row, clean = _cell_in_process(cell, args), True
        else:
            row, clean = _cell_subprocess(cell, args)
        rows.append(row)
        print(json.dumps(row), flush=True)
        if not clean and not args.cpu:
            # tpu_validate's probe-skip protocol: an unclean cell may have
            # wedged the chip — only continue if the backend still answers
            # a bounded probe, otherwise the remaining cells would hang
            # one after another
            if not probe():
                print(json.dumps({"error": "backend unhealthy after "
                                  "failed cell — stopping sweep",
                                  "cells_run": len(rows)}), flush=True)
                break
    ok = [r for r in rows if "env_steps_per_sec" in r]
    if ok:
        best = max(ok, key=lambda r: r["env_steps_per_sec"])
        print(json.dumps({"winner": best}))


if __name__ == "__main__":
    main()
