"""Tensor-parallel smoke: the `tp` rulebook end to end through the CLI.

The CI-stage proof that true tensor-parallel compute actually executes
and is GATED the way PR 13 promises — by tolerance bands, not digests.
A tiny 3-episode, 2-replica CPU train run on a 1x2 mesh with
``--partition-rules tp`` must

- exit 0 with ``run_start`` recording ``mesh 1x2`` / ``rules tp`` and a
  partition summary that genuinely splits leaves over ``mp``,
- write a ``perf.json`` whose ledger carries BOTH the carving-comparable
  plain ``chunk_step`` entry and the ``chunk_step_sharded`` capture of
  the partitioned executable — the latter with a non-empty collective
  block (the psum-accumulated contractions are all-reduces the HLO
  can't hide),
- write a complete ``curves.json`` and gate through ``bench_diff``:
  self-compare clean (rc 0), an injected curve regression caught
  (rc 1) — the banded-acceptance workflow the tp contract rests on.

Run by ``tools/ci_check.sh`` after the multihost stage; standalone:

    JAX_PLATFORMS=cpu python tools/tp_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

# the 1x2 mesh needs 2 virtual CPU devices — the flag is read at backend
# init (first jax.devices()), so setting it before any device work is
# enough even though jax may already be imported
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

EPISODES = 3


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"tp smoke: FAIL — {msg}")
    return 1


def main() -> int:
    _configure_jax()
    import jax

    if len(jax.devices()) < 2:
        return fail(f"needs 2 virtual CPU devices, backend has "
                    f"{len(jax.devices())} (XLA_FLAGS latched too late?)")
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    tmp = tempfile.mkdtemp(prefix="gsc_tp_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(EPISODES), "--replicas", "2",
        "--chunk", "3", "--mesh", "1x2", "--partition-rules", "tp",
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --partition-rules tp")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    start = [e for e in events if e["event"] == "run_start"][0]
    if start.get("mesh") != "1x2" or start.get("partition_rules") != "tp":
        return fail(f"run_start records mesh={start.get('mesh')!r} "
                    f"rules={start.get('partition_rules')!r}")
    specs = start.get("partition_specs") or {}
    split = sum(n for spec, n in specs.items()
                if spec != "PartitionSpec()")
    if split <= 0:
        return fail(f"tp partition summary splits no leaf: {specs}")

    perf_path = os.path.join(rdir, "perf.json")
    if not os.path.exists(perf_path):
        return fail("perf.json not written")
    entries = json.load(open(perf_path)).get("entries") or {}
    plain = entries.get("chunk_step") or {}
    sharded = entries.get("chunk_step_sharded") or {}
    if not plain.get("available"):
        return fail(f"plain chunk_step capture missing/failed: {plain}")
    if not sharded.get("available"):
        return fail(f"chunk_step_sharded capture missing/failed: "
                    f"{sharded}")
    col = sharded.get("collectives") or {}
    if not col.get("count"):
        return fail(f"partitioned executable shows no collectives — "
                    f"tp contractions should all-reduce: {col}")
    if "collectives" not in plain:
        return fail("plain capture predates the collective-mining "
                    "ledger (no collectives block)")

    curves_path = os.path.join(rdir, "curves.json")
    if not os.path.exists(curves_path):
        return fail("curves.json not written")
    curves = json.load(open(curves_path))
    if curves.get("episodes") != EPISODES \
            or curves["summary"].get("final_window_return") is None:
        return fail(f"curves.json incomplete: episodes="
                    f"{curves.get('episodes')} "
                    f"summary={curves.get('summary')}")

    # the banded-acceptance gate itself: self-compare clean, injected
    # envelope regression caught — rc discipline identical to CI's
    import bench_diff
    traj = os.path.join(tmp, "traj.json")
    doc = bench_diff.ingest([curves_path], traj)
    (row_name,) = [n for n in doc["rows"] if n.startswith("curves_")]
    rc = bench_diff.main(["diff", row_name, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"tp curves self-compare rc={rc} (want 0)")
    base_final = doc["rows"][row_name]["metrics"]["final_window_return"]
    bad = dict(curves)
    bad["summary"] = {**curves["summary"],
                      "final_window_return":
                          base_final - 10 * abs(base_final) - 100.0}
    bad_path = os.path.join(tmp, "bad_curves.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected tp curve regression rc={rc} (want 1)")

    print(f"tp smoke: OK — 1x2 tp run green, {split} leaves split, "
          f"{col['count']} collectives / {col['bytes']} B banked in "
          "perf.json, curves envelope-gated both directions")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
