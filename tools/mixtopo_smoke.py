"""Mixed-topology smoke: a tiny 2-topology mixed train run must work.

The CI-stage proof that the mix path actually executes end to end: a
2-episode, 2-replica CPU training run with ``--topo-mix "schedule,line3"``
(schedule = the triangle network, so the batch spans two networks) must

- exit 0,
- leave ``harness_episode`` events in the run's ``events.jsonl`` whose
  ``per_topology_return`` carries BOTH topology names (per-replica
  attribution survived the vmapped dispatch),
- record per-topology ``topology_return`` gauges in ``metrics.json``,
- end the stream with ``run_end status=ok``.

Run by ``tools/ci_check.sh`` before the chaos stage; standalone:

    JAX_PLATFORMS=cpu python tools/mixtopo_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MIX = "schedule,line3"


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    tmp = tempfile.mkdtemp(prefix="gsc_mixtopo_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "2", "--replicas", "2",
        "--chunk", "3", "--topo-mix", MIX,
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        print(f"mixtopo smoke: FAIL — train rc={r.exit_code} under "
              f"--topo-mix {MIX!r}")
        return 1
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]
    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    harness = [e for e in events if e["event"] == "harness_episode"]
    names = set()
    for e in harness:
        names |= set((e.get("per_topology_return") or {}))
    if len(names) < 2:
        print(f"mixtopo smoke: FAIL — expected per-topology returns for "
              f"2 networks on harness_episode events, saw {sorted(names)}")
        return 1
    snap = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    # hub.snapshot() flattens to prometheus exposition names:
    # gsc_topology_return{run="...",topology="<name>"}
    gauges = [k for k in snap if k.startswith("gsc_topology_return")]
    hit = {n for n in names if any(n in g for g in gauges)}
    if hit != names:
        print(f"mixtopo smoke: FAIL — topology_return gauges missing for "
              f"{sorted(names - hit)} (have {gauges})")
        return 1
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        print(f"mixtopo smoke: FAIL — stream tail {end}")
        return 1
    run_start = next(e for e in events if e["event"] == "run_start")
    if run_start.get("topo_mix") != MIX:
        print(f"mixtopo smoke: FAIL — run_start topo_mix "
              f"{run_start.get('topo_mix')!r} != {MIX!r}")
        return 1
    print(f"mixtopo smoke: OK — mixed batch over {sorted(names)} "
          f"({len(harness)} harness episodes, gauges + events present, "
          "run_end status=ok)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
