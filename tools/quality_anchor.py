"""Non-learned quality anchors — the BASELINE-protocol comparison the
parity oracles can't give.

The reference's torch/torch-geometric agent stack is not installable in
this image, so its *trained* policy can't be re-run here.  This tool
builds the substitute anchor the VERDICT asks for: score NON-LEARNED
baselines with the exact same env/reward/success accounting the learned
agent is scored with, on the same scenarios, so the learned numbers have
external yardsticks instead of only their own first-vs-last deltas:

- ``uniform``  — equal scheduling weight to every real node (the
  reference's dummy uniform schedule, coordsim/main.py dummy data /
  ``cli simulate``'s default).
- ``greedy``   — min-load: each control interval, ALL weight on the node
  with the most remaining capacity (cap_now - current load), recomputed
  every interval.
- ``prop``     — capacity-proportional: weight each destination by its
  remaining capacity (a classic load-balancer; the strongest non-learned
  anchor here).
- ``learned``  — optional (``--checkpoint``): greedy actor from a
  ``cli train`` / checkpoint file, rolled out with the identical loop.

Scenarios:
- ``flagship`` — Abilene in4-rand-cap1-2, abc chain, 200-step episodes
  (the benchmark workload of BASELINE.md).
- ``unseen``   — the r3 generalization setting: a mutate_caps Abilene
  variant whose cap seed is OUTSIDE the 4-variant training schedule
  (seeds 0-3 train, seed 4 here).

Episodes run CHUNKED (50-step device calls) per the TPU envelope; every
policy is vmapped over ``--replicas`` envs with per-replica traffic.

    python tools/quality_anchor.py --cpu --replicas 4 --episodes 2
    python tools/quality_anchor.py --replicas 64 --episodes 4 \
        --checkpoint results/.../checkpoint
"""
from __future__ import annotations

import argparse
import functools
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def make_policy(kind, env, actor=None, actor_params=None):
    """-> policy(env_state, obs, topo, cap_now) -> flat [A] action in [0,1].
    All policies are pure jnp functions of the replica's own state, so they
    vmap and run inside the chunked rollout scan."""
    import jax
    import jax.numpy as jnp

    n, c, s, _ = env.limits.scheduling_shape

    def _sched_from_dest(w):
        # [N] destination weights -> [N,C,S,N] (same weights for every
        # (src, sfc, sf) row; env.step masks padded src/dst).  Rows MUST
        # be normalized here: the engine's WRR picks argmax(w - realized
        # ratio) (engine.py:508-517) and realized ratios sum to 1, so
        # unnormalized rows degenerate to winner-take-all — only the
        # learned-agent path's post_process_action normalizes.
        w = w / jnp.maximum(w.sum(), 1e-9)
        return jnp.broadcast_to(w, (n, c, s, n)).reshape(-1)

    if kind == "uniform":
        def policy(env_state, obs, topo, cap_now):
            return _sched_from_dest(topo.node_mask.astype(jnp.float32))
    elif kind == "greedy":
        def policy(env_state, obs, topo, cap_now):
            rem = cap_now - env_state.sim.node_load.sum(-1)
            rem = jnp.where(topo.node_mask, rem, -jnp.inf)
            return _sched_from_dest(
                jax.nn.one_hot(jnp.argmax(rem), n, dtype=jnp.float32))
    elif kind == "prop":
        def policy(env_state, obs, topo, cap_now):
            rem = cap_now - env_state.sim.node_load.sum(-1)
            w = jnp.clip(rem, 0.0) + 1e-3
            return _sched_from_dest(w * topo.node_mask)
    elif kind == "learned":
        def policy(env_state, obs, topo, cap_now):
            a = jnp.clip(actor.apply(actor_params, obs), 0.0, 1.0)
            return env.process_action(a)
    else:
        raise ValueError(kind)
    return policy


def score_policy(env, topo, traffic_fn, policy, steps, chunk, replicas,
                 episodes, seed):
    """Mean episodic return / success over ``episodes`` episodes of
    ``replicas`` vmapped envs (fresh traffic per episode via
    ``traffic_fn(ep)``); episodes run as ``steps/chunk`` chunked device
    calls (never one long scan — the TPU per-call envelope).  One compile
    per policy: traffic is an argument of the jitted chunk call."""
    import jax
    import jax.numpy as jnp

    def one_step(carry, _, traf):
        env_state, obs = carry
        # traf is the per-replica schedule here (inside vmap): [T, N]
        cap_now = traf.node_cap[
            jnp.clip(env_state.sim.run_idx, 0,
                     traf.node_cap.shape[0] - 1)]
        action = policy(env_state, obs, topo, cap_now)
        env_state, obs, reward, done, info = env.step(
            env_state, topo, traf, action)
        return (env_state, obs), (reward, info["succ_ratio"])

    # traffic is an ARGUMENT (not a closure) so successive episodes with
    # fresh traffic hit the same compiled executable
    @jax.jit
    def chunk_call(env_states, obs, traffic):
        def per_replica(env_state, ob, traf):
            return jax.lax.scan(
                functools.partial(one_step, traf=traf),
                (env_state, ob), None, length=chunk)
        (env_states, obs), (rews, succs) = jax.vmap(per_replica)(
            env_states, obs, traffic)
        return env_states, obs, rews.sum(1), succs[:, -1]

    reset = jax.jit(jax.vmap(lambda k, t: env.reset(k, topo, t)))
    rets, succs = [], []
    for ep in range(episodes):
        traffic = traffic_fn(ep)
        keys = jax.random.split(
            jax.random.PRNGKey(seed + ep), replicas)
        env_states, obs = reset(keys, traffic)
        total = jnp.zeros((replicas,))
        last_succ = None
        for _ in range(steps // chunk):
            env_states, obs, rews, last_succ = chunk_call(
                env_states, obs, traffic)
            total = total + rews
        rets.append(float(total.mean()))
        succs.append(float(last_succ.mean()))
    return (sum(rets) / len(rets), sum(succs) / len(succs))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=16)
    ap.add_argument("--episodes", type=int, default=2,
                    help="episodes per scenario (fresh traffic each)")
    ap.add_argument("--episode-steps", type=int, default=200)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--checkpoint", default=None,
                    help="score a trained actor too (cli train checkpoint)")
    ap.add_argument("--scenarios", nargs="+",
                    default=["flagship", "unseen"],
                    choices=["flagship", "unseen"])
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    from __graft_entry__ import _flagship
    from gsc_tpu.sim.traffic_device import DeviceTraffic
    from gsc_tpu.topology.compiler import compile_topology
    from gsc_tpu.topology.synthetic import abilene, mutate_caps

    steps, chunk, B = args.episode_steps, args.chunk, args.replicas
    if steps % chunk:
        raise SystemExit(f"--chunk {chunk} must divide "
                         f"--episode-steps {steps}")
    env, agent_cfg, topo_flag, _ = _flagship(episode_steps=steps,
                                             gen_traffic=False)

    scen_topos = {}
    if "flagship" in args.scenarios:
        scen_topos["flagship"] = topo_flag
    if "unseen" in args.scenarios:
        # cap seed 4 = first variant OUTSIDE the r3 4-network training
        # schedule (seeds 0-3); same (1, 3) cap range as rand-cap1-2
        scen_topos["unseen"] = compile_topology(
            mutate_caps(abilene(), (1, 3), seed=4),
            max_nodes=env.limits.max_nodes,
            max_edges=env.limits.max_edges)

    if {"flagship", "unseen"} <= scen_topos.keys():
        # anchor sanity (ADVICE r5): bit-identical anchor rows across the
        # two scenarios could mask the unseen topology never reaching the
        # scoring path — so PROVE the topologies differ where it matters
        import numpy as np
        cap_a = np.asarray(scen_topos["flagship"].node_cap)
        cap_b = np.asarray(scen_topos["unseen"].node_cap)
        if np.array_equal(cap_a, cap_b):
            raise SystemExit(
                "anchor sanity: flagship and unseen scenario topologies "
                "have IDENTICAL node_cap arrays — the unseen cap draw is "
                "not reaching the scoring path")
        print(json.dumps({
            "anchor_sanity": "node_cap_arrays_differ",
            "n_differing_nodes": int((cap_a != cap_b).sum())}))

    policies = {k: make_policy(k, env) for k in ("uniform", "greedy",
                                                 "prop")}
    if args.checkpoint:
        from gsc_tpu.agents.ddpg import DDPG
        from gsc_tpu.utils.checkpoint import load_full_or_partial
        ddpg = DDPG(env, agent_cfg)
        batched = DeviceTraffic(env.sim_cfg, env.service, topo_flag,
                                steps).sample_batch(jax.random.PRNGKey(0), 1)
        one_traffic = jax.tree_util.tree_map(lambda x: x[0], batched)
        _, obs0 = env.reset(jax.random.PRNGKey(0), topo_flag, one_traffic)
        example = ddpg.init(jax.random.PRNGKey(0), obs0)
        restored, _ = load_full_or_partial(args.checkpoint, example)
        policies["learned"] = make_policy(
            "learned", env, actor=ddpg.actor,
            actor_params=restored["state"].actor_params)

    table = {}
    scen_traffic_fns = {}
    for scen, topo in scen_topos.items():
        dt = DeviceTraffic(env.sim_cfg, env.service, topo, steps)
        sample = jax.jit(dt.sample_batch, static_argnums=1)
        traffic_cache = {}  # every policy scores the SAME traffic draws

        def traffic_fn(ep, _sample=sample, _cache=traffic_cache):
            if ep not in _cache:
                _cache[ep] = _sample(
                    jax.random.fold_in(jax.random.PRNGKey(args.seed), ep),
                    B)
            return _cache[ep]

        scen_traffic_fns[scen] = traffic_fn
        for name, pol in policies.items():
            t0 = time.time()
            r, s = score_policy(env, topo, traffic_fn, pol, steps, chunk,
                                B, args.episodes, args.seed)
            row = {"mean_return": round(r, 3),
                   "final_succ_ratio": round(s, 4),
                   "episodes": args.episodes, "replicas": B,
                   "wall_s": round(time.time() - t0, 1)}
            table[f"{scen}/{name}"] = row
            print(json.dumps({"scenario": scen, "policy": name, **row}))

    fa, un = table.get("flagship/greedy"), table.get("unseen/greedy")
    if fa and un and (fa["mean_return"], fa["final_succ_ratio"]) == \
            (un["mean_return"], un["final_succ_ratio"]):
        # identical greedy rows under DIFFERENT cap draws: plausible (the
        # traffic and ingress set are unchanged, and greedy can saturate
        # the same argmax path), but exactly the coincidence that would
        # also appear if the unseen topology never reached scoring — so
        # re-run greedy on the unseen topology and record that the repeat
        # reproduces the number through the real scoring path
        r2, s2 = score_policy(env, scen_topos["unseen"],
                              scen_traffic_fns["unseen"],
                              policies["greedy"], steps, chunk, B,
                              args.episodes, args.seed)
        print(json.dumps({
            "anchor_sanity": "greedy_rows_identical_across_scenarios",
            "unseen_rescore": {"mean_return": round(r2, 3),
                               "final_succ_ratio": round(s2, 4)},
            "reproduced": (round(r2, 3) == un["mean_return"]
                           and round(s2, 4) == un["final_succ_ratio"])}))
    print(json.dumps({"backend": jax.default_backend(),
                      "episode_steps": steps, "table": table}, indent=1))


if __name__ == "__main__":
    main()
