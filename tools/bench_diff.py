"""Cross-run perf regression tracker over the banked bench artifacts.

The repo accumulates a perf trajectory nobody reads mechanically:
``BENCH_r0*.json`` (env-steps/s ladder rounds), ``MULTICHIP_r0*.json``
(mesh-carving bit-equality matrices), ``SERVE_r0*.json`` (latency-SLA
legs), the per-run ``perf.json`` cost ledgers (gsc_tpu.obs.perf) and the
per-run ``curves.json`` learning-curve envelopes (gsc_tpu.obs.curves:
final-window return, AUC, episodes-to-threshold — the banded quality
envelope ROADMAP item 2 trades bit-exactness against).
This tool makes that trajectory a guarded artifact:

- **ingest**: normalize any mix of those files into rows of one
  cumulative ``BENCH_TRAJECTORY.json`` (schema-versioned, keyed by
  artifact name; re-ingesting updates in place);
- **diff**: compare a current row (by name, or straight from a file)
  against a NAMED BASELINE row with per-metric tolerance bands, exit
  nonzero on any regression — the fusion-budget discipline of the
  megakernel PR, generalized to every perf-relevant number.

Verdicts per metric: ``ok`` (within band), ``improved``, ``regression``
(beyond band in the bad direction), ``missing`` (only one side has it —
informational, never fatal).  Overall verdict is ``regression`` iff any
metric regressed; a baseline name that is not in the trajectory is the
distinct ``missing-baseline`` verdict (exit 3), so CI can tell "got
slower" from "never measured".

Exit codes: 0 ok/improved, 1 regression, 2 usage/parse error,
3 missing baseline.

Usage:
    python tools/bench_diff.py ingest --scan . --out BENCH_TRAJECTORY.json
    python tools/bench_diff.py ingest results/run1/perf.json
    python tools/bench_diff.py diff BENCH_r04 --baseline BENCH_r03
    python tools/bench_diff.py diff results/run2/perf.json \
        --baseline perf_run1 --tolerance mfu=0.3
    python tools/bench_diff.py --selftest

Stdlib only: this must run on a login node with no JAX installed.
"""
from __future__ import annotations

import argparse
import glob
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional, Tuple

TRAJECTORY_SCHEMA_VERSION = 1

# metric gating rules, matched by key SUFFIX (first match wins):
# (suffix, higher_is_better, relative tolerance band[, absolute band
# floor]).  A metric with no matching rule is carried in the rows but
# never gated — flops/bytes legitimately move when the model changes;
# rates/latencies/fusion counts are the contract.  The absolute floor
# exists for metrics that legitimately sit at or cross ZERO (episode
# returns): band = max(tol * |baseline|, floor), so a baseline of ~0
# never shrinks the band to nothing and flags pure noise as regression
# (the strictly-positive perf metrics keep the historic relative-only
# band — an explicit floor of 0.0).
METRIC_RULES: List[Tuple] = [
    ("env_steps_per_sec", True, 0.10),
    ("vs_baseline", True, 0.10),
    ("rps", True, 0.15),
    ("p99_ms", False, 0.25),
    ("p50_ms", False, 0.25),
    ("mfu", True, 0.15),
    ("sps", True, 0.15),             # mixtopo mixed/homogeneous rates
    # ASYNC mesh rounds (r02+): scaling-efficiency axis — per-grid rate
    # divided by device count (suffix does NOT end in `sps`, so it needs
    # its own band), and the HLO-mined collective count on the compiled
    # dp-sharded replay ingest (the zero-collective contract: ANY growth
    # means blocks started paying a gather/reshard per ingest)
    ("sps_per_device", True, 0.15),
    ("ingest_collectives", False, 0.0),
    ("fusions", False, 0.05),
    ("jit_traces", False, 0.0),      # any retrace growth is churn
    ("legs_ok", True, 0.0),
    ("bit_equal", True, 0.0),
    ("cold_start_s", False, 0.25),
    ("cache_hit_start_s", False, 0.25),
    # learning-curve envelope metrics (per-run curves.json summaries,
    # gsc_tpu.obs.curves) — the quality_anchor trade currency ROADMAP
    # item 2 names: a tensor-parallel rulebook is acceptable when these
    # stay inside the bands, not only when results are bit-identical.
    # Returns legitimately cross zero, so they carry absolute floors
    # (episode-return units / episodes / |TD| units respectively).
    ("final_window_return", True, 0.20, 1.0),
    ("auc_return", True, 0.25, 1.0),
    ("episodes_to_threshold", False, 0.25, 1.0),
    ("final_window_td_abs", False, 0.30, 0.05),
    # serving SLO metrics (cli serve / PolicyServer slo summaries, banked
    # per serve_bench leg and as per-run slo.json documents) — SERVE rows
    # gate on serving QUALITY, not just rps/p99.  Ratios legitimately sit
    # at/near zero (a healthy run has no deadline misses), so every band
    # carries an absolute floor in ratio units.
    ("slo_deadline_miss_ratio", False, 0.25, 0.02),
    ("slo_pad_waste", False, 0.25, 0.05),
    ("slo_queue_wait_frac", False, 0.30, 0.05),
    ("slo_burn_rate", False, 0.25, 0.25),
    ("slo_attainment", True, 0.05, 0.02),
    # async actor/learner rows (ASYNC_r*, tools/async_bench.py): the
    # learner-idle fraction is the decoupling claim itself — the learner
    # must not creep back toward blocking on acting.  Lower is better; a
    # healthy run sits near zero, so the band carries an absolute floor
    # in ratio units (the per-leg *_sps rates gate under the shared 15%
    # `sps` band above, and per-leg trace counts under `jit_traces`).
    ("learner_idle_frac", False, 0.25, 0.05),
    # flight-recorder lag/idle axes on ASYNC rows: the p99 policy lag is
    # the staleness contract (a learner suddenly training on much older
    # acting policies regresses generalization claims even when raw sps
    # holds), the max per-actor idle fraction is the dispatch-side twin
    # of learner_idle_frac — an actor spending its wall blocked on the
    # channel means the learn side became the bottleneck.  Both sit near
    # small integers / zero on healthy runs, so both carry absolute
    # floors (versions / ratio units).
    ("policy_lag_p99", False, 0.50, 1.0),
    ("actor_idle_frac", False, 0.25, 0.10),
]

# filename patterns `ingest --scan` picks up.  perf.json ledgers and
# curves.json learning curves are searched RECURSIVELY: runs write them
# at results/<id>/<timestamp>/ (utils.experiment.setup_result_dir
# layout), arbitrarily deep below the scan root.
SCAN_PATTERNS = ("BENCH_r*.json", "MULTICHIP_r*.json", "SERVE_r*.json",
                 "MIXTOPO_r*.json", "SCEN_r*.json", "ASYNC_r*.json",
                 "CHAOS_r*.json",
                 "**/perf.json", "**/curves.json", "**/slo.json")


def metric_rule(name: str) -> Optional[Tuple[bool, float, float]]:
    """(higher_is_better, relative tolerance, absolute band floor) for a
    gated metric; None = informational."""
    for rule in METRIC_RULES:
        suffix, higher, tol = rule[:3]
        if name.endswith(suffix):
            return higher, tol, (rule[3] if len(rule) > 3 else 0.0)
    return None


# ------------------------------------------------------------- extraction
def _num(v) -> Optional[float]:
    return float(v) if isinstance(v, (int, float)) \
        and not isinstance(v, bool) else None


def _bench_row(d: Dict) -> Dict:
    """A bench.py artifact line (possibly a driver wrapper's `parsed`)."""
    status = d.get("status") or ("failed" if d.get("error") else "ok")
    metrics: Dict[str, float] = {}
    if status == "ok":
        if _num(d.get("value")) is not None:
            metrics["env_steps_per_sec"] = float(d["value"])
        if _num(d.get("vs_baseline")) is not None:
            metrics["vs_baseline"] = float(d["vs_baseline"])
        # MIXTOPO/SCEN rounds share the metric name but report paired
        # rates: the `_sps` suffix gates them under the 15% rate band;
        # the ratios and the scenario_regen walls are context
        for k in ("mixed_sps", "homogeneous_sps", "mixed_vs_homogeneous",
                  "factory_sps", "host_regen_sps", "factory_vs_host",
                  "factory_scenario_regen_s", "host_scenario_regen_s",
                  # ASYNC rounds: sync control + per-actor-count async
                  # rates (`_sps` band), the learner-idle fraction (its
                  # own lower-is-better band), speedups + curve metrics
                  "sync_sps", "async1_sps", "async2_sps", "async4_sps",
                  "learner_idle_frac", "async2_vs_sync", "async4_vs_sync",
                  # ASYNC mesh rounds (r02): dp-leg rates (`_sps` band),
                  # the per-device scaling axis (`_sps_per_device`
                  # band), the zero-collective ingest count (0%
                  # tolerance), speedup ratios as context
                  "async_dp2_sps", "async_dp4_sps",
                  "async2_sps_per_device", "async_dp2_sps_per_device",
                  "async_dp4_sps_per_device", "ingest_collectives",
                  "async_dp2_vs_async2", "async_dp4_vs_async2",
                  # flight-recorder lag/idle axes on ASYNC rows: p99
                  # staleness + worst per-actor idle gate under their
                  # own lower-is-better bands
                  "policy_lag_p99", "actor_idle_frac",
                  # CHAOS rounds (tools/chaos_smoke.py --round): the
                  # fault-injected vs fault-free rates gate under the
                  # shared 15% `_sps` band — self-healing must cost
                  # recovery DETOURS, not steady-state throughput.  The
                  # recovery tallies land as informational keys (no
                  # band: how many faults a plan fires is the plan's
                  # business, drift is context not regression)
                  "chaos_sps", "control_sps", "chaos_vs_control",
                  "recoveries_total", "actor_restarts",
                  "blocks_quarantined",
                  "sync_final_window_return", "async_final_window_return",
                  "sync_auc_return", "async_auc_return"):
            if _num(d.get(k)) is not None:
                metrics[k] = float(d[k])
        for fn, n in (d.get("jit_traces") or {}).items():
            if _num(n) is not None:
                metrics[f"{fn}_jit_traces"] = float(n)
        # MIXTOPO/SCEN rounds record per-leg trace counts; keys end in
        # `_jit_traces` so the 0%-tolerance retrace band gates them too
        for leg in ("homogeneous", "mixed", "factory", "host_regen",
                    "sync", "async1", "async2", "async4",
                    "async_dp2", "async_dp4"):
            for fn, n in (d.get(f"jit_traces_{leg}") or {}).items():
                if _num(n) is not None:
                    metrics[f"{leg}_{fn}_jit_traces"] = float(n)
        for fn, cost in (d.get("cost") or {}).items():
            for k in ("fusions", "mfu", "flops", "bytes_accessed"):
                if _num((cost or {}).get(k)) is not None:
                    metrics[f"{fn}_{k}"] = float(cost[k])
    return {"kind": "bench", "status": status, "metrics": metrics,
            "context": {k: d.get(k) for k in
                        ("pipeline", "precision", "substep_impl", "unroll",
                         "mesh", "topo_mix", "async_actors",
                         "policy_lag_max", "produced_steps",
                         "ingested_steps", "ring_shards") if k in d}}


def _multichip_row(d: Dict) -> Dict:
    metrics: Dict[str, float] = {}
    for k in ("legs_ok", "legs_total", "devices"):
        if _num(d.get(k)) is not None:
            metrics[k] = float(d[k])
    if "bit_equal_across_carvings" in d:
        metrics["bit_equal"] = 1.0 if d["bit_equal_across_carvings"] else 0.0
    walls = [leg.get("wall_s") for leg in d.get("legs") or []
             if _num(leg.get("wall_s")) is not None]
    if walls:
        metrics["mean_leg_wall_s"] = round(sum(walls) / len(walls), 3)
    return {"kind": "multichip", "status": d.get("status", "ok"),
            "metrics": metrics, "context": {"mode": d.get("mode")}}


# the SLO-summary keys that become gated `slo_*` metrics on serve rows
# (arrival rate / p99 target are context, not gates — and an `_rps`
# suffix would wrongly match the throughput band)
_SLO_GATED_KEYS = ("deadline_miss_ratio", "pad_waste", "queue_wait_frac",
                   "burn_rate", "attainment")


def _slo_metrics(slo: Dict, prefix: str = "") -> Dict[str, float]:
    out: Dict[str, float] = {}
    for k in _SLO_GATED_KEYS:
        if _num((slo or {}).get(k)) is not None:
            out[f"{prefix}slo_{k}"] = float(slo[k])
    return out


def _serve_row(d: Dict) -> Dict:
    metrics: Dict[str, float] = {}
    for k in ("cold_start_s", "cache_hit_start_s"):
        if _num(d.get(k)) is not None:
            metrics[k] = float(d[k])
    legs = d.get("legs") or {}
    for leg_name, leg in legs.items():
        for k in ("rps", "p50_ms", "p99_ms"):
            if _num((leg or {}).get(k)) is not None:
                metrics[f"{leg_name}_{k}"] = float(leg[k])
        # per-leg SLO summary (serve_bench banks the cli serve `slo`
        # block): deadline-miss ratio, pad waste, queue-wait fraction,
        # burn rate, attainment gate under the slo_* bands
        metrics.update(_slo_metrics((leg or {}).get("slo"),
                                    prefix=f"{leg_name}_"))
    # flat single-run serve JSON (cli serve output) has rps/p99 top-level
    for k in ("rps", "p50_ms", "p99_ms"):
        if _num(d.get(k)) is not None:
            metrics[k] = float(d[k])
    metrics.update(_slo_metrics(d.get("slo")))
    return {"kind": "serve", "status": d.get("status", "ok"),
            "metrics": metrics,
            "context": {k: d.get(k) for k in ("tier", "buckets", "platform")
                        if k in d}}


def _slo_row(d: Dict) -> Dict:
    """A per-run slo.json document (gsc_tpu.obs.slo, written by
    PolicyServer.close): the same gated slo_* axes as a serve row, plus
    the run's latency percentiles."""
    metrics = _slo_metrics(d)
    for k in ("p50_latency_ms", "p99_latency_ms"):
        if _num(d.get(k)) is not None:
            # suffix-normalize so the p50/p99 latency bands gate them
            metrics[k.replace("_latency", "")] = float(d[k])
    if _num(d.get("requests")) is not None:
        metrics["requests"] = float(d["requests"])   # informational
    return {"kind": "slo", "status": "ok", "metrics": metrics,
            "context": {"run": d.get("run"), "tier": d.get("tier"),
                        "slo_schema": d.get("schema_version"),
                        "deadline_ms": d.get("deadline_ms")}}


def _perf_row(d: Dict) -> Dict:
    """A gsc_tpu.obs.perf ledger (perf.json)."""
    metrics: Dict[str, float] = {}
    for name, e in (d.get("entries") or {}).items():
        if not (e or {}).get("available"):
            continue
        for k in ("fusions", "mfu", "flops", "bytes_accessed",
                  "arithmetic_intensity", "wall_s_mean"):
            if _num(e.get(k)) is not None:
                metrics[f"{name}_{k}"] = float(e[k])
        # collective count/bytes per entry (the tp-vs-sharded
        # interconnect axis).  Informational, never gated: collective
        # payload legitimately moves with the model and the rulebook —
        # the point is that the comparison is machine-READ, the verdict
        # stays with the learning-curve/throughput bands
        col = e.get("collectives") or {}
        for k in ("count", "bytes"):
            if _num(col.get(k)) is not None:
                metrics[f"{name}_collective_{k}"] = float(col[k])
    return {"kind": "perf_ledger", "status": "ok", "metrics": metrics,
            "context": {"backend": d.get("backend"), "run": d.get("run"),
                        "ledger_schema": d.get("schema_version")}}


def _curves_row(d: Dict) -> Dict:
    """A gsc_tpu.obs.curves learning-curve document (curves.json).  The
    summary's envelope metrics gate; ``episodes_to_threshold`` is often
    null (a run that never rose has no time-to-learn) and is then simply
    absent — the diff reports it as ``missing``, never a regression."""
    summary = d.get("summary") or {}
    metrics: Dict[str, float] = {}
    for k in ("final_window_return", "auc_return", "episodes_to_threshold",
              "final_window_td_abs", "first_window_return"):
        if _num(summary.get(k)) is not None:
            metrics[k] = float(summary[k])
    if _num(d.get("episodes")) is not None:
        metrics["episodes"] = float(d["episodes"])
    return {"kind": "curves", "status": "ok", "metrics": metrics,
            "context": {"run": d.get("run"),
                        "curves_schema": d.get("schema_version"),
                        "window": summary.get("window")}}


def extract_row(path: str) -> Optional[Dict]:
    """Classify + normalize one artifact file; None if unrecognized."""
    try:
        with open(path) as f:
            d = json.load(f)
    except (OSError, json.JSONDecodeError) as e:
        print(f"[bench_diff] skipping {path}: {e}", file=sys.stderr)
        return None
    if not isinstance(d, dict):
        return None
    # driver wrapper rounds bank the artifact line under "parsed"; a
    # wrapper whose run produced no parseable line at all is still a
    # FAILED bench row (round never ran != round was slow)
    if "parsed" in d:
        if not isinstance(d["parsed"], dict):
            d = {"metric": "env_steps_per_sec_per_chip",
                 "status": "failed"}
        else:
            d = d["parsed"]
    if d.get("metric") == "env_steps_per_sec_per_chip":
        row = _bench_row(d)
    elif d.get("metric") == "serve_requests_per_sec" or (
            "legs" in d and "cache_hit_start_s" in d):
        row = _serve_row(d)
    elif d.get("mode") == "mesh_matrix" or "bit_equal_across_carvings" in d:
        row = _multichip_row(d)
    elif "schema_version" in d and "entries" in d:
        row = _perf_row(d)
    elif "schema_version" in d and "series" in d and "summary" in d:
        row = _curves_row(d)
    elif "schema_version" in d and "deadline_miss_ratio" in d:
        row = _slo_row(d)
    else:
        return None
    base = os.path.basename(path)
    name = os.path.splitext(base)[0]
    if name in ("perf", "curves", "slo"):
        # per-run artifacts share their filename; key by run dir (or the
        # document's recorded run id) so two runs never collide
        run = (row.get("context") or {}).get("run")
        name = f"{name}_{run or os.path.basename(os.path.dirname(os.path.abspath(path)))}"
    row.update(name=name, source=path)
    return row


# -------------------------------------------------------------- trajectory
def load_trajectory(path: str) -> Dict:
    if path and os.path.exists(path):
        with open(path) as f:
            doc = json.load(f)
        if doc.get("schema_version") != TRAJECTORY_SCHEMA_VERSION:
            print(f"[bench_diff] {path} has schema "
                  f"{doc.get('schema_version')!r}; rewriting as "
                  f"v{TRAJECTORY_SCHEMA_VERSION}", file=sys.stderr)
            doc = {"schema_version": TRAJECTORY_SCHEMA_VERSION,
                   "rows": doc.get("rows", {})}
        return doc
    return {"schema_version": TRAJECTORY_SCHEMA_VERSION, "rows": {}}


def write_trajectory(path: str, doc: Dict) -> str:
    """Atomic rewrite (temp + os.replace) — same contract as the obs
    snapshot writer, reimplemented here to stay stdlib-only."""
    fd, tmp = tempfile.mkstemp(dir=os.path.dirname(os.path.abspath(path))
                               or ".", prefix=".bench_traj.")
    try:
        with os.fdopen(fd, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        os.replace(tmp, path)
    except BaseException:
        if os.path.exists(tmp):
            os.unlink(tmp)
        raise
    return path


def ingest(paths: List[str], out: str, scan: Optional[str] = None) -> Dict:
    doc = load_trajectory(out)
    candidates = list(paths)
    if scan:
        for pattern in SCAN_PATTERNS:
            candidates.extend(sorted(glob.glob(os.path.join(scan, pattern),
                                               recursive=True)))
    seen = set()
    ingested = []
    for p in candidates:
        p = os.path.normpath(p)
        if p in seen:
            continue
        seen.add(p)
        row = extract_row(p)
        if row is None:
            continue
        doc["rows"][row["name"]] = {k: v for k, v in row.items()
                                    if k != "name"}
        ingested.append(row["name"])
    write_trajectory(out, doc)
    print(f"[bench_diff] {out}: {len(doc['rows'])} row(s) "
          f"({len(ingested)} ingested: {', '.join(ingested) or '-'})")
    return doc


# -------------------------------------------------------------------- diff
def diff_rows(current: Dict, baseline: Dict,
              tolerances: Optional[Dict[str, float]] = None) -> Dict:
    """Per-metric verdicts for a (current, baseline) row pair.

    A non-ok CURRENT row is its own overall verdict (``failed-current``,
    gated like a regression): a crashed round has no measurements, and
    diffing its empty metric set would otherwise come out clean — the
    exact "never ran reads as fine" failure the status field exists to
    prevent.  A non-ok BASELINE is ``failed-baseline`` (gated like
    missing-baseline: there is nothing to regress against)."""
    tolerances = tolerances or {}
    if current.get("status", "ok") != "ok":
        return {"verdict": "failed-current", "regressions": [],
                "gated_metrics": 0, "current": current.get("name"),
                "baseline": baseline.get("name"), "metrics": {}}
    if baseline.get("status", "ok") != "ok":
        return {"verdict": "failed-baseline", "regressions": [],
                "gated_metrics": 0, "current": current.get("name"),
                "baseline": baseline.get("name"), "metrics": {}}
    cm, bm = current.get("metrics", {}), baseline.get("metrics", {})
    per_metric = {}
    regressions = []
    for name in sorted(set(cm) | set(bm)):
        rule = metric_rule(name)
        if name not in cm or name not in bm:
            per_metric[name] = {"verdict": "missing",
                                "current": cm.get(name),
                                "baseline": bm.get(name)}
            continue
        cur, base = cm[name], bm[name]
        rec = {"current": cur, "baseline": base}
        if base != 0:
            rec["change_pct"] = round(100.0 * (cur - base) / abs(base), 2)
        if rule is None:
            rec["verdict"] = "informational"
        else:
            higher, tol, floor = rule
            tol = tolerances.get(name, tol)
            delta = (cur - base) if higher else (base - cur)   # + is good
            # the floor keeps a near-zero baseline (returns oscillating
            # around 0) from shrinking the band to nothing and gating
            # on noise; 0.0 for the strictly-positive perf metrics
            band = max(tol * abs(base), floor)
            if delta < -band - 1e-12:
                rec["verdict"] = "regression"
                rec["tolerance"] = tol
                regressions.append(name)
            elif delta > band + 1e-12:
                rec["verdict"] = "improved"
            else:
                rec["verdict"] = "ok"
        per_metric[name] = rec
    gated = [m for m in per_metric
             if per_metric[m]["verdict"] in ("ok", "improved", "regression")]
    return {
        "verdict": "regression" if regressions else "ok",
        "regressions": regressions,
        "gated_metrics": len(gated),
        "current": current.get("name"),
        "baseline": baseline.get("name"),
        "metrics": per_metric,
    }


def resolve_row(spec: str, doc: Dict) -> Optional[Dict]:
    """A row by trajectory name, or extracted fresh from a file path."""
    row = doc.get("rows", {}).get(spec)
    if row is not None:
        return {**row, "name": spec}
    if os.path.exists(spec):
        return extract_row(spec)
    # a path-like spec (results/run1/perf.json) that doesn't exist is a
    # usage error, not a missing baseline
    return None


# ---------------------------------------------------------------- selftest
def selftest() -> int:
    import io
    with tempfile.TemporaryDirectory() as tmp:
        def dump(name, obj):
            p = os.path.join(tmp, name)
            with open(p, "w") as f:
                json.dump(obj, f)
            return p

        good = dump("BENCH_r98.json", {
            "metric": "env_steps_per_sec_per_chip", "status": "ok",
            "value": 2000.0, "vs_baseline": 15.3, "unit": "env-steps/s",
            "jit_traces": {"chunk_step": 1},
            "cost": {"chunk_step": {"available": True, "fusions": 280,
                                    "mfu": 0.02, "flops": 1e9}}})
        slow = dump("BENCH_r99.json", {
            "metric": "env_steps_per_sec_per_chip", "status": "ok",
            "value": 1500.0, "vs_baseline": 11.5, "unit": "env-steps/s",
            "jit_traces": {"chunk_step": 2},
            "cost": {"chunk_step": {"available": True, "fusions": 310,
                                    "mfu": 0.014, "flops": 1e9}}})
        wrapper = dump("BENCH_r97.json", {   # driver wrapper + failed row
            "n": 5, "rc": 1,
            "parsed": {"metric": "env_steps_per_sec_per_chip",
                       "value": 0.0, "error": "backend unreachable"}})
        perf = dump("perf.json", {
            "schema_version": 1, "backend": "cpu", "run": "selftest",
            "entries": {"episode_step": {
                "available": True, "flops": 6.6e6, "bytes_accessed": 6.7e6,
                "fusions": 718, "mfu": 1e-4, "wall_s_mean": 1.3}}})
        slo = dump("slo.json", {
            "schema_version": 1, "run": "sloself", "tier": "learned",
            "deadline_ms": 5.0, "requests": 200,
            "deadline_miss_ratio": 0.05, "pad_waste": 0.2,
            "queue_wait_frac": 0.3, "burn_rate": 1.0,
            "attainment": 0.99, "arrival_rate_rps": 900.0,
            "p50_latency_ms": 1.2, "p99_latency_ms": 6.0})
        curves = dump("curves.json", {
            "schema_version": 1, "run": "curveself", "episodes": 12,
            "series": {"episode": list(range(12))}, "per_topology": {},
            "summary": {"window": 10, "final_window_return": 20.0,
                        "first_window_return": -10.0, "auc_return": 5.0,
                        "episodes_to_threshold": 8,
                        "final_window_td_abs": 0.4}})
        traj = os.path.join(tmp, "BENCH_TRAJECTORY.json")
        doc = ingest([good, slow, wrapper, perf, curves, slo], traj)
        assert set(doc["rows"]) == {"BENCH_r98", "BENCH_r99", "BENCH_r97",
                                    "perf_selftest", "curves_curveself",
                                    "slo_sloself"}, \
            doc["rows"].keys()
        assert doc["rows"]["BENCH_r97"]["status"] == "failed"
        assert doc["rows"]["perf_selftest"]["metrics"][
            "episode_step_fusions"] == 718.0
        assert doc["rows"]["curves_curveself"]["metrics"][
            "final_window_return"] == 20.0
        assert doc["rows"]["slo_sloself"]["metrics"][
            "slo_deadline_miss_ratio"] == 0.05

        # per-run ledgers live at results/<id>/<timestamp>/perf.json —
        # `--scan` must find them recursively
        nested = os.path.join(tmp, "results", "exp1", "ts1", "perf.json")
        os.makedirs(os.path.dirname(nested))
        with open(nested, "w") as f:
            json.dump({"schema_version": 1, "backend": "cpu",
                       "run": "nested",
                       "entries": {"episode_step": {
                           "available": True, "flops": 1.0,
                           "fusions": 2}}}, f)
        doc2 = ingest([], os.path.join(tmp, "t2.json"), scan=tmp)
        assert "perf_nested" in doc2["rows"], doc2["rows"].keys()

        # self-compare: identical rows must be clean
        d = diff_rows({**doc["rows"]["BENCH_r98"], "name": "BENCH_r98"},
                      {**doc["rows"]["BENCH_r98"], "name": "BENCH_r98"})
        assert d["verdict"] == "ok" and not d["regressions"], d

        # slower + more fusions + retrace growth vs the good round: every
        # gated axis must flag
        d = diff_rows({**doc["rows"]["BENCH_r99"], "name": "BENCH_r99"},
                      {**doc["rows"]["BENCH_r98"], "name": "BENCH_r98"})
        assert d["verdict"] == "regression", d
        for m in ("env_steps_per_sec", "chunk_step_fusions",
                  "chunk_step_mfu", "chunk_step_jit_traces"):
            assert m in d["regressions"], (m, d["regressions"])
        # flops unchanged and ungated
        assert d["metrics"]["chunk_step_flops"]["verdict"] \
            == "informational", d["metrics"]["chunk_step_flops"]

        # the reverse direction is an improvement, not a regression
        d = diff_rows({**doc["rows"]["BENCH_r98"], "name": "BENCH_r98"},
                      {**doc["rows"]["BENCH_r99"], "name": "BENCH_r99"})
        assert d["verdict"] == "ok" \
            and d["metrics"]["env_steps_per_sec"]["verdict"] == "improved"

        # learning-curve envelope: a run that learns less (lower final-
        # window return / AUC, slower to threshold, more residual TD)
        # regresses on every curve axis; self-compare stays clean
        crow = {**doc["rows"]["curves_curveself"], "name": "cur"}
        d = diff_rows(crow, {**doc["rows"]["curves_curveself"],
                             "name": "base"})
        assert d["verdict"] == "ok" and not d["regressions"], d
        worse = {"name": "worse", "status": "ok", "kind": "curves",
                 "metrics": {"final_window_return": 10.0, "auc_return": 3.0,
                             "episodes_to_threshold": 11.0,
                             "final_window_td_abs": 0.6, "episodes": 12.0}}
        d = diff_rows(worse, crow)
        assert d["verdict"] == "regression", d
        for m in ("final_window_return", "auc_return",
                  "episodes_to_threshold", "final_window_td_abs"):
            assert m in d["regressions"], (m, d["regressions"])
        # `episodes` carries no rule — run length is context, not a gate
        assert d["metrics"]["episodes"]["verdict"] == "informational", d
        # absolute band floor: returns oscillating around zero must not
        # gate on noise (relative band alone would be ~0.002 here)
        d = diff_rows({"name": "n1",
                       "metrics": {"final_window_return": -0.01}},
                      {"name": "n0",
                       "metrics": {"final_window_return": 0.01}})
        assert d["verdict"] == "ok", d
        # ...while a real collapse past the floor still flags
        d = diff_rows({"name": "n2",
                       "metrics": {"final_window_return": -2.5}},
                      {"name": "n0",
                       "metrics": {"final_window_return": 0.01}})
        assert d["verdict"] == "regression", d

        # serving SLO bands: a run that misses more deadlines, wastes
        # more padding, queues longer and burns budget faster regresses
        # on every slo axis; attainment collapse flags too
        srow = {**doc["rows"]["slo_sloself"], "name": "slo_base"}
        d = diff_rows(srow, srow)
        assert d["verdict"] == "ok" and not d["regressions"], d
        worse_slo = {"name": "slo_bad", "status": "ok", "kind": "slo",
                     "metrics": {"slo_deadline_miss_ratio": 0.4,
                                 "slo_pad_waste": 0.6,
                                 "slo_queue_wait_frac": 0.7,
                                 "slo_burn_rate": 4.0,
                                 "slo_attainment": 0.6}}
        d = diff_rows(worse_slo, srow)
        assert d["verdict"] == "regression", d
        for m in ("slo_deadline_miss_ratio", "slo_pad_waste",
                  "slo_queue_wait_frac", "slo_burn_rate",
                  "slo_attainment"):
            assert m in d["regressions"], (m, d["regressions"])
        # the reverse direction improves, never flags
        d = diff_rows(srow, worse_slo)
        assert d["verdict"] == "ok" and not d["regressions"], d
        # absolute floors: near-zero miss-ratio jitter is noise, not a
        # regression (relative band alone would be ~0)
        d = diff_rows({"name": "j1",
                       "metrics": {"slo_deadline_miss_ratio": 0.015}},
                      {"name": "j0",
                       "metrics": {"slo_deadline_miss_ratio": 0.0}})
        assert d["verdict"] == "ok", d
        # serve artifacts with per-leg slo blocks gate by leg
        serve_art = dump("SERVE_r96.json", {
            "metric": "serve_requests_per_sec",
            "cold_start_s": 0.5, "cache_hit_start_s": 0.2,
            "legs": {"warm": {"rps": 5000.0, "p50_ms": 1.0,
                              "p99_ms": 4.0,
                              "slo": {"deadline_miss_ratio": 0.1,
                                      "pad_waste": 0.25,
                                      "queue_wait_frac": 0.4,
                                      "burn_rate": 2.0,
                                      "attainment": 0.95,
                                      "arrival_rate_rps": 5100.0}}}})
        srow2 = extract_row(serve_art)
        assert srow2["metrics"]["warm_slo_deadline_miss_ratio"] == 0.1, \
            srow2["metrics"]
        # arrival rate stays ungated context (an `_rps` suffix would
        # wrongly ride the throughput band)
        assert not any("arrival" in m for m in srow2["metrics"]), \
            srow2["metrics"]
        worse_leg = dict(srow2, name="serve_bad",
                         metrics={**srow2["metrics"],
                                  "warm_slo_deadline_miss_ratio": 0.5})
        d = diff_rows(worse_leg, {**srow2, "name": "serve_base"})
        assert d["verdict"] == "regression" \
            and "warm_slo_deadline_miss_ratio" in d["regressions"], d

        # SCEN rounds (on-device scenario factory vs host regen): the
        # paired `_sps` rates gate under the throughput band, per-leg
        # trace counts under the 0% retrace band, the ratio + deleted
        # scenario_regen walls stay informational context
        scen = dump("SCEN_r95.json", {
            "metric": "env_steps_per_sec_per_chip", "status": "ok",
            "factory_sps": 30.0, "host_regen_sps": 24.0,
            "factory_vs_host": 1.25, "factory_scenario_regen_s": 0.02,
            "host_scenario_regen_s": 1.9,
            "jit_traces_factory": {"chunk_step": 1, "factory_sample": 1},
            "jit_traces_host_regen": {"chunk_step": 1}})
        scrow = extract_row(scen)
        assert scrow["metrics"]["factory_sps"] == 30.0 \
            and scrow["metrics"]["host_regen_sps"] == 24.0, \
            scrow["metrics"]
        assert scrow["metrics"]["factory_factory_sample_jit_traces"] \
            == 1.0, scrow["metrics"]
        d = diff_rows({**scrow, "name": "scen_self"},
                      {**scrow, "name": "scen_base"})
        assert d["verdict"] == "ok" and not d["regressions"], d
        assert d["metrics"]["factory_vs_host"]["verdict"] \
            == "informational", d["metrics"]["factory_vs_host"]
        slower_scen = dict(scrow, name="scen_slow",
                           metrics={**scrow["metrics"],
                                    "factory_sps": 20.0})
        d = diff_rows(slower_scen, {**scrow, "name": "scen_base"})
        assert d["verdict"] == "regression" \
            and "factory_sps" in d["regressions"], d

        # ASYNC flight-recorder axes: lag blow-up / actors starving on
        # the channel regress under their own bands; the absolute
        # floors absorb healthy-run jitter (lag oscillating by a
        # version, idle a few points above zero)
        arow = dump("ASYNC_r90.json", {
            "metric": "env_steps_per_sec_per_chip", "status": "ok",
            "sync_sps": 100.0, "async2_sps": 130.0,
            "learner_idle_frac": 0.02, "policy_lag_p99": 2.0,
            "actor_idle_frac": 0.05})
        abase = extract_row(arow)
        assert abase["metrics"]["policy_lag_p99"] == 2.0 \
            and abase["metrics"]["actor_idle_frac"] == 0.05, \
            abase["metrics"]
        d = diff_rows({**abase, "name": "async_self"},
                      {**abase, "name": "async_base"})
        assert d["verdict"] == "ok" and not d["regressions"], d
        jittery = dict(abase, name="async_jitter",
                       metrics={**abase["metrics"],
                                "policy_lag_p99": 3.0,
                                "actor_idle_frac": 0.11})
        d = diff_rows(jittery, {**abase, "name": "async_base"})
        assert d["verdict"] == "ok", d   # within floor-widened bands
        stale = dict(abase, name="async_stale",
                     metrics={**abase["metrics"],
                              "policy_lag_p99": 9.0,
                              "actor_idle_frac": 0.40})
        d = diff_rows(stale, {**abase, "name": "async_base"})
        assert d["verdict"] == "regression", d
        for m in ("policy_lag_p99", "actor_idle_frac"):
            assert m in d["regressions"], (m, d["regressions"])

        # ASYNC mesh rounds (r02): the per-device scaling axis gates
        # under its own 15% band, the zero-collective ingest contract
        # under 0% tolerance — ONE collective appearing on the compiled
        # dp ingest is a regression, not jitter; dp-leg trace counts
        # ride the `_jit_traces` retrace band
        mrow = dump("ASYNC_r91.json", {
            "metric": "env_steps_per_sec_per_chip", "status": "ok",
            "async2_sps": 130.0, "async_dp2_sps": 120.0,
            "async_dp2_sps_per_device": 60.0,
            "ingest_collectives": 0, "ring_shards": {"async_dp2": 2},
            "jit_traces_async_dp2": {"replay_ingest": 1}})
        mbase = extract_row(mrow)
        assert mbase["metrics"]["async_dp2_sps_per_device"] == 60.0 \
            and mbase["metrics"]["ingest_collectives"] == 0.0 \
            and mbase["metrics"]["async_dp2_replay_ingest_jit_traces"] \
            == 1.0, mbase["metrics"]
        assert mbase["context"]["ring_shards"] == {"async_dp2": 2}, \
            mbase["context"]
        d = diff_rows({**mbase, "name": "mesh_self"},
                      {**mbase, "name": "mesh_base"})
        assert d["verdict"] == "ok" and not d["regressions"], d
        leaky = dict(mbase, name="mesh_leaky",
                     metrics={**mbase["metrics"],
                              "async_dp2_sps_per_device": 40.0,
                              "ingest_collectives": 1.0})
        d = diff_rows(leaky, {**mbase, "name": "mesh_base"})
        assert d["verdict"] == "regression", d
        for m in ("async_dp2_sps_per_device", "ingest_collectives"):
            assert m in d["regressions"], (m, d["regressions"])

        # a widened tolerance declassifies a small regression
        d = diff_rows({"name": "a", "metrics": {"x_mfu": 0.9}},
                      {"name": "b", "metrics": {"x_mfu": 1.0}},
                      tolerances={"x_mfu": 0.5})
        assert d["verdict"] == "ok", d

        # failed rows never diff clean: a crashed current gates like a
        # regression, a crashed baseline like a missing one
        d = diff_rows({**doc["rows"]["BENCH_r97"], "name": "BENCH_r97"},
                      {**doc["rows"]["BENCH_r98"], "name": "BENCH_r98"})
        assert d["verdict"] == "failed-current", d
        rc = main(["diff", "BENCH_r97", "--baseline", "BENCH_r98",
                   "--trajectory", traj])
        assert rc == 1, rc
        rc = main(["diff", "BENCH_r98", "--baseline", "BENCH_r97",
                   "--trajectory", traj])
        assert rc == 3, rc

        # CLI: missing baseline is its own verdict + exit code
        rc = main(["diff", "BENCH_r98", "--baseline", "BENCH_r77",
                   "--trajectory", traj])
        assert rc == 3, rc
        rc = main(["diff", "BENCH_r99", "--baseline", "BENCH_r98",
                   "--trajectory", traj])
        assert rc == 1, rc
        rc = main(["diff", "BENCH_r98", "--baseline", "BENCH_r98",
                   "--trajectory", traj])
        assert rc == 0, rc
    print("bench_diff selftest: OK")
    return 0


# --------------------------------------------------------------------- cli
def _parse_tolerances(specs: List[str]) -> Dict[str, float]:
    out = {}
    for s in specs:
        if "=" not in s:
            raise SystemExit(f"--tolerance expects metric=frac, got {s!r}")
        k, v = s.split("=", 1)
        try:
            out[k] = float(v)
        except ValueError:
            raise SystemExit(f"--tolerance {s!r}: {v!r} is not a number")
    return out


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--selftest", action="store_true",
                    help="synthetic-artifact verdict check (CI smoke)")
    sub = ap.add_subparsers(dest="cmd")
    ing = sub.add_parser("ingest", help="normalize artifacts into the "
                                        "cumulative trajectory")
    ing.add_argument("paths", nargs="*", help="artifact files")
    ing.add_argument("--scan", default=None,
                     help="also glob BENCH_r*/MULTICHIP_r*/SERVE_r*/SCEN_r*/"
                          "perf.json/curves.json/slo.json under this "
                          "directory")
    ing.add_argument("--out", default="BENCH_TRAJECTORY.json")
    dif = sub.add_parser("diff", help="current vs named baseline, exit "
                                      "nonzero on regression")
    dif.add_argument("current", help="trajectory row name or artifact path")
    dif.add_argument("--baseline", required=True,
                     help="trajectory row name (or artifact path)")
    dif.add_argument("--trajectory", default="BENCH_TRAJECTORY.json")
    dif.add_argument("--tolerance", action="append", default=[],
                     metavar="METRIC=FRAC",
                     help="override a metric's relative band "
                          "(repeatable), e.g. --tolerance mfu=0.3")
    dif.add_argument("--json", action="store_true",
                     help="emit the full diff as JSON")
    args = ap.parse_args(argv)

    if args.selftest:
        return selftest()
    if args.cmd == "ingest":
        if not args.paths and not args.scan:
            ing.error("give artifact paths and/or --scan DIR")
        ingest(args.paths, args.out, scan=args.scan)
        return 0
    if args.cmd == "diff":
        doc = load_trajectory(args.trajectory)
        current = resolve_row(args.current, doc)
        if current is None:
            print(f"bench_diff: current {args.current!r} is neither a "
                  f"trajectory row nor a readable artifact",
                  file=sys.stderr)
            return 2
        baseline = resolve_row(args.baseline, doc)
        if baseline is None:
            print(json.dumps({"verdict": "missing-baseline",
                              "baseline": args.baseline,
                              "known_rows": sorted(doc.get("rows", {}))}))
            return 3
        d = diff_rows(current, baseline,
                      _parse_tolerances(args.tolerance))
        if args.json:
            print(json.dumps(d, indent=1))
        else:
            print(f"bench_diff: {d['current']} vs baseline "
                  f"{d['baseline']}: {d['verdict'].upper()} "
                  f"({d['gated_metrics']} gated metric(s))")
            for name, rec in d["metrics"].items():
                if rec["verdict"] in ("regression", "improved"):
                    print(f"  {rec['verdict']:>11}  {name}: "
                          f"{rec['baseline']} -> {rec['current']} "
                          f"({rec.get('change_pct', '?')}%)")
        # failed-current gates like a regression (a crashed round must
        # not pass); failed-baseline like missing-baseline (nothing to
        # regress against)
        return {"regression": 1, "failed-current": 1,
                "failed-baseline": 3}.get(d["verdict"], 0)
    ap.error("give a subcommand (ingest | diff) or --selftest")
    return 2


if __name__ == "__main__":
    sys.exit(main())
