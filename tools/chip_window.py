"""Round-5 chip-window runner: wait for the axon tunnel, then execute
the prioritized measurement queue the moment it answers.

Complements ``tpu_validate.py`` (the round-4 validation queue, already
banked this round): this is the ROUND-5 plan — lever sweep toward the
20x bar first, then the sustained-learning exhibit, the on-chip MFU
table, and the remaining skipped validation stages.  Stages reuse
tpu_validate's bounded-subprocess framework (a faulted stage cannot
wedge the parent; results bank incrementally to CHIP_WINDOW.json, and
after any failed stage the backend is re-probed before spending the
next stage's timeout).

    python tools/chip_window.py               # wait + run
    python tools/chip_window.py --no-wait     # probe once, run or exit
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from tpu_validate import _probe, run_queue  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# banked separately from TPU_VALIDATION.json (the round-4 artifact this
# round already banked)
OUT = os.path.join(REPO, "CHIP_WINDOW.json")


def stages(py):
    t = os.path.join(REPO, "tools")
    return [
        # 1. the 20x push: measure the lever grid (scan_unroll x
        #    max_flows x B); winner feeds bench knobs
        ("lever_sweep", [py, os.path.join(t, "lever_sweep.py")], 3000),
        # 2. sustained learning at the throughput config (the r4 queue's
        #    failed stage): wall rate vs device rate + learning exhibit
        ("learning", [py, os.path.join(t, "learning_curve.py"),
                      "--replicas", "256", "--episodes", "12"], 3000),
        # 3. on-chip MFU/roofline (refines the static table in
        #    BENCH_NOTES)
        ("mfu", [py, os.path.join(t, "profile_substep.py"), "--mfu",
                 "--replicas", "64", "256", "512"], 1800),
        # 4. remaining r4 validation stages skipped on the wedged chip
        ("gnn_bench", [py, os.path.join(t, "gnn_bench.py")], 900),
        ("rung5", [py, os.path.join(REPO, "bench.py"), "--worker",
                   "32", "10", "1", "rung5"], 2400),
        # 5. on-chip anchor scoring (fast; non-learned rows only — the
        #    learned row rides the CPU checkpoint table)
        ("anchors", [py, os.path.join(t, "quality_anchor.py"),
                     "--replicas", "64", "--episodes", "2"], 1800),
    ]


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--no-wait", action="store_true")
    ap.add_argument("--poll-s", type=int, default=420)
    ap.add_argument("--max-wait-s", type=int, default=6 * 3600)
    args = ap.parse_args()
    py = sys.executable

    t0 = time.time()
    while not _probe(py):
        if args.no_wait or time.time() - t0 > args.max_wait_s:
            print("tunnel never answered", file=sys.stderr)
            sys.exit(1)
        print(f"[wait] tunnel down {round(time.time() - t0)}s; "
              f"next probe in {args.poll_s}s", file=sys.stderr)
        time.sleep(args.poll_s)
    print(f"[wait] tunnel UP after {round(time.time() - t0)}s — running "
          f"the round-5 queue", file=sys.stderr)

    results = {}
    run_queue(stages(py), results, out_path=OUT, py=py)
    print(json.dumps({k: v.get("ok") for k, v in results.items()}))


if __name__ == "__main__":
    main()
