"""SCEN bench: host-regen vs on-device scenario factory at equal B.

The factory's throughput claim, measured instead of asserted: two
fresh-subprocess legs run the SAME replica-parallel training shape
(equal B, equal episode_steps/chunk, per-episode scenario regeneration)
and differ ONLY in where the scenario pipeline runs:

- ``host_regen``: the PR 9 registry path with HOST traffic production —
  a K=4 ``--topo-mix``-style mixture whose per-replica
  ``TrafficSchedule`` is rebuilt in Python and shipped host->device
  every episode (``mix_traffic_host``), the cost the ``scenario_regen``
  phase makes visible;
- ``factory``: the on-device factory — one jitted ``factory_sample``
  call per episode draws fresh per-replica (topology, traffic, fault
  plan) tensors; the ``scenario_regen`` phase collapses to
  dispatch-enqueue time.

Banked as ``SCEN_r01.json`` (``--bank``): paired ``factory_sps`` /
``host_regen_sps`` rates (gated by tools/bench_diff.py under the 15%
``_sps`` band once ingested), per-leg ``scenario_regen`` walls, per-leg
dispatch trace counts (0%-band ``_jit_traces`` keys), and the
``factory_ge_host`` verdict the bank refuses to write green when the
claim fails.  The scenario DISTRIBUTIONS necessarily differ (a fixed
4-member mixture vs the sampled families) — the comparison is the
scenario-production pipeline at equal dispatch shape, not sim physics.

Usage:
    JAX_PLATFORMS=cpu python tools/scenario_bench.py --bank
    JAX_PLATFORMS=cpu python tools/scenario_bench.py --worker factory
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

B = 8
EPISODE_STEPS = 10
CHUNK = 5
MEASURE_EPISODES = 3
MAX_NODES, MAX_EDGES = 12, 16
HOST_MIX = "star6,ring6,line6,random8:3"
FACTORY_MIX = "factory:star-ring-line-random+shapes~faults"
LEG_TIMEOUT_S = 900


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def worker(leg: str) -> int:
    """One leg, printed as a JSON line (the bank parses the last line)."""
    _configure_jax()
    import jax
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from gsc_tpu.analysis.sentinels import CompileMonitor
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.utils.telemetry import PhaseTimer

    env, agent, _, _ = ge._flagship(
        max_nodes=MAX_NODES, max_edges=MAX_EDGES,
        episode_steps=EPISODE_STEPS, max_flows=64, gen_traffic=False)
    monitor = CompileMonitor().start()
    timer = PhaseTimer()
    base = jax.random.PRNGKey(0)

    if leg == "factory":
        from gsc_tpu.topology.factory import ScenarioFactory, parse_factory
        factory = ScenarioFactory(
            parse_factory(FACTORY_MIX), env.sim_cfg, env.service,
            EPISODE_STEPS, max_nodes=MAX_NODES, max_edges=MAX_EDGES)
        probs = jnp.full((factory.spec.num_families,),
                         1.0 / factory.spec.num_families)

        def episode_scenario(ep):
            return factory.sample_batch(
                jax.random.fold_in(base, 2000 + ep), probs, B)
    elif leg == "host_regen":
        from gsc_tpu.topology import DEFAULT_REGISTRY, TopologyBucket
        from gsc_tpu.topology.scenarios import (build_mix_entries,
                                                mix_traffic_host, plan_mix)
        bucket = TopologyBucket(MAX_NODES, MAX_EDGES)
        entries = build_mix_entries(HOST_MIX, DEFAULT_REGISTRY, bucket,
                                    dt=env.sim_cfg.dt)
        plan = plan_mix(entries, B, bucket, env.sim_cfg, EPISODE_STEPS)

        def episode_scenario(ep):
            # the PR 9 host production path: per-replica Python traffic
            # generation + the host->device ship, every episode
            traffic = mix_traffic_host(
                plan, env.sim_cfg, env.service, EPISODE_STEPS,
                seed_for=lambda r: 1000 * ep + r)
            return plan.topo, jax.device_put(traffic)
    else:
        raise SystemExit(f"unknown leg {leg!r}")

    pddpg = ParallelDDPG(env, agent, num_replicas=B, donate=True,
                         per_replica_topology=True)
    chunks = EPISODE_STEPS // CHUNK

    def run_episode(ep, state, buffers):
        with timer.phase("scenario_regen"):
            topo, traffic = episode_scenario(ep)
        env_states, obs = pddpg.reset_all(
            jax.random.fold_in(base, ep), topo, traffic)
        with timer.phase("dispatch"):
            for c in range(chunks):
                start = jnp.int32(ep * EPISODE_STEPS + c * CHUNK)
                state, buffers, env_states, obs, stats, _ = \
                    pddpg.chunk_step(state, buffers, env_states, obs,
                                     topo, traffic, start, CHUNK,
                                     learn=(c == chunks - 1))
        return state, buffers, stats

    # warmup episode 0: compiles + the agent's random-action start
    topo0, traffic0 = episode_scenario(0)
    env_states, obs = pddpg.reset_all(base, topo0, traffic0)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    t_warm = time.time()
    state, buffers, stats = run_episode(0, state, buffers)
    jax.block_until_ready(stats)
    warm_s = time.time() - t_warm
    # measured window: fresh timer so warmup compiles/regen don't ride
    timer = PhaseTimer()
    t0 = time.time()
    for ep in range(1, MEASURE_EPISODES + 1):
        state, buffers, stats = run_episode(ep, state, buffers)
    jax.block_until_ready(stats)
    wall = time.time() - t0
    sps = MEASURE_EPISODES * EPISODE_STEPS * B / wall
    phases = timer.summary()
    print(json.dumps({
        "leg": leg, "status": "ok", "sps": round(sps, 2),
        "episodes_measured": MEASURE_EPISODES, "replicas": B,
        "chunk": CHUNK, "episode_steps": EPISODE_STEPS,
        "measure_wall_s": round(wall, 2),
        "warmup_s": round(warm_s, 2),
        "scenario_regen_s": (phases.get("scenario_regen")
                             or {}).get("total_s", 0.0),
        "phases": phases,
        "jit_traces": {fn: t for fn, (t, _c)
                       in monitor.snapshot().items() if t and fn in
                       ("chunk_step", "reset_all", "factory_sample")},
        "final_return": round(float(stats["episodic_return"]), 4),
    }), flush=True)
    return 0


def _run_leg(leg: str) -> dict:
    """Fresh subprocess per leg (the 1-core box must never run two jax
    programs concurrently; a fresh process also keeps the legs'
    trace-count accounting independent)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", leg]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=LEG_TIMEOUT_S, env=env)
    except subprocess.TimeoutExpired:
        return {"leg": leg, "status": "failed",
                "reason": f"timeout after {LEG_TIMEOUT_S}s"}
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    for line in reversed(lines):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and row.get("leg") == leg:
            row["leg_wall_s"] = round(time.time() - t0, 1)
            return row
    return {"leg": leg, "status": "failed",
            "reason": f"rc={out.returncode}, no parseable row",
            "tail": (out.stdout + out.stderr)[-2000:]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", default=None,
                    help="run one leg in-process (factory|host_regen)")
    ap.add_argument("--bank", action="store_true",
                    help="write SCEN_r01.json next to the repo root")
    ap.add_argument("--out", default=None,
                    help="bank path (default <repo>/SCEN_r01.json)")
    args = ap.parse_args(argv)
    if args.worker:
        return worker(args.worker)

    legs = {leg: _run_leg(leg) for leg in ("host_regen", "factory")}
    ok = all(l.get("status") == "ok" for l in legs.values())
    doc = {
        "metric": "env_steps_per_sec_per_chip",
        "unit": "env-steps/s", "round": 1, "platform": "cpu",
        "status": "ok" if ok else "failed",
        "replicas": B, "chunk": CHUNK, "episode_steps": EPISODE_STEPS,
        "episodes_measured": MEASURE_EPISODES,
        "host_mix": HOST_MIX, "factory_mix": FACTORY_MIX,
        "legs": [legs["host_regen"], legs["factory"]],
    }
    if ok:
        f, h = legs["factory"], legs["host_regen"]
        doc.update({
            "factory_sps": f["sps"], "host_regen_sps": h["sps"],
            "factory_vs_host": round(f["sps"] / h["sps"], 3),
            "factory_scenario_regen_s": f["scenario_regen_s"],
            "host_scenario_regen_s": h["scenario_regen_s"],
            "jit_traces_factory": f["jit_traces"],
            "jit_traces_host_regen": h["jit_traces"],
            "factory_ge_host": f["sps"] >= h["sps"],
            "note": (
                "Equal-B comparison on the 1-core CPU box (fresh "
                "subprocess per leg, warm persistent compile cache, "
                f"warmup episode excluded): replacing per-episode HOST "
                f"scenario production (K=4 registry mixture, per-replica "
                f"Python traffic + host->device ship) with the jitted "
                f"on-device factory draw moves the scenario_regen wall "
                f"from {h['scenario_regen_s']}s to "
                f"{f['scenario_regen_s']}s over "
                f"{MEASURE_EPISODES} episodes and the env-steps/s from "
                f"{h['sps']} to {f['sps']}.  Distributions necessarily "
                "differ (fixed mixture vs sampled families) — the "
                "comparison is the scenario pipeline at equal dispatch "
                "shape."),
        })
        try:
            import jax
            doc["jax"] = jax.__version__
        except Exception:
            pass
    claim_holds = ok and doc.get("factory_ge_host", False)
    if ok and not claim_holds:
        # a round whose factory leg LOSES must never read as a healthy
        # row: mark it failed (bench_diff's failed-current discipline)
        doc["status"] = "failed"
        doc["reason"] = ("factory_sps < host_regen_sps — the round does "
                         "not support the throughput claim")
    print(json.dumps(doc, indent=1))
    if args.bank or args.out:
        out = args.out or os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), "SCEN_r01.json")
        if not claim_holds:
            # never overwrite a previously banked GREEN artifact with a
            # losing/failed round — park the evidence next to it (the
            # SCEN_r*.json scan still ingests it as a failed row)
            out = os.path.splitext(out)[0] + ".failed.json"
        with open(out, "w") as fobj:
            json.dump(doc, fobj, indent=1)
            fobj.write("\n")
        print(f"[scenario_bench] banked {out}")
        if not claim_holds:
            print("[scenario_bench] FAIL: "
                  f"{doc.get('reason', 'leg failure')}")
            return 1
    return 0 if claim_holds else 1


if __name__ == "__main__":
    sys.exit(main())
