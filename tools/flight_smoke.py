"""Flight-recorder smoke: the async fleet's black-box layer end to end
through the real CLI.

The CI-stage proof that the PR-17 observability actually lands on a real
``cli train --async`` run plus a deliberately wedged fleet:

- a tiny 3-episode, 2-replica, 2-actor CPU train run with the series
  recorder on must exit 0 and leave a schema-versioned ``series.json``
  whose rings are non-trivial (>= 3 metrics, including the async verdict
  series) and whose LAST points agree with the final ``metrics.json``
  snapshot — history never drifts from the gauges,
- the same run's event stream must reconstruct a STRICT-validator-clean
  Chrome trace with one track per actor (rollout/put spans), the
  channel's put→pop residency slices, learner ingest/learn-burst spans
  and BALANCED publish→adopt flow arrows,
- an injected wedge (one fleet thread registered with the watchdog and
  never beating again, stuck in ``blocked_put``) must produce a stall
  event NAMING that thread and phase, then escalate into a
  ``blackbox.json`` post-mortem carrying the series tail and the
  thread-phase picture,
- gate through ``bench_diff``: an ASYNC-shaped row with the new
  ``policy_lag_p99`` / ``actor_idle_frac`` fields self-compares clean
  (rc 0) while an injected staleness blow-up is caught (rc 1).

Run by ``tools/ci_check.sh`` after the async stage; standalone:

    JAX_PLATFORMS=cpu python tools/flight_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import time

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

EPISODES = 3
ACTORS = 2
SERIES_WINDOW = 256
# the wedge stage's per-thread heartbeat budget (escalation fires at
# budget * (1 + escalate_after) of silence; the poll floor is 0.25s)
WEDGE_BUDGET_S = 0.05


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"flight smoke: FAIL — {msg}")
    return 1


def _check_series(rdir: str):
    """series.json: schema-versioned, non-trivial, last points == the
    final metrics.json gauges.  Returns (error, n_series, n_matched)."""
    from gsc_tpu.obs import SERIES_SCHEMA_VERSION
    spath = os.path.join(rdir, "series.json")
    if not os.path.exists(spath):
        return "series.json missing from the run dir", 0, 0
    doc = json.load(open(spath))
    if doc.get("schema_version") != SERIES_SCHEMA_VERSION:
        return f"series.json schema_version {doc.get('schema_version')}", 0, 0
    series = doc.get("series") or {}
    if len(series) < 3:
        return f"series.json holds {len(series)} rings (want >= 3)", 0, 0
    for want in ("gsc_sps{", "gsc_learner_idle_frac{",
                 "gsc_actor_idle_frac{"):
        if not any(k.startswith(want) for k in series):
            return f"series.json missing the {want}... ring", 0, 0
    snap = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    matched = 0
    for name, pts in series.items():
        if any(a[0] > b[0] for a, b in zip(pts, pts[1:])):
            return f"ring {name} timestamps not monotone", 0, 0
        if name in snap:
            if abs(float(snap[name]) - float(pts[-1][1])) > 1e-9:
                return (f"ring {name} last point {pts[-1][1]} != "
                        f"snapshot {snap[name]}"), 0, 0
            matched += 1
    if matched < 3:
        return (f"only {matched} rings intersect metrics.json "
                "(want >= 3)"), 0, 0
    return None, len(series), matched


def _check_trace(rdir: str):
    """Strict-validator-clean async trace with per-actor tracks and
    balanced flow arrows.  Returns (error, n_trace_events)."""
    from gsc_tpu.obs.trace import (ACTOR_TRACK_BASE, TRACE_TRACKS,
                                   build_trace, read_events,
                                   validate_trace)
    events = read_events(os.path.join(rdir, "events.jsonl"))
    kinds = {e.get("event") for e in events}
    if not {"async_actor_ep", "async_learner_spans"} <= kinds:
        return f"flight-ledger events missing from the stream: {kinds}", 0
    trace = build_trace(events)
    errors = validate_trace(trace)
    if errors:
        return f"trace validator: {errors[:3]} (+{len(errors) - 3})" \
            if len(errors) > 3 else f"trace validator: {errors}", 0
    tev = trace["traceEvents"]
    names = {e["args"]["name"] for e in tev
             if e["ph"] == "M" and e["name"] == "thread_name"}
    want_tracks = {f"actor{a}" for a in range(ACTORS)}
    if not want_tracks <= names:
        return f"actor tracks {want_tracks} not announced (got {names})", 0
    rollout_tids = {e["tid"] for e in tev if e["ph"] == "X"
                    and e["name"].startswith("rollout ep")}
    if rollout_tids != {ACTOR_TRACK_BASE + a for a in range(ACTORS)}:
        return f"rollout spans on tracks {rollout_tids}", 0
    if not any(e["ph"] == "X" and e["name"].startswith("block s")
               and e["tid"] == TRACE_TRACKS["channel"] for e in tev):
        return "no channel residency slices", 0
    ltid = TRACE_TRACKS["learner"]
    for name in ("replay_ingest", "learn_burst"):
        if not any(e["ph"] == "X" and e["name"].startswith(name)
                   and e["tid"] == ltid for e in tev):
            return f"no {name} spans on the learner track", 0
    for flow in ("chan", "publish v"):
        n_s = sum(1 for e in tev
                  if e["ph"] == "s" and e["name"].startswith(flow))
        n_f = sum(1 for e in tev
                  if e["ph"] == "f" and e["name"].startswith(flow))
        if n_s != n_f:
            return f"{flow!r} flows unbalanced: {n_s} starts/{n_f} ends", 0
    return None, len(tev)


def _check_wedge(tmp: str):
    """Injected wedge: a watched fleet thread that never beats again must
    stall BY NAME and escalate into the black-box dump."""
    from gsc_tpu.obs import BLACKBOX_SCHEMA_VERSION, RunObserver
    obs = RunObserver(os.path.join(tmp, "wedge"), run_id="wedge",
                      series_window=32, watchdog_budget_s=WEDGE_BUDGET_S,
                      watchdog_escalate=1, compile_events=False)
    obs.start(meta={"stage": "flight_smoke_wedge"})
    obs.hub.series("policy_lag", 2.0)
    obs.resume_watchdog()
    obs.watch_fleet(["actor0", "actor1", "learner"],
                    budget_s=WEDGE_BUDGET_S)
    obs.hub.note_thread_phase("actor0", "dispatch")
    obs.hub.note_thread_phase("actor1", "blocked_put")
    deadline = time.time() + 10.0
    while time.time() < deadline \
            and not os.path.exists(obs.blackbox_path):
        # healthy threads (and the main loop) keep beating; actor1 never
        # beats again — the wedge under test
        obs.hub.beat("episode")
        obs.hub.beat("actor0")
        obs.hub.beat("learner")
        time.sleep(0.02)
    obs.close()
    if not os.path.exists(obs.blackbox_path):
        return "wedged actor never escalated into blackbox.json"
    doc = json.load(open(obs.blackbox_path))
    if doc.get("schema_version") != BLACKBOX_SCHEMA_VERSION:
        return f"blackbox schema_version {doc.get('schema_version')}"
    if doc.get("reason") != "watchdog_escalation:actor1":
        return f"blackbox reason {doc.get('reason')!r}"
    if doc.get("thread_phases", {}).get("actor1") != "blocked_put":
        return f"blackbox thread_phases {doc.get('thread_phases')}"
    if not any(k.startswith("gsc_policy_lag") for k in doc.get("series", {})):
        return "blackbox series tail missing the policy_lag ring"
    events = [json.loads(line) for line in open(obs.events_path)]
    stalls = [e for e in events if e.get("event") == "stall"
              and e.get("thread") == "actor1"]
    if not stalls:
        return "no stall event naming the wedged actor"
    if stalls[0].get("last_phase") != "blocked_put":
        return f"stall last_phase {stalls[0].get('last_phase')!r}"
    return None


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    tmp = tempfile.mkdtemp(prefix="gsc_flight_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(EPISODES), "--replicas", "2",
        "--chunk", "3", "--async", "--async-actors", str(ACTORS),
        "--obs-series-window", str(SERIES_WINDOW), "--no-perf",
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --async")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    err, n_series, n_matched = _check_series(rdir)
    if err:
        return fail(err)
    err, n_trace = _check_trace(rdir)
    if err:
        return fail(err)
    err = _check_wedge(tmp)
    if err:
        return fail(err)

    # bench_diff gate over the ASYNC row's new staleness/idle fields:
    # self-compare clean, injected policy-lag blow-up caught
    import bench_diff
    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    info = [e for e in events if e.get("event") == "async_train"][-1]
    row = {"metric": "env_steps_per_sec_per_chip", "status": "ok",
           "async_actors": ACTORS, "sync_sps": 100.0, "async2_sps": 100.0,
           "learner_idle_frac": round(float(info["learner_idle_frac"]), 4),
           "policy_lag_p99": float(info["policy_lag_p99"]),
           "actor_idle_frac": round(float(info["actor_idle_frac"]), 4)}
    row_path = os.path.join(tmp, "ASYNC_r98.json")
    with open(row_path, "w") as f:
        json.dump(row, f)
    traj = os.path.join(tmp, "traj.json")
    doc = bench_diff.ingest([row_path], traj)
    got = doc["rows"]["ASYNC_r98"]["metrics"]
    if "policy_lag_p99" not in got or "actor_idle_frac" not in got:
        return fail(f"ASYNC row missing flight metrics: {sorted(got)}")
    rc = bench_diff.main(["diff", "ASYNC_r98", "--baseline", "ASYNC_r98",
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"ASYNC self-compare rc={rc} (want 0)")
    bad = dict(row, policy_lag_p99=float(info["policy_lag_p99"]) + 50.0)
    bad_path = os.path.join(tmp, "ASYNC_bad.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", "ASYNC_r98",
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected policy-lag blow-up rc={rc} (want 1)")

    print(f"flight smoke: OK — {n_series} series rings ({n_matched} "
          f"snapshot-matched), validator-clean async trace "
          f"({n_trace} events), wedged actor1 escalated into "
          "blackbox.json, ASYNC flight fields gated both directions")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
