"""Chaos smoke: a tiny fault-injected train run must self-heal to rc=0.

The CI-stage proof that the resilience subsystem's recovery paths actually
execute: a 4-episode CPU training run with an injected prefetcher death
AND a NaN-poisoned episode (``GSC_FAULT_PLAN``-style plan passed via
``--fault-plan``) must

- exit 0 with a finite final learner state (state_finite == 1 on the last
  drained episode event),
- leave matching structured ``recovery`` events in the run's
  ``events.jsonl`` (site=prefetcher/action=restart and
  site=learner_state/action=rollback),
- end the stream with ``run_end status=ok``.

Run by ``tools/ci_check.sh`` after the lint/report stages; standalone:

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

# NaN early so a post-rollback episode still drains (and proves finite)
# before the run ends; the prefetcher death hits the last staged episode
PLAN = "nan_grads@1;prefetch_die@3"
EXPECTED = {("prefetcher", "restart"), ("learner_state", "rollback")}


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def write_tiny_configs(cfg: str):
    """Smallest trainable scenario (mirrors the test suite's tiny-config
    shape): 3-node triangle, 3-step episodes, 8-wide nets."""
    import yaml

    from gsc_tpu.topology.synthetic import triangle, write_graphml

    os.makedirs(cfg, exist_ok=True)
    write_graphml(triangle(), os.path.join(cfg, "tri.graphml"))
    dump = lambda name, obj: yaml.safe_dump(
        obj, open(os.path.join(cfg, name), "w"))
    dump("svc.yaml", {
        "sfc_list": {"sfc_1": ["a", "b", "c"]},
        "sf_list": {n: {"processing_delay_mean": 5.0,
                        "processing_delay_stdev": 0.0} for n in "abc"}})
    dump("sim.yaml", {
        "inter_arrival_mean": 10.0, "deterministic_arrival": True,
        "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
        "flow_size_shape": 0.001, "deterministic_size": True,
        "run_duration": 100, "ttl_choices": [100], "max_flows": 32})
    dump("agent.yaml", {
        "graph_mode": True, "episode_steps": 3, "objective": "prio-flow",
        "GNN_features": 4, "GNN_num_layers": 1, "GNN_num_iter": 1,
        "actor_hidden_layer_nodes": [8], "critic_hidden_layer_nodes": [8],
        "mem_limit": 32, "batch_size": 4, "nb_steps_warmup_critic": 3})
    dump("sched.yaml", {
        "training_network_files": [os.path.join(cfg, "tri.graphml")],
        "inference_network": os.path.join(cfg, "tri.graphml")})
    return [os.path.join(cfg, "agent.yaml"), os.path.join(cfg, "sim.yaml"),
            os.path.join(cfg, "svc.yaml"), os.path.join(cfg, "sched.yaml"),
            "--max-nodes", "8", "--max-edges", "8", "--quiet"]


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    tmp = tempfile.mkdtemp(prefix="gsc_chaos_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "4",
        "--result-dir", os.path.join(tmp, "res"),
        "--fault-plan", PLAN])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        print(f"chaos smoke: FAIL — train rc={r.exit_code} under plan "
              f"{PLAN!r}")
        return 1
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]
    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    seen = {(e.get("site"), e.get("action"))
            for e in events if e["event"] == "recovery"}
    missing = EXPECTED - seen
    if missing:
        print(f"chaos smoke: FAIL — recovery events missing {missing}; "
              f"saw {seen}")
        return 1
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        print(f"chaos smoke: FAIL — stream tail {end}")
        return 1
    episodes = [e for e in events if e["event"] == "episode"]
    # the LAST drained episode ran on the rolled-back (finite) state
    if not episodes or float(episodes[-1].get("state_finite", 0)) != 1.0:
        print("chaos smoke: FAIL — final drained episode not finite: "
              f"{episodes[-1] if episodes else None}")
        return 1
    print(f"chaos smoke: OK — survived {PLAN!r} "
          f"({sorted(seen)} recoveries, run_end status=ok)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
