"""Chaos smoke: tiny fault-injected train runs must self-heal to rc=0.

The CI-stage proof that the resilience subsystem's recovery paths actually
execute, in two legs:

**Serial leg** — a 4-episode CPU training run with an injected prefetcher
death AND a NaN-poisoned episode (``GSC_FAULT_PLAN``-style plan passed
via ``--fault-plan``) must

- exit 0 with a finite final learner state (state_finite == 1 on the last
  drained episode event),
- leave matching structured ``recovery`` events in the run's
  ``events.jsonl`` (site=prefetcher/action=restart and
  site=learner_state/action=rollback),
- end the stream with ``run_end status=ok``.

**Async leg** — a fresh-subprocess real-CLI ``train --async`` run under
``actor_die@a0:1;ring_poison@2;learner_transient@3`` must

- exit 0 with one matching ``recovery`` event per fired fleet site
  (actor/restart, replay/quarantine, learner/retry),
- carry the drain proof in its ``async_train`` event (produced ==
  ingested, transitions_lost == 0 — the poisoned block was dropped, not
  lost, and counted),
- adopt zero poisoned versions (no publish skip, no non-finite episode),
- leave no ``fault_plan_unfired`` entries.

Run by ``tools/ci_check.sh`` after the lint/report stages; standalone:

    JAX_PLATFORMS=cpu python tools/chaos_smoke.py

``--round OUT.json`` additionally banks a CHAOS_r* bench row: a
fault-free async control leg vs the chaos leg WITH a mid-run SIGTERM +
``--resume auto`` continuation — chaos_sps/control_sps land in
bench_diff's shared 15% ``_sps`` band, recoveries_total/actor_restarts
ride along as informational keys.
"""
from __future__ import annotations

import json
import os
import signal
import subprocess
import sys
import tempfile
import time

# runnable from any cwd: the repo root is this file's parent's parent
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# NaN early so a post-rollback episode still drains (and proves finite)
# before the run ends; the prefetcher death hits the last staged episode
PLAN = "nan_grads@1;prefetch_die@3"
EXPECTED = {("prefetcher", "restart"), ("learner_state", "rollback")}

# the async fleet ladder: an actor death (restart), a poisoned replay
# block (quarantine) and a transient learn-burst dispatch (retry).  ONE
# actor thread so episode 1 is actor 0's (round-robin assignment keys
# actor_die@a0:<ep> to episodes that actor actually claims).
ASYNC_PLAN = "actor_die@a0:1;ring_poison@2;learner_transient@3"
ASYNC_EXPECTED = {("actor", "restart"), ("replay", "quarantine"),
                  ("learner", "retry")}


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def write_tiny_configs(cfg: str):
    """Smallest trainable scenario (mirrors the test suite's tiny-config
    shape): 3-node triangle, 3-step episodes, 8-wide nets."""
    import yaml

    from gsc_tpu.topology.synthetic import triangle, write_graphml

    os.makedirs(cfg, exist_ok=True)
    write_graphml(triangle(), os.path.join(cfg, "tri.graphml"))
    dump = lambda name, obj: yaml.safe_dump(
        obj, open(os.path.join(cfg, name), "w"))
    dump("svc.yaml", {
        "sfc_list": {"sfc_1": ["a", "b", "c"]},
        "sf_list": {n: {"processing_delay_mean": 5.0,
                        "processing_delay_stdev": 0.0} for n in "abc"}})
    dump("sim.yaml", {
        "inter_arrival_mean": 10.0, "deterministic_arrival": True,
        "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
        "flow_size_shape": 0.001, "deterministic_size": True,
        "run_duration": 100, "ttl_choices": [100], "max_flows": 32})
    dump("agent.yaml", {
        "graph_mode": True, "episode_steps": 3, "objective": "prio-flow",
        "GNN_features": 4, "GNN_num_layers": 1, "GNN_num_iter": 1,
        "actor_hidden_layer_nodes": [8], "critic_hidden_layer_nodes": [8],
        "mem_limit": 32, "batch_size": 4, "nb_steps_warmup_critic": 3})
    dump("sched.yaml", {
        "training_network_files": [os.path.join(cfg, "tri.graphml")],
        "inference_network": os.path.join(cfg, "tri.graphml")})
    return [os.path.join(cfg, "agent.yaml"), os.path.join(cfg, "sim.yaml"),
            os.path.join(cfg, "svc.yaml"), os.path.join(cfg, "sched.yaml"),
            "--max-nodes", "8", "--max-edges", "8", "--quiet"]


def _cli_env() -> dict:
    """Fresh-subprocess environment: CPU jax + the repo-shared persistent
    compile cache (the subprocess's compiles are disk hits)."""
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"),
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1",
               JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="-1")
    return env


def _async_argv(args, episodes: int, res: str, plan=None, resume=False):
    argv = [sys.executable, "-m", "gsc_tpu.cli", "train", *args,
            "--episodes", str(episodes), "--replicas", "2", "--async",
            "--async-actors", "1", "--chunk", "3", "--result-dir", res]
    if plan:
        argv += ["--fault-plan", plan]
    if resume:
        argv += ["--resume", "auto"]
    return argv


def _read_events(rdir: str):
    return [json.loads(line)
            for line in open(os.path.join(rdir, "events.jsonl"))]


def _find_events_file(res_root: str):
    for root, _, files in os.walk(res_root):
        if "events.jsonl" in files:
            return os.path.join(root, "events.jsonl")
    return None


def _check_async_events(events, expect_sites=ASYNC_EXPECTED,
                        quarantined: int = 1, restarts: int = 1):
    """Shared assertions over one async chaos run's event stream; returns
    an error string or None."""
    seen = {(e.get("site"), e.get("action"))
            for e in events if e["event"] == "recovery"}
    missing = expect_sites - seen
    if missing:
        return f"recovery events missing {missing}; saw {seen}"
    at = [e for e in events if e["event"] == "async_train"]
    if not at:
        return "no async_train summary event"
    info = at[-1]
    # the drain proof: the quarantined block was dropped AND counted —
    # nothing produced went missing
    if info.get("produced_steps") != info.get("ingested_steps") \
            or info.get("transitions_lost") != 0:
        return (f"drain accounting broken: produced="
                f"{info.get('produced_steps')} ingested="
                f"{info.get('ingested_steps')} lost="
                f"{info.get('transitions_lost')}")
    if info.get("blocks_quarantined") != quarantined:
        return (f"expected {quarantined} quarantined block(s), got "
                f"{info.get('blocks_quarantined')}")
    if info.get("actor_restarts") != restarts:
        return (f"expected {restarts} actor restart(s), got "
                f"{info.get('actor_restarts')}")
    # zero poisoned versions adopted: nothing non-finite ever reached a
    # publish (no skip event) and no drained episode acted on a
    # non-finite state
    if any(e["event"] == "weight_publish_skipped" for e in events):
        return "a non-finite publish was attempted"
    bad = [e for e in events if e["event"] == "episode"
           and e.get("state_finite") not in (None, True, 1, 1.0)]
    if bad:
        return f"non-finite drained episode(s): {bad[:2]}"
    if any(e["event"] == "fault_plan_unfired" for e in events):
        return "fault plan entries never fired (mis-keyed plan)"
    return None


def serial_leg(tmp: str) -> int:
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "4",
        "--result-dir", os.path.join(tmp, "res"),
        "--fault-plan", PLAN])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        print(f"chaos smoke: FAIL — train rc={r.exit_code} under plan "
              f"{PLAN!r}")
        return 1
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]
    events = _read_events(rdir)
    seen = {(e.get("site"), e.get("action"))
            for e in events if e["event"] == "recovery"}
    missing = EXPECTED - seen
    if missing:
        print(f"chaos smoke: FAIL — recovery events missing {missing}; "
              f"saw {seen}")
        return 1
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        print(f"chaos smoke: FAIL — stream tail {end}")
        return 1
    episodes = [e for e in events if e["event"] == "episode"]
    # the LAST drained episode ran on the rolled-back (finite) state
    if not episodes or float(episodes[-1].get("state_finite", 0)) != 1.0:
        print("chaos smoke: FAIL — final drained episode not finite: "
              f"{episodes[-1] if episodes else None}")
        return 1
    print(f"chaos smoke: OK — serial leg survived {PLAN!r} "
          f"({sorted(seen)} recoveries, run_end status=ok)")
    return 0


def async_leg(tmp: str) -> int:
    """Fresh-subprocess real-CLI `train --async` under the fleet plan."""
    args = write_tiny_configs(os.path.join(tmp, "acfg"))
    res = os.path.join(tmp, "ares")
    proc = subprocess.run(
        _async_argv(args, 6, res, plan=ASYNC_PLAN), cwd=REPO,
        env=_cli_env(), capture_output=True, text=True, timeout=900)
    if proc.returncode != 0:
        print(proc.stdout)
        print(proc.stderr)
        print(f"chaos smoke: FAIL — async train rc={proc.returncode} "
              f"under plan {ASYNC_PLAN!r}")
        return 1
    rdir = json.loads(proc.stdout.strip().splitlines()[-1])["result_dir"]
    events = _read_events(rdir)
    err = _check_async_events(events)
    if err:
        print(f"chaos smoke: FAIL — async leg: {err}")
        return 1
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        print(f"chaos smoke: FAIL — async stream tail {end}")
        return 1
    info = [e for e in events if e["event"] == "async_train"][-1]
    print(f"chaos smoke: OK — async leg survived {ASYNC_PLAN!r} "
          f"(restart+quarantine+retry recoveries, "
          f"produced=ingested={info['produced_steps']}, "
          f"run_end status=ok)")
    return 0


def bank_round(out_path: str) -> int:
    """The CHAOS_r* bench row: fault-free async control vs the chaos leg
    with a mid-run SIGTERM + `--resume auto` continuation.  Rates come
    from each run's async_train summary (produced_steps / wall_s — the
    fleet's own drain-proof ledger), so the chaos leg's rate folds in
    every recovery detour it took."""
    tmp = tempfile.mkdtemp(prefix="gsc_chaos_round_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    episodes = 40

    # ---- control: fault-free async run, fresh subprocess
    cres = os.path.join(tmp, "control")
    proc = subprocess.run(_async_argv(args, episodes, cres), cwd=REPO,
                          env=_cli_env(), capture_output=True, text=True,
                          timeout=1800)
    if proc.returncode != 0:
        print(proc.stderr)
        print(f"chaos round: FAIL — control rc={proc.returncode}")
        return 1
    crdir = json.loads(proc.stdout.strip().splitlines()[-1])["result_dir"]
    cinfo = [e for e in _read_events(crdir)
             if e["event"] == "async_train"][-1]
    control_sps = cinfo["produced_steps"] / cinfo["wall_s"]

    # ---- chaos: plan + mid-run SIGTERM once every site has fired
    xres = os.path.join(tmp, "chaos")
    proc = subprocess.Popen(
        _async_argv(args, episodes, xres, plan=ASYNC_PLAN), cwd=REPO,
        env=_cli_env(), stdout=subprocess.PIPE, stderr=subprocess.PIPE,
        text=True)
    try:
        deadline = time.time() + 600
        fired = False
        # preempt only once every site has fired AND the run has drained
        # enough episodes for the startup wall and the recovery detours
        # to amortize — a rate measured over 4 episodes is a startup
        # benchmark, not a chaos one
        min_drained = (3 * episodes) // 4
        while time.time() < deadline and proc.poll() is None:
            p = _find_events_file(xres)
            if p is not None:
                seen = set()
                drained = 0
                for line in open(p):
                    try:   # the live stream's last line may be torn
                        e = json.loads(line)
                    except ValueError:
                        continue
                    if e.get("event") == "recovery":
                        seen.add((e.get("site"), e.get("action")))
                    elif e.get("event") == "episode":
                        drained += 1
                if ASYNC_EXPECTED <= seen and drained >= min_drained:
                    fired = True
                    break
            time.sleep(0.25)
        if proc.poll() is not None:
            # every site fired before we could preempt — tolerated, the
            # resume below then continues a COMPLETED run's checkpoint
            out, err2 = proc.communicate()
        elif not fired:
            proc.kill()
            print("chaos round: FAIL — fault sites never all fired")
            return 1
        else:
            proc.send_signal(signal.SIGTERM)
            out, err2 = proc.communicate(timeout=300)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    if proc.returncode != 0:
        print(err2)
        print(f"chaos round: FAIL — chaos leg rc={proc.returncode} "
              f"(SIGTERM must exit 0 with a snapshot)")
        return 1
    tail = json.loads(out.strip().splitlines()[-1])
    preempted = tail.get("status") == "preempted"
    if preempted and ((tail.get("drain") or {}).get("transitions_lost")
                      != 0):
        print(f"chaos round: FAIL — preempt drain proof missing: {tail}")
        return 1
    xrdir = tail["result_dir"]
    xevents = _read_events(xrdir)
    err = _check_async_events(xevents)
    if err:
        print(f"chaos round: FAIL — chaos leg: {err}")
        return 1
    xinfo = [e for e in xevents if e["event"] == "async_train"][-1]

    # ---- resume: fault-free continuation from the snapshot
    done = tail.get("episodes_completed", episodes)
    resumed = 0
    if preempted:
        proc = subprocess.run(
            _async_argv(args, episodes, xres, resume=True), cwd=REPO,
            env=_cli_env(), capture_output=True, text=True, timeout=1800)
        if proc.returncode != 0:
            print(proc.stderr)
            print(f"chaos round: FAIL — resume rc={proc.returncode}")
            return 1
        rrdir = json.loads(
            proc.stdout.strip().splitlines()[-1])["result_dir"]
        reps = [e["episode"] for e in _read_events(rrdir)
                if e["event"] == "episode"]
        if not reps or min(reps) < done:
            print(f"chaos round: FAIL — resume re-ran below the "
                  f"snapshot's counter ({done}): {sorted(reps)[:5]}")
            return 1
        resumed = len(reps)

    chaos_sps = xinfo["produced_steps"] / xinfo["wall_s"]
    recoveries = sum(1 for e in xevents if e["event"] == "recovery")
    row = {
        "metric": "env_steps_per_sec_per_chip", "unit": "env-steps/s",
        "status": "ok", "platform": "cpu", "round": "chaos",
        "plan": ASYNC_PLAN, "replicas": 2, "async_actors": 1,
        "chunk": 3, "episode_steps": 3, "episodes": episodes,
        "control_sps": round(control_sps, 2),
        "chaos_sps": round(chaos_sps, 2),
        "chaos_vs_control": round(chaos_sps / control_sps, 4),
        "recoveries_total": recoveries,
        "actor_restarts": xinfo["actor_restarts"],
        "blocks_quarantined": xinfo["blocks_quarantined"],
        "preempted": preempted,
        "episodes_at_preempt": done if preempted else None,
        "episodes_resumed": resumed,
    }
    with open(out_path, "w") as f:
        json.dump(row, f, indent=1)
        f.write("\n")
    print(f"chaos round: OK — banked {out_path} "
          f"(chaos {row['chaos_sps']} vs control {row['control_sps']} "
          f"env-steps/s, {recoveries} recoveries, "
          f"preempted={preempted} resumed={resumed})")
    return 0


def main(argv=None) -> int:
    argv = sys.argv[1:] if argv is None else argv
    _configure_jax()
    if argv and argv[0] == "--round":
        return bank_round(argv[1] if len(argv) > 1
                          else os.path.join(REPO, "CHAOS_r01.json"))
    tmp = tempfile.mkdtemp(prefix="gsc_chaos_")
    rc = serial_leg(tmp)
    if rc:
        return rc
    return async_leg(tmp)


if __name__ == "__main__":
    sys.exit(main())
