"""Hyperparameter sweep for one-config-that-is-both-fast-and-learns.

VERDICT r3 weak #4: the perf config (B=256) learns shallowly while the
quality config (B=64) benches at half the rate; no LR/noise/burst study
existed.  This sweeps learning_rate x rand_sigma x learn_steps at a fixed
replica count on the flagship scenario, appending one JSON line per cell
to ``--out`` (resume-safe: finished cells are skipped on rerun).

On TPU::

    python tools/quality_sweep.py --replicas 256 --episodes 24

Each cell reports first/last-k return and success ratio plus wall-clock
env-steps/s, so the ">= 0.64 success at >= 1500 env-steps/s wall" bar can
be read straight off the output.  CPU smoke: --cpu --replicas 2
--episodes 2 --episode-steps 25 --grid-lr 1e-3 --grid-sigma 0.3.
"""
from __future__ import annotations

import argparse
import dataclasses
import itertools
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def run_cell(args, lr, sigma, learn_steps, batch_size, seed):
    import jax

    from __graft_entry__ import _flagship
    from gsc_tpu.env.env import ServiceCoordEnv
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    T, B, chunk = args.episode_steps, args.replicas, args.chunk
    env, agent, topo, _ = _flagship(episode_steps=T, gen_traffic=False)
    agent = dataclasses.replace(agent, learning_rate=lr, rand_sigma=sigma,
                                learn_steps=learn_steps,
                                batch_size=batch_size)
    env = ServiceCoordEnv(env.service, env.sim_cfg, agent, env.limits)
    dt = DeviceTraffic(env.sim_cfg, env.service, topo, T)
    sample_batch = jax.jit(lambda k: dt.sample_batch(k, B))
    pddpg = ParallelDDPG(env, agent, num_replicas=B, donate=True)

    from gsc_tpu.sim.traffic import generate_traffic
    one_traffic = generate_traffic(env.sim_cfg, env.service, topo, T, seed=0)
    _, one_obs = env.reset(jax.random.PRNGKey(seed), topo, one_traffic)
    state = pddpg.init(jax.random.PRNGKey(seed + 1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    from gsc_tpu.parallel.harness import run_chunked_episodes

    t0 = time.time()
    _, _, returns, succ, final_succ = run_chunked_episodes(
        pddpg, topo,
        lambda ep: sample_batch(jax.random.fold_in(
            jax.random.PRNGKey(seed + 3), ep)),
        state, buffers, args.episodes, T, chunk, seed)
    wall = time.time() - t0
    k = min(5, max(1, len(returns) // 4))
    return {
        "lr": lr, "sigma": sigma, "learn_steps": learn_steps,
        "batch_size": batch_size,
        "replicas": B, "episodes": args.episodes, "episode_steps": T,
        "first_k_return": round(sum(returns[:k]) / k, 3),
        "last_k_return": round(sum(returns[-k:]) / k, 3),
        "first_k_succ": round(sum(succ[:k]) / k, 4),
        "last_k_succ": round(sum(succ[-k:]) / k, 4),
        # end-of-episode slice — the number the ">= 0.64" bar refers to
        "first_k_final_succ": round(sum(final_succ[:k]) / k, 4),
        "last_k_final_succ": round(sum(final_succ[-k:]) / k, 4),
        "env_steps_per_sec_wall": round(
            args.episodes * T * B / wall, 1),
        "wall_s": round(wall, 1),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=256)
    ap.add_argument("--episodes", type=int, default=24)
    ap.add_argument("--episode-steps", type=int, default=200)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--out", default="quality_sweep.jsonl")
    ap.add_argument("--grid-lr", type=float, nargs="+",
                    default=[1e-3, 3e-4, 3e-3])
    ap.add_argument("--grid-sigma", type=float, nargs="+",
                    default=[0.3, 0.15])
    ap.add_argument("--grid-learn-steps", type=int, nargs="+",
                    default=[0, 400],
                    help="0 = episode_steps (reference schedule)")
    ap.add_argument("--grid-batch", type=int, nargs="+", default=[100],
                    help="critic/actor batch size per learn step — the "
                    "large-B lever: at B=256 an episode adds 256x the "
                    "flagship data but the burst length must NOT grow "
                    "(r4 sweep: 3x burst regresses); scale the batch "
                    "instead (reference default 100)")
    args = ap.parse_args()
    assert args.episode_steps % args.chunk == 0

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    # a "cell" includes the run shape, so re-sweeping at a different
    # replica count / length into the same file collects fresh data
    # instead of skipping everything
    def cell_key(lr, sigma, learn_steps, batch):
        return (lr, sigma, learn_steps, batch, args.replicas,
                args.episodes, args.episode_steps)

    done = set()
    if os.path.exists(args.out):
        for line in open(args.out):
            try:
                r = json.loads(line)
                done.add((r["lr"], r["sigma"], r["learn_steps"],
                          r.get("batch_size", 100), r["replicas"],
                          r["episodes"], r["episode_steps"]))
            except (json.JSONDecodeError, KeyError):
                continue
    cells = list(itertools.product(args.grid_lr, args.grid_sigma,
                                   args.grid_learn_steps,
                                   args.grid_batch))
    for lr, sigma, ls, batch in cells:
        ls_eff = None if ls == 0 else ls
        if cell_key(lr, sigma, ls_eff, batch) in done \
                or cell_key(lr, sigma, ls, batch) in done:
            print(f"[sweep] skip done cell lr={lr} sigma={sigma} "
                  f"learn_steps={ls} batch={batch}", file=sys.stderr)
            continue
        print(f"[sweep] cell lr={lr} sigma={sigma} learn_steps={ls} "
              f"batch={batch}", file=sys.stderr)
        row = run_cell(args, lr, sigma, ls_eff, batch, args.seed)
        with open(args.out, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(json.dumps(row))


if __name__ == "__main__":
    main()
