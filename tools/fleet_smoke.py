"""Fleet smoke: continuous batching + live hot-swap through the real CLI.

The CI-stage proof that the serving fleet executes end to end: a 2-worker
SPR-tier run (no checkpoint — the fallback tier shares the whole
batcher/dispatcher/watcher path without paying an AOT compile) with
``--continuous`` and ONE forced hot-swap fired under load must

- exit 0 with ZERO dropped/errored requests and every published version
  swapped into every worker (`swaps == workers * published_versions`),
- leave ``weight_swap`` events (one per worker) and ``serve_flush``
  events that ALL carry the ``policy_version`` field, in ``events.jsonl``,
- expose per-worker queue-depth gauges and per-worker request counters in
  the /metrics exposition (``metrics.json`` is the same snapshot the live
  endpoint serves) — the PR 12 gauges must not collide across workers,
- write the fleet-merged ``slo.json`` and gate through ``bench_diff``:
  self-compare rc 0, an injected p99 regression rc 1.

Run by ``tools/ci_check.sh`` after the serveobs stage; standalone:

    JAX_PLATFORMS=cpu python tools/fleet_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REQUESTS = 48
WORKERS = 2


def fail(msg: str) -> int:
    print(f"fleet smoke: FAIL — {msg}")
    return 1


def main() -> int:
    from chaos_smoke import _configure_jax, write_tiny_configs
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    tmp = tempfile.mkdtemp(prefix="gsc_fleet_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    configs = args[:4]
    extra = [a for a in args[4:] if a != "--quiet"]
    r = CliRunner().invoke(cli, [
        "serve", *configs, *extra,          # no checkpoint: SPR tier
        "--requests", str(REQUESTS), "--concurrency", "6",
        "--buckets", "1,4", "--deadline-ms", "2", "--pool-steps", "2",
        "--continuous", "--workers", str(WORKERS),
        "--hot-swap-dir", os.path.join(tmp, "weights"),
        "--swap-poll-s", "0.02", "--fire-swaps", "1",
        "--trace-sample", "1", "--slo-p99-ms", "100",
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"serve rc={r.exit_code}")
    out = json.loads(r.output.strip().splitlines()[-1])
    rdir = out["result_dir"]

    # zero dropped/errored requests across the swap — the hot-swap
    # contract, and the reason the fleet exists
    if out["errors"]:
        return fail(f"{out['errors']} request(s) dropped/errored across "
                    f"the hot-swap: {out['error_detail']}")
    if out["workers"] != WORKERS or out["mode"] != "continuous":
        return fail(f"fleet shape wrong: {out['workers']} workers, "
                    f"mode {out['mode']}")
    if out["published_versions"] != 1:
        return fail(f"--fire-swaps 1 published "
                    f"{out['published_versions']} versions")
    if out["swaps"] != WORKERS:
        return fail(f"expected every worker to swap once: swaps="
                    f"{out['swaps']} != {WORKERS}")
    if out["policy_version"] != 1:
        return fail(f"worker policy_version {out['policy_version']} != 1")

    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    flushes = [e for e in events if e["event"] == "serve_flush"]
    swaps = [e for e in events if e["event"] == "weight_swap"]
    if not flushes:
        return fail("no serve_flush events recorded")
    missing = [e for e in flushes if "policy_version" not in e]
    if missing:
        return fail(f"{len(missing)}/{len(flushes)} serve_flush events "
                    "missing policy_version")
    if sorted(e.get("worker") for e in swaps) != ["w0", "w1"]:
        return fail(f"weight_swap events wrong: "
                    f"{[(e.get('worker'), e.get('version')) for e in swaps]}")
    if not all(e.get("weights_applied") for e in swaps):
        return fail("SPR action republish should apply as real weights")
    workers_seen = {e.get("worker") for e in flushes}
    if not {"w0", "w1"} <= workers_seen:
        return fail(f"flushes from only {workers_seen} — least-queue-"
                    "depth routing never spread the load")

    # per-worker gauges/counters in the /metrics exposition (metrics.json
    # is the same hub snapshot the live endpoint serves)
    mj = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    for w in ("w0", "w1"):
        if not any("serve_queue_depth" in k and f'worker="{w}"' in k
                   for k in mj):
            return fail(f"no worker-tagged queue-depth gauge for {w}")
        if not any("serve_requests_total" in k and f'worker="{w}"' in k
                   for k in mj):
            return fail(f"no worker-tagged request counter for {w}")

    # fleet-merged slo.json gates through bench_diff
    slo_path = os.path.join(rdir, "slo.json")
    if not os.path.exists(slo_path):
        return fail("fleet slo.json not written")
    doc = json.load(open(slo_path))
    if doc.get("schema_version") != 1 or doc.get("requests") != REQUESTS:
        return fail(f"fleet slo.json incomplete: schema="
                    f"{doc.get('schema_version')} requests="
                    f"{doc.get('requests')}")
    if sorted(doc.get("fleet_workers") or []) != ["w0", "w1"]:
        return fail(f"slo.json fleet_workers {doc.get('fleet_workers')}")
    import bench_diff
    traj = os.path.join(tmp, "traj.json")
    doc2 = bench_diff.ingest([slo_path], traj)
    (row_name,) = [n for n in doc2["rows"] if n.startswith("slo_")]
    rc = bench_diff.main(["diff", row_name, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"slo self-compare rc={rc} (want 0)")
    bad = dict(doc)
    bad["p99_latency_ms"] = (doc["p99_latency_ms"] or 1.0) * 2.0 + 1.0
    bad_path = os.path.join(tmp, "bad_slo.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected p99 regression rc={rc} (want 1)")

    print(f"fleet smoke: OK — {REQUESTS} requests over {WORKERS} "
          f"continuous workers with {out['swaps']} hot-swap(s) under "
          f"load, zero drops, policy_version on all {len(flushes)} "
          "flushes, per-worker gauges exposed, fleet slo.json gated")
    return 0


if __name__ == "__main__":
    sys.exit(main())
