"""Serve smoke: tiny checkpoint -> in-process server -> cache-hit restart.

The CI-stage proof that the serving subsystem's whole lifecycle executes:

1. train a 2-episode tiny checkpoint (triangle network, 8-wide nets);
2. ``cli serve`` run 1 (cold): N requests through the AOT-compiled policy
   must exit 0 with zero request errors, a recorded p99 latency, and a
   compiled-policy artifact written to the cache dir;
3. ``cli serve`` run 2 (warm): every bucket must report ``cache_hit``
   (the serialized module was deserialized — the policy was NOT re-traced)
   and p99 must again be recorded;
4. the run's events.jsonl must carry ``serve_start`` + a final
   ``serve_stats`` and end with ``run_end status=ok``.

Run by ``tools/ci_check.sh`` after the chaos stage; standalone:

    JAX_PLATFORMS=cpu python tools/serve_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REQUESTS = 12


def _fail(msg: str) -> int:
    print(f"serve smoke: FAIL — {msg}")
    return 1


def main() -> int:
    # the chaos stage owns the shared smoke plumbing (cpu pin + repo
    # .jax_cache persistent-compile settings + tiny config writer)
    from chaos_smoke import _configure_jax, write_tiny_configs

    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    tmp = tempfile.mkdtemp(prefix="gsc_serve_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    opts = [a for a in args[4:] if a != "--quiet"]

    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "2",
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        return _fail(f"tiny train rc={r.exit_code}")
    train_out = json.loads(r.output.strip().splitlines()[-1])
    ckpt = train_out["checkpoint"]
    if "compile_warmup_s" not in train_out:
        return _fail("evaluate() lost the compile/warmup split fields")

    serve_args = ["serve", *args[:4], ckpt, *opts,
                  "--requests", str(REQUESTS), "--concurrency", "4",
                  "--buckets", "1,4", "--deadline-ms", "2",
                  "--result-dir", os.path.join(tmp, "serve_res")]
    outs = []
    for leg in ("cold", "warm"):
        rr = CliRunner().invoke(cli, serve_args)
        if rr.exit_code != 0:
            print(rr.output)
            if rr.exception is not None:
                import traceback
                traceback.print_exception(type(rr.exception), rr.exception,
                                          rr.exception.__traceback__)
            return _fail(f"{leg} serve rc={rr.exit_code}")
        out = json.loads(rr.output.strip().splitlines()[-1])
        if out["errors"]:
            return _fail(f"{leg} serve answered with {out['errors']} "
                         f"errors: {out['error_detail']}")
        if not out["p99_ms"] > 0:
            return _fail(f"{leg} serve recorded no p99 latency: {out}")
        outs.append(out)

    cold, warm = outs
    cache_dir = cold["artifact_cache"]
    blobs = [f for f in os.listdir(cache_dir) if f.endswith(".stablehlo")]
    if len(blobs) != 2:   # one artifact per bucket
        return _fail(f"expected 2 compiled-policy artifacts in "
                     f"{cache_dir}, found {blobs}")
    cold_hits = [b["cache_hit"] for b in cold["startup"]["buckets"].values()]
    warm_hits = [b["cache_hit"] for b in warm["startup"]["buckets"].values()]
    if any(cold_hits) or not all(warm_hits):
        return _fail(f"cache-hit pattern wrong: cold={cold_hits} "
                     f"warm={warm_hits}")

    events = [json.loads(line) for line in
              open(os.path.join(warm["result_dir"], "events.jsonl"))]
    kinds = [e["event"] for e in events]
    if "serve_start" not in kinds or "serve_stats" not in kinds:
        return _fail(f"serve events missing from stream: {kinds}")
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        return _fail(f"stream tail {end}")

    print(f"serve smoke: OK — {REQUESTS} requests/leg, cold p99 "
          f"{cold['p99_ms']} ms @ {cold['rps']} req/s, warm startup "
          f"{warm['startup']['startup_s']}s with all-bucket cache hits")
    return 0


if __name__ == "__main__":
    sys.exit(main())
