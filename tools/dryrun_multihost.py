"""Multi-host / multi-chip dryruns on virtual CPU meshes.

Two modes, no TPU needed for either:

**Multi-PROCESS mode** (default): the full sharded train step across N
separate processes, each owning a slice of a virtual CPU mesh —
``jax.distributed.initialize`` over a localhost coordinator, a global
mesh from all processes' devices, per-process host data fed in via
``host_local_array_to_global_array``, one rollout+learn step whose
gradient psum crosses process boundaries.  Same SPMD code path a v5e-16
data-parallel run takes, with gRPC standing in for ICI/DCN.

**Mesh-MATRIX mode** (``--mesh-matrix``): the pjit-sharded single-process
path (``parallel.partition.ShardingPlan``) across a matrix of mesh
carvings and partition rulebooks, proving the PR 8 contract end to end:

- every ``DPxMP`` carving of the same device count produces a
  BIT-IDENTICAL final learner state — **including legs whose parameters
  are actually sharded over mp** (the leg rows record how many leaves
  were split);
- an elastic-resume leg checkpoints a run on an 8-device mesh and
  resumes it in a fresh 4-device process via ``cli train --resume auto``
  (host-gathered checkpoints reshard onto whatever mesh the resuming
  process builds), asserting the episode counter stays monotone;
- ``tp`` legs (PR 13: true tensor-parallel compute, psum-accumulated
  contractions) are EXEMPT from the digest set by design — their
  acceptance is BANDED: each tp leg's learning-curve envelope
  (final-window return, AUC) must land inside the bench_diff tolerance
  bands against the bit-exact control legs (``tools/bench_diff.py``'s
  ``final_window_return``/``auc_return`` rules — one definition of the
  band, shared with CI's curve gating).

Both modes follow the bench.py failed-row discipline: every leg runs in
a fresh subprocess under its own timeout budget, a failure emits a
structured ``{"status": "failed", "reason": ...}`` row (never a bare
timeout tail), and a bounded backend probe gates each next leg so one
wedged leg cannot cascade.  ``--bank PATH`` writes the whole round as a
MULTICHIP_r*.json artifact with per-leg mesh shapes.

Launcher::

    python tools/dryrun_multihost.py                 # 2 procs x 4 devices
    python tools/dryrun_multihost.py --procs 2 --devices-per-proc 2
    python tools/dryrun_multihost.py --mesh-matrix   # carving bit-equality
    python tools/dryrun_multihost.py --mesh-matrix --elastic \\
        --bank MULTICHIP_r06.json                    # full banked round
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

#: default carving matrix: same 8 devices, three carvings, both bit-exact
#: rulebooks at the extremes — all final-state digests must agree (the
#: replicated 8x1 leg doubles as the "rules are a no-op fallback"
#: witness) — plus two tensor-parallel legs whose curves must land
#: inside the tolerance bands vs those controls (never in the digest
#: set: tp trades bit-equality for psum-parallel compute).
DEFAULT_LEGS = ("8x1:replicated,8x1:sharded,4x2:sharded,2x4:sharded,"
                "1x8:sharded,4x2:tp,2x4:tp")
LEG_TIMEOUT = 600      # per-leg budget: tiny stack, warm cache is ~1 min
PROBE_TIMEOUT = 120


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def _cpu_env(n_devices: int) -> dict:
    """Subprocess env pinned to an n-device virtual CPU platform; never
    touches the TPU plugin, shares the repo compile cache so repeat legs
    are disk hits."""
    env = {k: v for k, v in os.environ.items()
           if k not in ("PALLAS_AXON_POOL_IPS", "XLA_FLAGS")}
    env["JAX_PLATFORMS"] = "cpu"
    env["XLA_FLAGS"] = (
        f"--xla_force_host_platform_device_count={n_devices}")
    env["PYTHONPATH"] = REPO
    env.setdefault("JAX_COMPILATION_CACHE_DIR",
                   os.path.join(REPO, ".jax_cache"))
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS", "1")
    env.setdefault("JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES", "-1")
    return env


def probe(n_devices: int, timeout: int = PROBE_TIMEOUT) -> bool:
    """Bounded-time backend health check in a fresh process — the gate
    between legs (bench.py's probe contract): a leg that wedged its
    backend must fail ITS row, not hang every row after it."""
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax; print('PROBE_OK', len(jax.devices()))"],
            timeout=timeout, capture_output=True, text=True,
            env=_cpu_env(n_devices))
        return r.returncode == 0 and "PROBE_OK" in r.stdout
    except subprocess.TimeoutExpired:
        return False


def _tail(text: str, n: int = 800) -> str:
    return (text or "")[-n:]


# ------------------------------------------------------------- mesh matrix
def mesh_leg(shape: str, rules: str, episodes: int, replicas: int) -> None:
    """One carving leg (runs in its own subprocess): chunked episodes of
    the tiny flagship stack under a ShardingPlan, final learner state
    digested with sha256 over the host-gathered leaves.  The recipe is
    ``__graft_entry__.sharded_training_leg`` — shared with
    tests/test_multichip.py so the CI verdict and the tier-1 test agree
    on what "bit-identical" means.  Prints ONE JSON row the launcher
    parses."""
    sys.path.insert(0, REPO)
    from __graft_entry__ import sharded_training_leg
    from gsc_tpu.parallel import ShardingPlan

    t0 = time.time()
    plan = ShardingPlan.from_spec(shape, rules=rules)
    leg = sharded_training_leg(plan, episodes=episodes, replicas=replicas)
    print(json.dumps({
        "status": "ok", "leg": "carving", "mesh": plan.describe(),
        "rules": rules, "replicas": replicas, "episodes": episodes,
        "digest": leg["digest"],
        "final_return": round(leg["final_return"], 6),
        # the whole per-episode curve: tp legs gate on its envelope
        # (bench_diff bands) instead of joining the digest set
        "returns": [round(r, 6) for r in leg["returns"]],
        "sharded_leaves": leg["sharded_leaves"],
        "spec_counts": leg["spec_counts"],
        "wall_s": round(time.time() - t0, 1)}), flush=True)


def _parse_leg_row(stdout: str):
    for line in reversed((stdout or "").strip().splitlines()):
        try:
            row = json.loads(line)
            if isinstance(row, dict) and "status" in row:
                return row
        except json.JSONDecodeError:
            continue
    return None


def run_leg(shape: str, rules: str, episodes: int, replicas: int,
            n_devices: int, timeout: int) -> dict:
    """Launch one carving leg in a fresh subprocess under its timeout
    budget; structured failed row on timeout / crash / unparseable
    output."""
    cmd = [sys.executable, os.path.abspath(__file__), "--mesh-leg",
           shape, rules, str(episodes), str(replicas)]
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True, env=_cpu_env(n_devices))
    except subprocess.TimeoutExpired as e:
        return {"status": "failed", "leg": "carving", "mesh": shape,
                "rules": rules,
                "reason": f"leg timed out after {timeout}s",
                "tail": _tail(e.stderr.decode() if isinstance(
                    e.stderr, bytes) else e.stderr)}
    row = _parse_leg_row(r.stdout)
    if r.returncode != 0 or row is None:
        return {"status": "failed", "leg": "carving", "mesh": shape,
                "rules": rules,
                "reason": f"leg exited rc={r.returncode}"
                          + ("" if row else " with no parseable row"),
                "tail": _tail(r.stderr)}
    return row


def _write_tiny_configs(cfg_dir: str) -> list:
    """Minimal triangle config quadruple for the elastic-resume legs
    (mirrors tests/test_agent.write_tiny_configs — duplicated here so the
    tool never imports the test tree)."""
    import yaml

    sys.path.insert(0, REPO)
    from gsc_tpu.topology.synthetic import triangle, write_graphml

    os.makedirs(cfg_dir, exist_ok=True)
    write_graphml(triangle(), os.path.join(cfg_dir, "tri.graphml"))
    with open(os.path.join(cfg_dir, "svc.yaml"), "w") as f:
        yaml.safe_dump({
            "sfc_list": {"sfc_1": ["a", "b", "c"]},
            "sf_list": {n: {"processing_delay_mean": 5.0,
                            "processing_delay_stdev": 0.0}
                        for n in "abc"}}, f)
    with open(os.path.join(cfg_dir, "sim.yaml"), "w") as f:
        yaml.safe_dump({
            "inter_arrival_mean": 10.0, "deterministic_arrival": True,
            "flow_dr_mean": 1.0, "flow_dr_stdev": 0.0,
            "flow_size_shape": 0.001, "deterministic_size": True,
            "run_duration": 100, "ttl_choices": [100], "max_flows": 32}, f)
    with open(os.path.join(cfg_dir, "agent.yaml"), "w") as f:
        yaml.safe_dump({
            "graph_mode": True, "episode_steps": 3,
            "objective": "prio-flow", "GNN_features": 4,
            "GNN_num_layers": 1, "GNN_num_iter": 1,
            "actor_hidden_layer_nodes": [8],
            "critic_hidden_layer_nodes": [8],
            "mem_limit": 32, "batch_size": 4,
            "nb_steps_warmup_critic": 3}, f)
    with open(os.path.join(cfg_dir, "sched.yaml"), "w") as f:
        yaml.safe_dump({
            "training_network_files": [os.path.join(cfg_dir,
                                                    "tri.graphml")],
            "inference_network": os.path.join(cfg_dir, "tri.graphml")}, f)
    return [os.path.join(cfg_dir, "agent.yaml"),
            os.path.join(cfg_dir, "sim.yaml"),
            os.path.join(cfg_dir, "svc.yaml"),
            os.path.join(cfg_dir, "sched.yaml"),
            "--max-nodes", "8", "--max-edges", "8", "--quiet"]


def elastic_leg(from_mesh: str, to_mesh: str, from_devices: int,
                to_devices: int, replicas: int, timeout: int) -> dict:
    """Checkpoint a sharded run on ``from_mesh`` (``from_devices``
    devices), then resume it via ``--resume auto`` in a FRESH process
    that only has ``to_devices`` devices and builds ``to_mesh`` — the
    lost-hosts scenario.  The resumed run must continue with a monotone
    episode counter.  Callers derive mesh shapes and ``replicas`` from
    the actual device counts (run_matrix does) — cli train refuses a
    mesh its backend cannot provide, so a mislabeled row cannot bank."""
    import tempfile

    t0 = time.time()
    work = tempfile.mkdtemp(prefix="gsc_elastic_")
    cfg = _write_tiny_configs(os.path.join(work, "cfg"))
    res = os.path.join(work, "res")
    base = [sys.executable, "-m", "gsc_tpu.cli", "train", *cfg,
            "--replicas", str(replicas), "--chunk", "3",
            "--partition-rules", "sharded", "--result-dir", res]
    row = {"leg": "elastic_resume", "from_mesh": from_mesh,
           "to_mesh": to_mesh, "from_devices": from_devices,
           "to_devices": to_devices}
    try:
        r1 = subprocess.run(
            base + ["--mesh", from_mesh, "--episodes", "2",
                    "--ckpt-interval", "1"],
            timeout=timeout, capture_output=True, text=True, cwd=REPO,
            env=_cpu_env(from_devices))
        if r1.returncode != 0:
            return {**row, "status": "failed",
                    "reason": f"first run exited rc={r1.returncode}",
                    "tail": _tail(r1.stderr)}
        r2 = subprocess.run(
            base + ["--mesh", to_mesh, "--episodes", "4",
                    "--resume", "auto"],
            timeout=timeout, capture_output=True, text=True, cwd=REPO,
            env=_cpu_env(to_devices))
        if r2.returncode != 0:
            return {**row, "status": "failed",
                    "reason": f"resume run exited rc={r2.returncode}",
                    "tail": _tail(r2.stderr)}
    except subprocess.TimeoutExpired as e:
        return {**row, "status": "failed",
                "reason": f"elastic leg timed out after {timeout}s",
                "tail": _tail(e.stderr.decode() if isinstance(
                    e.stderr, bytes) else e.stderr)}
    # the resumed run's events must continue past the checkpointed count.
    # Episodes are grouped PER RUN (keyed by the run_start mesh, like
    # tests/test_multichip.py) — a pooled >=2 filter would read a resume
    # that silently restarted at 0 and ran 0..3 as a monotone [2, 3]
    by_mesh: dict = {}
    for root, _, files in os.walk(res):
        if "events.jsonl" in files:
            mesh_key, eps = None, []
            with open(os.path.join(root, "events.jsonl")) as f:
                for line in f:
                    try:
                        ev = json.loads(line)
                    except json.JSONDecodeError:
                        continue
                    if ev.get("event") == "run_start":
                        mesh_key = ev.get("mesh")
                    elif ev.get("event") == "episode":
                        eps.append(ev["episode"])
            by_mesh.setdefault(mesh_key, []).extend(eps)
    first = sorted(by_mesh.get(from_mesh, []))
    resumed = sorted(by_mesh.get(to_mesh, []))
    if first != [0, 1] or resumed != [2, 3]:
        return {**row, "status": "failed",
                "reason": "resumed episode counter not monotone from the "
                          f"checkpoint (expected {from_mesh}=[0, 1] then "
                          f"{to_mesh}=[2, 3], got {from_mesh}={first} "
                          f"{to_mesh}={resumed})"}
    return {**row, "status": "ok", "resumed_episodes": resumed,
            "wall_s": round(time.time() - t0, 1)}


def _curve_envelope(returns) -> dict:
    """The learning-curve envelope of a leg's per-episode returns —
    the same two length-robust metrics ``gsc_tpu.obs.curves`` banks
    (final-window return with w = min(10, len), AUC = mean), computed
    with plain arithmetic so the launcher stays jax-free."""
    returns = [float(r) for r in returns or []]
    if not returns:
        return {}
    w = min(10, len(returns))
    return {"final_window_return": sum(returns[-w:]) / w,
            "auc_return": sum(returns) / len(returns)}


def _gate_tp_legs(tp_legs: list, exact_legs: list) -> list:
    """Banded acceptance for tp carving legs: each leg's envelope vs
    the bit-exact control legs' (first ok control), under the SAME
    tolerance bands bench_diff applies to curves.json rows — one band
    definition, so this verdict and the CI curve gate can never
    disagree on what 'inside the envelope' means.  One verdict row per
    tp leg; an empty list gates nothing (no tp legs requested)."""
    if not tp_legs:
        return []
    sys.path.insert(0, os.path.join(REPO, "tools"))
    from bench_diff import metric_rule  # stdlib-only, jax-free

    if not exact_legs:
        return [{"mesh": r.get("mesh"), "ok": False,
                 "reason": "no bit-exact control leg to band against"}
                for r in tp_legs]
    control = _curve_envelope(exact_legs[0].get("returns"))
    out = []
    for leg in tp_legs:
        env = _curve_envelope(leg.get("returns"))
        row = {"mesh": leg.get("mesh"), "ok": True,
               "control_mesh": exact_legs[0].get("mesh")}
        if not env or not control:
            row.update(ok=False,
                       reason="leg or control row carries no returns "
                              "(pre-PR13 artifact?)")
            out.append(row)
            continue
        for name, base in control.items():
            higher, tol, floor = metric_rule(name)
            band = max(tol * abs(base), floor)
            cur = env[name]
            delta = (base - cur) if higher else (cur - base)
            row[name] = {"current": round(cur, 6),
                         "baseline": round(base, 6),
                         "band": round(band, 6)}
            if delta > band:
                row["ok"] = False
                row["reason"] = (f"{name} {cur:.6g} outside band "
                                 f"{band:.6g} of control {base:.6g}")
        out.append(row)
    return out


def run_matrix(legs: str, episodes: int, replicas: int, n_devices: int,
               leg_timeout: int, elastic: bool, bank: str) -> int:
    """The full round: carving legs (probe-gated, per-leg budgets) +
    optional elastic-resume leg, bit-equality verdict, optional
    MULTICHIP_r*.json artifact."""
    sys.path.insert(0, REPO)
    from gsc_tpu.meshspec import (PARTITION_RULEBOOKS,  # jax-free
                                  validate_partition_rules)
    parsed = []
    for cell in legs.split(","):
        cell = cell.strip()
        if not cell:
            continue
        shape, _, rules = cell.partition(":")
        rules = rules or "replicated"
        try:
            validate_partition_rules(rules)
        except ValueError:
            print(json.dumps({
                "status": "failed",
                "reason": f"leg {cell!r}: rules must be "
                          + "|".join(PARTITION_RULEBOOKS)}))
            return 2
        parsed.append((shape, rules))

    rows = []
    aborted = False
    for shape, rules in parsed:
        if aborted:
            row = {"status": "failed", "leg": "carving",
                   "mesh": shape, "rules": rules,
                   "reason": "skipped: backend probe failed after "
                             "an earlier leg"}
            rows.append(row)
            # same structured-row discipline as a run leg: bankless
            # callers (the CI smoke) only see stdout
            print(json.dumps(row), flush=True)
            continue
        row = run_leg(shape, rules, episodes, replicas, n_devices,
                      leg_timeout)
        rows.append(row)
        print(json.dumps(row), flush=True)
        if row["status"] != "ok" and not probe(n_devices):
            # the failed leg wedged the backend: fail the REMAINING rows
            # structurally instead of hanging each one in turn
            aborted = True
    if elastic:
        # meshes/replicas DERIVED from the device count so the banked row
        # always describes the run (8 devices: 4x2 -> 4x1, the default)
        if aborted:
            row = {"leg": "elastic_resume", "status": "failed",
                   "reason": "skipped: backend probe failed after "
                             "an earlier leg"}
        elif n_devices < 2 or n_devices % 2:
            row = {"leg": "elastic_resume", "status": "failed",
                   "reason": f"--elastic needs an even device count >= 2 "
                             f"to halve, got {n_devices}"}
        else:
            half = n_devices // 2
            row = elastic_leg(f"{half}x2", f"{half}x1",
                              from_devices=n_devices, to_devices=half,
                              replicas=n_devices, timeout=leg_timeout * 2)
        rows.append(row)
        print(json.dumps(row), flush=True)

    ok_carvings = [r for r in rows
                   if r.get("leg") == "carving" and r["status"] == "ok"]
    # tp legs trade bit-equality for psum-parallel compute: they NEVER
    # join the digest set — they gate on the curve-envelope bands below
    exact = [r for r in ok_carvings if r.get("rules") != "tp"]
    tp_legs = [r for r in ok_carvings if r.get("rules") == "tp"]
    digests = {r["digest"] for r in exact}
    sharded_proven = any(r.get("sharded_leaves", 0) > 0
                         for r in ok_carvings)
    all_ok = all(r["status"] == "ok" for r in rows)
    exact_requested = [r for r in rows if r.get("leg") == "carving"
                       and r.get("rules") != "tp"]
    # a tp-ONLY matrix has no digest claim to make — bit-equality is
    # vacuously true and the tp gate below reports the real problem
    # ("no bit-exact control leg to band against"), not an empty set
    bit_equal = len(exact) == len(exact_requested) \
        and (len(digests) == 1 if exact_requested else True)
    tp_verdicts = _gate_tp_legs(tp_legs, exact)
    tp_clean = all(v["ok"] for v in tp_verdicts)
    verdict = {
        "status": "ok" if (all_ok and bit_equal and tp_clean)
        else "failed",
        "mode": "mesh_matrix", "devices": n_devices,
        "legs_ok": len([r for r in rows if r["status"] == "ok"]),
        "legs_total": len(rows),
        "bit_equal_across_carvings": bit_equal,
        "sharded_params_proven": sharded_proven,
    }
    if tp_legs:
        verdict["tp_legs"] = len(tp_legs)
        verdict["tp_within_band"] = tp_clean
        verdict["tp_envelope"] = tp_verdicts
    if not all_ok:
        verdict["reason"] = "; ".join(
            f"{r.get('mesh', r.get('leg'))}: {r['reason']}"
            for r in rows if r["status"] != "ok")[:500]
    elif not bit_equal:
        verdict["reason"] = (f"final-state digests diverge across "
                             f"carvings: {sorted(digests)}")
    elif not tp_clean:
        verdict["reason"] = "; ".join(
            f"tp {v['mesh']}: {v['reason']}"
            for v in tp_verdicts if not v["ok"])[:500]
    print(json.dumps(verdict), flush=True)
    if bank:
        artifact = {**verdict, "ok": verdict["status"] == "ok",
                    "legs": rows}
        tmp = bank + ".tmp"
        with open(tmp, "w") as f:
            json.dump(artifact, f, indent=1)
        os.replace(tmp, bank)
        print(f"[dryrun] banked {bank}", file=sys.stderr)
    return 0 if verdict["status"] == "ok" else 1


# ----------------------------------------------------------- multi-process
def launch(procs: int, devices_per_proc: int, timeout: int = 600) -> int:
    import tempfile

    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if k != "PALLAS_AXON_POOL_IPS"}  # never touch the TPU plugin
    workers = []
    for pid in range(procs):
        env = dict(env_base)
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_proc}")
        # workers write to FILES, not pipes: they block on collectives
        # together, and one worker stalling on a full 64 KB stdout pipe
        # while the launcher drains another would deadlock the whole run
        log = tempfile.NamedTemporaryFile(mode="w+", prefix=f"mh{pid}_",
                                          suffix=".log", delete=False)
        workers.append((subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid), str(procs), str(port), str(devices_per_proc)],
            env=env, stdout=log, stderr=subprocess.STDOUT), log))
    rc = 0
    timed_out = []
    deadline = time.time() + timeout
    for pid, (w, log) in enumerate(workers):
        try:
            w.wait(timeout=max(deadline - time.time(), 1))
        except subprocess.TimeoutExpired:
            w.kill()
            w.wait()
            timed_out.append(pid)
            rc = rc or 124
        log.flush()
        log.seek(0)
        out = log.read()
        log.close()
        os.unlink(log.name)
        sys.stderr.write(f"--- worker {pid} (rc={w.returncode}) ---\n"
                         + out[-2000:])
        if pid == 0 and w.returncode == 0:
            for line in out.splitlines():
                if line.startswith("dryrun_multihost"):
                    print(line)
        rc = rc or w.returncode
    if rc != 0:
        # bench.py failed-row discipline: a structured reason the caller
        # (and any banked artifact) can read, never just a log tail
        print(json.dumps({
            "status": "failed", "mode": "multi_process",
            "procs": procs, "devices_per_proc": devices_per_proc,
            "reason": (f"workers {timed_out} timed out after {timeout}s"
                       if timed_out else f"a worker exited rc={rc}")}))
    return rc


def worker(pid: int, procs: int, port: int, devices_per_proc: int) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, REPO)
    from gsc_tpu.parallel.mesh import init_distributed

    init_distributed(coordinator=f"localhost:{port}",
                     num_processes=procs, process_id=pid)
    assert jax.process_count() == procs
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    print(f"[worker {pid}] global devices={n_global} local={n_local}")
    assert n_local == devices_per_proc, (n_local, devices_per_proc)

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.parallel.mesh import make_hybrid_mesh
    from gsc_tpu.sim.traffic import generate_traffic

    env, agent, topo, _ = _flagship(max_nodes=8, max_edges=8,
                                    episode_steps=2, max_flows=32,
                                    gen_traffic=False)
    B = n_global            # one env replica per global device
    B_local = n_local
    mesh = make_hybrid_mesh()           # [procs, local] (dcn, dp)
    spec = P(("dcn", "dp"))             # replicas sharded over both axes

    def to_global(tree):
        return multihost_utils.host_local_array_to_global_array(
            tree, mesh, spec)

    # each process materializes only ITS replicas' traffic and replay shard
    local_seeds = range(pid * B_local, (pid + 1) * B_local)
    traffic = to_global(jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[generate_traffic(env.sim_cfg, env.service, topo, 2, seed=s)
          for s in local_seeds]))
    pddpg = ParallelDDPG(env, agent, num_replicas=B, sample_mode="local")

    # replicated inputs (identical on every process) pass as host values;
    # a single-replica reset builds the learner-init example
    one_traffic = generate_traffic(env.sim_cfg, env.service, topo, 2, seed=0)
    _, one_obs = env.reset(jax.random.PRNGKey(0), topo, one_traffic)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    # allocate only the LOCAL replay shard (global B still sizes capacity)
    buffers = to_global(pddpg.init_buffers(one_obs, num_replicas=B_local))

    with mesh:
        env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo,
                                          traffic)
        state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
            state, buffers, env_states, obs, topo, traffic, jnp.int32(0))
        state, metrics = pddpg.learn_burst(state, buffers)
        jax.block_until_ready((stats, metrics))

    # the reductions inside the jitted steps leave these fully replicated,
    # so every process can read them directly
    ret = float(stats["episodic_return"])
    loss = float(metrics["critic_loss"])
    if pid == 0:
        print(f"dryrun_multihost({procs}x{devices_per_proc}): ok — "
              f"return={ret:.3f} critic_loss={loss:.4f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--worker", nargs=4, type=int, default=None,
                    metavar=("PID", "PROCS", "PORT", "DEVS"))
    ap.add_argument("--timeout", type=int, default=600,
                    help="multi-process mode: whole-run budget")
    # ---- mesh-matrix mode -------------------------------------------
    ap.add_argument("--mesh-matrix", action="store_true",
                    help="run the pjit carving matrix instead of the "
                         "multi-process dryrun")
    ap.add_argument("--legs", default=DEFAULT_LEGS,
                    help="comma-separated DPxMP:rules carving legs "
                         f"(default {DEFAULT_LEGS})")
    ap.add_argument("--episodes", type=int, default=2)
    ap.add_argument("--replicas", type=int, default=8)
    ap.add_argument("--devices", type=int, default=8,
                    help="virtual CPU devices per carving leg")
    ap.add_argument("--leg-timeout", type=int, default=LEG_TIMEOUT,
                    help="per-leg subprocess budget (seconds)")
    ap.add_argument("--elastic", action="store_true",
                    help="add the 8-device -> 4-device --resume auto leg")
    ap.add_argument("--bank", default=None,
                    help="write the round as a MULTICHIP_r*.json artifact")
    ap.add_argument("--mesh-leg", nargs=4, default=None,
                    metavar=("SHAPE", "RULES", "EPISODES", "REPLICAS"),
                    help=argparse.SUPPRESS)   # internal: one carving leg
    args = ap.parse_args()
    if args.worker is not None:
        worker(*args.worker)
    elif args.mesh_leg is not None:
        shape, rules, episodes, replicas = args.mesh_leg
        mesh_leg(shape, rules, int(episodes), int(replicas))
    elif args.mesh_matrix:
        sys.exit(run_matrix(args.legs, args.episodes, args.replicas,
                            args.devices, args.leg_timeout, args.elastic,
                            args.bank))
    else:
        sys.exit(launch(args.procs, args.devices_per_proc, args.timeout))


if __name__ == "__main__":
    main()
