"""Multi-HOST dryrun: the full sharded train step across N separate
processes, each owning a slice of a virtual CPU mesh.

``dryrun_multichip`` (driver contract) proves the multi-chip shardings on
one process; this tool proves the MULTI-PROCESS half of the distributed
backend (VERDICT r3 missing #1): ``jax.distributed.initialize`` over a
localhost coordinator, a global mesh built from all processes' devices,
per-process host data fed in via ``host_local_array_to_global_array``,
and one rollout+learn step whose gradient psum crosses process boundaries.
No TPU needed — same SPMD code path a v5e-16 data-parallel run takes,
with gRPC standing in for ICI/DCN.

Launcher::

    python tools/dryrun_multihost.py                 # 2 procs x 4 devices
    python tools/dryrun_multihost.py --procs 2 --devices-per-proc 2

Each worker prints its local view; process 0 prints the final
``dryrun_multihost(P x D): ok`` line the caller greps for.
"""
from __future__ import annotations

import argparse
import os
import socket
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("localhost", 0))
        return s.getsockname()[1]


def launch(procs: int, devices_per_proc: int, timeout: int = 600) -> int:
    import tempfile

    port = _free_port()
    env_base = {k: v for k, v in os.environ.items()
                if k != "PALLAS_AXON_POOL_IPS"}  # never touch the TPU plugin
    workers = []
    for pid in range(procs):
        env = dict(env_base)
        env["XLA_FLAGS"] = (
            f"--xla_force_host_platform_device_count={devices_per_proc}")
        # workers write to FILES, not pipes: they block on collectives
        # together, and one worker stalling on a full 64 KB stdout pipe
        # while the launcher drains another would deadlock the whole run
        log = tempfile.NamedTemporaryFile(mode="w+", prefix=f"mh{pid}_",
                                          suffix=".log", delete=False)
        workers.append((subprocess.Popen(
            [sys.executable, os.path.abspath(__file__), "--worker",
             str(pid), str(procs), str(port), str(devices_per_proc)],
            env=env, stdout=log, stderr=subprocess.STDOUT), log))
    rc = 0
    deadline = time.time() + timeout
    for pid, (w, log) in enumerate(workers):
        try:
            w.wait(timeout=max(deadline - time.time(), 1))
        except subprocess.TimeoutExpired:
            w.kill()
            w.wait()
            rc = rc or 124
        log.flush()
        log.seek(0)
        out = log.read()
        log.close()
        os.unlink(log.name)
        sys.stderr.write(f"--- worker {pid} (rc={w.returncode}) ---\n"
                         + out[-2000:])
        if pid == 0 and w.returncode == 0:
            for line in out.splitlines():
                if line.startswith("dryrun_multihost"):
                    print(line)
        rc = rc or w.returncode
    return rc


def worker(pid: int, procs: int, port: int, devices_per_proc: int) -> None:
    import jax
    jax.config.update("jax_platforms", "cpu")

    sys.path.insert(0, REPO)
    from gsc_tpu.parallel.mesh import init_distributed

    init_distributed(coordinator=f"localhost:{port}",
                     num_processes=procs, process_id=pid)
    assert jax.process_count() == procs
    n_global = len(jax.devices())
    n_local = len(jax.local_devices())
    print(f"[worker {pid}] global devices={n_global} local={n_local}")
    assert n_local == devices_per_proc, (n_local, devices_per_proc)

    import jax.numpy as jnp
    from jax.experimental import multihost_utils
    from jax.sharding import PartitionSpec as P

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.parallel.mesh import make_hybrid_mesh
    from gsc_tpu.sim.traffic import generate_traffic

    env, agent, topo, _ = _flagship(max_nodes=8, max_edges=8,
                                    episode_steps=2, max_flows=32,
                                    gen_traffic=False)
    B = n_global            # one env replica per global device
    B_local = n_local
    mesh = make_hybrid_mesh()           # [procs, local] (dcn, dp)
    spec = P(("dcn", "dp"))             # replicas sharded over both axes

    def to_global(tree):
        return multihost_utils.host_local_array_to_global_array(
            tree, mesh, spec)

    # each process materializes only ITS replicas' traffic and replay shard
    local_seeds = range(pid * B_local, (pid + 1) * B_local)
    traffic = to_global(jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[generate_traffic(env.sim_cfg, env.service, topo, 2, seed=s)
          for s in local_seeds]))
    pddpg = ParallelDDPG(env, agent, num_replicas=B, sample_mode="local")

    # replicated inputs (identical on every process) pass as host values;
    # a single-replica reset builds the learner-init example
    one_traffic = generate_traffic(env.sim_cfg, env.service, topo, 2, seed=0)
    _, one_obs = env.reset(jax.random.PRNGKey(0), topo, one_traffic)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    # allocate only the LOCAL replay shard (global B still sizes capacity)
    buffers = to_global(pddpg.init_buffers(one_obs, num_replicas=B_local))

    with mesh:
        env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo,
                                          traffic)
        state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
            state, buffers, env_states, obs, topo, traffic, jnp.int32(0))
        state, metrics = pddpg.learn_burst(state, buffers)
        jax.block_until_ready((stats, metrics))

    # the reductions inside the jitted steps leave these fully replicated,
    # so every process can read them directly
    ret = float(stats["episodic_return"])
    loss = float(metrics["critic_loss"])
    if pid == 0:
        print(f"dryrun_multihost({procs}x{devices_per_proc}): ok — "
              f"return={ret:.3f} critic_loss={loss:.4f}")


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--procs", type=int, default=2)
    ap.add_argument("--devices-per-proc", type=int, default=4)
    ap.add_argument("--worker", nargs=4, type=int, default=None,
                    metavar=("PID", "PROCS", "PORT", "DEVS"))
    ap.add_argument("--timeout", type=int, default=600)
    args = ap.parse_args()
    if args.worker is not None:
        worker(*args.worker)
    else:
        sys.exit(launch(args.procs, args.devices_per_proc, args.timeout))


if __name__ == "__main__":
    main()
