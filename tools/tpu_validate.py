"""One-shot TPU validation runbook — run this the moment the axon tunnel
answers (``python tools/tpu_validate.py``).

Stages (each in a bounded-time subprocess so a fault can't wedge the
parent; results accumulate in TPU_VALIDATION.json):

1. probe     — backend init in a child with a timeout
2. pallas    — compiled (non-interpret) Pallas GAT kernel vs the dense
               XLA embedder on the flagship shapes (the interpret-mode
               parity test runs in CI; this validates the real kernel)
3. bench     — the flagship bench ladder (delegates to bench.py; B=256
               first, partial-result banking, compile cache)
4. learning  — a short full-scale learning-curve run with ON-DEVICE
               per-episode traffic (tools/learning_curve.py) — its wall
               rate vs the bench device rate closes the r3 sustained-
               throughput question
5. gnn_bench — dense vs Pallas embedder timings at replay-batch shapes
               (fwd and, via the round-4 custom VJP, fwd+bwd)
6. profile   — substep trace at B=256, top fusions by self-time (the
               20x-push evidence: batched-sort + threefry elision wins)
7. rung5     — BASELINE config 5 with the FLAGSHIP architecture (factored
               action head) at B=32: the r3 OOM must be gone

After these land, run the quality sweep separately (it is hours, not
minutes): ``python tools/quality_sweep.py --replicas 256 --episodes 24``
— priors from the CPU sweep (BENCH_NOTES): spend cells on lr x sigma,
skip longer learn bursts.
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PALLAS_CHECK = """
import sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, numpy as np
import __graft_entry__ as ge
from gsc_tpu.models.nets import Actor
env, agent, topo, traffic = ge._flagship()
_, obs = env.reset(jax.random.PRNGKey(0), topo, traffic)
import dataclasses
outs = {{}}
for impl in ("dense", "pallas"):
    a = Actor(agent=dataclasses.replace(agent, gnn_impl=impl),
              action_dim=env.limits.action_dim, gnn_impl=impl)
    params = a.init(jax.random.PRNGKey(1), obs)
    outs[impl] = np.asarray(jax.jit(a.apply)(params, obs))
# same init -> same params tree; kernels must agree numerically
diff = float(np.max(np.abs(outs["dense"] - outs["pallas"])))
rel = diff / (float(np.max(np.abs(outs["dense"]))) + 1e-9)
print("PALLAS_PARITY", diff, rel)
assert rel < 5e-2, (diff, rel)
"""


PROBE_CODE = "import jax; print(jax.devices())"


DEFAULT_OUT = os.path.join(REPO, "TPU_VALIDATION.json")


def _save(results, out_path=None):
    with open(out_path or DEFAULT_OUT, "w") as f:
        json.dump(results, f, indent=1)


def _text(raw):
    """TimeoutExpired payloads are bytes (even with text=True) and can be
    truncated mid-UTF-8-sequence by the kill."""
    if isinstance(raw, bytes):
        return raw.decode(errors="replace")
    return raw or ""


def run_stage(name, cmd, timeout, results, out_path=None):
    t0 = time.time()
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True)
        ok = r.returncode == 0
        out = (r.stdout or "")[-1500:]
        err = (r.stderr or "")[-1500:]
    except subprocess.TimeoutExpired as e:
        # keep BOTH partial streams: bench/rung5 print banked measurement
        # lines to stdout after every episode, and the compile/fault
        # diagnostics land on stderr
        ok = False
        out = _text(e.stdout)[-1500:]
        err = (f"timeout after {timeout}s | "
               + _text(e.stderr))[-1500:]
    results[name] = {"ok": ok, "wall_s": round(time.time() - t0, 1),
                     "stdout_tail": out, "stderr_tail": err}
    print(f"[{name}] {'OK' if ok else 'FAIL'} "
          f"({results[name]['wall_s']}s)", file=sys.stderr)
    _save(results, out_path)
    return ok


def _probe(py, timeout=240):
    try:
        r = subprocess.run([py, "-c", PROBE_CODE], timeout=timeout,
                           capture_output=True, text=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def run_queue(stages, results, out_path=None, py=None):
    """Run bounded-subprocess stages with the probe-skip-bank protocol:
    after a FAILED stage, re-probe instead of burning each remaining
    stage's full timeout on a wedged backend.  Shared by this round-4
    validation queue and tools/chip_window.py (round-5 queue)."""
    py = py or sys.executable
    prev_ok = True
    for name, cmd, timeout in stages:
        if not prev_ok and not _probe(py):
            results[name] = {"ok": False, "skipped":
                             "backend unhealthy after previous stage"}
            print(f"[{name}] SKIP (backend unhealthy)", file=sys.stderr)
            _save(results, out_path)
            continue
        prev_ok = run_stage(name, cmd, timeout, results, out_path)
    return results


def main():
    results = {}
    py = sys.executable
    if not run_stage("probe", [py, "-c", PROBE_CODE], 240, results):
        print("TPU backend unreachable — nothing to validate",
              file=sys.stderr)
        sys.exit(1)
    # bench.py's own worst case (one grace rung + post-rung probe retries)
    # can reach ~5600 s; the stage cap must sit above it
    stages = [
        ("pallas", [py, "-c", _PALLAS_CHECK.format(repo=REPO)], 600),
        ("bench", [py, os.path.join(REPO, "bench.py")], 6000),
        ("learning",
         [py, os.path.join(REPO, "tools", "learning_curve.py"),
          "--replicas", "256", "--episodes", "12"], 3000),
        ("gnn_bench",
         [py, os.path.join(REPO, "tools", "gnn_bench.py")], 900),
        ("profile",
         [py, os.path.join(REPO, "tools", "profile_substep.py"),
          "--replicas", "256", "--chunk", "50"], 1500),
        ("rung5", [py, os.path.join(REPO, "bench.py"), "--worker",
                   "32", "10", "1", "rung5"], 2400),
    ]
    run_queue(stages, results)
    print(json.dumps(results.get("bench", {}), indent=1))


if __name__ == "__main__":
    main()
