"""One-shot TPU validation runbook — run this the moment the axon tunnel
answers (``python tools/tpu_validate.py``).

Stages (each in a bounded-time subprocess so a fault can't wedge the
parent; results accumulate in TPU_VALIDATION.json):

1. probe     — backend init in a child with a timeout
2. pallas    — compiled (non-interpret) Pallas GAT kernel vs the dense
               XLA embedder on the flagship shapes (the interpret-mode
               parity test runs in CI; this validates the real kernel)
3. bench     — the flagship bench ladder (delegates to bench.py)
4. learning  — a short full-scale learning-curve run (tools/learning_curve.py)
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_PALLAS_CHECK = """
import sys
sys.path.insert(0, {repo!r})
import jax, jax.numpy as jnp, numpy as np
import __graft_entry__ as ge
from gsc_tpu.models.nets import Actor
env, agent, topo, traffic = ge._flagship()
_, obs = env.reset(jax.random.PRNGKey(0), topo, traffic)
import dataclasses
outs = {{}}
for impl in ("dense", "pallas"):
    a = Actor(agent=dataclasses.replace(agent, gnn_impl=impl),
              action_dim=env.limits.action_dim, gnn_impl=impl)
    params = a.init(jax.random.PRNGKey(1), obs)
    outs[impl] = np.asarray(jax.jit(a.apply)(params, obs))
# same init -> same params tree; kernels must agree numerically
diff = float(np.max(np.abs(outs["dense"] - outs["pallas"])))
rel = diff / (float(np.max(np.abs(outs["dense"]))) + 1e-9)
print("PALLAS_PARITY", diff, rel)
assert rel < 5e-2, (diff, rel)
"""


def run_stage(name, cmd, timeout, results):
    t0 = time.time()
    try:
        r = subprocess.run(cmd, timeout=timeout, capture_output=True,
                           text=True)
        ok = r.returncode == 0
        out = (r.stdout or "")[-1500:]
        err = (r.stderr or "")[-1500:]
    except subprocess.TimeoutExpired:
        ok, out, err = False, "", f"timeout after {timeout}s"
    results[name] = {"ok": ok, "wall_s": round(time.time() - t0, 1),
                     "stdout_tail": out, "stderr_tail": err}
    print(f"[{name}] {'OK' if ok else 'FAIL'} "
          f"({results[name]['wall_s']}s)", file=sys.stderr)
    with open(os.path.join(REPO, "TPU_VALIDATION.json"), "w") as f:
        json.dump(results, f, indent=1)
    return ok


def main():
    results = {}
    py = sys.executable
    if not run_stage("probe", [py, "-c",
                               "import jax; print(jax.devices())"],
                     240, results):
        print("TPU backend unreachable — nothing to validate",
              file=sys.stderr)
        sys.exit(1)
    run_stage("pallas", [py, "-c", _PALLAS_CHECK.format(repo=REPO)],
              600, results)
    run_stage("bench", [py, os.path.join(REPO, "bench.py")], 3600, results)
    run_stage("learning",
              [py, os.path.join(REPO, "tools", "learning_curve.py"),
               "--replicas", "64", "--episodes", "12"], 3000, results)
    print(json.dumps(results["bench"], indent=1))


if __name__ == "__main__":
    main()
