"""Async actor/learner smoke: the decoupled rollout/learn path end to
end through the real CLI.

The CI-stage proof that ``cli train --async`` actually executes the
Sebulba-style split: a tiny 3-episode, 2-replica, 2-actor CPU train run
must

- exit 0 with ``run_start`` recording the async knobs and the stream
  carrying one ``episode`` event per episode (completion order, episode
  index on every event) plus the drain-proved ``async_train`` tail
  (produced == ingested, zero transitions lost),
- stream with ZERO retraces: EXACTLY one trace each for
  ``rollout_episodes`` / ``reset_all`` / ``learn_burst`` /
  ``replay_ingest`` across every actor/learner interleaving
  (``--no-perf`` so the AOT capture does not add its own trace),
- land the staleness/decoupling gauges in metrics.json: ``policy_lag``,
  ``replay_lag``, ``learner_idle_frac``, ``replay_fill_frac`` and the
  ``actor_dispatch``/``learner_idle`` phase histograms,
- keep the learner-idle fraction under a GENEROUS smoke threshold
  (0.95 — a 3-episode CPU run is compile-dominated; the real <0.10
  bound is tools/async_bench.py's gate at measured steady state),
- gate through ``bench_diff``: an ASYNC-shaped row self-compares clean
  (rc 0) while an injected env-steps/s regression is caught (rc 1).

A second FORCED-4-DEVICE stage (fresh subprocess,
``--xla_force_host_platform_device_count=4`` — the parent's jax is
already initialised single-device) proves the ``--async --mesh``
composition end to end: ``cli train --async --mesh 4x1`` must exit 0
with the replay ring dp-sharded over all 4 devices
(``async_train.ring_shards == 4``) and ZERO collectives on the
compiled ingest (``ingest_collectives == 0`` — HLO-mined at prewarm),
the same one-trace-per-entry-point contract as the single-device
stage, a publisher version adopted by BOTH consumers — an actor
(an episode acted under ``policy_version >= 1``) and a serve-side
``VersionWatcher`` polling the ``--hot-swap-dir`` root — and a tp-only
mesh (``--mesh 1x4``) refused with recarve instructions.

Run by ``tools/ci_check.sh`` after the scenario stage; standalone:

    JAX_PLATFORMS=cpu python tools/async_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

EPISODES = 3
ACTORS = 2
# compile-dominated tiny run: this only proves the ledger exists and is
# sane, not the steady-state decoupling claim (async_bench owns that)
SMOKE_IDLE_MAX = 0.95
# the mesh stage: enough episodes that a published version is adopted
# by a later-acting episode DETERMINISTICALLY under the default
# max_staleness=0 backpressure bound (two episodes per actor ahead max:
# by episode index >= 4 at least one burst has published)
MESH_DEVICES = 4
MESH_EPISODES = 6
MESH_TIMEOUT_S = 900


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"async smoke: FAIL — {msg}")
    return 1


def mesh_worker() -> int:
    """The forced-4-device stage body (own subprocess: the parent's jax
    is already initialised with one device)."""
    _configure_jax()
    import jax

    if len(jax.devices()) != MESH_DEVICES:
        return fail(f"mesh stage needs {MESH_DEVICES} forced host "
                    f"devices, found {len(jax.devices())}")
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    tmp = tempfile.mkdtemp(prefix="gsc_async_mesh_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    hot = os.path.join(tmp, "hot")

    # a tp-only carving of the same 4 devices is refused up front, with
    # recarve instructions, before any compile
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "1", "--replicas", "4",
        "--async", "--mesh", "1x4",
        "--result-dir", os.path.join(tmp, "refused")])
    if r.exit_code == 0 or "dp" not in r.output:
        return fail(f"tp-only --async --mesh 1x4 not refused "
                    f"(rc={r.exit_code}): {r.output[-500:]}")

    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(MESH_EPISODES),
        "--replicas", str(MESH_DEVICES), "--chunk", "3",
        "--async", "--async-actors", str(ACTORS),
        "--mesh", f"{MESH_DEVICES}x1",
        "--hot-swap-dir", hot, "--publish-interval", "1",
        "--no-perf",
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --async --mesh")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]
    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]

    # the composed-path accounting tail: ring sharded over every device,
    # zero collectives on the compiled ingest, nothing lost
    at = [e for e in events if e["event"] == "async_train"]
    if not at:
        return fail("no async_train accounting event in the stream")
    info = at[-1]
    if info.get("ring_shards") != MESH_DEVICES:
        return fail(f"ring_shards {info.get('ring_shards')} != "
                    f"{MESH_DEVICES} — the replay ring did not shard "
                    "over the mesh")
    if info.get("ingest_collectives") != 0:
        return fail(f"ingest_collectives {info.get('ingest_collectives')}"
                    " — the dp-sharded ingest is paying a gather/reshard")
    if info.get("mesh") != f"{MESH_DEVICES}x1":
        return fail(f"async_train mesh {info.get('mesh')!r}")
    if info["produced_steps"] != info["ingested_steps"] \
            or info["transitions_lost"] != 0:
        return fail(f"drain accounting broken under mesh: {info}")
    if info.get("publishes", 0) < 1:
        return fail(f"no publishes under mesh: {info}")

    # zero retrace after warmup, same contract as the single-device
    # stage: the sharded dispatch is PRE-built before actor threads
    # start, the ingest is AOT-compiled at prewarm (its one .lower()
    # counts as the single trace)
    traces = {}
    for e in events:
        if e["event"] == "compile" and e.get("stage") == "trace":
            traces[e["fn"]] = e.get("count")
    for fn in ("rollout_episodes", "reset_all", "learn_burst"):
        if traces.get(fn) != 1:
            return fail(f"expected exactly 1 {fn} trace under --mesh, "
                        f"saw {traces.get(fn)} (all: {traces})")
    if (traces.get("replay_ingest") or 0) > 1:
        return fail(f"replay_ingest traced {traces.get('replay_ingest')} "
                    f"times (want <= 1): {traces}")

    # publisher adoption, consumer 1 — an actor: with publish-interval 1
    # and the default staleness bound, a later episode must have ACTED
    # under a published version
    eps = [e for e in events if e["event"] == "episode"]
    if sorted(e["episode"] for e in eps) != list(range(MESH_EPISODES)):
        return fail(f"episode events cover "
                    f"{sorted(e['episode'] for e in eps)}")
    top_ver = max(e.get("policy_version", 0) for e in eps)
    if top_ver < 1:
        return fail("no actor adopted a published version "
                    f"(max episode policy_version {top_ver})")

    # publisher adoption, consumer 2 — a serve watcher polling the SAME
    # hot-swap root the learner published to (the one-publisher
    # contract: learner actors and the serving fleet read the same
    # bytes)
    from gsc_tpu.serve.fleet import VersionWatcher, read_latest

    rec = read_latest(hot)
    if rec is None or rec.get("version", 0) < 1:
        return fail(f"hot-swap root has no published version: {rec}")

    class _Server:
        policy_version = 0
        fingerprint = None

        def apply_weights(self, leaves, version, fingerprint, meta=None):
            self.policy_version = version
            self.fingerprint = fingerprint

    srv = _Server()
    watcher = VersionWatcher(hot, srv, publisher=None)
    if not watcher.poll_once():
        return fail("serve watcher did not swap to the published version")
    if srv.policy_version != rec["version"]:
        return fail(f"watcher adopted {srv.policy_version}, latest.json "
                    f"says {rec['version']}")

    print("async mesh smoke: OK — "
          f"{MESH_EPISODES} episodes over {ACTORS} actors on a "
          f"{MESH_DEVICES}x1 mesh, ring_shards={info['ring_shards']}, "
          f"ingest_collectives={info['ingest_collectives']}, "
          f"1 trace per entry point ({traces}), actor adopted v{top_ver}, "
          f"serve watcher adopted v{srv.policy_version}, tp-only refused")
    return 0


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    tmp = tempfile.mkdtemp(prefix="gsc_async_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(EPISODES), "--replicas", "2",
        "--chunk", "3", "--async", "--async-actors", str(ACTORS),
        "--no-perf",   # the AOT cost capture would add its own trace —
                       # this stage pins the DISPATCH trace counts
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --async")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    run_start = next(e for e in events if e["event"] == "run_start")
    knobs = run_start.get("async") or {}
    if knobs.get("actors") != ACTORS:
        return fail(f"run_start async knobs missing/wrong: {knobs}")

    # one episode event per episode, each stamped with its actor + the
    # policy version it acted under (completion order is allowed to
    # differ from index order — the index rides on every event)
    eps = [e for e in events if e["event"] == "episode"]
    if sorted(e["episode"] for e in eps) != list(range(EPISODES)):
        return fail(f"episode events cover "
                    f"{sorted(e['episode'] for e in eps)}, want "
                    f"{list(range(EPISODES))}")
    if not all("policy_version" in e and "actor" in e for e in eps):
        return fail("episode events missing actor/policy_version stamps")

    # the drain-proved tail: nothing lost, everything ingested
    at = [e for e in events if e["event"] == "async_train"]
    if not at:
        return fail("no async_train accounting event in the stream")
    info = at[-1]
    if info["produced_steps"] != info["ingested_steps"] \
            or info["transitions_lost"] != 0:
        return fail(f"drain accounting broken: {info}")
    if not (0.0 <= info["learner_idle_frac"] <= SMOKE_IDLE_MAX):
        return fail(f"learner_idle_frac {info['learner_idle_frac']} "
                    f"outside [0, {SMOKE_IDLE_MAX}]")

    # ZERO retraces across every actor/learner interleaving: exactly one
    # trace per async entry point (a second rollout_episodes trace means
    # an actor raced the jit cache; a second replay_ingest means the
    # ring/block shapes became a compile axis)
    traces = {}
    for e in events:
        if e["event"] == "compile" and e.get("stage") == "trace":
            traces[e["fn"]] = e.get("count")
    for fn in ("rollout_episodes", "reset_all", "learn_burst",
               "replay_ingest"):
        if traces.get(fn) != 1:
            return fail(f"expected exactly 1 {fn} trace across the async "
                        f"interleavings, saw {traces.get(fn)} "
                        f"(all: {traces})")

    # staleness/decoupling gauges + phase histograms in the snapshot
    snap = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    for g in ("gsc_policy_lag", "gsc_replay_lag", "gsc_learner_idle_frac",
              "gsc_replay_fill_frac", "gsc_actor_policy_version"):
        if not any(k.startswith(g + "{") for k in snap):
            return fail(f"metrics.json missing gauge {g}")
    for ph in ("actor_dispatch", "learner_idle", "replay_ingest"):
        if not any(f'phase="{ph}"' in k for k in snap):
            return fail(f"metrics.json missing phase histogram {ph!r}")
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        return fail(f"stream tail {end}")

    # bench_diff gate over an ASYNC-shaped row: self-compare clean,
    # injected env-steps/s regression caught
    import bench_diff
    rate = (eps[-1].get("sps") if eps else None) or 1.0
    row = {"metric": "env_steps_per_sec_per_chip", "status": "ok",
           "async_actors": ACTORS,
           "sync_sps": round(float(rate), 2),
           "async2_sps": round(float(rate), 2),
           "learner_idle_frac": round(float(info["learner_idle_frac"]), 4),
           "jit_traces_async2": {fn: traces[fn] for fn in
                                 ("rollout_episodes", "reset_all",
                                  "learn_burst", "replay_ingest")}}
    row_path = os.path.join(tmp, "ASYNC_r99.json")
    with open(row_path, "w") as f:
        json.dump(row, f)
    traj = os.path.join(tmp, "traj.json")
    doc = bench_diff.ingest([row_path], traj)
    if "ASYNC_r99" not in doc["rows"]:
        return fail("bench_diff ingest did not scan the ASYNC row")
    got = doc["rows"]["ASYNC_r99"]["metrics"]
    if "learner_idle_frac" not in got or "async2_sps" not in got \
            or "async2_replay_ingest_jit_traces" not in got:
        return fail(f"ASYNC row metrics incomplete: {sorted(got)}")
    rc = bench_diff.main(["diff", "ASYNC_r99", "--baseline", "ASYNC_r99",
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"ASYNC self-compare rc={rc} (want 0)")
    bad = dict(row, async2_sps=round(float(rate) * 0.5, 2))
    bad_path = os.path.join(tmp, "ASYNC_bad.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", "ASYNC_r99",
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected env-steps/s regression rc={rc} (want 1)")

    print(f"async smoke: OK — {EPISODES} episodes over {ACTORS} actors "
          f"with 1 trace per entry point ({traces}), "
          f"produced==ingested=={info['ingested_steps']}, "
          f"learner_idle_frac={info['learner_idle_frac']}, "
          "ASYNC row gated both directions")

    # stage 2: the --async --mesh composition on 4 forced host devices
    # (fresh subprocess — THIS process's jax initialised single-device)
    import subprocess
    env = {**os.environ, "JAX_PLATFORMS": "cpu",
           "XLA_FLAGS":
           f"--xla_force_host_platform_device_count={MESH_DEVICES}"}
    env.pop("PALLAS_AXON_POOL_IPS", None)
    try:
        out = subprocess.run(
            [sys.executable, os.path.abspath(__file__), "--worker-mesh"],
            capture_output=True, text=True, timeout=MESH_TIMEOUT_S,
            env=env)
    except subprocess.TimeoutExpired:
        return fail(f"mesh stage timed out after {MESH_TIMEOUT_S}s")
    tail = (out.stdout + out.stderr).strip().splitlines()
    for line in tail[-25:]:
        print(f"  [mesh] {line}")
    if out.returncode != 0:
        return fail(f"mesh stage rc={out.returncode}")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    if "--worker-mesh" in sys.argv:
        sys.exit(mesh_worker())
    sys.exit(main())
