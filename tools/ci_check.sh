#!/usr/bin/env bash
# One-entry-point CI gate: static analysis first (cheap, catches the
# jit-discipline regressions gsc-lint encodes), then the report selftest,
# then the tier-1 pytest command from ROADMAP.md.  A new unsuppressed
# gsc-lint finding fails the gate BEFORE any test compiles — suppress it
# in tools/gsc_lint_baseline.json (with a written reason) only when it is
# an accepted trace-time case, otherwise fix it.
#
# Usage: bash tools/ci_check.sh [--lint-only]
set -euo pipefail
cd "$(dirname "$0")/.."

echo "== gsc-lint (rules R1-R10, baseline: tools/gsc_lint_baseline.json) =="
# the summary line carries a stale-suppression count when the baseline
# has drifted — `python tools/gsc_lint.py --prune-stale` clears it
python tools/gsc_lint.py gsc_tpu/ tools/ bench.py

echo "== gsc-lint self-check (concurrency rules must catch a seeded inversion) =="
# negative control: a throwaway ABBA lock-order fixture MUST fail the
# linter — if it passes, the R6-R10 pass is wired out of the gate and
# the green lint stage above is meaningless.  Explicit rm (not a trap:
# the tier-1 EXIT trap below would override it).
SELFCHECK_DIR=$(mktemp -d /tmp/gsc_lint_selfcheck.XXXXXX)
cat > "$SELFCHECK_DIR/inversion.py" <<'PYEOF'
import threading


class Inverted:
    def __init__(self):
        self.a_lock = threading.Lock()
        self.b_lock = threading.Lock()

    def fwd(self):
        with self.a_lock:
            with self.b_lock:
                pass

    def rev(self):
        with self.b_lock:
            with self.a_lock:
                pass
PYEOF
if python tools/gsc_lint.py --no-baseline -q "$SELFCHECK_DIR/inversion.py" \
        >/dev/null 2>&1; then
    rm -rf "$SELFCHECK_DIR"
    echo "ci_check: FAIL — gsc-lint passed a seeded lock-order inversion" >&2
    exit 1
fi
rm -rf "$SELFCHECK_DIR"
echo "ci_check: self-check OK (seeded inversion rejected)"

echo "== obs_report selftest (event-schema smoke) =="
python tools/obs_report.py --selftest

if [[ "${1:-}" == "--lint-only" ]]; then
    echo "ci_check: lint-only pass OK"
    exit 0
fi

echo "== megakernel interpret-parity smoke (pallas substep == xla) =="
# one fast scenario through both substep impls, full post-interval state
# bit-compared (the standalone `pytest -m megakernel` group runs the whole
# battery inside tier-1 below; this stage fails FAST and by name when the
# kernel drifts)
env JAX_PLATFORMS=cpu python -m pytest \
    "tests/test_megakernel.py::test_megakernel_parity_smoke" -q \
    -p no:cacheprovider

echo "== serve smoke (AOT policy serving: cold compile -> cache-hit restart) =="
# tiny checkpoint -> in-process server -> N requests twice: run 1 must
# write the compiled-policy artifacts and record p99; run 2 must hit the
# cache on every bucket (tools/serve_smoke.py asserts rc, events, hits)
env JAX_PLATFORMS=cpu python tools/serve_smoke.py

echo "== multihost smoke (pjit carving bit-equality + tp envelope) =="
# three fresh-subprocess carving legs — replicated and sharded must land
# BIT-identical final learner states over the same 8 virtual CPU
# devices, and the 1x2 tp leg (true tensor-parallel compute, psum
# partial products) must land inside the bench_diff curve-envelope
# bands vs those controls (tp never joins the digest set — banded
# acceptance IS its contract).  The tool exits nonzero on digest
# divergence, an out-of-band tp leg, a failed leg, or a wedged backend,
# with structured {"status":"failed","reason":...} rows, never a bare
# tail
env JAX_PLATFORMS=cpu python tools/dryrun_multihost.py --mesh-matrix \
    --legs "8x1:replicated,4x2:sharded,1x2:tp" --leg-timeout 420

echo "== tp smoke (tensor-parallel CLI run -> collectives in perf.json + curve gate) =="
# a tiny real-CLI train run on a 1x2 mesh with --partition-rules tp must
# rc=0 with run_start recording the tp book, perf.json carrying the
# partitioned executable's all-reduce count/bytes next to the
# carving-comparable plain capture, and the curves envelope gating
# through bench_diff (self-compare rc 0, injected regression rc 1) —
# tools/tp_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/tp_smoke.py

echo "== mixtopo smoke (mixed-topology batch: 2 networks, one dispatch) =="
# a tiny 2-episode train run with --topo-mix "schedule,line3" must exit 0
# with per-topology return gauges in metrics.json and per_topology_return
# on every harness_episode event (tools/mixtopo_smoke.py asserts both
# plus the run_end status and the run_start topo_mix tag)
env JAX_PLATFORMS=cpu python tools/mixtopo_smoke.py

echo "== perfobs smoke (cost ledger -> perf.json + trace export + bench_diff) =="
# a tiny train run must write a complete perf.json cost ledger (FLOPs/
# bytes/fusions/MFU for episode_step), its rotated events stream must
# export as VALID trace-event JSON, and bench_diff must self-compare
# clean while failing an injected synthetic regression
# (tools/perfobs_smoke.py asserts all three)
env JAX_PLATFORMS=cpu python tools/perfobs_smoke.py

echo "== learnobs smoke (learn ledger -> curves.json + /metrics + bench_diff gate) =="
# a tiny mixed-topology train run must write a complete curves.json
# (return/TD series + per-topology coverage of both mixture members +
# envelope summary), land learn_signal events + td/grad/topology gauges,
# scrape cleanly over the /metrics endpoint, and gate through bench_diff
# (self-compare rc 0, injected curve regression rc 1) —
# tools/learnobs_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/learnobs_smoke.py

echo "== serveobs smoke (request tracing + SLO engine -> slo.json + trace + gate) =="
# a tiny SPR-tier serve run with --trace-sample 1 and a deliberately low
# --slo-p99-ms must write a complete slo.json (attainment/burn/deadline-
# miss/pad-waste/decomposition), leave sampled request spans that export
# as a VALID trace with request->flush flow arrows, scrape cleanly over
# /metrics (live queue-depth probe current), and gate through bench_diff
# (self-compare rc 0, injected SLO regression rc 1) —
# tools/serveobs_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/serveobs_smoke.py

echo "== fleet smoke (continuous batching + hot-swap under load, 2 workers) =="
# a 2-worker SPR-tier real-CLI run with --continuous and one forced
# hot-swap must rc=0 with ZERO dropped requests, policy_version on every
# serve_flush event, per-worker queue gauges in the /metrics exposition,
# weight_swap events from both workers, and the fleet-merged slo.json
# gating through bench_diff (self-compare rc 0, injected p99 regression
# rc 1) — tools/fleet_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/fleet_smoke.py

echo "== scenario smoke (on-device factory + auto-curriculum, zero retraces) =="
# a tiny 3-episode factory train run (--topo-mix factory:... --no-perf)
# must rc=0 with EXACTLY one trace each for factory_sample/reset_all/
# chunk_step across the randomized scenario stream, one curriculum event
# per episode with floored weights, curriculum_weight{family=} gauges in
# metrics.json AND over a live /metrics scrape, and a SCEN-shaped row
# gating through bench_diff (self-compare rc 0, injected env-steps/s
# regression rc 1) — tools/scenario_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/scenario_smoke.py

echo "== async smoke (decoupled actor/learner through the real CLI) =="
# a tiny 3-episode --async run (2 replicas, 2 actors, --no-perf) must
# rc=0 with EXACTLY one trace each for rollout_episodes/reset_all/
# learn_burst/replay_ingest across every actor/learner interleaving,
# the drain-proved async_train tail (produced == ingested, zero lost),
# policy_lag/replay_lag/learner_idle_frac gauges + actor/learner phase
# histograms in metrics.json, and an ASYNC-shaped row gating through
# bench_diff (self-compare rc 0, injected env-steps/s regression rc 1)
# — tools/async_smoke.py asserts all of it.  Its second stage forces 4
# host devices in a fresh subprocess and proves the --async --mesh 4x1
# composition: ring dp-sharded over all 4 devices, ZERO collectives on
# the compiled ingest, one trace per entry point, a published version
# adopted by an actor AND a serve VersionWatcher off --hot-swap-dir,
# tp-only (1x4) refused with recarve instructions
env JAX_PLATFORMS=cpu python tools/async_smoke.py

echo "== flight smoke (series rings + async trace + black-box post-mortem) =="
# the same tiny --async run with the series recorder on must leave a
# schema-versioned series.json whose last ring points equal the final
# metrics.json gauges, an event stream that reconstructs a STRICT-
# validator-clean trace (per-actor tracks, channel residency, balanced
# publish->adopt flows), and a deliberately wedged fleet thread must
# stall BY NAME then escalate into blackbox.json; the ASYNC row's new
# policy_lag_p99/actor_idle_frac fields gate through bench_diff
# (self-compare rc 0, injected staleness blow-up rc 1) —
# tools/flight_smoke.py asserts all of it
env JAX_PLATFORMS=cpu python tools/flight_smoke.py

echo "== chaos smoke (resilience: injected faults must self-heal) =="
# two legs: a tiny CPU train run under an injected prefetcher death +
# NaN episode, then a fresh-subprocess real-CLI `train --async` run
# under actor_die@a0:1;ring_poison@2;learner_transient@3 — both must
# exit 0 with matching structured `recovery` events in events.jsonl;
# the async leg additionally proves the drain accounting (produced ==
# ingested, zero transitions lost past the quarantined block) and that
# no poisoned version was ever adopted (tools/chaos_smoke.py asserts
# all of it; `--round` banks the CHAOS_r* bench row with the mid-run
# SIGTERM + --resume auto continuation)
env JAX_PLATFORMS=cpu python tools/chaos_smoke.py

echo "== tier-1 tests (ROADMAP.md verify command) =="
# per-invocation log: concurrent ci_check runs must not interleave tees
# and corrupt each other's DOTS_PASSED tally
T1LOG=$(mktemp /tmp/ci_check_t1.XXXXXX.log)
trap 'rm -f "$T1LOG"' EXIT
# `|| rc=$?` keeps set -e from aborting at a red pytest pipeline — the
# DOTS_PASSED tally must print precisely on failing runs
rc=0
timeout -k 10 1200 env JAX_PLATFORMS=cpu python -m pytest tests/ -q \
    -m 'not slow' --continue-on-collection-errors -p no:cacheprovider \
    -p no:xdist -p no:randomly 2>&1 | tee "$T1LOG" || rc=$?
echo DOTS_PASSED=$(grep -aE '^[.FEsx]+( *\[ *[0-9]+%\])?$' "$T1LOG" \
    | tr -cd . | wc -c)
exit $rc
