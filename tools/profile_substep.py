"""Substep profiler: trace the engine's control-interval scan and rank
fusions by self-time.

The r3 perf unlocks all came from exactly this loop (trace -> aggregate ->
kill the dominant op class); this makes it a one-command repo tool instead
of ad-hoc /tmp scripts.  Captures a fresh jax.profiler trace of ``--calls``
chunked rollout calls at the given replica count, parses the
trace-events JSON (.gz) for the device track, and prints the top-K ops by
total self duration plus the per-substep wall.

    python tools/profile_substep.py --replicas 256 --chunk 50
    python tools/profile_substep.py --cpu --replicas 4 --chunk 5  # smoke

Only FRESH trace dirs are globbed (stale files double-count — r3 gotcha).
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=256)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--calls", type=int, default=3)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--episode-steps", type=int, default=200)
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    T, B, chunk = args.episode_steps, args.replicas, args.chunk
    env, agent, topo, _ = _flagship(episode_steps=T, gen_traffic=False)
    dt = DeviceTraffic(env.sim_cfg, env.service, topo, T)
    traffic = jax.jit(lambda k: dt.sample_batch(k, B))(jax.random.PRNGKey(0))
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    def call(state, buffers, env_states, obs, start):
        return pddpg.rollout_episodes(state, buffers, env_states, obs,
                                      topo, traffic, jnp.int32(start), chunk)

    # compile + warm
    out = call(state, buffers, env_states, obs, 0)
    jax.block_until_ready(out)
    state, buffers, env_states, obs = out[:4]

    trace_dir = tempfile.mkdtemp(prefix="substep_trace_")
    t0 = time.time()
    with jax.profiler.trace(trace_dir):
        for c in range(args.calls):
            out = call(state, buffers, env_states, obs, (c + 1) * chunk)
            state, buffers, env_states, obs = out[:4]
        jax.block_until_ready(out)
    wall = time.time() - t0

    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        print(json.dumps({"error": "no trace written", "dir": trace_dir}))
        return
    agg = collections.Counter()
    counts = collections.Counter()
    for fp in files:
        with gzip.open(fp, "rt") as f:
            data = json.load(f)
        events = data.get("traceEvents", [])
        # restrict to DEVICE lanes (XLA ops): host python/TSL lanes also
        # carry dur and would otherwise pollute the ranking.  pid names
        # come from process_name metadata events; fall back to all lanes
        # if no device track exists (plain CPU backend).
        dev_pids = {ev.get("pid") for ev in events
                    if ev.get("ph") == "M"
                    and ev.get("name") == "process_name"
                    and any(s in str((ev.get("args") or {}).get("name", ""))
                            .lower() for s in ("/device:", "tpu", "gpu",
                                               "xla"))}
        for ev in events:
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            if dev_pids and ev.get("pid") not in dev_pids:
                continue
            name = ev.get("name", "")
            args_d = ev.get("args") or {}
            key = args_d.get("long_name") or name
            agg[key.split("(")[0][:80]] += ev["dur"]
            counts[key.split("(")[0][:80]] += 1
    total = sum(agg.values())
    env_steps = args.calls * chunk * B
    print(json.dumps({
        "backend": jax.default_backend(), "replicas": B, "chunk": chunk,
        "calls": args.calls, "wall_s": round(wall, 3),
        "env_steps_per_sec": round(env_steps / wall, 1),
        "trace_total_us": total,
    }))
    width = max((len(k) for k, _ in agg.most_common(args.top)), default=10)
    for name, dur in agg.most_common(args.top):
        print(f"{dur/1e3:10.2f} ms  {100*dur/max(total,1):5.1f}%  "
              f"x{counts[name]:<6} {name:<{width}}")


if __name__ == "__main__":
    main()
