"""Substep profiler: trace the engine's control-interval scan and rank
fusions by self-time.

The r3 perf unlocks all came from exactly this loop (trace -> aggregate ->
kill the dominant op class); this makes it a one-command repo tool instead
of ad-hoc /tmp scripts.  Captures a fresh jax.profiler trace of ``--calls``
chunked rollout calls at the given replica count, parses the
trace-events JSON (.gz) for the device track, and prints the top-K ops by
total self duration plus the per-substep wall.

    python tools/profile_substep.py --replicas 256 --chunk 50
    python tools/profile_substep.py --cpu --replicas 4 --chunk 5  # smoke

Only FRESH trace dirs are globbed (stale files double-count — r3 gotcha).

``--mfu`` switches to the roofline sweep: for each replica count it lowers
the chunked rollout call, reads XLA's own per-executable cost analysis
(flops + bytes accessed — exact for the one-hot engine, whose FLOPs are
static dot shapes), times the call, and prints sustained FLOP/s vs chip
peak plus the arithmetic-intensity regime.  This is the VERDICT r4 item:
"what fraction of peak does the chip sustain, and is the substep
FLOP-bound or op-count-bound at B=256?"

    python tools/profile_substep.py --mfu --replicas 64 256 512
    python tools/profile_substep.py --mfu --cpu --replicas 2 4 --chunk 5
"""
from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


# TPU v5e (v5 lite) single-chip peaks; overridable for other parts.
PEAK_BF16_FLOPS = float(os.environ.get("GSC_PEAK_BF16_FLOPS", 197e12))
PEAK_HBM_BPS = float(os.environ.get("GSC_PEAK_HBM_BPS", 819e9))


def _build(env_steps, B, chunk):
    """Shared setup: flagship scenario, device traffic, chunked rollout."""
    import jax
    import jax.numpy as jnp

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    env, agent, topo, _ = _flagship(episode_steps=env_steps,
                                    gen_traffic=False)
    dt = DeviceTraffic(env.sim_cfg, env.service, topo, env_steps)
    traffic = jax.jit(lambda k: dt.sample_batch(k, B))(
        jax.random.PRNGKey(0))
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    def call(state, buffers, env_states, obs, start):
        return pddpg.rollout_episodes(state, buffers, env_states, obs,
                                      topo, traffic, jnp.int32(start), chunk)

    return call, (state, buffers, env_states, obs)


def _cost(compiled):
    """Flops/bytes from XLA's executable cost analysis (version-tolerant:
    older jaxlibs return a per-device list)."""
    ca = compiled.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0]
    flops = float(ca.get("flops", 0.0))
    byts = float(ca.get("bytes accessed", 0.0))
    return flops, byts


def mfu_sweep(args):
    """Roofline table: XLA-counted FLOPs/bytes per rollout call vs measured
    wall, at each replica count.  Regime call: compare the measured wall to
    the compute-roof time (flops/peak) and memory-roof time (bytes/bw) —
    if the wall dwarfs both roofs, the substep is op-COUNT (launch/fusion
    latency) bound, which is what the r3 trace showed pre-one-hot."""
    import jax

    from gsc_tpu.analysis.hlo import count_fusions

    chunk = args.chunk
    rows = []
    for B in args.replicas:
        call, carry = _build(args.episode_steps, B, chunk)
        lowered = jax.jit(call).lower(*carry, 0)
        compiled = lowered.compile()
        flops, byts = _cost(compiled)
        n_fusions = count_fusions(compiled)
        out = compiled(*carry, 0)           # warm (engine already compiled)
        jax.block_until_ready(out)
        t0 = time.time()
        for c in range(args.calls):
            out = compiled(*out[:4], (c + 1) * chunk)
        jax.block_until_ready(out)
        wall = (time.time() - t0) / args.calls
        # per-substep figures: one rollout call = chunk control steps, each
        # sim_cfg.run_duration/dt substeps; flops is per CALL
        t_flops = flops / PEAK_BF16_FLOPS
        t_bytes = byts / PEAK_HBM_BPS
        roof = max(t_flops, t_bytes)
        if wall > 3 * roof:
            regime = "op-count-bound"
        elif t_flops >= t_bytes:
            regime = "FLOP-bound"
        else:
            regime = "bytes-bound"
        rows.append({
            "backend": jax.default_backend(),  # TPU peaks are meaningless
                                               # on the --cpu smoke path
            "replicas": B, "chunk": chunk,
            "wall_per_call_s": round(wall, 4),
            "env_steps_per_sec": round(chunk * B / wall, 1),
            "gflops_per_call": round(flops / 1e9, 2),
            "gbytes_per_call": round(byts / 1e9, 3),
            "sustained_tflops": round(flops / wall / 1e12, 3),
            "mfu_vs_bf16_peak": round(flops / wall / PEAK_BF16_FLOPS, 4),
            "hbm_frac": round(byts / wall / PEAK_HBM_BPS, 4),
            "arith_intensity": round(flops / max(byts, 1.0), 2),
            "compute_roof_s": round(t_flops, 5),
            "memory_roof_s": round(t_bytes, 5),
            "hlo_fusions": n_fusions,
            "regime": regime,
        })
        print(json.dumps(rows[-1]))
    print(json.dumps({"backend": jax.default_backend(),
                      "peak_bf16_tflops": PEAK_BF16_FLOPS / 1e12,
                      "peak_hbm_gbps": PEAK_HBM_BPS / 1e9,
                      "note": ("engine dots run f32 Precision.HIGHEST "
                               "(multi-pass bf16 on the MXU), so MXU "
                               "issue-slot occupancy is ~3-6x the raw "
                               "mfu_vs_bf16_peak figure"),
                      "rows": rows}, indent=1))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, nargs="+", default=[256])
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--calls", type=int, default=3)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--episode-steps", type=int, default=200)
    ap.add_argument("--mfu", action="store_true",
                    help="roofline sweep over --replicas instead of a trace")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")

    if args.mfu:
        mfu_sweep(args)
        return

    if len(args.replicas) > 1:
        raise SystemExit("trace mode profiles ONE replica count; pass a "
                         "single --replicas value (or use --mfu to sweep)")
    B, chunk = args.replicas[0], args.chunk
    call, (state, buffers, env_states, obs) = _build(
        args.episode_steps, B, chunk)

    # compile + warm
    out = call(state, buffers, env_states, obs, 0)
    jax.block_until_ready(out)
    state, buffers, env_states, obs = out[:4]

    trace_dir = tempfile.mkdtemp(prefix="substep_trace_")
    t0 = time.time()
    with jax.profiler.trace(trace_dir):
        for c in range(args.calls):
            out = call(state, buffers, env_states, obs, (c + 1) * chunk)
            state, buffers, env_states, obs = out[:4]
        jax.block_until_ready(out)
    wall = time.time() - t0

    files = glob.glob(os.path.join(trace_dir, "**", "*.trace.json.gz"),
                      recursive=True)
    if not files:
        print(json.dumps({"error": "no trace written", "dir": trace_dir}))
        return
    agg = collections.Counter()
    counts = collections.Counter()
    for fp in files:
        with gzip.open(fp, "rt") as f:
            data = json.load(f)
        events = data.get("traceEvents", [])
        # restrict to DEVICE lanes (XLA ops): host python/TSL lanes also
        # carry dur and would otherwise pollute the ranking.  pid names
        # come from process_name metadata events; fall back to all lanes
        # if no device track exists (plain CPU backend).
        dev_pids = {ev.get("pid") for ev in events
                    if ev.get("ph") == "M"
                    and ev.get("name") == "process_name"
                    and any(s in str((ev.get("args") or {}).get("name", ""))
                            .lower() for s in ("/device:", "tpu", "gpu",
                                               "xla"))}
        for ev in events:
            if ev.get("ph") != "X" or "dur" not in ev:
                continue
            if dev_pids and ev.get("pid") not in dev_pids:
                continue
            name = ev.get("name", "")
            args_d = ev.get("args") or {}
            key = args_d.get("long_name") or name
            agg[key.split("(")[0][:80]] += ev["dur"]
            counts[key.split("(")[0][:80]] += 1
    total = sum(agg.values())
    env_steps = args.calls * chunk * B
    print(json.dumps({
        "backend": jax.default_backend(), "replicas": B, "chunk": chunk,
        "calls": args.calls, "wall_s": round(wall, 3),
        "env_steps_per_sec": round(env_steps / wall, 1),
        "trace_total_us": total,
    }))
    width = max((len(k) for k, _ in agg.most_common(args.top)), default=10)
    for name, dur in agg.most_common(args.top):
        print(f"{dur/1e3:10.2f} ms  {100*dur/max(total,1):5.1f}%  "
              f"x{counts[name]:<6} {name:<{width}}")


if __name__ == "__main__":
    main()
