"""Serve-obs smoke: request-path tracing + SLO engine end to end.

The CI-stage proof that the serving observability layer executes through
the real CLI: a tiny SPR-tier serve run (no checkpoint — the fallback
tier shares the whole batcher/tracer/SLO path without paying an AOT
compile) with request-span sampling on and a deliberately LOW
``--slo-p99-ms`` must

- exit 0 with a complete ``slo`` block in its JSON output and a
  schema-versioned ``slo.json`` in the result dir (objectives echoed,
  attainment + burn rate + deadline-miss ratio + pad waste + latency
  decomposition all present),
- leave ``serve_flush`` spans (always recorded) and head-sampled
  ``serve_request_span`` events in ``events.jsonl`` whose
  queue + batch + device decomposition sums to the recorded latency,
- export through ``gsc_tpu.obs.trace.build_trace`` as VALID trace-event
  JSON with slices on the serve/serve_request tracks and at least one
  request→flush flow arrow (``validate_trace`` returns no errors),
- scrape cleanly over the live ``/metrics`` endpoint, with the
  hub's LIVE queue-depth probe current at snapshot time (in-process
  roundtrip — a fixed port would collide across concurrent CI stages),
- gate through ``bench_diff``: the run's slo.json row self-compares
  clean (rc 0) while an injected deadline-miss regression is caught
  (rc 1).

Run by ``tools/ci_check.sh`` after the learnobs stage; standalone:

    JAX_PLATFORMS=cpu python tools/serveobs_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

REQUESTS = 24
SLO_P99_MS = "1"        # deliberately low: misses must be observable


def fail(msg: str) -> int:
    print(f"serveobs smoke: FAIL — {msg}")
    return 1


def check_endpoint() -> str:
    """In-process /metrics roundtrip with a LIVE gauge registered: the
    scrape must carry the probe's CURRENT value, and every series must
    parse back identical to the snapshot."""
    from gsc_tpu.obs import MetricsEndpoint, MetricsHub

    hub = MetricsHub(tags={"run": "smoke"})
    hub.counter("serve_rejected_total", 2, reason="queue_full")
    depth = {"value": 3}
    hub.live_gauge("serve_queue_depth", lambda: depth["value"])
    ep = MetricsEndpoint(hub, port=0).start()
    try:
        depth["value"] = 7    # mutate AFTER registration: scrape must see 7
        body = urllib.request.urlopen(ep.url, timeout=10).read().decode()
        parsed = {}
        for line in body.strip().splitlines():
            name, value = line.rsplit(" ", 1)
            parsed[name] = float(value)
        depth_key = 'gsc_serve_queue_depth{run="smoke"}'
        if parsed.get(depth_key) != 7.0:
            return (f"live queue-depth probe stale in scrape: "
                    f"{parsed.get(depth_key)}")
        snap = {k: float(v) for k, v in hub.snapshot().items()}
        if parsed != snap:
            return f"endpoint scrape != snapshot ({parsed} vs {snap})"
    finally:
        ep.stop()
    return ""


def main() -> int:
    from chaos_smoke import _configure_jax, write_tiny_configs
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli

    err = check_endpoint()
    if err:
        return fail(err)

    tmp = tempfile.mkdtemp(prefix="gsc_serveobs_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    configs = args[:4]
    extra = [a for a in args[4:] if a != "--quiet"]
    r = CliRunner().invoke(cli, [
        "serve", *configs, *extra,          # no checkpoint: SPR tier
        "--requests", str(REQUESTS), "--concurrency", "4",
        "--buckets", "1,4", "--deadline-ms", "2", "--pool-steps", "2",
        "--trace-sample", "1", "--slo-p99-ms", SLO_P99_MS,
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"serve rc={r.exit_code}")
    out = json.loads(r.output.strip().splitlines()[-1])
    if out["errors"]:
        return fail(f"serve answered with errors: {out['error_detail']}")
    rdir = out["result_dir"]
    slo_out = out.get("slo") or {}
    if slo_out.get("deadline_miss_ratio") is None \
            or slo_out.get("attainment") is None \
            or slo_out.get("burn_rate") is None:
        return fail(f"CLI slo block incomplete: {slo_out}")

    # slo.json: complete, schema-versioned, objectives echoed
    slo_path = os.path.join(rdir, "slo.json")
    if not os.path.exists(slo_path):
        return fail("slo.json not written")
    doc = json.load(open(slo_path))
    if doc.get("schema_version") != 1:
        return fail(f"slo.json schema wrong: {doc.get('schema_version')}")
    if (doc.get("objectives") or {}).get("p99_ms") != float(SLO_P99_MS):
        return fail(f"slo.json objectives not echoed: "
                    f"{doc.get('objectives')}")
    for key in ("deadline_miss_ratio", "attainment", "burn_rate",
                "pad_waste", "arrival_rate_rps", "queue_wait_frac"):
        if doc.get(key) is None:
            return fail(f"slo.json missing {key}")
    if doc.get("requests") != REQUESTS:
        return fail(f"slo.json requests {doc.get('requests')} != "
                    f"{REQUESTS}")
    if not doc.get("decomposition_ms"):
        return fail("slo.json missing the latency decomposition")

    # span events: flush-level always, request spans sampled at N=1
    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    flushes = [e for e in events if e["event"] == "serve_flush"]
    spans = [e for e in events if e["event"] == "serve_request_span"]
    if not flushes:
        return fail("no serve_flush events recorded")
    if len(spans) != REQUESTS:
        return fail(f"--trace-sample 1 should span every request: "
                    f"{len(spans)} != {REQUESTS}")
    for s in spans[:5]:
        total = s["queue_wait_ms"] + s["batch_wait_ms"] + s["device_ms"]
        if abs(total - s["latency_ms"]) > 0.01:
            return fail(f"span decomposition does not sum to latency: {s}")

    # trace export: valid, with serve_request slices + flow arrows
    from gsc_tpu.obs.trace import (TRACE_TRACKS, build_trace, read_events,
                                   validate_trace)
    trace = build_trace(read_events(rdir))
    errors = validate_trace(trace)
    if errors:
        return fail(f"trace invalid: {errors[:3]}")
    req_tid = TRACE_TRACKS["serve_request"]
    req_slices = [e for e in trace["traceEvents"]
                  if e.get("ph") == "X" and e.get("tid") == req_tid]
    flows = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    if len(req_slices) != REQUESTS:
        return fail(f"serve_request track has {len(req_slices)} slices, "
                    f"want {REQUESTS}")
    if not flows:
        return fail("no request->flush flow arrows in the trace")

    # bench_diff gate: self-compare clean, injected regression caught
    import bench_diff
    traj = os.path.join(tmp, "traj.json")
    doc2 = bench_diff.ingest([slo_path], traj)
    (row_name,) = [n for n in doc2["rows"] if n.startswith("slo_")]
    rc = bench_diff.main(["diff", row_name, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"slo self-compare rc={rc} (want 0)")
    # inject on pad_waste, which can never saturate at 1.0 on a real run
    # (a flush always carries >= 1 real request) — a deadline-miss ratio
    # already at 1.0 under the deliberately-low objective would leave no
    # headroom to regress into
    bad = dict(doc)
    bad["pad_waste"] = (doc["pad_waste"] or 0.0) + 0.5
    bad["deadline_miss_ratio"] = min(
        (doc["deadline_miss_ratio"] or 0.0) + 0.5, 1.0)
    bad_path = os.path.join(tmp, "bad_slo.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected SLO regression rc={rc} (want 1)")

    print(f"serveobs smoke: OK — {len(spans)} request spans across "
          f"{len(flushes)} flushes, slo.json complete + gated "
          f"(deadline-miss {doc['deadline_miss_ratio']}, pad-waste "
          f"{doc['pad_waste']}), trace valid with flow links, "
          "/metrics live-gauge scrape clean")
    return 0


if __name__ == "__main__":
    sys.exit(main())
