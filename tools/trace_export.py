"""Export a run's events.jsonl as Chrome/Perfetto trace-event JSON.

Usage:
    python tools/trace_export.py <run_dir | events.jsonl> [-o trace.json]
    python tools/trace_export.py <run_dir> --validate-only

Renders the obs event stream (``cli train`` / ``cli serve`` write it) into
the trace-event format that https://ui.perfetto.dev and chrome://tracing
open directly: one track per logical thread (episode loop, prefetcher,
serve, watchdog, compile), watchdog stalls as instant events, recovery
ladders chained by flow arrows — so a stall or pipeline bubble is visible
on a timeline instead of inferred from log-line deltas.  Rotated streams
(``--obs-rotate-mb``: events.jsonl.N..1) are walked transparently.

The export always runs the strict validator
(:func:`gsc_tpu.obs.trace.validate_trace`: monotone ts, matched B/E
pairs, pid/tid on every event) and exits nonzero on any violation — CI's
perfobs stage counts on that.  jax-free: only the obs package's pure
rendering half is imported.
"""
from __future__ import annotations

import argparse
import os
import sys
from typing import List, Optional

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", help="run directory or events.jsonl")
    ap.add_argument("-o", "--out", default=None,
                    help="output trace path [default: <run_dir>/trace.json]")
    ap.add_argument("--validate-only", action="store_true",
                    help="build + validate without writing the trace file")
    args = ap.parse_args(argv)

    from gsc_tpu.obs.trace import build_trace, read_events, validate_trace

    try:
        events = read_events(args.path)
    except FileNotFoundError as e:
        print(f"trace_export: {e}", file=sys.stderr)
        return 2
    trace = build_trace(events)
    errors = validate_trace(trace)
    if errors:
        print(f"trace_export: INVALID trace ({len(errors)} problem(s)):",
              file=sys.stderr)
        for err in errors[:20]:
            print(f"  - {err}", file=sys.stderr)
        return 1
    n = len(trace["traceEvents"])
    if args.validate_only:
        print(f"trace_export: valid ({n} events)")
        return 0
    out = args.out
    if out is None:
        base = (args.path if os.path.isdir(args.path)
                else os.path.dirname(os.path.abspath(args.path)))
        out = os.path.join(base, "trace.json")
    import json
    with open(out, "w") as f:
        json.dump(trace, f)
    print(f"trace_export: wrote {out} ({n} events) — open it at "
          "https://ui.perfetto.dev or chrome://tracing")
    return 0


if __name__ == "__main__":
    sys.exit(main())
