"""Run the UNMODIFIED reference simulator (/root/reference) under the
minisimpy shim, and dump its metrics as JSON.

Two modes:
- ``standalone``: the reference's coordsim/main.py path (dummy triangle
  placement/schedule, FlowSimulator driven directly) — reference
  coordsim/main.py:19-66.
- ``interface``: the RL-facing adapter loop (siminterface.Simulator
  init + N x apply with a uniform SimulatorAction) — the exact per-control-
  step loop the reference agent drives (siminterface/simulator.py:125-231,
  controller/duration_controller.py:36-80).  This is both the golden-parity
  oracle and the baseline step-rate denominator.

The reference tree is used READ-ONLY via sys.path; nothing is copied.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import time

REFERENCE = os.environ.get("GSC_REFERENCE_DIR", "/root/reference")


def _install_shim():
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    import minisimpy
    sys.modules["simpy"] = minisimpy
    # geopy is not installed either; the reader only needs
    # geopy.distance.distance(a, b).km (reader.py:11, 216-227).  We back it
    # with the same haversine great-circle formula gsc_tpu's topology
    # compiler uses, so parity comparisons isolate ENGINE semantics — the
    # haversine-vs-geodesic delta (<0.5% of link delay) is the documented
    # divergence from true upstream (gsc_tpu/topology/compiler.py:9-14).
    import math
    import types

    class _Dist:
        def __init__(self, a, b):
            (lat1, lon1), (lat2, lon2) = a, b
            r = 6371008.8
            p1, p2 = math.radians(lat1), math.radians(lat2)
            dp, dl = p2 - p1, math.radians(lon2 - lon1)
            h = (math.sin(dp / 2) ** 2 +
                 math.cos(p1) * math.cos(p2) * math.sin(dl / 2) ** 2)
            self.meters = 2 * r * math.asin(math.sqrt(h))
            self.km = self.meters / 1000.0

    geopy = types.ModuleType("geopy")
    geopy.distance = types.ModuleType("geopy.distance")
    geopy.distance.distance = _Dist
    sys.modules["geopy"] = geopy
    sys.modules["geopy.distance"] = geopy.distance
    # the reference's plugin packages (coordsim/forwarders/__init__.py etc.)
    # use the pre-3.12 loader.find_module().load_module() API; restore a
    # compat shim on this interpreter (3.12 removed find_module)
    import importlib.machinery as _mach

    if not hasattr(_mach.FileFinder, "find_module"):
        def _find_module(self, name, path=None):
            spec = self.find_spec(name)
            return spec.loader if spec is not None else None
        _mach.FileFinder.find_module = _find_module
    if not hasattr(_mach.SourceFileLoader, "load_module"):
        import importlib.util as _util

        def _load_module(self, name):
            if name in sys.modules:
                return sys.modules[name]
            spec = _util.spec_from_loader(name, self)
            mod = _util.module_from_spec(spec)
            sys.modules[name] = mod
            self.exec_module(mod)
            return mod
        _mach.SourceFileLoader.load_module = _load_module
    sys.path.insert(0, REFERENCE)


def _metrics_dict(m):
    """Common metrics extraction shared by every mode."""
    return {
        "generated_flows": int(m["generated_flows"]),
        "processed_flows": int(m["processed_flows"]),
        "dropped_flows": int(m["dropped_flows"]),
        "total_active_flows": int(m["total_active_flows"]),
        "avg_end2end_delay": float(m["avg_end2end_delay"]),
        "dropped_by_reason": {k: int(v) for k, v in
                              m["dropped_flow_reasons"].items()},
    }


def uniform_action(network, sfc_list, sf_list):
    """Uniform schedule + place-everything action, the same 'dummy agent'
    our cli simulate uses (spinterface SimulatorAction schema:
    placement {node: [sf]}, scheduling {node: {sfc: {sf: {node: w}}}})."""
    from spinterface import SimulatorAction
    nodes = list(network.nodes.keys())
    n = len(nodes)
    placement = {v: list(sf_list.keys()) for v in nodes}
    scheduling = {
        v: {sfc: {sf: {u: 1.0 / n for u in nodes}
                  for sf in sf_list.keys()}
            for sfc in sfc_list.keys()}
        for v in nodes}
    return SimulatorAction(placement, scheduling)


def run_interface(network_file, service_file, config_file, steps, seed):
    from siminterface import Simulator

    sim = Simulator(os.path.join(REFERENCE, network_file),
                    os.path.join(REFERENCE, service_file),
                    os.path.join(REFERENCE, config_file),
                    test_mode=False)
    t_init0 = time.time()
    sim.init(seed)
    init_s = time.time() - t_init0
    action = uniform_action(sim.network, sim.sfc_list, sim.sf_list)
    t0 = time.time()
    for _ in range(steps):
        sim.apply(action)
    apply_s = time.time() - t0
    out = {
        "mode": "interface",
        "network": network_file,
        "steps": steps,
        "seed": seed,
        "sim_now": float(sim.env.now),
        "init_wall_s": round(init_s, 4),
        "apply_wall_s": round(apply_s, 4),
        "steps_per_sec": round(steps / apply_s, 2) if apply_s else None,
        **_metrics_dict(sim.params.metrics.metrics),
    }
    return out


def run_perflow(network_file, service_file, config_file, duration, seed):
    """FlowController (per-flow external decisions) loop: init, then apply
    a decision per presented flow — policy: always process at the flow's
    CURRENT node (the same local-processing policy the rebuild's
    ``cli simulate`` uses in per_flow mode) — until sim time reaches
    ``duration``.  coordsim/controller/flow_controller.py:21-92."""
    from siminterface import Simulator

    sim = Simulator(os.path.join(REFERENCE, network_file),
                    os.path.join(REFERENCE, service_file),
                    os.path.join(REFERENCE, config_file),
                    test_mode=False)
    state = sim.init(seed)
    decisions = 0
    t0 = time.time()
    while float(sim.env.now) < duration:
        flow = state.flow

        class _A:  # duck-typed per-flow action (.flow, .destination_node_id)
            pass

        a = _A()
        a.flow = flow
        # local processing; completed flows are routed toward their egress
        # (a same-node decision for a to-eg flow only burns 1 ms of TTL,
        # flowsimulator.py:93-97)
        a.destination_node_id = (flow.egress_node_id
                                 if getattr(flow, "forward_to_eg", False)
                                 and flow.egress_node_id is not None
                                 else flow.current_node_id)
        state = sim.apply(a)
        decisions += 1
    wall = time.time() - t0
    return {
        "mode": "perflow", "network": network_file, "duration": duration,
        "seed": seed, "decisions": decisions, "wall_s": round(wall, 4),
        "sim_now": float(sim.env.now),
        **_metrics_dict(sim.params.metrics.metrics),
    }


def run_standalone(network_file, service_file, config_file, duration, seed):
    """coordsim/main.py:19-66 equivalent, programmatic (same objects, same
    order) so we can choose network/duration without CLI quirks."""
    import random

    import numpy
    import simpy

    import coordsim.network.dummy_data as dummy_data
    from coordsim.metrics.metrics import Metrics
    from coordsim.reader import reader
    from coordsim.simulation.flowsimulator import FlowSimulator
    from coordsim.simulation.simulatorparams import SimulatorParams

    import logging
    log = logging.getLogger("run_reference")
    env = simpy.Environment()
    random.seed(seed)
    numpy.random.seed(seed)
    network, ing, eg = reader.read_network(
        os.path.join(REFERENCE, network_file), node_cap=10, link_cap=10)
    sfc_list = reader.get_sfc(os.path.join(REFERENCE, service_file))
    sf_list = reader.get_sf(os.path.join(REFERENCE, service_file), "")
    config = reader.get_config(os.path.join(REFERENCE, config_file))
    metrics = Metrics(network, sf_list)
    params = SimulatorParams(
        log, network, ing, eg, sfc_list, sf_list, config, metrics,
        sf_placement=dummy_data.triangle_placement,
        schedule=dummy_data.triangle_schedule)
    sim = FlowSimulator(env, params)
    sim.start()
    t0 = time.time()
    env.run(until=duration)
    wall = time.time() - t0
    m = metrics.metrics
    return {
        "mode": "standalone", "network": network_file,
        "duration": duration, "seed": seed, "wall_s": round(wall, 4),
        "generated_flows": int(m["generated_flows"]),
        "processed_flows": int(m["processed_flows"]),
        "dropped_flows": int(m["dropped_flows"]),
        "avg_end2end_delay": float(m["avg_end2end_delay"]),
    }


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--mode", choices=["interface", "standalone", "perflow"],
                    default="interface")
    ap.add_argument("--network",
                    default="configs/networks/triangle/"
                            "triangle-in2-cap10-delay10.graphml")
    ap.add_argument("--service",
                    default="configs/service_functions/abc.yaml")
    ap.add_argument("--config",
                    default="configs/config/simulator/sample_config.yaml")
    ap.add_argument("--steps", type=int, default=200)
    ap.add_argument("--duration", type=int, default=20000)
    ap.add_argument("--seed", type=int, default=1234)
    args = ap.parse_args()
    _install_shim()
    import logging
    logging.basicConfig(level=logging.ERROR)
    if args.mode == "interface":
        out = run_interface(args.network, args.service, args.config,
                            args.steps, args.seed)
    elif args.mode == "perflow":
        out = run_perflow(args.network, args.service, args.config,
                          args.duration, args.seed)
    else:
        out = run_standalone(args.network, args.service, args.config,
                             args.duration, args.seed)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
