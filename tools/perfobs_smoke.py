"""Perf-observability smoke: ledger + trace + bench_diff on a tiny run.

The CI-stage proof that the performance-observability layer actually
produces its artifacts end to end: a 3-episode CPU training run (with a
deliberately tiny ``--obs-rotate-mb`` so segment rotation is exercised
too) must

- write a ``perf.json`` cost ledger whose ``episode_step`` entry carries
  FLOPs, bytes, a fusion count, per-dispatch wall and an MFU estimate
  (schema-versioned, arithmetically consistent);
- yield an events stream that ``tools/trace_export.py`` renders into
  trace-event JSON passing the strict validator (monotone ts, matched
  B/E pairs, pid/tid everywhere) — across the rotated segments;
- ingest cleanly into a ``BENCH_TRAJECTORY.json`` next to the repo's
  banked BENCH_r*/MULTICHIP_r*/SERVE_r* artifacts, SELF-COMPARE clean
  (rc 0), and FAIL (rc != 0) against an injected synthetic regression.

Run by ``tools/ci_check.sh`` before the chaos stage; standalone:

    JAX_PLATFORMS=cpu python tools/perfobs_smoke.py
"""
from __future__ import annotations

import json
import os
import subprocess
import sys
import tempfile

# runnable from any cwd: the repo root is this file's parent's parent
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update("jax_compilation_cache_dir",
                          os.path.join(REPO, ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"perfobs smoke: FAIL — {msg}")
    return 1


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from chaos_smoke import write_tiny_configs
    from gsc_tpu.cli import cli

    tmp = tempfile.mkdtemp(prefix="gsc_perfobs_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", "3",
        "--result-dir", os.path.join(tmp, "res"),
        "--obs-rotate-mb", "0.002"])     # ~2 KiB: forces rotation
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code}")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    # ---- cost ledger --------------------------------------------------
    perf_path = os.path.join(rdir, "perf.json")
    if not os.path.exists(perf_path):
        return fail(f"no perf.json in {rdir}")
    perf = json.load(open(perf_path))
    e = (perf.get("entries") or {}).get("episode_step") or {}
    for field in ("flops", "bytes_accessed", "fusions", "dispatches",
                  "wall_s_mean", "mfu"):
        if not e.get(field):
            return fail(f"perf.json episode_step missing/zero {field!r}: "
                        f"{e}")
    if e["dispatches"] != 3:
        return fail(f"expected 3 dispatches, ledger has {e['dispatches']}")
    print(f"perfobs smoke: ledger ok (schema v{perf['schema_version']}, "
          f"{e['fusions']} fusions, mfu {e['mfu']})")

    # rotation actually happened and the report reader reassembles it
    if not os.path.exists(os.path.join(rdir, "events.jsonl.1")):
        return fail("--obs-rotate-mb 0.002 produced no rotated segment")
    import obs_report
    summary = obs_report.summarize(obs_report.load_events(rdir),
                                   perf=obs_report.load_perf(rdir))
    if summary["episodes"] != 3 or summary["status"] != "ok":
        return fail(f"rotated-stream summary wrong: "
                    f"episodes={summary['episodes']} "
                    f"status={summary['status']}")
    if not summary["perf"]:
        return fail("obs_report did not surface the perf section")

    # ---- trace export -------------------------------------------------
    trace_out = os.path.join(tmp, "trace.json")
    r2 = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         rdir, "-o", trace_out], capture_output=True, text=True)
    if r2.returncode != 0:
        return fail(f"trace_export rc={r2.returncode}: {r2.stderr}")
    print(r2.stdout.strip())

    # ---- bench_diff ---------------------------------------------------
    import bench_diff
    traj = os.path.join(tmp, "BENCH_TRAJECTORY.json")
    doc = bench_diff.ingest([perf_path], traj, scan=REPO)
    row_name = next((n for n, row in doc["rows"].items()
                     if row["kind"] == "perf_ledger"
                     and row["source"] == os.path.normpath(perf_path)),
                    None)
    if row_name is None:
        return fail("run's perf.json did not ingest into the trajectory")
    rc = bench_diff.main(["diff", row_name, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"self-compare rc={rc} (expected 0)")
    # injected regression: halve the rate-like metrics, bloat the counts
    bad = json.loads(json.dumps(doc["rows"][row_name]))
    bad["metrics"] = {k: (v * 2 if k.endswith(("fusions", "jit_traces"))
                          else v * 0.5)
                      for k, v in bad["metrics"].items()}
    doc["rows"]["perf_injected"] = bad
    bench_diff.write_trajectory(traj, doc)
    rc = bench_diff.main(["diff", "perf_injected", "--baseline", row_name,
                          "--trajectory", traj])
    if rc == 0:
        return fail("injected regression passed the diff gate")
    rc = bench_diff.main(["diff", row_name, "--baseline", "no_such_row",
                          "--trajectory", traj])
    if rc != 3:
        return fail(f"missing baseline rc={rc} (expected 3)")
    print("perfobs smoke: OK (ledger + rotation + trace + bench_diff)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
