"""Minimal clean-room implementation of the simpy API surface the reference
coordsim uses, so the reference simulator can run UNMODIFIED in this image
(simpy is not installed and cannot be installed) for golden-parity checks
and baseline measurement.

Implemented from simpy's documented semantics — not from simpy source:
- ``Environment``: ``now``, ``step()``, ``run(until=None|number|event)``,
  ``process(gen)``, ``timeout(delay, value=None)``, ``event()``
- ``Process``: yieldable, resumes parent with the generator's return value
- ``Event``: ``succeed(value=None)``, yieldable
- event ordering: ``(time, priority, insertion_id)`` — process-init events
  are URGENT (priority 0), timeouts / succeeded events / process
  completions are NORMAL (priority 1), ties broken FIFO — matching simpy's
  scheduling rules so same-timestamp behavior is reproduced.

Usage: ``sys.modules["simpy"] = tools.minisimpy`` before importing any
reference module (see run_reference.py).
"""
from __future__ import annotations

from heapq import heappop, heappush
from itertools import count

URGENT = 0
NORMAL = 1
_PENDING = object()


class Event:
    """A one-shot event; processes waiting on it resume when it fires."""

    def __init__(self, env: "Environment"):
        self.env = env
        self.callbacks = []          # None once processed
        self._value = _PENDING

    @property
    def triggered(self) -> bool:
        return self._value is not _PENDING

    @property
    def processed(self) -> bool:
        return self.callbacks is None

    @property
    def value(self):
        return None if self._value is _PENDING else self._value

    def succeed(self, value=None) -> "Event":
        if self._value is not _PENDING:
            raise RuntimeError(f"{self!r} has already been triggered")
        self._value = value
        self.env._schedule(self, NORMAL)
        return self


class Timeout(Event):
    def __init__(self, env, delay, value=None):
        if delay < 0:
            raise ValueError(f"negative delay {delay}")
        super().__init__(env)
        self._value = value
        env._schedule(self, NORMAL, delay)


class Process(Event):
    """Wraps a generator; each yielded event schedules the next resumption.
    The Process itself is an Event that fires (with the generator's return
    value) when the generator finishes."""

    def __init__(self, env, generator):
        super().__init__(env)
        self._generator = generator
        init = Event(env)
        init._value = None
        init.callbacks.append(self._resume)
        env._schedule(init, URGENT)

    def _resume(self, event: Event) -> None:
        while True:
            try:
                target = self._generator.send(event.value)
            except StopIteration as stop:
                self._value = stop.value
                self.env._schedule(self, NORMAL)
                return
            if not isinstance(target, Event):
                raise RuntimeError(
                    f"process yielded a non-event: {target!r}")
            if target.callbacks is not None:
                target.callbacks.append(self._resume)
                return
            # target already processed -> resume immediately, same timestep
            event = target


class Environment:
    def __init__(self, initial_time=0):
        self._now = initial_time
        self._queue = []             # heap of (time, priority, eid, event)
        self._eid = count()

    @property
    def now(self):
        return self._now

    # ------------------------------------------------------------- factories
    def event(self) -> Event:
        return Event(self)

    def timeout(self, delay, value=None) -> Timeout:
        return Timeout(self, delay, value)

    def process(self, generator) -> Process:
        return Process(self, generator)

    # ------------------------------------------------------------- execution
    def _schedule(self, event: Event, priority: int, delay=0) -> None:
        heappush(self._queue,
                 (self._now + delay, priority, next(self._eid), event))

    def step(self) -> None:
        """Process the single next event."""
        t, _, _, event = heappop(self._queue)
        self._now = t
        callbacks, event.callbacks = event.callbacks, None
        for cb in callbacks:
            cb(event)

    def peek(self):
        return self._queue[0][0] if self._queue else float("inf")

    def run(self, until=None):
        if until is None:
            while self._queue:
                self.step()
            return None
        if isinstance(until, Event):
            if until.processed:
                return until.value
            fired = []
            until.callbacks.append(fired.append)
            while not fired:
                if not self._queue:
                    raise RuntimeError(
                        "no scheduled events left but until event is "
                        "still pending")
                self.step()
            return until.value
        at = until
        if at <= self._now:
            raise ValueError(
                f"until ({at}) must be greater than now ({self._now})")
        stop = Event(self)
        stop._value = None
        self._schedule(stop, URGENT, at - self._now)
        while self._queue:
            if self._queue[0][3] is stop:
                heappop(self._queue)
                self._now = at
                return None
            self.step()
        return None


class _EventsNamespace:
    """``simpy.events`` compatibility: the reference's
    ExternalDecisionMaker introspects ``simpy.events.Event`` when scanning
    the queue for same-instant scheduling conflicts
    (external_decision_maker.py:33-41)."""

    Event = Event
    Timeout = Timeout


events = _EventsNamespace
