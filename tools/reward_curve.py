"""Reward-curve comparison vs the UNMODIFIED reference simulator — the
BASELINE-protocol "reproduce the reference's reward curve on config 1"
anchor, done without the reference's (uninstallable) torch agent stack.

Both sides run the flagship config-1 scenario (Abilene in4-rand-cap1-2,
abc chain, sample_config, matched seed) under the SAME uniform
place-everywhere action, and both reward streams are computed by ONE
implementation — ``gsc_tpu.env.rewards.compute_reward`` (itself a
line-cited port of gym_env.py:223-380) — from each simulator's
per-interval flow metrics.  What this isolates is the SIMULATOR'S
contribution to the reward signal: if the engine's physics diverged, the
curves would split; matched curves mean an agent training on gsc_tpu sees
the same reward landscape the reference agent saw.

Per-interval metrics come from DELTAS of cumulative counters
(processed/dropped/total_end2end_delay) on both sides — deliberately NOT
from the reference's run_* metrics, whose reset timing belongs to its
result-writer SimPy process (writer.py:222) and would entangle the
comparison with writer scheduling.

    python tools/reward_curve.py                  # both sides + compare
    python tools/reward_curve.py --side reference # (no jax import)
    python tools/reward_curve.py --side engine
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if REPO not in sys.path:
    sys.path.insert(0, REPO)
_TOOLS = os.path.join(REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)


def no_tpu_env():
    """A subprocess environment that cannot register the TPU backend —
    jax-free reference runs and CPU-side children must never block on
    the shared tunnel.  Single definition; the parity tests import it."""
    return {k: v for k, v in os.environ.items()
            if k != "PALLAS_AXON_POOL_IPS"}
REFERENCE = os.environ.get("GSC_REFERENCE_DIR", "/root/reference")
NETWORK = "configs/networks/abilene/abilene-in4-rand-cap1-2.graphml"
SERVICE = "configs/service_functions/abc.yaml"
CONFIG = "configs/config/simulator/sample_config.yaml"
SEED = 1234


def reference_curve(steps):
    """Per-step cumulative (processed, dropped, e2e_sum) from the real
    reference coordsim under the minisimpy shim.  No jax anywhere."""
    import run_reference
    run_reference._install_shim()
    from siminterface import Simulator

    sim = Simulator(os.path.join(REFERENCE, NETWORK),
                    os.path.join(REFERENCE, SERVICE),
                    os.path.join(REFERENCE, CONFIG), test_mode=False)
    sim.init(SEED)
    action = run_reference.uniform_action(sim.network, sim.sfc_list,
                                          sim.sf_list)
    rows = []
    for _ in range(steps):
        sim.apply(action)
        m = sim.params.metrics.metrics
        rows.append({"processed": int(m["processed_flows"]),
                     "dropped": int(m["dropped_flows"]),
                     "e2e_sum": float(m["total_end2end_delay"])})
    return {"side": "reference", "n_nodes": len(sim.network.nodes),
            "rows": rows}


def uniform_engine_run(network, steps, seed, config=None, overrides=None,
                       max_nodes=24, max_edges=37, per_step=False):
    """THE canonical uniform-action engine harness (cli-simulate
    semantics): uniform schedule over real nodes, everything placed
    everywhere.  Shared by tests/test_reference_parity.py (final-metrics
    parity) and the reward-curve anchor (``per_step=True`` captures the
    cumulative counter series) so the two can't desynchronize.  Returns
    the final SimMetrics, plus the per-step row list when asked.

    Backend selection is the CALLER's job (conftest pins CPU for tests;
    this tool's main() pins CPU before dispatch) — a config update here
    would be a silent no-op in any process whose backend already
    initialized."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from gsc_tpu.config.loader import load_service, load_sim
    from gsc_tpu.config.schema import EnvLimits
    from gsc_tpu.sim.engine import SimEngine
    from gsc_tpu.sim.traffic import generate_traffic
    from gsc_tpu.topology.compiler import load_topology

    svc = load_service(os.path.join(REFERENCE, SERVICE))
    sim_cfg = load_sim(config or os.path.join(REFERENCE, CONFIG),
                       **(overrides or {}))
    limits = EnvLimits.for_service(svc, max_nodes=max_nodes,
                                   max_edges=max_edges)
    topo = load_topology(network, max_nodes=max_nodes, max_edges=max_edges,
                         seed=seed)
    traffic = generate_traffic(sim_cfg, svc, topo, steps, seed)
    engine = SimEngine(svc, sim_cfg, limits)
    nm = np.asarray(topo.node_mask)
    sched = np.zeros(limits.scheduling_shape, np.float32)
    sched[:, :, :, nm] = 1.0 / nm.sum()
    placement = jnp.asarray(
        np.broadcast_to(nm[:, None], (max_nodes, limits.max_sfs)).copy())
    state = engine.init(jax.random.PRNGKey(seed), topo)
    rows = []
    metrics = None
    for _ in range(steps):
        state, metrics = engine.apply(state, topo, traffic,
                                      jnp.asarray(sched), placement)
        if per_step:
            rows.append({"processed": int(metrics.processed),
                         "dropped": int(metrics.dropped),
                         "e2e_sum": float(metrics.sum_e2e)})
    return metrics, int(nm.sum()), rows


def engine_curve(steps):
    """Cumulative series from the gsc_tpu engine (CPU), uniform
    schedule/placement, matched seed."""
    _, n_nodes, rows = uniform_engine_run(
        os.path.join(REFERENCE, NETWORK), steps, SEED, per_step=True)
    return {"side": "engine", "n_nodes": n_nodes, "rows": rows}


def rewards_from_cumulative(rows, n_nodes, steps):
    """Per-interval reward via compute_reward on cumulative deltas.
    Uniform place-everywhere -> [N,3] all-true placement on real nodes;
    prio-flow objective with the reference's auto target + EWMA chain."""
    import jax.numpy as jnp
    import numpy as np

    from gsc_tpu.config.schema import AgentConfig
    from gsc_tpu.env.rewards import compute_reward, reward_constants

    agent = AgentConfig(objective="prio-flow", episode_steps=steps)
    # abc chain: 3 x 5 ms processing means (abc.yaml)
    min_delay, diameter = reward_constants(agent, [5.0, 5.0, 5.0])
    node_mask = jnp.arange(24) < n_nodes
    placement = jnp.broadcast_to(
        node_mask[:, None], (24, 3))

    class _M:  # duck-typed SimMetrics view over one interval's deltas
        def __init__(self, proc, drop, e2e):
            self.run_processed = jnp.asarray(proc, jnp.float32)
            self.run_dropped = jnp.asarray(drop, jnp.float32)
            self._e2e = e2e

        def run_avg_e2e(self):
            return jnp.where(self.run_processed > 0,
                             self._e2e / jnp.maximum(self.run_processed, 1),
                             0.0)

    ewma = jnp.ones(())
    out = []
    prev = {"processed": 0, "dropped": 0, "e2e_sum": 0.0}
    for row in rows:
        m = _M(row["processed"] - prev["processed"],
               row["dropped"] - prev["dropped"],
               jnp.asarray(row["e2e_sum"] - prev["e2e_sum"], jnp.float32))
        r, ewma, _ = compute_reward(agent, m, placement, node_mask, 3,
                                    min_delay, diameter, ewma)
        out.append(float(np.asarray(r)))
        prev = row
    return out


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--side", choices=["reference", "engine", "both"],
                    default="both")
    ap.add_argument("--steps", type=int, default=50)
    ap.add_argument("--out", default=None,
                    help="write the comparison JSON here")
    args = ap.parse_args()

    if args.side == "reference":
        print(json.dumps(reference_curve(args.steps)))
        return
    import jax  # engine/both sides: pin CPU before any backend touch
    jax.config.update("jax_platforms", "cpu")
    if args.side == "engine":
        print(json.dumps(engine_curve(args.steps)))
        return

    # both: reference in a clean subprocess (no jax/TPU registration)
    env = no_tpu_env()
    r = subprocess.run(
        [sys.executable, os.path.abspath(__file__), "--side", "reference",
         "--steps", str(args.steps)],
        capture_output=True, text=True, timeout=600, env=env, cwd=REPO)
    if r.returncode != 0:
        raise SystemExit(f"reference side failed: {r.stderr[-2000:]}")
    ref = json.loads(r.stdout.strip().splitlines()[-1])
    eng = engine_curve(args.steps)

    import numpy as np
    rr = rewards_from_cumulative(ref["rows"], ref["n_nodes"], args.steps)
    re_ = rewards_from_cumulative(eng["rows"], eng["n_nodes"], args.steps)
    a, b = np.asarray(rr), np.asarray(re_)
    if a.std() > 0 and b.std() > 0:
        corr = float(np.corrcoef(a, b)[0, 1])
    else:
        # one-sided constancy is a shape MISMATCH, not a perfect match —
        # only two identical constant curves score 1.0 here
        corr = 1.0 if np.allclose(a, b, atol=1e-6) else 0.0
    result = {
        "scenario": "abilene-in4-rand-cap1-2 / abc / sample_config",
        "steps": args.steps, "seed": SEED,
        "reference_rewards": [round(x, 4) for x in rr],
        "engine_rewards": [round(x, 4) for x in re_],
        "max_abs_diff": round(float(np.max(np.abs(a - b))), 4),
        "mean_abs_diff": round(float(np.mean(np.abs(a - b))), 4),
        "pearson_r": round(corr, 4),
        "reference_mean": round(float(a.mean()), 4),
        "engine_mean": round(float(b.mean()), 4),
    }
    if args.out:
        with open(args.out, "w") as f:
            json.dump(result, f, indent=1)
    print(json.dumps({k: v for k, v in result.items()
                      if not k.endswith("_rewards")}, indent=1))


if __name__ == "__main__":
    main()
