"""Measure the reference's per-control-step rate on this machine's CPU and
write BASELINE_MEASURED.json — the denominator for bench.py's vs_baseline.

What is measured: the reference's own adapter loop — siminterface.Simulator
init + N x apply(uniform action) (siminterface/simulator.py:125-231) on the
flagship scenario (Abilene in4-rand-cap1-2, abc 3-SF chain,
sample_config.yaml: 200 steps x 100 ms runs — BASELINE.md workload row).
This is the reference ENV-PHYSICS cost only; its real training loop adds a
torch GNN forward per step plus a 200-gradient-step burst per episode
(simple_ddpg.py:280-329), so the recorded steps/sec OVERSTATES the
reference's end-to-end SPS and vs_baseline is conservative.

(The full reference training loop is not runnable in this image:
torch_geometric / gym / stable_baselines3 are not installed, and installs
are prohibited.  The simulator loop runs unmodified via tools/minisimpy.)
"""
from __future__ import annotations

import json
import os
import statistics
import subprocess
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
NETWORK = "configs/networks/abilene/abilene-in4-rand-cap1-2.graphml"
STEPS = 200
REPEATS = 3


def main():
    rates = []
    runs = []
    for seed in range(REPEATS):
        r = subprocess.run(
            [sys.executable, os.path.join(HERE, "run_reference.py"),
             "--mode", "interface", "--network", NETWORK,
             "--steps", str(STEPS), "--seed", str(1234 + seed)],
            capture_output=True, text=True, timeout=900)
        r.check_returncode()
        out = json.loads(r.stdout.strip().splitlines()[-1])
        rates.append(out["steps_per_sec"])
        runs.append(out)
    result = {
        "reference_cpu_sps": round(statistics.median(rates), 2),
        "what": "siminterface init+apply loop (env physics only, no NN) "
                "on the flagship Abilene scenario; overstates the "
                "reference's full training-loop SPS, so vs_baseline is "
                "conservative",
        "network": NETWORK,
        "steps_per_run": STEPS,
        "repeats": REPEATS,
        "all_rates": rates,
        "sample_run": {k: runs[0][k] for k in
                       ("generated_flows", "processed_flows",
                        "dropped_flows", "avg_end2end_delay")},
    }
    path = os.path.join(REPO, "BASELINE_MEASURED.json")
    with open(path, "w") as f:
        json.dump(result, f, indent=1)
    print(json.dumps(result))


if __name__ == "__main__":
    main()
