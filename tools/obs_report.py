"""Render a run's ``events.jsonl`` into per-episode / per-phase summaries.

Usage:
    python tools/obs_report.py <run_dir | events.jsonl>  [--json]
    python tools/obs_report.py --selftest

Reads the event stream the ``gsc_tpu.obs`` subsystem writes (``cli train``
does by default), prints:

- a per-run header with the dtype policy (the ``precision`` event /
  run_start meta: policy name plus param/gnn/mlp/replay dtypes) and the
  engine knobs (run_start meta: ``substep_impl`` + ``unroll``) so a
  throughput comparison across runs is attributable to precision and
  substep engine;
- a per-episode table: SPS, return, success ratio, learner losses, the
  per-episode *delta* of each pipeline phase's host wall (the stream
  carries cumulative ``PhaseTimer`` totals), and device bytes-in-use;
- a final per-phase summary (total wall, mean ms per episode);
- a jit-compile summary from the retrace sentinel's ``compile`` events
  (gsc_tpu.analysis.sentinels.CompileMonitor): traces / XLA compiles and
  compile seconds per jitted entry point, with a retrace-churn flag when
  an entry point traced more than ``--retrace-threshold`` times (a
  steady-state pipelined loop traces each entry point once per static-arg
  variant; more means weak-type scalars or shape drift re-triggering
  tracing);
- every ``stall`` / ``invariant_violation`` record, verbatim fields;
- a recovery timeline from the resilience subsystem's ``recovery`` /
  ``escalation`` events: one line per self-healing action (dispatch retry,
  prefetcher restart, pipeline-off degradation, learner-state rollback,
  checkpoint resave, preemption snapshot) with per-(site, action) totals —
  a run that exits 0 after surviving faults shows HOW it survived;
- a device-memory growth check: bytes_in_use at the first vs last episode
  per device, flagged when growth exceeds ``--mem-growth-threshold``
  (a leaking HBM buffer shows as monotonic growth long before an OOM);
- a learning-dynamics section from the on-device learn ledger's
  ``learn_signal`` events (gsc_tpu.obs.learning): per-topology
  |TD-error| table (mixed batches AND the serial path's stamped
  topology), last-episode Q distribution moments, per-layer grad-norm
  peaks + param norms, replay fill;
- an async-fleet section for ``cli train --async`` runs, from the
  run-level ``async_train`` event plus the deferred flight-recorder
  ledgers (``async_actor_ep`` / ``async_learner_spans``,
  gsc_tpu.parallel.async_rl): a per-actor table (episodes / chunks /
  steps / rollout wall / channel-blocked wall / idle fraction /
  adoptions), the learner's policy-lag percentiles and wall
  decomposition (ingest vs learn-burst vs idle), and the weight
  adoption timeline (publish -> per-actor adopt latency per version);
- a serving section for ``cli serve`` runs, from the ``serve_start`` /
  ``serve_stats`` events (gsc_tpu.serve.PolicyServer): tier, requests/s,
  p50/p99 latency overall and per batch bucket, bucket occupancy,
  per-bucket startup (artifact-cache hit + prepare wall), the
  latency-decomposition table (queue-wait / batch-formation wait /
  device wall / fan-out mean per bucket, from the request-path tracer),
  the SLO verdict (attainment, error-budget burn rate, deadline-miss
  ratio, arrival-rate EWMA), and the rejection + pad-waste accounting.

``--json`` emits the same summary as one machine-readable JSON object.
``--selftest`` synthesizes a stream (including a stall and a leak),
renders it, and asserts both are flagged — the CI smoke target.

Stdlib only: this must run on a login node with no JAX installed.
"""
from __future__ import annotations

import argparse
import json
import os
import sys
import tempfile
from typing import Dict, List, Optional

PHASES = ("host_sample", "host_sample_wait", "dispatch", "drain")
# flag growth only past an absolute floor: allocator warmup on a small run
# doubles tiny numbers without meaning anything
MEM_FLOOR_BYTES = 16 * 2 ** 20


def load_events(path: str) -> List[Dict]:
    """Accept a run dir or the events.jsonl itself; walk rotated segments
    (``--obs-rotate-mb`` writes events.jsonl.N .. .1 before the live
    file) oldest-first so the stream reads as one; skip torn tail lines
    (the stream may still be appending).

    Events come back SORTED by ``ts`` within each run_start-delimited
    slice (stable): the hub stamps ``ts`` before taking the sink lock,
    so concurrent threads can interleave out of order in the file — the
    phase-delta logic below assumes one monotone stream.  The sort is
    per-run, never global, so appended runs whose wall clock stepped
    backwards (NTP, VM resume) cannot interleave across run
    boundaries."""
    if os.path.isdir(path):
        path = os.path.join(path, "events.jsonl")
    older = []
    n = 1
    while os.path.exists(f"{path}.{n}"):
        older.append(f"{path}.{n}")
        n += 1
    segments = list(reversed(older)) + (
        [path] if os.path.exists(path) else [])
    if not segments:
        raise SystemExit(f"no events stream at {path}")
    events = []
    for seg in segments:
        with open(seg) as f:
            for line in f:
                line = line.strip()
                if not line:
                    continue
                try:
                    events.append(json.loads(line))
                except json.JSONDecodeError:
                    continue   # torn final line of a live run
    def _ts(e):
        ts = e.get("ts") if isinstance(e, dict) else None
        return float(ts) if isinstance(ts, (int, float)) \
            and not isinstance(ts, bool) else float("-inf")

    out, seg = [], []
    for e in events:
        if isinstance(e, dict) and e.get("event") == "run_start" and seg:
            seg.sort(key=_ts)
            out.extend(seg)
            seg = []
        seg.append(e)
    seg.sort(key=_ts)
    out.extend(seg)
    return out


def load_perf(path: str) -> Optional[Dict]:
    """The run's cost ledger (``perf.json``, gsc_tpu.obs.perf) if one was
    written next to the event stream; None otherwise."""
    if not os.path.isdir(path):
        path = os.path.dirname(os.path.abspath(path))
    p = os.path.join(path, "perf.json")
    if not os.path.exists(p):
        return None
    try:
        with open(p) as f:
            return json.load(f)
    except (OSError, json.JSONDecodeError):
        return None


def phase_deltas(episodes: List[Dict]) -> List[Dict[str, float]]:
    """Per-episode phase seconds from the cumulative totals each episode
    event carries."""
    out, prev = [], {}
    for ev in episodes:
        totals = {name: info.get("total_s", 0.0)
                  for name, info in (ev.get("phases") or {}).items()}
        out.append({name: round(t - prev.get(name, 0.0), 4)
                    for name, t in totals.items()})
        prev = totals
    return out


def device_mem_series(episodes: List[Dict]) -> Dict[str, List[int]]:
    """{device: [bytes_in_use per episode]} over devices that report."""
    series: Dict[str, List[int]] = {}
    for ev in episodes:
        for rec in ev.get("device_memory") or []:
            if "bytes_in_use" in rec:
                series.setdefault(rec["device"], []).append(
                    rec["bytes_in_use"])
    return series


def last_run(events: List[Dict]) -> List[Dict]:
    """The JSONL sink appends, so a reused --obs-dir accumulates several
    runs in one stream; summarize the LAST one (mixing runs would produce
    negative phase deltas and interleaved episode numbers)."""
    starts = [i for i, e in enumerate(events)
              if e.get("event") == "run_start"]
    return events[starts[-1]:] if starts else events


def compile_summary(events: List[Dict],
                    retrace_threshold: int = 3) -> Dict:
    """Per-entry-point jit trace/compile totals from ``compile`` events,
    plus the names whose trace count exceeds the churn threshold."""
    per_fn: Dict[str, Dict] = {}
    for ev in events:
        if ev.get("event") != "compile":
            continue
        fn = ev.get("fn", "?")
        rec = per_fn.setdefault(
            fn, {"traces": 0, "xla_compiles": 0, "compile_s": 0.0})
        # compile_s totals BOTH stages: tracing+transform wall is often
        # the dominant share for large fused programs
        if ev.get("stage") == "trace":
            rec["traces"] += 1
            rec["compile_s"] = round(
                rec["compile_s"] + float(ev.get("duration_s") or 0.0), 4)
        elif ev.get("stage") == "xla":
            rec["xla_compiles"] += 1
            rec["compile_s"] = round(
                rec["compile_s"] + float(ev.get("duration_s") or 0.0), 4)
    flags = sorted(fn for fn, rec in per_fn.items()
                   if rec["traces"] > retrace_threshold)
    return {"per_fn": per_fn, "retrace_flags": flags}


def perf_summary(perf: Optional[Dict]) -> Optional[Dict]:
    """Condense a perf.json cost ledger for the report: one row per
    watched entry point (FLOPs, bytes, fusions, MFU, roofline regime,
    per-dispatch wall) plus the phase split and schema version."""
    if not perf:
        return None
    rows = {}
    for name, e in sorted((perf.get("entries") or {}).items()):
        if not (e or {}).get("available"):
            rows[name] = {"available": False, "error": (e or {}).get("error")}
            continue
        roof = e.get("roofline") or {}
        col = e.get("collectives") or {}
        rows[name] = {
            "flops": e.get("flops"),
            "bytes_accessed": e.get("bytes_accessed"),
            "fusions": e.get("fusions"),
            "dispatches": e.get("dispatches"),
            "wall_ms_mean": (round(1e3 * e["wall_s_mean"], 3)
                             if e.get("wall_s_mean") is not None else None),
            "mfu": e.get("mfu"),
            "regime": roof.get("regime"),
            "roof_multiple": roof.get("roof_multiple"),
            # cross-device movers per call (partitioned executables
            # only; 0 on single-device programs, absent on pre-PR13
            # ledgers) — the tp-vs-sharded interconnect columns
            "collective_count": col.get("count"),
            "collective_bytes": col.get("bytes"),
        }
    phases = perf.get("phases") or {}
    dispatch_s = (phases.get("dispatch") or {}).get("total_s") or 0.0
    host_s = sum((info or {}).get("total_s") or 0.0
                 for name, info in phases.items() if name != "dispatch")
    return {
        "schema_version": perf.get("schema_version"),
        "backend": perf.get("backend"),
        "peaks": perf.get("peaks"),
        "entries": rows,
        # device-vs-host split: dispatch wall is time handing work to the
        # device (covers device compute on a saturated pipeline), the
        # rest is host-side sampling/draining
        "device_vs_host": {"dispatch_s": round(dispatch_s, 4),
                           "host_s": round(host_s, 4)},
    }


def summarize(events: List[Dict], mem_growth_threshold: float = 0.2,
              retrace_threshold: int = 3,
              perf: Optional[Dict] = None) -> Dict:
    runs_in_stream = max(
        sum(1 for e in events if e.get("event") == "run_start"), 1)
    events = last_run(events)
    episodes = [e for e in events if e.get("event") == "episode"]
    stalls = [e for e in events if e.get("event") == "stall"]
    violations = [e for e in events
                  if e.get("event") == "invariant_violation"]
    recoveries = [e for e in events if e.get("event") == "recovery"]
    escalations = [e for e in events if e.get("event") == "escalation"]
    deltas = phase_deltas(episodes)

    rows = []
    for ev, d in zip(episodes, deltas):
        mem = [r.get("bytes_in_use") for r in (ev.get("device_memory") or [])
               if "bytes_in_use" in r]
        rows.append({
            "episode": ev.get("episode"),
            "sps": ev.get("sps"),
            "return": ev.get("episodic_return"),
            "succ": ev.get("mean_succ_ratio"),
            "critic_loss": ev.get("critic_loss"),
            "actor_loss": ev.get("actor_loss"),
            **{f"{p}_ms": round(1e3 * d.get(p, 0.0), 1) for p in PHASES
               if p in d},
            "trunc": ev.get("truncated_arrivals", 0),
            "drops": sum((ev.get("drop_reasons") or {}).values()),
            "mem_mb": round(sum(mem) / 2 ** 20, 1) if mem else None,
        })

    phase_summary = {}
    if episodes:
        final = episodes[-1].get("phases") or {}
        for name, info in sorted(final.items()):
            phase_summary[name] = {
                "total_s": info.get("total_s"),
                "count": info.get("count"),
                "mean_ms": info.get("mean_ms"),
            }

    # HBM-data availability: distinguish "no allocator stats on this
    # backend" (CPU memory_stats() is None) from "usage was flat" — the
    # device records carry available/backend either way
    mem_unavailable = sorted({
        rec.get("backend", "unknown")
        for ev in episodes for rec in (ev.get("device_memory") or [])
        if rec.get("available") is False})
    mem_flags = []
    for device, series in device_mem_series(episodes).items():
        if len(series) < 2:
            continue
        first, last = series[0], series[-1]
        growth = (last - first) / max(first, 1)
        if last - first > MEM_FLOOR_BYTES and growth > mem_growth_threshold:
            mem_flags.append({
                "device": device,
                "first_bytes": first, "last_bytes": last,
                "growth_pct": round(100 * growth, 1),
            })

    last_run_end = next((e for e in reversed(events)
                         if e.get("event") == "run_end"), None)
    # dtype-policy header fields: the trainer emits one `precision` event
    # per run (RunObserver.record_precision); run_start meta carries the
    # policy name too — either suffices for the header
    precision_ev = next((e for e in events
                         if e.get("event") == "precision"), None)
    run_start = next((e for e in events
                      if e.get("event") == "run_start"), None)
    precision = None
    if precision_ev is not None:
        precision = {k: precision_ev.get(k)
                     for k in ("name", "param_dtype", "gnn_compute",
                               "mlp_compute", "replay_dtype")}
    elif run_start is not None and run_start.get("precision"):
        precision = {"name": run_start["precision"]}
    # engine-knob header fields (run_start meta, cli train): the substep
    # implementation and scan-unroll factor the run was built with, so a
    # throughput comparison across runs attributes the engine share
    engine = None
    if run_start is not None and run_start.get("substep_impl"):
        engine = {"substep_impl": run_start["substep_impl"],
                  "unroll": run_start.get("unroll", 1)}
    # mesh header fields (run_start meta, cli train --mesh): the DPxMP
    # carving, the partition rulebook and the compact per-leaf spec
    # counts, so a multi-chip run's layout is readable off the report
    mesh = None
    if run_start is not None and run_start.get("mesh"):
        mesh = {"mesh": run_start["mesh"],
                "partition_rules": run_start.get("partition_rules"),
                "partition_specs": run_start.get("partition_specs") or {}}
    # mixed-topology section (cli train --topo-mix): harness_episode
    # events carry per-topology mean returns when the batch is a mixture
    # — aggregated here per network name so a collapsing mixture member
    # is readable off the report, not buried in replica vectors.
    # Single-replica runs stamp a `topology` field on their episode
    # events instead (the serial trainer path) — merged into the SAME
    # table, so homogeneous and mixed runs report through one surface.
    topo_mix = (run_start or {}).get("topo_mix")
    per_topology = {}

    def _topo_rec(name):
        return per_topology.setdefault(
            name, {"episodes": 0, "sum": 0.0, "last": None})

    for ev in events:
        if ev.get("event") == "harness_episode":
            for name, v in (ev.get("per_topology_return") or {}).items():
                rec = _topo_rec(name)
                rec["episodes"] += 1
                rec["sum"] += float(v)
                rec["last"] = float(v)
        elif ev.get("event") == "episode" and ev.get("topology") \
                and isinstance(ev.get("episodic_return"), (int, float)):
            rec = _topo_rec(str(ev["topology"]))
            rec["episodes"] += 1
            rec["sum"] += float(ev["episodic_return"])
            rec["last"] = float(ev["episodic_return"])
    per_topology = {
        name: {"episodes": r["episodes"],
               "mean_return": round(r["sum"] / max(r["episodes"], 1), 3),
               "last_return": round(r["last"], 3)}
        for name, r in per_topology.items()}
    # learning-dynamics section (the on-device learn ledger,
    # gsc_tpu.obs.learning): per-topology |TD-error|, Q distribution
    # moments, per-layer grad/param norm health, replay fill — one
    # learn_signal event per drained episode
    learning = _learning_summary(
        [e for e in events if e.get("event") == "learn_signal"])
    # async-fleet section (cli train --async): the run-level async_train
    # info event plus the deferred flight-recorder ledgers
    async_fleet = _async_summary(events)
    # serving section (cli serve runs): the final serve_stats event holds
    # the cumulative numbers; serve_start carries startup + cache hits
    serve_start = next((e for e in events
                        if e.get("event") == "serve_start"), None)
    serve_stats = [e for e in events if e.get("event") == "serve_stats"]
    serving = None
    if serve_start is not None or serve_stats:
        # headline numbers come from the last NON-worker stats record
        # when one exists (single-server runs); in a fleet every
        # serve_stats is worker-tagged, so the shared-histogram numbers
        # (p50/p99/rps) are fleet-wide on any of them while the request
        # total comes from fleet_stats below
        untagged = [e for e in serve_stats if not e.get("worker")]
        if untagged:
            last = untagged[-1]
        elif serve_stats:
            # fleet run: prefer a real worker's record over the spr
            # brownout tier's (it closes last, and its tier/SLO would
            # mislabel a learned fleet's headline)
            non_spr = [e for e in serve_stats if e.get("worker") != "spr"]
            last = (non_spr or serve_stats)[-1]
        else:
            last = {}
        # fleet view (cli serve --workers N): per-worker final stats
        # (each worker's serve_stats carry worker= + worker-local
        # requests/occupancy), the fleet_stats total record, and the
        # hot-swap timeline from weight_swap events
        per_worker: Dict[str, Dict] = {}
        for ev in serve_stats:
            if ev.get("worker"):
                per_worker[ev["worker"]] = {
                    "requests": ev.get("worker_requests",
                                       ev.get("requests")),
                    "occupancy": ev.get("occupancy") or {},
                    "queue_depth": ev.get("queue_depth"),
                    "policy_version": ev.get("policy_version", 0),
                    "swaps": ev.get("swaps", 0),
                }
        fleet_stats = next((e for e in reversed(events)
                            if e.get("event") == "fleet_stats"), None)
        swap_timeline = [
            {"worker": ev.get("worker"), "version": ev.get("version"),
             "ts": ev.get("ts"), "swap_ms": ev.get("swap_ms"),
             "requests_in_flight": ev.get("requests_in_flight"),
             "weights_applied": ev.get("weights_applied")}
            for ev in events if ev.get("event") == "weight_swap"]
        serving = {
            "tier": last.get("tier") or (serve_start or {}).get("tier"),
            "requests": last.get("requests"),
            "rps": last.get("rps"),
            "p50_ms": last.get("p50_ms"),
            "p99_ms": last.get("p99_ms"),
            "queue_depth": last.get("queue_depth"),
            "occupancy": last.get("occupancy") or {},
            "buckets": last.get("buckets") or {},
            "startup_s": (serve_start or {}).get("startup_s"),
            "bucket_prepare": (serve_start or {}).get("bucket_prepare")
            or {},
            # request-path tracing + SLO engine (gsc_tpu.obs.slo): the
            # final serve_stats carries the per-bucket latency split,
            # the SLO snapshot and the rejection totals when tracing ran
            "decomposition": last.get("decomposition") or {},
            "slo": last.get("slo"),
            "rejected": last.get("rejected") or {},
            "workers": per_worker,
            "fleet": fleet_stats,
            "swap_timeline": swap_timeline,
        }
        if fleet_stats is not None and not untagged:
            # fleet run: the request total, merged SLO verdict and
            # merged occupancy are the fleet's, not the last-reporting
            # worker's
            serving["requests"] = fleet_stats.get("requests",
                                                  serving["requests"])
            if fleet_stats.get("slo"):
                serving["slo"] = fleet_stats["slo"]
            merged_occ: Dict[str, int] = {}
            for rec in per_worker.values():
                for b, n in (rec.get("occupancy") or {}).items():
                    merged_occ[b] = merged_occ.get(b, 0) + int(n)
            if merged_occ:
                serving["occupancy"] = merged_occ
    return {
        "episodes": len(episodes),
        "run": (episodes[0].get("run") if episodes
                else (serve_start or {}).get("run")),
        "serving": serving,
        "runs_in_stream": runs_in_stream,
        "status": (last_run_end or {}).get("status"),
        "precision": precision,
        "engine": engine,
        "mesh": mesh,
        "topo_mix": topo_mix,
        "per_topology": per_topology,
        "learning": learning,
        "async_fleet": async_fleet,
        "rows": rows,
        "phase_summary": phase_summary,
        "stalls": stalls,
        "invariant_violations": violations,
        "recoveries": recoveries,
        "escalations": escalations,
        "recovery_totals": _recovery_totals(recoveries),
        "memory_growth_flags": mem_flags,
        "memory_unavailable_backends": mem_unavailable,
        "drop_totals": _drop_totals(episodes),
        "compiles": compile_summary(events, retrace_threshold),
        "perf": perf_summary(perf),
    }


def _learning_summary(learn_events: List[Dict]) -> Optional[Dict]:
    """Condense the per-episode ``learn_signal`` stream: per-topology
    |TD| means, first->last overall |TD|, the last episode's Q moments,
    per-layer grad-norm peaks (exploding gradients show as a peak far
    above the last value) + last param norms, and replay fill."""
    if not learn_events:
        return None
    per_topo: Dict[str, Dict] = {}
    grad_peak: Dict[str, float] = {}
    td_series = []
    for ev in learn_events:
        for name, v in (ev.get("per_topology_td") or {}).items():
            rec = per_topo.setdefault(
                name, {"episodes": 0, "sum": 0.0, "last": None})
            rec["episodes"] += 1
            rec["sum"] += float(v)
            rec["last"] = float(v)
        for layer, v in (ev.get("grad_norms") or {}).items():
            if isinstance(v, (int, float)):
                grad_peak[layer] = max(grad_peak.get(layer, 0.0), float(v))
        if isinstance(ev.get("td_abs_mean"), (int, float)):
            td_series.append(float(ev["td_abs_mean"]))
    last = learn_events[-1]
    return {
        "episodes": len(learn_events),
        "per_topology_td": {
            name: {"episodes": r["episodes"],
                   "mean_td_abs": round(r["sum"] / max(r["episodes"], 1), 6),
                   "last_td_abs": round(r["last"], 6)}
            for name, r in per_topo.items()},
        "td_abs_first": td_series[0] if td_series else None,
        "td_abs_last": td_series[-1] if td_series else None,
        "q_last": {k: last.get(k)
                   for k in ("q_mean", "q_std", "q_min", "q_max")},
        "grad_norm_peak": {k: round(v, 6)
                           for k, v in sorted(grad_peak.items())},
        "grad_norms_last": last.get("grad_norms") or {},
        "param_norms_last": last.get("param_norms") or {},
        "replay_fill_last": (last.get("replay") or {}).get("fill"),
    }


def _async_summary(events: List[Dict]) -> Optional[Dict]:
    """Condense the async-fleet flight-recorder records: the run-level
    ``async_train`` info event plus the deferred ``async_actor_ep`` /
    ``async_learner_spans`` ledgers (gsc_tpu.parallel.async_rl).  Three
    views: a per-actor table (episodes / chunks / steps / rollout wall /
    channel-blocked wall / idle fraction / adoptions), the learner's
    lag + wall decomposition (ingest vs learn-burst vs idle), and the
    weight adoption timeline (publish -> per-actor adopt latency per
    version)."""
    info = next((e for e in reversed(events)
                 if e.get("event") == "async_train"), None)
    actor_eps = [e for e in events if e.get("event") == "async_actor_ep"]
    spans = [e for e in events
             if e.get("event") == "async_learner_spans"]
    if info is None and not actor_eps and not spans:
        return None
    fracs = (info or {}).get("actor_idle_fracs") or []
    per_actor: Dict[int, Dict] = {}
    adopts_by_ver: Dict[int, Dict[int, float]] = {}
    for ev in actor_eps:
        aid = int(ev.get("actor", 0))
        rec = per_actor.setdefault(aid, {
            "episodes": 0, "chunks": 0, "steps": 0, "rollout_s": 0.0,
            "blocked_s": 0.0, "adopts": 0, "last_version": 0})
        rec["episodes"] += 1
        for c in ev.get("chunks") or []:
            rec["chunks"] += 1
            rec["rollout_s"] += float(c[1]) - float(c[0])
        for p in ev.get("puts") or []:
            rec["blocked_s"] += float(p[1])
            rec["steps"] += int(p[2])
        for a in ev.get("adopts") or []:
            rec["adopts"] += 1
            ver = int(a[1])
            rec["last_version"] = max(rec["last_version"], ver)
            prev = adopts_by_ver.setdefault(ver, {}).get(aid)
            ts = float(a[0])
            if prev is None or ts < prev:
                adopts_by_ver[ver][aid] = ts
    for aid, rec in per_actor.items():
        rec["rollout_s"] = round(rec["rollout_s"], 4)
        rec["blocked_s"] = round(rec["blocked_s"], 4)
        if aid < len(fracs):
            rec["idle_frac"] = fracs[aid]
    ingest_s = burst_s = 0.0
    n_ingests = n_bursts = 0
    lags: List[int] = []
    publishes: Dict[int, float] = {}
    for ev in spans:
        for r in ev.get("ingests") or []:
            n_ingests += 1
            ingest_s += float(r[1]) - float(r[0])
            lags.append(int(r[4]))
        for r in ev.get("bursts") or []:
            n_bursts += 1
            burst_s += float(r[1]) - float(r[0])
        for r in ev.get("publishes") or []:
            ver, ts = int(r[1]), float(r[0])
            if ver not in publishes or ts < publishes[ver]:
                publishes[ver] = ts
    timeline = []
    for ver in sorted(publishes):
        timeline.append({
            "version": ver, "publish_ts": publishes[ver],
            "adopt_lag_s": {
                aid: round(ts - publishes[ver], 4)
                for aid, ts in sorted(
                    (adopts_by_ver.get(ver) or {}).items())}})
    orphan_adopts = sorted(v for v in adopts_by_ver if v not in publishes)
    wall = (info or {}).get("wall_s")
    idle_s = (info or {}).get("learner_idle_s")
    decomposition = {
        "ingest_s": round(ingest_s, 4), "n_ingests": n_ingests,
        "burst_s": round(burst_s, 4), "n_bursts": n_bursts,
        "idle_s": idle_s,
        # the remainder is scheduling + publish + drain overhead — a
        # learner whose wall is neither ingesting, learning nor idling
        # is losing time to the loop itself
        "other_s": (round(wall - ingest_s - burst_s - idle_s, 4)
                    if isinstance(wall, (int, float))
                    and isinstance(idle_s, (int, float)) else None),
    }
    lag = {
        "samples": len(lags),
        "max": max(lags) if lags else 0,
        "mean": (round(sum(lags) / len(lags), 4) if lags else 0.0),
    }
    if info:
        for k in ("policy_lag_p50", "policy_lag_p99", "policy_lag_max",
                  "policy_lag_mean"):
            if isinstance(info.get(k), (int, float)):
                lag[k.replace("policy_lag_", "")] = info[k]
    return {
        "info": {k: info.get(k) for k in (
            "actors", "episodes_drained", "produced_steps",
            "ingested_steps", "transitions_lost", "bursts", "publishes",
            "published_version", "wall_s", "learner_idle_frac",
            "actor_idle_frac")} if info else None,
        "per_actor": per_actor,
        "lag": lag,
        "decomposition": decomposition,
        "adoption_timeline": timeline,
        "orphan_adopt_versions": orphan_adopts,
    }


def _recovery_totals(recoveries: List[Dict]) -> Dict[str, int]:
    """``{"site/action": count}`` over the recovery timeline."""
    totals: Dict[str, int] = {}
    for ev in recoveries:
        key = f"{ev.get('site', '?')}/{ev.get('action', '?')}"
        totals[key] = totals.get(key, 0) + 1
    return totals


def _drop_totals(episodes: List[Dict]) -> Dict[str, int]:
    totals: Dict[str, int] = {}
    for ev in episodes:
        for reason, n in (ev.get("drop_reasons") or {}).items():
            totals[reason] = totals.get(reason, 0) + int(n)
    return totals


def _fmt(v, width) -> str:
    if v is None:
        s = "-"
    elif isinstance(v, float):
        s = f"{v:.3f}" if abs(v) < 1000 else f"{v:.0f}"
    else:
        s = str(v)
    return s.rjust(width)


def render_text(summary: Dict, out=sys.stdout):
    w = out.write
    w(f"run: {summary['run']}  episodes: {summary['episodes']}  "
      f"status: {summary['status']}\n")
    perf = summary.get("perf")
    if perf:
        w(f"perf ledger: schema v{perf.get('schema_version')}  "
          f"backend {perf.get('backend')}\n")
    prec = summary.get("precision")
    if prec:
        detail = ""
        if prec.get("param_dtype"):
            detail = (f"  (param {prec['param_dtype']} / gnn "
                      f"{prec.get('gnn_compute')} / mlp "
                      f"{prec.get('mlp_compute')} / replay "
                      f"{prec.get('replay_dtype')})")
        w(f"precision: {prec.get('name')}{detail}\n")
    eng = summary.get("engine")
    if eng:
        w(f"substep: {eng.get('substep_impl')}  "
          f"unroll: {eng.get('unroll')}\n")
    mesh = summary.get("mesh")
    if mesh:
        specs = mesh.get("partition_specs") or {}
        spec_txt = ", ".join(f"{k} x{v}" for k, v in specs.items())
        w(f"mesh: {mesh.get('mesh')}  rules: "
          f"{mesh.get('partition_rules')}"
          + (f"  ({spec_txt})" if spec_txt else "") + "\n")
    if summary.get("topo_mix"):
        w(f"topo mix: {summary['topo_mix']}\n")
    if summary.get("runs_in_stream", 1) > 1:
        w(f"(stream holds {summary['runs_in_stream']} appended runs — "
          "showing the last)\n")
    sv = summary.get("serving")
    if sv:
        w(f"\nserving ({sv.get('tier')} tier): "
          f"{sv.get('requests')} requests  {sv.get('rps')} req/s  "
          f"p50 {sv.get('p50_ms')} ms  p99 {sv.get('p99_ms')} ms  "
          f"startup {sv.get('startup_s')}s\n")
        buckets = set(sv.get("buckets", {})) | set(sv.get("occupancy", {})) \
            | set(sv.get("bucket_prepare", {}))
        for b in sorted(buckets, key=int):
            lat = sv.get("buckets", {}).get(b, {})
            prep = sv.get("bucket_prepare", {}).get(b, {})
            w(f"  bucket {b:>4}: occupancy "
              f"{sv.get('occupancy', {}).get(b, 0):>6}   "
              f"p50 {lat.get('p50_ms', '-'):>8} ms   "
              f"p99 {lat.get('p99_ms', '-'):>8} ms   "
              f"cache_hit {str(prep.get('cache_hit', '-')):<5} "
              f"prepare {prep.get('prepare_s', '-')}s\n")
        slo = sv.get("slo")
        if slo:
            w(f"  SLO: p99 target {_fmt(slo.get('p99_target_ms'), 1)} ms  "
              f"attainment {_fmt(slo.get('attainment'), 1)}  "
              f"budget burn {_fmt(slo.get('burn_rate'), 1)}x  "
              f"deadline-miss {_fmt(slo.get('deadline_miss_ratio'), 1)}  "
              f"arrival {_fmt(slo.get('arrival_rate_rps'), 1)} rps\n")
            w(f"  pad waste {_fmt(slo.get('pad_waste'), 1)}  "
              f"queue-wait fraction "
              f"{_fmt(slo.get('queue_wait_frac'), 1)}\n")
        if sv.get("rejected"):
            rej = sv["rejected"]
            w("  rejected: " + "  ".join(
                f"{reason} {n}" for reason, n in sorted(rej.items()))
              + "\n")
        if sv.get("decomposition"):
            w("  latency decomposition (ms mean per bucket: queue-wait /"
              " batch-formation / device / fan-out):\n")
            w(f"  {'bucket':>8} {'queue_ms':>10} {'batch_ms':>10} "
              f"{'device_ms':>10} {'fanout_ms':>10}\n")
            for b in sorted(sv["decomposition"], key=int):
                row = sv["decomposition"][b]
                w(f"  {b:>8} {_fmt(row.get('queue_ms'), 10)} "
                  f"{_fmt(row.get('batch_ms'), 10)} "
                  f"{_fmt(row.get('device_ms'), 10)} "
                  f"{_fmt(row.get('fanout_ms'), 10)}\n")
        if sv.get("workers"):
            fl = sv.get("fleet") or {}
            head = f"\n  fleet: {len(sv['workers'])} worker(s)"
            if fl:
                head += (f"  {fl.get('requests')} requests total  "
                         f"{fl.get('swaps')} hot-swap(s)")
                brown = fl.get("brownout") or {}
                if any(brown.values()):
                    head += "  brownout: " + "  ".join(
                        f"{reason} {n}"
                        for reason, n in sorted(brown.items()) if n)
            w(head + "\n")
            w(f"  {'worker':>8} {'requests':>9} {'queue':>6} "
              f"{'version':>8} {'swaps':>6} {'occupancy':<24}\n")
            for name in sorted(sv["workers"]):
                rec = sv["workers"][name]
                occ = " ".join(f"b{b}:{n}" for b, n in
                               sorted((rec.get("occupancy") or {}).items(),
                                      key=lambda kv: int(kv[0])))
                w(f"  {name:>8} {_fmt(rec.get('requests'), 9)} "
                  f"{_fmt(rec.get('queue_depth'), 6)} "
                  f"{_fmt(rec.get('policy_version'), 8)} "
                  f"{_fmt(rec.get('swaps'), 6)} {occ:<24}\n")
        if sv.get("swap_timeline"):
            w("  hot-swap timeline (version @ wall, requests in flight "
              "at the swap):\n")
            t00 = sv["swap_timeline"][0].get("ts") or 0.0
            for s in sv["swap_timeline"]:
                dt = (s.get("ts") or 0.0) - t00
                w(f"    +{dt:7.3f}s  v{s.get('version')}"
                  f"  worker {s.get('worker') or '-':<5}"
                  f"  in-flight {_fmt(s.get('requests_in_flight'), 3)}"
                  f"  swap {_fmt(s.get('swap_ms'), 1)} ms"
                  + ("" if s.get("weights_applied", True)
                     else "  (version stamp only)") + "\n")
    rows = summary["rows"]
    if rows:
        w("(*_ms columns are phase-wall deltas between consecutive "
          "episode events; on pipelined runs the deferred drain shifts "
          "attribution one row — totals below are exact)\n")
        cols = list(rows[0].keys())
        widths = {c: max(len(c), 9) for c in cols}
        w("  ".join(c.rjust(widths[c]) for c in cols) + "\n")
        for r in rows:
            w("  ".join(_fmt(r.get(c), widths[c]) for c in cols) + "\n")
    if summary.get("per_topology"):
        w("\nper-topology returns (mixed batch, mean over the topology's "
          "replicas):\n")
        w(f"  {'topology':<28} {'episodes':>8} {'mean_return':>12} "
          f"{'last_return':>12}\n")
        for name, rec in sorted(summary["per_topology"].items()):
            w(f"  {name:<28} {rec['episodes']:>8} "
              f"{rec['mean_return']:>12} {rec['last_return']:>12}\n")
    ln = summary.get("learning")
    if ln:
        w(f"\nlearning dynamics (on-device learn ledger, "
          f"{ln['episodes']} episode(s)):\n")
        w(f"  |TD| mean: {ln.get('td_abs_first')} -> "
          f"{ln.get('td_abs_last')}   Q last: "
          f"mean {ln['q_last'].get('q_mean')}  std "
          f"{ln['q_last'].get('q_std')}  min {ln['q_last'].get('q_min')}  "
          f"max {ln['q_last'].get('q_max')}   replay fill "
          f"{ln.get('replay_fill_last')}\n")
        if ln.get("per_topology_td"):
            w(f"  {'topology':<28} {'episodes':>8} {'mean_|TD|':>12} "
              f"{'last_|TD|':>12}\n")
            for name, rec in sorted(ln["per_topology_td"].items()):
                w(f"  {name:<28} {rec['episodes']:>8} "
                  f"{rec['mean_td_abs']:>12} {rec['last_td_abs']:>12}\n")
        if ln.get("grad_norm_peak"):
            w("  grad/param health (peak grad norm | last grad | "
              "last param, per layer):\n")
            for layer in sorted(ln["grad_norm_peak"]):
                w(f"    {layer:<28} peak {ln['grad_norm_peak'][layer]:>12} "
                  f" last {_fmt(ln['grad_norms_last'].get(layer), 12)} "
                  f" param {_fmt(ln['param_norms_last'].get(layer), 12)}\n")
    af = summary.get("async_fleet")
    if af:
        inf = af.get("info") or {}
        w(f"\nasync fleet ({inf.get('actors', '?')} actor(s), wall "
          f"{inf.get('wall_s', '?')}s): produced "
          f"{inf.get('produced_steps', '?')} steps, ingested "
          f"{inf.get('ingested_steps', '?')}, lost "
          f"{inf.get('transitions_lost', '?')}; "
          f"{inf.get('bursts', '?')} burst(s), "
          f"{inf.get('publishes', '?')} publish(es) "
          f"(last v{inf.get('published_version', '?')})\n")
        lag = af.get("lag") or {}
        w(f"  policy lag (versions): mean {_fmt(lag.get('mean'), 1)}  "
          f"p50 {_fmt(lag.get('p50'), 1)}  p99 {_fmt(lag.get('p99'), 1)}  "
          f"max {_fmt(lag.get('max'), 1)}  "
          f"({lag.get('samples', 0)} ingest(s))\n")
        dec = af.get("decomposition") or {}
        w(f"  learner wall: ingest {_fmt(dec.get('ingest_s'), 1)}s "
          f"({dec.get('n_ingests')}x)  learn-burst "
          f"{_fmt(dec.get('burst_s'), 1)}s ({dec.get('n_bursts')}x)  "
          f"idle {_fmt(dec.get('idle_s'), 1)}s "
          f"(frac {_fmt(inf.get('learner_idle_frac'), 1)})  "
          f"other {_fmt(dec.get('other_s'), 1)}s\n")
        if af.get("per_actor"):
            w(f"  {'actor':>6} {'episodes':>8} {'chunks':>7} {'steps':>8} "
              f"{'rollout_s':>10} {'blocked_s':>10} {'idle_frac':>10} "
              f"{'adopts':>7} {'last_v':>7}\n")
            for aid in sorted(af["per_actor"]):
                rec = af["per_actor"][aid]
                w(f"  {aid:>6} {_fmt(rec.get('episodes'), 8)} "
                  f"{_fmt(rec.get('chunks'), 7)} "
                  f"{_fmt(rec.get('steps'), 8)} "
                  f"{_fmt(rec.get('rollout_s'), 10)} "
                  f"{_fmt(rec.get('blocked_s'), 10)} "
                  f"{_fmt(rec.get('idle_frac'), 10)} "
                  f"{_fmt(rec.get('adopts'), 7)} "
                  f"{_fmt(rec.get('last_version'), 7)}\n")
        if af.get("adoption_timeline"):
            w("  adoption timeline (publish wall offset; per-actor "
              "adopt lag after the publish):\n")
            t00 = af["adoption_timeline"][0].get("publish_ts") or 0.0
            for rec in af["adoption_timeline"]:
                dt = (rec.get("publish_ts") or 0.0) - t00
                adopters = rec.get("adopt_lag_s") or {}
                tail = "  ".join(
                    f"actor{aid} +{adopters[aid]:.3f}s"
                    for aid in sorted(adopters)) or "(not adopted)"
                w(f"    +{dt:7.3f}s  v{rec.get('version')}  -> {tail}\n")
        if af.get("orphan_adopt_versions"):
            w("  (adopted version(s) with no recorded publish: "
              + ", ".join(f"v{v}" for v in af["orphan_adopt_versions"])
              + " — initial weights or a truncated ledger)\n")
    if perf and perf.get("entries"):
        w("\nperf (device-cost ledger, per watched entry point):\n")
        w(f"  {'entry':<20} {'flops':>12} {'bytes':>12} {'fusions':>8} "
          f"{'coll':>6} {'coll_B':>10} "
          f"{'disp':>6} {'wall_ms':>9} {'mfu':>10} {'regime':<14} "
          f"{'roof_x':>8}\n")
        for name, r in perf["entries"].items():
            if not r.get("available", True):
                w(f"  {name:<20} (cost model unavailable: "
                  f"{r.get('error')})\n")
                continue
            w(f"  {name:<20} {_fmt(r.get('flops'), 12)} "
              f"{_fmt(r.get('bytes_accessed'), 12)} "
              f"{_fmt(r.get('fusions'), 8)} "
              f"{_fmt(r.get('collective_count'), 6)} "
              f"{_fmt(r.get('collective_bytes'), 10)} "
              f"{_fmt(r.get('dispatches'), 6)} "
              f"{_fmt(r.get('wall_ms_mean'), 9)} "
              f"{r.get('mfu') if r.get('mfu') is not None else '-':>10} "
              f"{(r.get('regime') or '-'):<14} "
              f"{_fmt(r.get('roof_multiple'), 8)}\n")
        dvh = perf.get("device_vs_host") or {}
        w(f"  device-vs-host wall: dispatch {dvh.get('dispatch_s')}s / "
          f"host {dvh.get('host_s')}s\n")
    w("\nper-phase host wall (cumulative):\n")
    for name, info in summary["phase_summary"].items():
        w(f"  {name:<18} total {info['total_s']:>9}s   "
          f"count {info['count']:>5}   mean {info['mean_ms']:>8} ms\n")
    if summary["drop_totals"]:
        w("\nsim drop totals: "
          + json.dumps(summary["drop_totals"]) + "\n")
    compiles = summary.get("compiles") or {}
    if compiles.get("per_fn"):
        w("\njit compiles (retrace sentinel):\n")
        for fn, rec in sorted(compiles["per_fn"].items()):
            w(f"  {fn:<20} traces {rec['traces']:>3}   xla "
              f"{rec['xla_compiles']:>3}   compile {rec['compile_s']:>8}s\n")
    if compiles.get("retrace_flags"):
        w(f"\n!! RETRACE CHURN: {', '.join(compiles['retrace_flags'])} "
          "traced more than the steady-state budget — look for weak-type "
          "scalars or shape drift in the episode loop\n")
    if summary.get("recoveries"):
        recs = summary["recoveries"]
        w(f"\nrecovery timeline ({len(recs)} action(s); totals "
          + json.dumps(summary.get("recovery_totals", {})) + "):\n")
        for r in recs:
            line = (f"  ep {r.get('episode', '-'):>4}  "
                    f"{r.get('site', '?')}/{r.get('action', '?')}")
            if r.get("fault"):
                line += f"  fault={r['fault']}"
            if r.get("attempt") is not None:
                line += f"  attempt={r['attempt']}"
            w(line + "\n")
            if r.get("detail"):
                w(f"        {r['detail']}\n")
    for esc in summary.get("escalations") or []:
        w(f"\n!! WATCHDOG ESCALATION: quiet {esc.get('age_s')}s "
          f"(budget {esc.get('budget_s')}s x "
          f"{esc.get('quiet_periods')} periods) -> {esc.get('action')}\n")
    if summary["stalls"]:
        w(f"\n!! {len(summary['stalls'])} STALL(s):\n")
        for s in summary["stalls"]:
            w(f"  age {s.get('age_s')}s / budget {s.get('budget_s')}s — "
              f"stuck in phase {s.get('last_phase')!r} "
              f"({s.get('last_phase_state')}), dispatch-drain lag "
              f"{s.get('dispatch_drain_lag')}, "
              f"prefetch queue {s.get('prefetch_queue_depth', '-')}, "
              f"prefetcher alive {s.get('prefetcher_alive', '-')}\n")
    if summary["invariant_violations"]:
        w(f"\n!! {len(summary['invariant_violations'])} INVARIANT "
          "VIOLATION(s):\n")
        for v in summary["invariant_violations"]:
            w(f"  episode {v.get('episode')}: "
              + "; ".join(v.get("violations", [])) + "\n")
    if summary["memory_growth_flags"]:
        w("\n!! DEVICE MEMORY GROWTH:\n")
        for m in summary["memory_growth_flags"]:
            w(f"  {m['device']}: {m['first_bytes']} -> {m['last_bytes']} "
              f"bytes (+{m['growth_pct']}%)\n")
    if summary.get("memory_unavailable_backends"):
        w("\ndevice memory: no HBM data — backend(s) "
          f"{', '.join(summary['memory_unavailable_backends'])} report "
          "no allocator stats (memory_stats() is None on CPU); flat "
          "usage and missing data are NOT the same thing\n")
    if not (summary["stalls"] or summary["invariant_violations"]
            or summary["memory_growth_flags"]
            or summary.get("recoveries")
            or (summary.get("compiles") or {}).get("retrace_flags")):
        w("\nhealthy: no stalls, no invariant violations, no device "
          "memory growth, no retrace churn, no recovery actions\n")


# ------------------------------------------------------------------ selftest
def _synthetic_events(path: str, episodes: int = 5):
    """A stream with the real schema: growing cumulative phases, one stall,
    leaking device memory."""
    base = 1_000_000_000.0
    with open(path, "w") as f:
        def emit(rec):
            f.write(json.dumps(rec) + "\n")

        emit({"event": "run_start", "ts": base, "run": "selftest",
              "episodes": episodes, "precision": "bf16",
              "substep_impl": "pallas", "unroll": 2,
              "mesh": "4x2", "partition_rules": "sharded",
              "topo_mix": "schedule,abilene+bursty",
              "partition_specs": {"PartitionSpec()": 87,
                                  "PartitionSpec(None, 'mp')": 44}})
        # mixed-topology harness events: per-replica topology names +
        # per-topology mean returns ride each episode's harness record
        for ep in range(2):
            emit({"event": "harness_episode", "ts": base + ep,
                  "run": "selftest", "episode": ep,
                  "episodic_return": 1.0 + ep, "mean_succ_ratio": 0.5,
                  "final_succ_ratio": 0.5,
                  "per_replica_return": [2.0 + ep, 0.0 + ep],
                  "topology": ["abilene.graphml", "abilene+bursty"],
                  "per_topology_return": {"abilene.graphml": 2.0 + ep,
                                          "abilene+bursty": 0.0 + ep},
                  "state_finite": True})
        # the dtype-gauge event the trainer emits via record_precision
        emit({"event": "precision", "ts": base, "run": "selftest",
              "name": "bf16", "param_dtype": "float32",
              "gnn_compute": "bfloat16", "mlp_compute": "bfloat16",
              "replay_dtype": "bfloat16"})
        # retrace-sentinel events: one healthy entry point (single trace
        # + compile) and one churning (retraces every episode)
        emit({"event": "compile", "ts": base, "run": "selftest",
              "fn": "episode_step", "stage": "trace",
              "duration_s": 0.8, "count": 1})
        emit({"event": "compile", "ts": base, "run": "selftest",
              "fn": "episode_step", "stage": "xla",
              "duration_s": 2.5, "count": 1})
        for k in range(5):
            emit({"event": "compile", "ts": base + k, "run": "selftest",
                  "fn": "leaky_fn", "stage": "trace",
                  "duration_s": 0.1, "count": k + 1})
        # learn_signal events (the on-device learn ledger): per-topology
        # |TD| segments, Q moments, layer norms, replay fill — the
        # learning-dynamics section must surface the TD trend, the
        # per-layer grad-norm peak and the replay fill
        for ep in range(2):
            emit({"event": "learn_signal", "ts": base + ep + 0.5,
                  "run": "selftest", "episode": ep,
                  "td_abs_mean": 0.5 - 0.1 * ep,
                  "per_topology_td": {"abilene.graphml": 0.4,
                                      "abilene+bursty": 0.6 - 0.1 * ep},
                  "q_mean": 0.3, "q_std": 0.1, "q_min": -0.2, "q_max": 0.9,
                  "grad_norms": {"actor/Dense_0": 1.5 + ep,
                                 "critic/Dense_0": 2.0},
                  "param_norms": {"actor/Dense_0": 10.0,
                                  "critic/Dense_0": 12.0},
                  "replay": {"size": [16], "fill": 0.5,
                             "age_mean_steps": 7.5}})
        disp = drain = 0.0
        for ep in range(episodes):
            disp += 0.010
            drain += 0.002
            emit({"event": "episode", "ts": base + ep, "run": "selftest",
                  "episode": ep, "global_step": 4 * ep + 3,
                  "sps": 100.0 + ep, "episodic_return": -1.0 + 0.1 * ep,
                  # serial-path topology identity: single-replica runs
                  # stamp the scheduled network on their episode events
                  "topology": "line3.graphml",
                  "mean_succ_ratio": 0.5, "critic_loss": 0.2,
                  "actor_loss": -0.1, "q_values": 0.3,
                  "drop_reasons": {"TTL": ep, "DECISION": 0,
                                   "LINK_CAP": 0, "NODE_CAP": 1},
                  "truncated_arrivals": 0, "replay_bytes": 4096,
                  "phases": {
                      "dispatch": {"total_s": round(disp, 4),
                                   "count": ep + 1, "mean_ms": 10.0},
                      "drain": {"total_s": round(drain, 4),
                                "count": ep + 1, "mean_ms": 2.0},
                      # per-episode scenario production (the cost the
                      # on-device factory deletes) rides the generic
                      # phase columns — locked in here so the rendering
                      # never silently drops it
                      "scenario_regen": {"total_s": round(0.01 * (ep + 1),
                                                          4),
                                         "count": ep + 1,
                                         "mean_ms": 10.0}},
                  # 64 MiB -> 64+96*ep MiB: well past floor + threshold;
                  # the second device has NO allocator stats (the CPU
                  # memory_stats()=None shape) — the report must call
                  # that out instead of reading it as flat usage
                  "device_memory": [{
                      "device": "FAKE_TPU_0", "available": True,
                      "backend": "tpu",
                      "bytes_in_use": (64 + 96 * ep) * 2 ** 20,
                      "peak_bytes_in_use": 256 * 2 ** 20,
                      "bytes_limit": 16 * 2 ** 30},
                      {"device": "FAKE_CPU_0", "available": False,
                       "backend": "cpu"}]})
        emit({"event": "stall", "ts": base + episodes, "run": "selftest",
              "age_s": 12.5, "budget_s": 10.0, "last_phase": "dispatch",
              "last_phase_state": "running", "episodes_dispatched": 5,
              "episodes_drained": 4, "dispatch_drain_lag": 1,
              "heartbeats": {"episode": 12.5, "prefetcher": 0.2},
              "prefetch_queue_depth": 2, "prefetcher_alive": True})
        emit({"event": "invariant_violation", "ts": base + episodes,
              "run": "selftest", "episode": 3,
              "violations": ["negative node_load"]})
        # resilience recovery timeline: a dispatch retry and a rollback,
        # plus one watchdog escalation — the report must surface all three
        emit({"event": "recovery", "ts": base + 2, "run": "selftest",
              "episode": 1, "site": "dispatch", "action": "retry",
              "fault": "TransientDispatchError('injected')", "attempt": 1,
              "detail": "backing off 0.05s before re-dispatch"})
        emit({"event": "recovery", "ts": base + 3, "run": "selftest",
              "episode": 2, "site": "learner_state", "action": "rollback",
              "fault": "non_finite_state",
              "detail": "restored snapshot of episode 1"})
        emit({"event": "escalation", "ts": base + 4, "run": "selftest",
              "age_s": 0.8, "budget_s": 0.2, "quiet_periods": 2,
              "action": "callback"})
        # serving events (cli serve / PolicyServer): startup with one
        # cache hit + one cold bucket, then a final cumulative stats
        # record — the report must surface rps/p50/p99 and the per-bucket
        # occupancy + cache-hit pattern
        emit({"event": "serve_start", "ts": base + 5, "run": "selftest",
              "tier": "learned", "buckets": [1, 4], "deadline_ms": 5.0,
              "startup_s": 1.25,
              "bucket_prepare": {"1": {"cache_hit": True,
                                       "prepare_s": 0.2},
                                 "4": {"cache_hit": False,
                                       "prepare_s": 0.9}},
              "cache_dir": "/tmp/cache", "fingerprint": "abc"})
        # request-path tracer spans: flush slices are always recorded,
        # request spans head-sampled — the trace exporter (not this
        # report) renders them; they must round-trip the reader unharmed
        emit({"event": "serve_flush", "ts": base + 5.2, "run": "selftest",
              "flush_id": 0, "bucket": 4, "n_real": 3,
              "pad_fraction": 0.25, "device_ms": 1.5, "queue_depth": 2})
        emit({"event": "serve_request_span", "ts": base + 5.1,
              "run": "selftest", "trace_id": 0, "flush_id": 0,
              "bucket": 4, "queue_wait_ms": 0.4, "batch_wait_ms": 3.1,
              "device_ms": 1.5, "fanout_ms": 0.1, "latency_ms": 5.0,
              "deadline_miss": False})
        emit({"event": "serve_stats", "ts": base + 6, "run": "selftest",
              "tier": "learned", "final": True, "requests": 200,
              "rps": 512.5, "p50_ms": 1.2, "p99_ms": 7.9, "mean_ms": 1.9,
              "max_ms": 9.0, "queue_depth": 0,
              "occupancy": {"1": 40, "4": 160},
              "buckets": {"1": {"p50_ms": 0.9, "p99_ms": 2.0,
                                "requests": 40},
                          "4": {"p50_ms": 1.3, "p99_ms": 7.9,
                                "requests": 160}},
              # SLO engine + tracer extras (gsc_tpu.obs.slo): the final
              # stats event folds in the decomposition, SLO snapshot
              # and rejection totals — the report must surface all three
              "decomposition": {"1": {"queue_ms": 0.2, "batch_ms": 5.0,
                                      "device_ms": 0.8,
                                      "fanout_ms": 0.05},
                                "4": {"queue_ms": 0.9, "batch_ms": 2.1,
                                      "device_ms": 1.5,
                                      "fanout_ms": 0.12}},
              "slo": {"p99_target_ms": 10.0, "attainment": 0.97,
                      "burn_rate": 3.0, "deadline_miss_ratio": 0.12,
                      "deadline_misses": 24, "arrival_rate_rps": 812.0,
                      "pad_waste": 0.31, "queue_wait_frac": 0.22},
              "rejected": {"queue_full": 3, "stopping": 0}})
        # fleet view (cli serve --workers N + --hot-swap-dir): per-worker
        # final serve_stats, the hot-swap timeline, and the fleet total
        # record — the report renders the worker table + swap timeline
        emit({"event": "weight_swap", "ts": base + 5.4, "run": "selftest",
              "worker": "w0", "version": 2, "fingerprint": "def",
              "tier": "learned", "swap_ms": 0.8, "weights_applied": True,
              "requests_in_flight": 3})
        emit({"event": "weight_swap", "ts": base + 5.6, "run": "selftest",
              "worker": "w1", "version": 2, "fingerprint": "def",
              "tier": "learned", "swap_ms": 0.5, "weights_applied": True,
              "requests_in_flight": 1})
        emit({"event": "serve_stats", "ts": base + 6.1, "run": "selftest",
              "tier": "learned", "final": True, "requests": 120,
              "worker": "w0", "worker_requests": 120,
              "policy_version": 2, "swaps": 1,
              "rps": 512.5, "p50_ms": 1.2, "p99_ms": 7.9, "mean_ms": 1.9,
              "max_ms": 9.0, "queue_depth": 1,
              "occupancy": {"1": 20, "4": 100}, "buckets": {}})
        emit({"event": "serve_stats", "ts": base + 6.2, "run": "selftest",
              "tier": "learned", "final": True, "requests": 80,
              "worker": "w1", "worker_requests": 80,
              "policy_version": 2, "swaps": 1,
              "rps": 512.5, "p50_ms": 1.2, "p99_ms": 7.9, "mean_ms": 1.9,
              "max_ms": 9.0, "queue_depth": 0,
              "occupancy": {"1": 20, "4": 60}, "buckets": {}})
        emit({"event": "fleet_stats", "ts": base + 6.3, "run": "selftest",
              "final": True, "workers": ["w0", "w1"], "requests": 200,
              "swaps": 2, "brownout": {"slo_burn": 0, "overflow": 5},
              "per_worker": {}, "slo": None})
        # async-fleet flight recorder (cli train --async): the deferred
        # per-actor episode ledgers + learner spans + the run-level
        # async_train info event — the report renders the per-actor
        # table, the lag/idle decomposition and the adoption timeline
        t = base + 4
        emit({"event": "async_actor_ep", "ts": t + 1.0, "run": "selftest",
              "ep": 0, "actor": 0,
              "chunks": [[t, t + 0.1, 0], [t + 0.2, t + 0.3, 1]],
              "puts": [[t + 0.1, 0.02, 64, 0, 1],
                       [t + 0.3, 0.0, 64, 1, 3]],
              "adopts": [[t + 0.15, 1]]})
        emit({"event": "async_actor_ep", "ts": t + 1.0, "run": "selftest",
              "ep": 1, "actor": 1,
              "chunks": [[t + 0.05, 0.15 + t, 0]],
              "puts": [[t + 0.15, 0.5, 64, 0, 2]],
              "adopts": [[t + 0.4, 1]]})
        emit({"event": "async_learner_spans", "ts": t + 1.0,
              "run": "selftest", "part": 0, "parts": 1,
              "ingests": [[t + 0.11, t + 0.12, 64, 0, 0, 1],
                          [t + 0.16, t + 0.17, 64, 0, 0, 2],
                          [t + 0.31, t + 0.32, 64, 1, 1, 3]],
              "bursts": [[t + 0.12, t + 0.14, 2]],
              "publishes": [[t + 0.14, 1]]})
        emit({"event": "async_train", "ts": t + 1.1, "run": "selftest",
              "actors": 2, "episodes_drained": 2, "produced_steps": 192,
              "ingested_steps": 192, "transitions_lost": 0, "bursts": 1,
              "publishes": 1, "published_version": 1, "max_staleness": 1,
              "max_replay_lag": 64, "policy_lag_max": 1,
              "policy_lag_mean": 0.33, "policy_lag_p50": 0,
              "policy_lag_p99": 1, "wall_s": 1.0, "learner_idle_s": 0.2,
              "learner_idle_frac": 0.2,
              "actor_idle_fracs": [0.02, 0.5], "actor_idle_frac": 0.5})
        emit({"event": "run_end", "ts": base + episodes + 1,
              "run": "selftest", "status": "ok", "episodes": episodes})


def _synthetic_perf(path: str):
    """A cost-ledger document with the gsc_tpu.obs.perf schema."""
    with open(path, "w") as f:
        json.dump({
            "schema_version": 1, "ts": 1_000_000_000.0, "backend": "cpu",
            "peaks": {"flops_per_s": 5e10, "bytes_per_s": 2e10},
            "run": "selftest",
            "entries": {
                "episode_step": {
                    "available": True, "flops": 6668188.0,
                    "bytes_accessed": 6770940.0, "fusions": 718,
                    "ops": {"while": 21, "dot": 167},
                    "collectives": {"ops": {}, "count": 0, "bytes": 0},
                    "arithmetic_intensity": 0.9848,
                    "dispatches": 5, "wall_s_total": 0.05,
                    "wall_s_mean": 0.01, "mfu": 0.0133,
                    "roofline": {"intensity": 0.9848, "ridge": 2.5,
                                 "regime": "memory_bound",
                                 "roof_multiple": 29.5}},
                "chunk_step_sharded": {
                    "available": True, "flops": 6668188.0,
                    "bytes_accessed": 6770940.0, "fusions": 731,
                    "ops": {"while": 21, "dot": 167},
                    # a partitioned executable: the tp interconnect
                    # columns the report must surface
                    "collectives": {
                        "ops": {"all-reduce": {"count": 6,
                                               "bytes": 73728}},
                        "count": 6, "bytes": 73728}},
                "serve_policy_b8": {"available": False,
                                    "error": "RuntimeError: no backend"},
            },
            "phases": {"dispatch": {"total_s": 0.05, "count": 5,
                                    "mean_ms": 10.0},
                       "drain": {"total_s": 0.01, "count": 5,
                                 "mean_ms": 2.0}},
        }, f)


def selftest() -> int:
    with tempfile.TemporaryDirectory() as tmp:
        path = os.path.join(tmp, "events.jsonl")
        _synthetic_events(path)
        _synthetic_perf(os.path.join(tmp, "perf.json"))
        summary = summarize(load_events(path), perf=load_perf(tmp))
        assert summary["episodes"] == 5, summary
        # perf section: ledger rows condensed, schema version surfaced,
        # the unavailable serve entry kept visible rather than dropped
        pf = summary["perf"]
        assert pf["schema_version"] == 1 and pf["backend"] == "cpu", pf
        row = pf["entries"]["episode_step"]
        assert row["fusions"] == 718 and row["mfu"] == 0.0133 \
            and row["regime"] == "memory_bound" \
            and row["wall_ms_mean"] == 10.0, row
        # the interconnect columns: 0 on the single-device entry, the
        # partitioned executable's all-reduce payload on the sharded one
        assert row["collective_count"] == 0, row
        sh = pf["entries"]["chunk_step_sharded"]
        assert sh["collective_count"] == 6 \
            and sh["collective_bytes"] == 73728, sh
        assert pf["entries"]["serve_policy_b8"]["available"] is False
        assert pf["device_vs_host"] == {"dispatch_s": 0.05,
                                        "host_s": 0.01}, pf
        # no-HBM-data flag: the CPU device reported available=False
        assert summary["memory_unavailable_backends"] == ["cpu"], summary
        assert summary["precision"] == {
            "name": "bf16", "param_dtype": "float32",
            "gnn_compute": "bfloat16", "mlp_compute": "bfloat16",
            "replay_dtype": "bfloat16"}, "precision header not surfaced"
        assert summary["engine"] == {
            "substep_impl": "pallas", "unroll": 2}, \
            "engine-knob header not surfaced"
        assert summary["mesh"] == {
            "mesh": "4x2", "partition_rules": "sharded",
            "partition_specs": {"PartitionSpec()": 87,
                                "PartitionSpec(None, 'mp')": 44}}, \
            "mesh header not surfaced"
        assert summary["topo_mix"] == "schedule,abilene+bursty", \
            "topo_mix header not surfaced"
        assert summary["per_topology"] == {
            "abilene.graphml": {"episodes": 2, "mean_return": 2.5,
                                "last_return": 3.0},
            "abilene+bursty": {"episodes": 2, "mean_return": 0.5,
                               "last_return": 1.0},
            # the serial path's stamped episode events land in the SAME
            # table as the harness's mixed-batch attribution
            "line3.graphml": {"episodes": 5, "mean_return": -0.8,
                              "last_return": -0.6}}, \
            "per-topology returns not aggregated"
        ln = summary["learning"]
        assert ln and ln["episodes"] == 2, ln
        assert ln["per_topology_td"]["abilene+bursty"] == {
            "episodes": 2, "mean_td_abs": 0.55, "last_td_abs": 0.5}, ln
        assert ln["td_abs_first"] == 0.5 and ln["td_abs_last"] == 0.4, ln
        assert ln["q_last"] == {"q_mean": 0.3, "q_std": 0.1,
                                "q_min": -0.2, "q_max": 0.9}, ln
        assert ln["grad_norm_peak"]["actor/Dense_0"] == 2.5, \
            "per-layer grad-norm peak not tracked"
        assert ln["replay_fill_last"] == 0.5, ln
        import io
        txt = io.StringIO()
        render_text(summary, out=txt)
        assert "SLO: p99 target" in txt.getvalue() \
            and "latency decomposition" in txt.getvalue() \
            and "rejected: queue_full 3" in txt.getvalue(), \
            "serving SLO/decomposition/rejection lines not rendered"
        assert "perf ledger: schema v1" in txt.getvalue(), \
            "perf schema-version header not rendered"
        assert "perf (device-cost ledger" in txt.getvalue() \
            and "memory_bound" in txt.getvalue(), \
            "perf section not rendered"
        assert "coll_B" in txt.getvalue() \
            and "73728" in txt.getvalue(), \
            "collective count/bytes columns not rendered"
        assert "no HBM data" in txt.getvalue(), \
            "memory-unavailable note not rendered"
        assert "mesh: 4x2  rules: sharded" in txt.getvalue(), \
            "mesh header line not rendered"
        assert "topo mix: schedule,abilene+bursty" in txt.getvalue(), \
            "topo-mix header line not rendered"
        assert "per-topology returns" in txt.getvalue() \
            and "abilene+bursty" in txt.getvalue(), \
            "per-topology table not rendered"
        assert "learning dynamics" in txt.getvalue() \
            and "grad/param health" in txt.getvalue(), \
            "learning-dynamics section not rendered"
        assert len(summary["stalls"]) == 1, "stall not surfaced"
        assert summary["stalls"][0]["last_phase"] == "dispatch"
        assert len(summary["invariant_violations"]) == 1
        assert summary["memory_growth_flags"], "memory growth not flagged"
        comp = summary["compiles"]["per_fn"]
        # 0.8 s trace + 2.5 s xla: both stages count as compile wall
        assert comp["episode_step"] == {
            "traces": 1, "xla_compiles": 1, "compile_s": 3.3}, comp
        assert summary["compiles"]["retrace_flags"] == ["leaky_fn"], \
            "retrace churn not flagged"
        assert len(summary["recoveries"]) == 2, "recovery timeline lost"
        assert summary["recovery_totals"] == {
            "dispatch/retry": 1, "learner_state/rollback": 1}, summary
        assert len(summary["escalations"]) == 1, "escalation not surfaced"
        sv = summary["serving"]
        assert sv and sv["tier"] == "learned" and sv["requests"] == 200, sv
        assert sv["rps"] == 512.5 and sv["p99_ms"] == 7.9, \
            "serving throughput/latency not surfaced"
        assert sv["occupancy"] == {"1": 40, "4": 160}, sv
        assert sv["bucket_prepare"]["1"]["cache_hit"] is True \
            and sv["bucket_prepare"]["4"]["cache_hit"] is False, \
            "per-bucket cache-hit pattern lost"
        assert sv["buckets"]["4"]["p99_ms"] == 7.9, sv
        # SLO engine + tracer section: attainment/burn, rejection and
        # pad-waste accounting, and the per-bucket latency split
        assert sv["slo"]["attainment"] == 0.97 \
            and sv["slo"]["burn_rate"] == 3.0 \
            and sv["slo"]["deadline_miss_ratio"] == 0.12, \
            "SLO snapshot not surfaced"
        assert sv["rejected"] == {"queue_full": 3, "stopping": 0}, \
            "rejection totals lost"
        assert sv["slo"]["pad_waste"] == 0.31, sv["slo"]
        assert sv["decomposition"]["4"]["batch_ms"] == 2.1 \
            and sv["decomposition"]["1"]["device_ms"] == 0.8, \
            "latency decomposition lost"
        # fleet view: per-worker table rows + the hot-swap timeline
        assert set(sv["workers"]) == {"w0", "w1"}, sv["workers"]
        assert sv["workers"]["w0"] == {
            "requests": 120, "occupancy": {"1": 20, "4": 100},
            "queue_depth": 1, "policy_version": 2, "swaps": 1}, \
            sv["workers"]
        assert sv["fleet"]["requests"] == 200 \
            and sv["fleet"]["swaps"] == 2, sv["fleet"]
        assert [s["version"] for s in sv["swap_timeline"]] == [2, 2] \
            and sv["swap_timeline"][0]["requests_in_flight"] == 3, \
            "hot-swap timeline lost"
        # async-fleet section: per-actor table, lag/idle decomposition,
        # adoption timeline — all three views reconstructed from the
        # deferred flight-recorder ledgers + the async_train info event
        af = summary["async_fleet"]
        assert af and af["info"]["actors"] == 2 \
            and af["info"]["transitions_lost"] == 0, af
        assert set(af["per_actor"]) == {0, 1}, af["per_actor"]
        a0 = af["per_actor"][0]
        assert a0["episodes"] == 1 and a0["chunks"] == 2 \
            and a0["steps"] == 128 and a0["adopts"] == 1 \
            and a0["last_version"] == 1, a0
        assert abs(a0["rollout_s"] - 0.2) < 1e-6 \
            and abs(a0["blocked_s"] - 0.02) < 1e-6, a0
        assert a0["idle_frac"] == 0.02 \
            and af["per_actor"][1]["idle_frac"] == 0.5, af["per_actor"]
        assert af["lag"]["samples"] == 3 and af["lag"]["max"] == 1 \
            and af["lag"]["p99"] == 1, af["lag"]
        dec = af["decomposition"]
        assert dec["n_ingests"] == 3 and dec["n_bursts"] == 1 \
            and abs(dec["ingest_s"] - 0.03) < 1e-6 \
            and abs(dec["burst_s"] - 0.02) < 1e-6, dec
        assert dec["idle_s"] == 0.2 \
            and abs(dec["other_s"] - (1.0 - 0.03 - 0.02 - 0.2)) < 1e-6, \
            dec
        tl = af["adoption_timeline"]
        assert len(tl) == 1 and tl[0]["version"] == 1, tl
        # actor0 adopted 0.01s after the publish, actor1 0.26s after
        assert abs(tl[0]["adopt_lag_s"][0] - 0.01) < 1e-6 \
            and abs(tl[0]["adopt_lag_s"][1] - 0.26) < 1e-6, tl
        assert af["orphan_adopt_versions"] == [], af
        async_txt = io.StringIO()
        render_text(summary, out=async_txt)
        assert "async fleet (2 actor(s)" in async_txt.getvalue() \
            and "adoption timeline" in async_txt.getvalue() \
            and "learner wall: ingest" in async_txt.getvalue(), \
            "async-fleet section not rendered"
        fleet_txt = io.StringIO()
        render_text(summary, out=fleet_txt)
        assert "fleet: 2 worker(s)" in fleet_txt.getvalue() \
            and "hot-swap timeline" in fleet_txt.getvalue() \
            and "brownout: overflow 5" in fleet_txt.getvalue(), \
            "fleet table / swap timeline not rendered"
        assert summary["drop_totals"]["TTL"] == 0 + 1 + 2 + 3 + 4
        deltas = phase_deltas([e for e in last_run(load_events(path))
                               if e.get("event") == "episode"])
        assert abs(deltas[2]["dispatch"] - 0.010) < 1e-6, deltas[2]
        render_text(summary)   # must not raise on a flagged stream
        # append-mode reuse: a second run landing in the same stream must
        # not corrupt the summary — the report partitions on run_start.
        # The appended run's timestamps are SHIFTED (a real second run
        # starts later; the reader now ts-sorts, so an identical-ts copy
        # would interleave with the first run's records)
        lines0 = [json.loads(line) for line in open(path)
                  if line.strip()]
        with open(path, "a") as f:
            for rec in lines0:
                f.write(json.dumps({**rec, "ts": rec["ts"] + 1000.0})
                        + "\n")
        s2 = summarize(load_events(path))
        assert s2["runs_in_stream"] == 2 and s2["episodes"] == 5, s2
        render_text(s2, out=open(os.devnull, "w"))
        # rotation roundtrip (--obs-rotate-mb layout): split the stream
        # into a .1 segment + live tail — the reader must walk the
        # segments and reassemble the identical stream
        lines = open(path).read().splitlines(keepends=True)
        cut = len(lines) // 2
        with open(path + ".1", "w") as f:
            f.writelines(lines[:cut])
        with open(path, "w") as f:
            f.writelines(lines[cut:])
        reassembled = sorted(
            (json.loads(line) for line in lines if line.strip()),
            key=lambda e: e["ts"])   # the reader's ts-sorted view
        assert load_events(path) == reassembled, \
            "rotated segments did not reassemble the stream"
        s3 = summarize(load_events(path))
        assert s3["runs_in_stream"] == 2 and s3["episodes"] == 5, s3
    print("obs_report selftest: OK")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("path", nargs="?",
                    help="run directory or events.jsonl")
    ap.add_argument("--json", action="store_true",
                    help="emit the summary as one JSON object")
    ap.add_argument("--mem-growth-threshold", type=float, default=0.2,
                    help="fractional bytes_in_use growth (first->last "
                         "episode) flagged as a leak [default 0.2]")
    ap.add_argument("--retrace-threshold", type=int, default=3,
                    help="traces per jitted entry point above which "
                         "retrace churn is flagged [default 3]")
    ap.add_argument("--selftest", action="store_true",
                    help="synthesize a stream and verify the report "
                         "flags its stall/leak (CI smoke target)")
    args = ap.parse_args(argv)
    if args.selftest:
        return selftest()
    if not args.path:
        ap.error("path required (or --selftest)")
    summary = summarize(load_events(args.path),
                        mem_growth_threshold=args.mem_growth_threshold,
                        retrace_threshold=args.retrace_threshold,
                        perf=load_perf(args.path))
    if args.json:
        json.dump(summary, sys.stdout, indent=1)
        sys.stdout.write("\n")
    else:
        render_text(summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
