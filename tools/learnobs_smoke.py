"""Learn-obs smoke: the training-quality observability layer end to end.

The CI-stage proof that the learn ledger actually executes through the
real CLI: a tiny 3-episode, 2-replica mixed-topology CPU train run
(``--topo-mix "schedule,line3"``, learn obs on by default) must

- exit 0 and write a complete schema-versioned ``curves.json`` (return +
  TD series as long as the run, per-topology series for BOTH mixture
  members, envelope summary present),
- leave one ``learn_signal`` event per episode in ``events.jsonl`` with
  per-topology |TD| covering both networks, plus ``td_abs_mean`` /
  ``grad_norm`` / ``topology_return`` gauges in ``metrics.json``,
- expose a scrapeable Prometheus ``/metrics`` endpoint (in-process
  roundtrip: every snapshot series parses back identically),
- gate through ``bench_diff``: the run's curves row self-compares clean
  (rc 0) while an injected envelope regression is caught (rc 1).

Run by ``tools/ci_check.sh`` after the perfobs stage; standalone:

    JAX_PLATFORMS=cpu python tools/learnobs_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MIX = "schedule,line3"
EPISODES = 3


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"learnobs smoke: FAIL — {msg}")
    return 1


def check_endpoint() -> str:
    """In-process /metrics scrape roundtrip (the CLI run binds no port in
    CI — a fixed port would collide across concurrent stages)."""
    from gsc_tpu.obs import MetricsEndpoint, MetricsHub

    hub = MetricsHub(tags={"run": "smoke"})
    hub.gauge("td_abs_mean", 0.75, topology="line3")
    hub.counter("episodes_drained", 2)
    ep = MetricsEndpoint(hub, port=0).start()
    try:
        body = urllib.request.urlopen(ep.url, timeout=10).read().decode()
        parsed = {}
        for line in body.strip().splitlines():
            name, value = line.rsplit(" ", 1)
            parsed[name] = float(value)
        snap = {k: float(v) for k, v in hub.snapshot().items()}
        if parsed != snap:
            return f"endpoint scrape != snapshot ({parsed} vs {snap})"
    finally:
        ep.stop()
    return ""


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    err = check_endpoint()
    if err:
        return fail(err)

    tmp = tempfile.mkdtemp(prefix="gsc_learnobs_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(EPISODES), "--replicas", "2",
        "--chunk", "3", "--topo-mix", MIX,
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --topo-mix {MIX!r}")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    signals = [e for e in events if e["event"] == "learn_signal"]
    if len(signals) != EPISODES:
        return fail(f"expected {EPISODES} learn_signal events, got "
                    f"{len(signals)}")
    names = set()
    for e in signals:
        names |= set(e.get("per_topology_td") or {})
    if len(names) < 2:
        return fail(f"per-topology |TD| should cover both mixture "
                    f"members, saw {sorted(names)}")
    snap = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    for prefix in ("gsc_td_abs_mean", "gsc_grad_norm{",
                   "gsc_topology_return", "gsc_replay_fill"):
        if not any(k.startswith(prefix) for k in snap):
            return fail(f"no {prefix}* gauge in metrics.json")

    curves_path = os.path.join(rdir, "curves.json")
    if not os.path.exists(curves_path):
        return fail("curves.json not written")
    curves = json.load(open(curves_path))
    if curves.get("schema_version") != 1 \
            or curves.get("episodes") != EPISODES:
        return fail(f"curves.json header wrong: "
                    f"schema={curves.get('schema_version')} "
                    f"episodes={curves.get('episodes')}")
    for key in ("episodic_return", "td_abs_mean"):
        col = curves["series"].get(key)
        if not col or len(col) != EPISODES:
            return fail(f"curves series {key!r} incomplete: {col}")
    if set(curves.get("per_topology") or {}) != names:
        return fail(f"curves per_topology {sorted(curves['per_topology'])} "
                    f"!= event names {sorted(names)}")
    if curves["summary"].get("final_window_return") is None:
        return fail("curves summary missing final_window_return")

    # bench_diff gate: self-compare clean, injected regression caught
    import bench_diff
    traj = os.path.join(tmp, "traj.json")
    doc = bench_diff.ingest([curves_path], traj)
    (row_name,) = [n for n in doc["rows"] if n.startswith("curves_")]
    rc = bench_diff.main(["diff", row_name, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"curves self-compare rc={rc} (want 0)")
    base_final = doc["rows"][row_name]["metrics"]["final_window_return"]
    bad = dict(curves)
    bad["summary"] = {**curves["summary"],
                      "final_window_return":
                          base_final - 10 * abs(base_final) - 100.0}
    bad_path = os.path.join(tmp, "bad_curves.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", row_name,
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected curve regression rc={rc} (want 1)")

    print(f"learnobs smoke: OK — {len(signals)} learn_signal episodes "
          f"over {sorted(names)}, curves.json complete + gated, "
          "/metrics scrape roundtrip clean")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
