"""Full-scale learning-curve run (BASELINE.md protocol: reproduce the
reference's quality metrics on the flagship scenario, then measure
throughput).

Trains ParallelDDPG on Abilene rand-cap1-2 (the reference benchmark
workload) for ``--episodes`` full 200-step episodes across ``--replicas``
vmapped envs and prints per-episode mean return / success ratio plus the
first-10 vs last-10 summary.  Episodes run CHUNKED (see bench.py) so the
TPU never sees a 200-step single-call scan.

On the single shared TPU run it via::

    python tools/learning_curve.py --replicas 64 --episodes 40

(CPU works too, smaller: --replicas 4 --episode-steps 50.)
"""
from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--replicas", type=int, default=64)
    ap.add_argument("--episodes", type=int, default=40)
    ap.add_argument("--episode-steps", type=int, default=200)
    ap.add_argument("--chunk", type=int, default=50)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--cpu", action="store_true")
    ap.add_argument("--host-traffic", action="store_true",
                    help="per-episode traffic on the HOST (the r3 path; "
                    "ships ~90 MB/episode at B=256 through the device "
                    "tunnel).  Default is on-device sampling.")
    # multi-host: launch one process per host with identical arguments
    # plus --coordinator host0:port --num-processes P --process-id i.
    # --replicas is then the GLOBAL replica count (must divide by P).
    ap.add_argument("--sample-mode", choices=("across", "local"),
                    default=None,
                    help="replay sampling: uniform across all shards vs "
                    "shard-local stratified (default: across single-host, "
                    "local multihost)")
    ap.add_argument("--coordinator", default=None,
                    help="multi-host coordinator address host:port")
    ap.add_argument("--num-processes", type=int, default=None)
    ap.add_argument("--process-id", type=int, default=None)
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    multihost = args.coordinator is not None
    if multihost:
        from gsc_tpu.parallel.mesh import init_distributed
        init_distributed(args.coordinator, args.num_processes,
                         args.process_id)
    import jax.numpy as jnp

    from __graft_entry__ import _flagship
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic import generate_traffic
    from gsc_tpu.sim.traffic_device import DeviceTraffic

    T, B, chunk = args.episode_steps, args.replicas, args.chunk
    assert T % chunk == 0
    env, agent, topo, _ = _flagship(episode_steps=T)

    # multi-host: global (dcn, dp) mesh, replicas sharded over both axes,
    # per-process host data fed in as local shards (same SPMD pattern as
    # tools/dryrun_multihost.py); single-host: everything below is a no-op
    # passthrough
    if multihost:
        from jax.experimental import multihost_utils
        from jax.sharding import NamedSharding, PartitionSpec as P

        from gsc_tpu.parallel.mesh import make_hybrid_mesh
        n_proc = jax.process_count()
        pid = jax.process_index()
        n_local = len(jax.local_devices())
        # replicas shard over (process, local-device), so B must divide by
        # the full device grid — fail here, not with an opaque sharding
        # error mid-run
        assert B % (n_proc * n_local) == 0, \
            f"--replicas {B} must be a multiple of " \
            f"processes*local_devices = {n_proc}*{n_local}"
        B_local = B // n_proc
        mesh = make_hybrid_mesh()
        spec = P(("dcn", "dp"))
        sharded = NamedSharding(mesh, spec)
        to_global = lambda tree: \
            multihost_utils.host_local_array_to_global_array(tree, mesh, spec)
        mesh_ctx = mesh
    else:
        import contextlib
        n_proc, pid, B_local = 1, 0, B
        sharded = None
        to_global = lambda tree: tree
        mesh_ctx = contextlib.nullcontext()

    if args.host_traffic:
        def episode_traffic(ep):
            # each process builds only its replicas' traces
            t0 = [generate_traffic(env.sim_cfg, env.service, topo, T,
                                   seed=1000 * ep + pid * B_local + s)
                  for s in range(B_local)]
            return to_global(
                jax.tree_util.tree_map(lambda *xs: jnp.stack(xs), *t0))
    else:
        dt = DeviceTraffic(env.sim_cfg, env.service, topo, T)
        sample_batch = jax.jit(lambda k: dt.sample_batch(k, B),
                               out_shardings=sharded)

        def episode_traffic(ep):
            return sample_batch(jax.random.fold_in(
                jax.random.PRNGKey(args.seed + 3), ep))

    # replay sampling: multihost defaults to shard-local stratified
    # sampling (no cross-process gather in the learn loop); note the
    # effective batch becomes B * max(batch_size // B, 1), which differs
    # from single-host 'across' sampling — the output JSON records the
    # mode so curves are never compared across semantics unknowingly
    sample_mode = args.sample_mode or ("local" if multihost else "across")
    pddpg = ParallelDDPG(env, agent, num_replicas=B,
                         sample_mode=sample_mode, donate=True)
    # single-replica reset (identical on every process) for learner init
    one_traffic = generate_traffic(env.sim_cfg, env.service, topo, T, seed=0)
    _, one_obs = env.reset(jax.random.PRNGKey(args.seed), topo, one_traffic)
    state = pddpg.init(jax.random.PRNGKey(args.seed + 1), one_obs)
    # each process allocates only its local replay shard
    buffers = to_global(pddpg.init_buffers(
        one_obs, num_replicas=B_local if multihost else None))
    traffic = episode_traffic(0)

    from gsc_tpu.parallel.harness import run_chunked_episodes

    t0 = time.time()

    def log_episode(ep, r, s, metrics):
        if pid == 0:
            print(f"episode={ep} return={r:.3f} succ={s:.3f} "
                  f"critic_loss={float(metrics['critic_loss']):.4f} "
                  f"elapsed={time.time() - t0:.0f}s", file=sys.stderr)

    with mesh_ctx:
        # episode 0 reuses the pre-loop traffic sample
        _, _, returns, succ, final_succ = run_chunked_episodes(
            pddpg, topo,
            lambda ep: episode_traffic(ep) if ep else traffic,
            state, buffers, args.episodes, T, chunk, args.seed,
            on_episode=log_episode)
    k = min(10, max(1, len(returns) // 4))
    if pid == 0:
        print(json.dumps({
            "replicas": B, "episodes": args.episodes, "episode_steps": T,
            "processes": n_proc, "sample_mode": sample_mode,
            "first_k_return": round(sum(returns[:k]) / k, 3),
            "last_k_return": round(sum(returns[-k:]) / k, 3),
            "first_k_succ": round(sum(succ[:k]) / k, 4),
            "last_k_succ": round(sum(succ[-k:]) / k, 4),
            "first_k_final_succ": round(sum(final_succ[:k]) / k, 4),
            "last_k_final_succ": round(sum(final_succ[-k:]) / k, 4),
            "wall_s": round(time.time() - t0, 1),
        }))


if __name__ == "__main__":
    main()
