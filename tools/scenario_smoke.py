"""Scenario-factory smoke: the on-device factory + auto-curriculum end
to end through the real CLI.

The CI-stage proof that the factory path actually executes: a tiny
3-episode, 2-replica CPU train run with
``--topo-mix factory:star-ring-line+shapes~faults`` must

- exit 0 with ``run_start`` recording the factory mix + curriculum
  knobs,
- stream with ZERO retraces: the compile events record EXACTLY one
  trace each for ``factory_sample`` / ``reset_all`` / ``chunk_step``
  (``--no-perf`` so the AOT capture does not add its own trace — 50
  randomized scenarios through one compiled program is the whole
  claim),
- emit one ``curriculum`` event per episode and a
  ``curriculum_weight{family=...}`` gauge per family, exposed over a
  live Prometheus ``/metrics`` endpoint (in-process scrape — the CLI
  run binds no port in CI),
- gate through ``bench_diff``: a SCEN-shaped row self-compares clean
  (rc 0) while an injected env-steps/s regression is caught (rc 1).

Run by ``tools/ci_check.sh`` before the chaos stage; standalone:

    JAX_PLATFORMS=cpu python tools/scenario_smoke.py
"""
from __future__ import annotations

import json
import os
import sys
import tempfile
import urllib.request

# runnable from any cwd: the repo root is this file's parent's parent
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

MIX = "factory:star-ring-line+shapes~faults"
FAMILIES = ("star", "ring", "line")
EPISODES = 3


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:   # the repo-shared persistent compile cache keeps this stage fast
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def fail(msg: str) -> int:
    print(f"scenario smoke: FAIL — {msg}")
    return 1


def check_curriculum_endpoint() -> str:
    """curriculum_weight gauges over a live /metrics scrape: the
    Curriculum emit pathway feeds the same hub the endpoint serves."""
    from gsc_tpu.env.curriculum import Curriculum, CurriculumConfig
    from gsc_tpu.obs import MetricsEndpoint, MetricsHub

    hub = MetricsHub(tags={"run": "smoke"})
    curr = Curriculum(list(FAMILIES), CurriculumConfig(floor=0.3))
    curr.fold_td([4.0, 1.0, 0.5], [2.0, 1.0, 1.0])
    curr.emit_weights(hub, episode=0)
    ep = MetricsEndpoint(hub, port=0).start()
    try:
        body = urllib.request.urlopen(ep.url, timeout=10).read().decode()
        got = {f for f in FAMILIES
               if any("curriculum_weight" in line
                      and f'family="{f}"' in line
                      for line in body.splitlines())}
        if got != set(FAMILIES):
            return (f"/metrics exposition missing curriculum_weight for "
                    f"{sorted(set(FAMILIES) - got)}")
        snap = {k: float(v) for k, v in hub.snapshot().items()}
        parsed = {}
        for line in body.strip().splitlines():
            name, value = line.rsplit(" ", 1)
            parsed[name] = float(value)
        if parsed != snap:
            return f"endpoint scrape != snapshot ({parsed} vs {snap})"
    finally:
        ep.stop()
    return ""


def main() -> int:
    _configure_jax()
    from click.testing import CliRunner

    from gsc_tpu.cli import cli
    from tools.chaos_smoke import write_tiny_configs

    err = check_curriculum_endpoint()
    if err:
        return fail(err)

    tmp = tempfile.mkdtemp(prefix="gsc_scenario_")
    args = write_tiny_configs(os.path.join(tmp, "cfg"))
    r = CliRunner().invoke(cli, [
        "train", *args, "--episodes", str(EPISODES), "--replicas", "2",
        "--chunk", "3", "--topo-mix", MIX, "--curriculum-floor", "0.3",
        "--no-perf",   # the AOT cost capture would add its own trace —
                       # this stage pins the DISPATCH trace counts
        "--result-dir", os.path.join(tmp, "res")])
    if r.exit_code != 0:
        print(r.output)
        if r.exception is not None:
            import traceback
            traceback.print_exception(type(r.exception), r.exception,
                                      r.exception.__traceback__)
        return fail(f"train rc={r.exit_code} under --topo-mix {MIX!r}")
    rdir = json.loads(r.output.strip().splitlines()[-1])["result_dir"]

    events = [json.loads(line)
              for line in open(os.path.join(rdir, "events.jsonl"))]
    run_start = next(e for e in events if e["event"] == "run_start")
    if run_start.get("topo_mix") != MIX:
        return fail(f"run_start topo_mix {run_start.get('topo_mix')!r} "
                    f"!= {MIX!r}")
    if (run_start.get("curriculum") or {}).get("floor") != 0.3:
        return fail(f"run_start curriculum knobs missing: "
                    f"{run_start.get('curriculum')}")

    # ZERO retraces across the randomized stream: exactly one trace per
    # dispatch entry point (a second chunk_step/factory_sample trace
    # means a sampled scenario became a compile axis)
    traces = {}
    for e in events:
        if e["event"] == "compile" and e.get("stage") == "trace":
            traces[e["fn"]] = e.get("count")
    for fn in ("factory_sample", "reset_all", "chunk_step"):
        if traces.get(fn) != 1:
            return fail(f"expected exactly 1 {fn} trace across "
                        f"{EPISODES} randomized episodes, saw "
                        f"{traces.get(fn)} (all: {traces})")

    cur = [e for e in events if e["event"] == "curriculum"]
    if len(cur) != EPISODES:
        return fail(f"expected {EPISODES} curriculum events, got "
                    f"{len(cur)}")
    w = cur[-1].get("weights") or {}
    if set(w) != set(FAMILIES):
        return fail(f"curriculum weights cover {sorted(w)}, want "
                    f"{sorted(FAMILIES)}")
    if abs(sum(w.values()) - 1.0) > 1e-3 or min(w.values()) < 0.3 / 3 - 1e-6:
        return fail(f"curriculum weights not a floored distribution: {w}")
    snap = json.load(open(os.path.join(rdir, "metrics.json")))["metrics"]
    missing = [f for f in FAMILIES
               if not any("curriculum_weight" in k and f'family="{f}"' in k
                          for k in snap)]
    if missing:
        return fail(f"metrics.json missing curriculum_weight gauges for "
                    f"{missing}")
    end = events[-1]
    if end.get("event") != "run_end" or end.get("status") != "ok":
        return fail(f"stream tail {end}")

    # bench_diff gate over a SCEN-shaped row: self-compare clean,
    # injected env-steps/s regression caught
    import bench_diff
    sps = [e for e in events if e["event"] == "episode"]
    rate = (sps[-1].get("sps") if sps else None) or 1.0
    scen = {"metric": "env_steps_per_sec_per_chip", "status": "ok",
            "factory_sps": round(float(rate), 2),
            "jit_traces_factory": {fn: traces[fn] for fn in
                                   ("factory_sample", "chunk_step",
                                    "reset_all")}}
    scen_path = os.path.join(tmp, "SCEN_r99.json")
    with open(scen_path, "w") as f:
        json.dump(scen, f)
    traj = os.path.join(tmp, "traj.json")
    bench_diff.ingest([scen_path], traj)
    rc = bench_diff.main(["diff", "SCEN_r99", "--baseline", "SCEN_r99",
                          "--trajectory", traj])
    if rc != 0:
        return fail(f"SCEN self-compare rc={rc} (want 0)")
    bad = dict(scen, factory_sps=round(float(rate) * 0.5, 2))
    bad_path = os.path.join(tmp, "SCEN_bad.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    rc = bench_diff.main(["diff", bad_path, "--baseline", "SCEN_r99",
                          "--trajectory", traj])
    if rc != 1:
        return fail(f"injected env-steps/s regression rc={rc} (want 1)")

    print(f"scenario smoke: OK — {EPISODES} factory episodes over "
          f"{sorted(w)} with 1 trace per entry point ({traces}), "
          "curriculum gauges live on /metrics, SCEN row gated both "
          "directions")
    return 0


if __name__ == "__main__":
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.abspath(__file__))))
    sys.exit(main())
