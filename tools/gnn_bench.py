"""Dense-XLA vs Pallas GATv2 embedder benchmark at replay-batch shapes.

Settles VERDICT r3 weak #6 with a number: the Pallas kernel
(gsc_tpu/ops/pallas_gat.py) has bit-exact parity evidence but no measured
throughput delta vs the dense XLA path, so ``gnn_impl`` has defaulted to
"dense" on vibes.  This benches the full GNNEmbedder forward (and the
learn-relevant forward+backward) on the kernel's own motivating case —
B replay graphs of N padded nodes (sample_agent.yaml: B=100, N=24) — and
prints a JSON table.

On TPU run::

    python tools/gnn_bench.py                  # flagship shapes
    python tools/gnn_bench.py --n 64 --feat 32 # bigger graphs

On CPU this still runs (pallas in interpret mode) to validate the tool,
but interpret-mode timings say nothing about the chip.
"""
from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def bench(fn, args, iters=30):
    import jax

    out = fn(*args)                      # compile
    jax.block_until_ready(out)
    t0 = time.time()
    for _ in range(iters):
        out = fn(*args)
    jax.block_until_ready(out)
    return (time.time() - t0) / iters


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=100)   # replay batch
    ap.add_argument("--n", type=int, default=24)        # padded nodes
    ap.add_argument("--feat", type=int, default=22)     # GNN features
    ap.add_argument("--layers", type=int, default=2)
    ap.add_argument("--iters", type=int, default=2)
    ap.add_argument("--cpu", action="store_true")
    args = ap.parse_args()

    import jax
    if args.cpu:
        jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    from gsc_tpu.models.gnn import GNNEmbedder

    B, N = args.batch, args.n
    rng = np.random.default_rng(0)
    nodes = jnp.asarray(rng.random((B, N, 3), np.float32))
    e = 2 * N
    ei = np.zeros((2, e), np.int32)
    em = np.zeros(e, bool)
    deg = min(N - 1, 3)
    k = 0
    for u in range(N):
        for d in range(1, deg + 1):
            if k < e:
                ei[:, k] = (u, (u + d) % N)
                em[k] = True
                k += 1
    ei = jnp.broadcast_to(jnp.asarray(ei), (B, 2, e))
    em = jnp.broadcast_to(jnp.asarray(em), (B, e))
    nm = jnp.ones((B, N), bool)

    results = {}
    params = None
    for impl in ("dense", "pallas"):
        emb = GNNEmbedder(hidden=args.feat, num_layers=args.layers,
                          num_iter=args.iters, impl=impl)
        if params is None:
            params = emb.init(jax.random.PRNGKey(0), nodes, ei, em, nm)
        fwd = jax.jit(lambda p, x: emb.apply(p, x, ei, em, nm).sum())
        grad = jax.jit(jax.grad(
            lambda p, x: emb.apply(p, x, ei, em, nm).sum()))
        results[impl] = {
            "forward_ms": round(bench(fwd, (params, nodes)) * 1e3, 3),
            # backward through the pallas path runs the kernel's custom
            # VJP (dense-math backward, pallas_gat.py)
            "forward_backward_ms": round(
                bench(grad, (params, nodes)) * 1e3, 3),
        }
        # parity while we're here (same params both impls)
        out = emb.apply(params, nodes, ei, em, nm)
        results[impl]["checksum"] = float(jnp.abs(out).sum())

    d, p = results["dense"], results["pallas"]
    out = {
        "backend": jax.default_backend(),
        "batch": B, "nodes": N, "feat": args.feat,
        "dense": d, "pallas": p,
        "parity_abs_diff": abs(d["checksum"] - p["checksum"]),
        "speedup_fwd": round(d["forward_ms"] / max(p["forward_ms"], 1e-9), 3),
    }
    if d.get("forward_backward_ms") and p.get("forward_backward_ms"):
        out["speedup_fwd_bwd"] = round(
            d["forward_backward_ms"] / p["forward_backward_ms"], 3)
    print(json.dumps(out))


if __name__ == "__main__":
    main()
