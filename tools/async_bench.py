"""ASYNC bench: sync control vs decoupled actor/learner at matched budgets.

The Sebulba-split's throughput claim, measured instead of asserted: four
fresh-subprocess legs run the SAME tiny flagship stack with the SAME
entry points (``reset_all`` / ``rollout_episodes`` / ``learn_burst``),
the same episode count and the same one-burst-per-episode gradient
budget (``learn_ratio=1.0``), and differ ONLY in how acting and
learning interleave:

- ``sync``: the control — one thread alternates rollout chunks and the
  episode's learn burst, the seed's strictly-coupled cadence (donating
  dispatch, the sync path's contract);
- ``async1`` / ``async2`` / ``async4``: ``run_async`` with 1 / 2 / 4
  actor threads feeding the device-resident ring through
  ``replay_ingest`` while the learner bursts back-to-back
  (``donate=False`` actor blocks, the one donated call is the ingest).

Banked as ``ASYNC_r01.json`` (``--bank``): per-leg env-steps/s (gated by
tools/bench_diff.py under the 15% ``_sps`` band once ingested), the
decoupling claim ``async >= sync at >= 2 actors``, the learner-idle
bound (``learner_idle_frac`` < 0.10 at steady state — the phase-ledger
proof the learner never waits on acting), the staleness ledger
(``policy_lag_max``, produced == ingested), and the banded learning-
curve equivalence (``final_window_return`` 20%/floor 1.0,
``auc_return`` 25%/floor 1.0 — actors act on K-burst-old weights by
design, so the bank refuses a green row only when the async curve
leaves the band, not when it is merely not bit-equal).  A round that
fails any gate parks as ``ASYNC_r01.failed.json`` — never overwriting a
previously banked green artifact — and still ingests as a failed row.

Round r02 (``--round r02``, banked as ``ASYNC_r02.json``) sweeps the
MESH axis instead of the actor-count axis: ``async2`` re-runs as the
single-device baseline, and ``async_dp2`` / ``async_dp4`` run the SAME
stack on 2 / 4 forced host devices
(``--xla_force_host_platform_device_count``) under a pure-dp
``ShardingPlan`` (``2x1`` / ``4x1``) — the dp-sharded replay ring with
the shard_map per-shard donated ingest.  Gates: drain accounting per
leg, ``ingest_collectives == 0`` on every dp leg (the HLO-mined
zero-collective ingest contract), learner-idle bound, and per-grid
throughput above the baseline's per-device share (``DP_SHARE_FLOOR``
— the forced devices slice ONE physical core, so dp legs pay real
overhead and can never win; the floor catches collective storms,
bench_diff's bands catch cross-round drift).

Usage:
    JAX_PLATFORMS=cpu python tools/async_bench.py --bank
    JAX_PLATFORMS=cpu python tools/async_bench.py --round r02 --bank
    JAX_PLATFORMS=cpu python tools/async_bench.py --worker async_dp2
"""
from __future__ import annotations

import argparse
import json
import os
import subprocess
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

B = 8
EPISODE_STEPS = 10
CHUNK = 5
MEASURE_EPISODES = 6
FINAL_WINDOW = 3
MAX_NODES, MAX_EDGES = 12, 16
LEG_TIMEOUT_S = 900
IDLE_FRAC_MAX = 0.10
CURVE_BANDS = {"final_window_return": (0.20, 1.0),
               "auc_return": (0.25, 1.0)}
LEGS = ("sync", "async1", "async2", "async4")
# round r02: the mesh sweep — single-device async2 baseline vs the SAME
# stack dp-sharded over 2 / 4 forced host devices (pure-dp plans)
LEGS_R02 = ("async2", "async_dp2", "async_dp4")
# per-grid throughput floor for the dp legs, as a fraction of
# async2_sps / devices: forced host devices slice ONE physical core N
# ways, so a dp leg pays real partition/sync overhead per device
# (measured ~33% at 2, ~45% at 4 on this box) and can never win.  The
# honest in-round gate is a FLOOR at the baseline's per-device share —
# dp-sharding must beat running the whole grid's work on 1/N of the
# core, which a collective-regressed ingest (the GSPMD row-scatter
# emitted 28 all-gathers before the shard_map rewrite) crashes
# through.  Cross-round drift of the banked absolute rates is
# bench_diff's 15% `_sps`/`_sps_per_device` bands' job, not this
# gate's; per-device SCALING is the chip window's to measure.
DP_SHARE_FLOOR = 1.0


def _leg_devices(leg: str) -> int:
    return int(leg[len("async_dp"):]) if leg.startswith("async_dp") else 1


def _configure_jax():
    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update(
            "jax_compilation_cache_dir",
            os.path.join(os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))), ".jax_cache"))
        jax.config.update("jax_persistent_cache_min_compile_time_secs", 1.0)
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
    except Exception:
        pass


def _curve_metrics(returns):
    w = returns[-FINAL_WINDOW:]
    return (round(sum(w) / len(w), 4),
            round(sum(returns) / len(returns), 4))


def worker(leg: str) -> int:
    """One leg, printed as a JSON line (the bank parses the last line)."""
    if leg not in LEGS and leg not in LEGS_R02:
        raise SystemExit(f"unknown leg {leg!r} "
                         f"(want one of {LEGS + LEGS_R02[1:]})")
    _configure_jax()
    import jax
    import jax.numpy as jnp

    import __graft_entry__ as ge
    from gsc_tpu.analysis.sentinels import CompileMonitor
    from gsc_tpu.parallel import ParallelDDPG, ShardingPlan
    from gsc_tpu.utils.telemetry import PhaseTimer

    devices = _leg_devices(leg)
    if leg.startswith("async_dp"):
        actors = 2   # matched to the async2 baseline leg
        if len(jax.devices()) != devices:
            raise SystemExit(
                f"{leg} needs {devices} forced host devices, found "
                f"{len(jax.devices())} — run via the bank (it sets "
                "--xla_force_host_platform_device_count)")
    else:
        actors = 0 if leg == "sync" else int(leg[len("async"):])
    plan = ShardingPlan.from_spec(f"{devices}x1") if devices > 1 else None
    env, agent, topo, traffic0 = ge._flagship(
        max_nodes=MAX_NODES, max_edges=MAX_EDGES,
        episode_steps=EPISODE_STEPS, max_flows=64)
    traffic = jax.tree_util.tree_map(
        lambda x: jnp.stack([x] * B), traffic0)
    monitor = CompileMonitor().start()
    base = jax.random.PRNGKey(0)
    chunks = EPISODE_STEPS // CHUNK
    # donate on the sync control (its historic dispatch contract); the
    # async legs hand actor blocks across threads by reference — their
    # one donated call is run_async's learner-owned replay_ingest
    pddpg = ParallelDDPG(env, agent, num_replicas=B,
                         donate=(actors == 0), plan=plan)
    env_states, obs = pddpg.reset_all(base, topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)

    row = {"leg": leg, "status": "ok", "replicas": B, "chunk": CHUNK,
           "episode_steps": EPISODE_STEPS,
           "episodes_measured": MEASURE_EPISODES, "async_actors": actors,
           "devices": devices,
           "mesh": plan.describe() if plan is not None else None}

    def traces():
        return {fn: t for fn, (t, _c) in monitor.snapshot().items()
                if t and fn in ("rollout_episodes", "learn_burst",
                                "reset_all", "replay_ingest")}

    if actors == 0:
        # the control: strictly alternating act/learn on one thread,
        # same entry points, one burst per episode
        def sync_episode(ep, state, buffers):
            env_states, obs = pddpg.reset_all(
                jax.random.fold_in(base, ep), topo, traffic)
            ret = 0.0
            for c in range(chunks):
                start = jnp.int32(ep * EPISODE_STEPS + c * CHUNK)
                state, buffers, env_states, obs, stats = \
                    pddpg.rollout_episodes(state, buffers, env_states,
                                           obs, topo, traffic, start,
                                           CHUNK)
                ret += float(stats["episodic_return"])
            state, _metrics = pddpg.learn_burst(state, buffers)
            return state, buffers, ret

        t_warm = time.time()
        state, buffers, _ = sync_episode(0, state, buffers)
        jax.block_until_ready(state.actor_params)
        warm_s = time.time() - t_warm
        returns = []
        t0 = time.time()
        for ep in range(1, MEASURE_EPISODES + 1):
            state, buffers, ret = sync_episode(ep, state, buffers)
            returns.append(ret)
        jax.block_until_ready(state.actor_params)
        wall = time.time() - t0
        final_w, auc = _curve_metrics(returns)
        row.update({
            "sps": round(MEASURE_EPISODES * EPISODE_STEPS * B / wall, 2),
            "measure_wall_s": round(wall, 2), "warmup_s": round(warm_s, 2),
            "final_window_return": final_w, "auc_return": auc,
            "returns": [round(r, 4) for r in returns],
            "jit_traces": traces(),
        })
    else:
        from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

        scenario_fn = lambda ep: (topo, traffic)   # noqa: E731
        cfg = AsyncConfig(actor_threads=actors)
        # warmup: one episode per actor compiles every entry point on
        # both sides of the split (reset_all/rollout_episodes actor-side,
        # replay_ingest/learn_burst learner-side)
        t_warm = time.time()
        res = run_async(pddpg, scenario_fn, state, buffers,
                        episodes=actors, episode_steps=EPISODE_STEPS,
                        chunk=CHUNK, seed=0, cfg=cfg)
        state, buffers = res.state, res.buffers
        warm_s = time.time() - t_warm
        timer = PhaseTimer()   # fresh ledger: warmup wall excluded
        t0 = time.time()
        res = run_async(pddpg, scenario_fn, state, buffers,
                        episodes=actors + MEASURE_EPISODES,
                        episode_steps=EPISODE_STEPS, chunk=CHUNK, seed=0,
                        cfg=cfg, timer=timer, start_episode=actors)
        wall = time.time() - t0
        # curve in EPISODE-INDEX order (completion order is a thread
        # race; the index rides on every drained record)
        eps = sorted(res.episodes, key=lambda r: r["episode"])
        returns = [r["episodic_return"] for r in eps]
        final_w, auc = _curve_metrics(returns)
        info = res.info
        sps = round(MEASURE_EPISODES * EPISODE_STEPS * B / wall, 2)
        row.update({
            "sps": sps,
            # per-grid vs per-device: on a real pod sps_per_device is the
            # scaling-efficiency axis; on the forced-device CPU box it
            # documents how thin the shared core is sliced
            "sps_per_device": round(sps / devices, 2),
            "ring_shards": info.get("ring_shards", 1),
            "ingest_collectives": info.get("ingest_collectives"),
            "measure_wall_s": round(wall, 2), "warmup_s": round(warm_s, 2),
            "final_window_return": final_w, "auc_return": auc,
            "returns": [round(r, 4) for r in returns],
            "learner_idle_frac": info["learner_idle_frac"],
            "learner_idle_s": info["learner_idle_s"],
            "bursts": info["bursts"],
            "produced_steps": info["produced_steps"],
            "ingested_steps": info["ingested_steps"],
            "transitions_lost": info["transitions_lost"],
            "policy_lag_max": info["policy_lag_max"],
            "policy_lag_mean": info["policy_lag_mean"],
            # flight-recorder lag/idle axes (PR 17): staleness
            # percentiles + the dispatch-side idle twin of the
            # learner-idle gate (max over actors; per-actor vector kept
            # for the leg record)
            "policy_lag_p50": info.get("policy_lag_p50", 0),
            "policy_lag_p99": info.get("policy_lag_p99", 0),
            "actor_idle_frac": info.get("actor_idle_frac", 0.0),
            "actor_idle_fracs": info.get("actor_idle_fracs", []),
            "phases": timer.summary(),
            "jit_traces": traces(),
        })
    print(json.dumps(row), flush=True)
    return 0


def _run_leg(leg: str) -> dict:
    """Fresh subprocess per leg (the 1-core box must never run two jax
    programs concurrently; a fresh process also keeps the legs'
    trace-count accounting independent)."""
    cmd = [sys.executable, os.path.abspath(__file__), "--worker", leg]
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    # mesh legs: carve N virtual host devices out of the one CPU before
    # jax initialises; non-mesh legs must NOT inherit a forced count
    # from the caller's environment
    devices = _leg_devices(leg)
    if devices > 1:
        env["XLA_FLAGS"] = \
            f"--xla_force_host_platform_device_count={devices}"
    else:
        env.pop("XLA_FLAGS", None)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    t0 = time.time()
    try:
        out = subprocess.run(cmd, capture_output=True, text=True,
                             timeout=LEG_TIMEOUT_S, env=env)
    except subprocess.TimeoutExpired:
        return {"leg": leg, "status": "failed",
                "reason": f"timeout after {LEG_TIMEOUT_S}s"}
    lines = [l for l in out.stdout.strip().splitlines() if l.strip()]
    for line in reversed(lines):
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and row.get("leg") == leg:
            row["leg_wall_s"] = round(time.time() - t0, 1)
            return row
    return {"leg": leg, "status": "failed",
            "reason": f"rc={out.returncode}, no parseable row",
            "tail": (out.stdout + out.stderr)[-2000:]}


def _within(name: str, a: float, b: float) -> bool:
    rel, floor = CURVE_BANDS[name]
    return abs(a - b) <= max(rel * abs(b), floor)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--worker", default=None,
                    help="run one leg in-process "
                         f"({'|'.join(LEGS + LEGS_R02[1:])})")
    ap.add_argument("--round", default="r01", choices=("r01", "r02"),
                    dest="round_", metavar="ROUND",
                    help="r01: actor-count sweep (sync control); "
                         "r02: mesh sweep (dp-sharded ring on forced "
                         "host devices)")
    ap.add_argument("--bank", action="store_true",
                    help="write ASYNC_<round>.json next to the repo root")
    ap.add_argument("--out", default=None,
                    help="bank path (default <repo>/ASYNC_<round>.json)")
    ap.add_argument("--trajectory", default=None,
                    help="also ingest the banked row into this "
                         "BENCH_TRAJECTORY.json")
    args = ap.parse_args(argv)
    if args.worker:
        return worker(args.worker)
    if args.round_ == "r02":
        return _main_r02(args)

    legs = {leg: _run_leg(leg) for leg in LEGS}
    ok = all(l.get("status") == "ok" for l in legs.values())
    doc = {
        "metric": "env_steps_per_sec_per_chip",
        "unit": "env-steps/s", "round": 1, "platform": "cpu",
        "status": "ok" if ok else "failed",
        "replicas": B, "chunk": CHUNK, "episode_steps": EPISODE_STEPS,
        "episodes_measured": MEASURE_EPISODES,
        "legs": [legs[leg] for leg in LEGS],
    }
    reasons = []
    if ok:
        s, a1, a2, a4 = (legs[leg] for leg in LEGS)
        idle = max(a2["learner_idle_frac"], a4["learner_idle_frac"])
        doc.update({
            "sync_sps": s["sps"], "async1_sps": a1["sps"],
            "async2_sps": a2["sps"], "async4_sps": a4["sps"],
            "async2_vs_sync": round(a2["sps"] / s["sps"], 3),
            "async4_vs_sync": round(a4["sps"] / s["sps"], 3),
            "async_actors": 2,   # the headline gated leg
            "learner_idle_frac": idle,
            "policy_lag_max": max(a2["policy_lag_max"],
                                  a4["policy_lag_max"]),
            # worst-case staleness p99 / actor-idle across the async
            # legs: the bench_diff `policy_lag_p99` and
            # `actor_idle_frac` bands gate these (BENCH_NOTES
            # conventions for ASYNC rows)
            "policy_lag_p99": max(a2.get("policy_lag_p99", 0),
                                  a4.get("policy_lag_p99", 0)),
            "actor_idle_frac": max(a2.get("actor_idle_frac", 0.0),
                                   a4.get("actor_idle_frac", 0.0)),
            "produced_steps": a2["produced_steps"],
            "ingested_steps": a2["ingested_steps"],
            "sync_final_window_return": s["final_window_return"],
            "async_final_window_return": a2["final_window_return"],
            "sync_auc_return": s["auc_return"],
            "async_auc_return": a2["auc_return"],
            "jit_traces_sync": s["jit_traces"],
            "jit_traces_async1": a1["jit_traces"],
            "jit_traces_async2": a2["jit_traces"],
            "jit_traces_async4": a4["jit_traces"],
        })
        # gate 1: the decoupling claim — async >= sync at >= 2 actors
        for leg in (a2, a4):
            if leg["sps"] < s["sps"]:
                reasons.append(
                    f"{leg['leg']}_sps {leg['sps']} < sync_sps {s['sps']} "
                    "— the round does not support the decoupling claim")
        # gate 2: the learner never waits on acting at steady state
        for leg in (a2, a4):
            if leg["learner_idle_frac"] >= IDLE_FRAC_MAX:
                reasons.append(
                    f"{leg['leg']} learner_idle_frac "
                    f"{leg['learner_idle_frac']} >= {IDLE_FRAC_MAX} — "
                    "the learner waited on acting")
        # gate 3: drain-proved accounting on every async leg
        for leg in (a1, a2, a4):
            if leg["transitions_lost"] != 0 \
                    or leg["produced_steps"] != leg["ingested_steps"]:
                reasons.append(f"{leg['leg']} lost transitions: "
                               f"produced {leg['produced_steps']} vs "
                               f"ingested {leg['ingested_steps']}")
        # gate 4: banded curve equivalence at the matched budget
        for name, s_key, a_key in (
                ("final_window_return", "sync_final_window_return",
                 "async_final_window_return"),
                ("auc_return", "sync_auc_return", "async_auc_return")):
            if not _within(name, doc[a_key], doc[s_key]):
                rel, floor = CURVE_BANDS[name]
                reasons.append(
                    f"async {name} {doc[a_key]} outside the "
                    f"{int(rel * 100)}%/floor-{floor} band around sync "
                    f"{doc[s_key]}")
        doc["async_ge_sync"] = not any("decoupling" in r for r in reasons)
        doc["note"] = (
            "Matched-budget comparison on the 1-core CPU box (fresh "
            "subprocess per leg, warm persistent compile cache, warmup "
            "episodes excluded): same entry points, same "
            f"{MEASURE_EPISODES}x{EPISODE_STEPS}x{B} env-step and "
            "one-burst-per-episode gradient budgets; the sync control "
            "alternates act/learn on one thread, the async legs feed "
            "the device-resident ring from 1/2/4 actor threads while "
            f"the learner bursts back-to-back.  sync {s['sps']} vs "
            f"async2 {a2['sps']} / async4 {a4['sps']} env-steps/s, "
            f"learner_idle_frac {idle}, policy_lag_max "
            f"{doc['policy_lag_max']}.  Curves are banded, not "
            "bit-equal: actors act on K-burst-old weights by design.")
        try:
            import jax
            doc["jax"] = jax.__version__
        except Exception:
            pass
    return _finish(doc, ok, reasons, args, "ASYNC_r01.json")


def _finish(doc, ok, reasons, args, default_name) -> int:
    claim_holds = ok and not reasons
    if ok and reasons:
        doc["status"] = "failed"
        doc["reason"] = "; ".join(reasons)
    print(json.dumps(doc, indent=1))
    if args.bank or args.out:
        out = args.out or os.path.join(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))), default_name)
        if not claim_holds:
            # never overwrite a previously banked GREEN artifact with a
            # losing/failed round — park the evidence next to it (the
            # ASYNC_r*.json scan still ingests it as a failed row)
            out = os.path.splitext(out)[0] + ".failed.json"
        with open(out, "w") as fobj:
            json.dump(doc, fobj, indent=1)
            fobj.write("\n")
        print(f"[async_bench] banked {out}")
        if args.trajectory:
            import bench_diff
            bench_diff.ingest([out], args.trajectory)
        if not claim_holds:
            print("[async_bench] FAIL: "
                  f"{doc.get('reason', 'leg failure')}")
            return 1
    return 0 if claim_holds else 1


def _main_r02(args) -> int:
    """The mesh round: dp-sharded ring on forced host devices vs the
    single-device async2 baseline, same actor count everywhere."""
    legs = {leg: _run_leg(leg) for leg in LEGS_R02}
    ok = all(l.get("status") == "ok" for l in legs.values())
    doc = {
        "metric": "env_steps_per_sec_per_chip",
        "unit": "env-steps/s", "round": 2, "platform": "cpu",
        "status": "ok" if ok else "failed",
        "replicas": B, "chunk": CHUNK, "episode_steps": EPISODE_STEPS,
        "episodes_measured": MEASURE_EPISODES, "async_actors": 2,
        "legs": [legs[leg] for leg in LEGS_R02],
    }
    reasons = []
    if ok:
        a2, d2, d4 = (legs[leg] for leg in LEGS_R02)
        dp_legs = (d2, d4)
        idle = max(l["learner_idle_frac"] for l in legs.values())
        doc.update({
            "async2_sps": a2["sps"],
            "async_dp2_sps": d2["sps"], "async_dp4_sps": d4["sps"],
            "async2_sps_per_device": a2["sps_per_device"],
            "async_dp2_sps_per_device": d2["sps_per_device"],
            "async_dp4_sps_per_device": d4["sps_per_device"],
            "async_dp2_vs_async2": round(d2["sps"] / a2["sps"], 3),
            "async_dp4_vs_async2": round(d4["sps"] / a2["sps"], 3),
            "mesh": {l["leg"]: l["mesh"] for l in dp_legs},
            "ring_shards": {l["leg"]: l["ring_shards"]
                            for l in legs.values()},
            # HLO-mined collective count on the compiled ingest, worst
            # dp leg — 0 or the round is dead (bench_diff gates growth
            # at 0% tolerance once banked)
            "ingest_collectives": max(int(l["ingest_collectives"] or 0)
                                      for l in dp_legs),
            "learner_idle_frac": idle,
            "policy_lag_max": max(l["policy_lag_max"]
                                  for l in legs.values()),
            "policy_lag_p99": max(l.get("policy_lag_p99", 0)
                                  for l in legs.values()),
            "actor_idle_frac": max(l.get("actor_idle_frac", 0.0)
                                   for l in legs.values()),
            "produced_steps": d4["produced_steps"],
            "ingested_steps": d4["ingested_steps"],
            "jit_traces_async2": a2["jit_traces"],
            "jit_traces_async_dp2": d2["jit_traces"],
            "jit_traces_async_dp4": d4["jit_traces"],
        })
        # gate 1: drain-proved accounting on every leg
        for l in legs.values():
            if l["transitions_lost"] != 0 \
                    or l["produced_steps"] != l["ingested_steps"]:
                reasons.append(f"{l['leg']} lost transitions: "
                               f"produced {l['produced_steps']} vs "
                               f"ingested {l['ingested_steps']}")
        # gate 2: the zero-collective ingest contract — blocks land on
        # the learner mesh exactly once and never move again
        for l in dp_legs:
            if int(l["ingest_collectives"] or 0) != 0:
                reasons.append(
                    f"{l['leg']} compiled replay_ingest with "
                    f"{l['ingest_collectives']} collective op(s) — the "
                    "dp-sharded ring is paying a gather/reshard per "
                    "block")
        # gate 3: the learner never waits on acting at steady state
        for l in legs.values():
            if l["learner_idle_frac"] >= IDLE_FRAC_MAX:
                reasons.append(
                    f"{l['leg']} learner_idle_frac "
                    f"{l['learner_idle_frac']} >= {IDLE_FRAC_MAX} — "
                    "the learner waited on acting")
        # gate 4: per-grid throughput above the baseline's per-device
        # share — see DP_SHARE_FLOOR for why this is a floor, not a band
        for l in dp_legs:
            floor = round(DP_SHARE_FLOOR * a2["sps"] / l["devices"], 2)
            if l["sps"] < floor:
                reasons.append(
                    f"{l['leg']}_sps {l['sps']} < {floor} "
                    f"(async2_sps {a2['sps']} / {l['devices']} devices) "
                    "— sharding overhead ate the whole parallelism "
                    "budget (collective storm on the hot path?)")
        doc["note"] = (
            "Mesh sweep on the 1-core CPU box (fresh subprocess per "
            "leg; dp legs carve the core into forced host devices with "
            "--xla_force_host_platform_device_count, so per-grid "
            "throughput can only LOSE to sharding overhead — the gate "
            "is a FLOOR at async2_sps/devices, the baseline's "
            "per-device share, not a speedup claim; cross-round drift "
            "gates under bench_diff's 15% rate bands).  All "
            f"legs: {MEASURE_EPISODES}x{EPISODE_STEPS}x{B} env-steps, "
            "2 actor threads, one burst per episode.  dp legs run the "
            "replay ring resident-sharded over the plan's dp axis with "
            "the shard_map per-shard donated ingest; "
            f"ingest_collectives {doc['ingest_collectives']} (HLO-mined "
            "on the AOT-compiled ingest executable).  async2 "
            f"{a2['sps']} vs async_dp2 {d2['sps']} / async_dp4 "
            f"{d4['sps']} env-steps/s, learner_idle_frac {idle}, "
            f"policy_lag_p99 {doc['policy_lag_p99']}.")
        try:
            import jax
            doc["jax"] = jax.__version__
        except Exception:
            pass
    return _finish(doc, ok, reasons, args, "ASYNC_r02.json")


if __name__ == "__main__":
    sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
    sys.exit(main())
