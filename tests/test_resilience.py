"""Resilience subsystem tests: fault-plan grammar, no-fault bit-identity
of the divergence guard, every rung of the degradation ladder under
injected faults (retry -> prefetcher restart -> pipeline off -> rollback),
watchdog escalation, checksummed checkpoint rotation with resume-auto
fallback, and the SIGTERM -> snapshot -> --resume auto roundtrip.

All marked ``resilience`` — `pytest -m resilience -q` is the standalone
smoke group.
"""
import json
import os
import signal
import subprocess
import sys
import time

import jax
import numpy as np
import pytest

from gsc_tpu.agents import Trainer
from gsc_tpu.resilience import (
    FaultPlan,
    PreemptionGuard,
    RetryPolicy,
    TransientDispatchError,
    call_with_retry,
)
from tests.test_agent import make_driver, make_stack

pytestmark = pytest.mark.resilience

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _assert_trees_equal(a, b):
    jax.tree_util.tree_map(
        lambda x, y: np.testing.assert_array_equal(np.asarray(x),
                                                   np.asarray(y)), a, b)


def _train(episodes=4, fault_plan=None, obs=None, seed=7, **trainer_kw):
    env, agent, topo, traffic = make_stack()
    driver = make_driver(env, agent, topo, traffic)
    t = Trainer(env, driver, agent, seed=seed, obs=obs,
                fault_plan=fault_plan, **trainer_kw)
    state, buffer = t.train(episodes=episodes)
    return t, state, buffer


@pytest.fixture(scope="module")
def reference_run():
    """One faultless default-config run the fault tests compare against —
    retry and prefetcher-restart recoveries must be BIT-invisible in the
    training results."""
    t, state, buffer = _train()
    return state, buffer, t.history


def _assert_matches_reference(reference_run, state, buffer, history):
    s_ref, b_ref, h_ref = reference_run
    _assert_trees_equal(
        (s_ref.actor_params, s_ref.critic_params, s_ref.rng,
         b_ref.data, b_ref.pos, b_ref.size),
        (state.actor_params, state.critic_params, state.rng,
         buffer.data, buffer.pos, buffer.size))
    assert len(history) == len(h_ref)
    for ra, rb in zip(h_ref, history):
        for k in ra:
            if k != "sps":
                assert ra[k] == rb[k], (k, ra[k], rb[k])


# -------------------------------------------------------------- fault plan
def test_fault_plan_grammar_and_fire_once(monkeypatch):
    plan = FaultPlan.parse("prefetch_die@1;nan_grads@3 , slow_episode@2:1.5")
    assert [(s.site, s.episode, s.arg) for s in plan.specs] == [
        ("prefetch_die", 1, None), ("nan_grads", 3, None),
        ("slow_episode", 2, 1.5)]
    # exact-match fire, exactly once
    assert plan.fire("prefetch_die", 0) is None
    spec = plan.fire("prefetch_die", 1)
    assert spec is not None and spec.fired
    assert plan.fire("prefetch_die", 1) is None
    # at_or_after (the checkpoint-site semantics: saves only happen every
    # interval, so an exact key could never land)
    assert plan.fire("nan_grads", 5, at_or_after=True).episode == 3
    assert [s.site for s in plan.unfired()] == ["slow_episode"]

    for bad in ("bogus@1", "nan_grads@x", "nan_grads", "nan_grads@-1",
                "nan_grads@1:z", ""):
        with pytest.raises(ValueError):
            FaultPlan.parse(bad)

    monkeypatch.setenv("GSC_FAULT_PLAN", "dispatch_transient@0")
    env_plan = FaultPlan.from_env()
    assert env_plan.specs[0].site == "dispatch_transient"
    # an explicit flag value overrides the env var...
    assert FaultPlan.from_env("nan_grads@2").specs[0].site == "nan_grads"
    # ...and an EXPLICIT empty flag disables injection even under an
    # exported env plan (the clean control leg of a chaos comparison)
    assert FaultPlan.from_env("") is None
    monkeypatch.delenv("GSC_FAULT_PLAN")
    assert FaultPlan.from_env() is None


def test_fault_plan_async_grammar():
    """The fleet sites' key forms: a<actor>:<episode> (actor-keyed),
    v<version> (version-keyed), plain ints for burst-keyed — with
    actor-aware matching and per-site validation errors."""
    plan = FaultPlan.parse("actor_die@a0:3;watcher_stall@a1:4:0.5;"
                           "publish_corrupt@v2;ring_poison@5;"
                           "learner_transient@7")
    assert [s.key for s in plan.specs] == ["a0:3", "a1:4", "v2", "5", "7"]
    assert plan.specs[1].arg == 0.5
    # actor-keyed specs never fire on the wrong actor, even at the right
    # episode — chaos runs must not be racy on thread scheduling
    assert plan.fire("actor_die", 3, actor=1) is None
    spec = plan.fire("actor_die", 3, actor=0)
    assert spec is not None and spec.fired
    assert plan.fire("actor_die", 3, actor=0) is None   # exactly once
    assert plan.fire("publish_corrupt", 2).key == "v2"

    for bad, msg in [("actor_die@3", "actor-keyed"),
                     ("actor_die@a0", "missing episode"),
                     ("actor_die@ax:3", "not an integer"),
                     ("actor_die@a-1:3", ">= 0"),
                     ("watcher_stall@v1", "actor-keyed"),
                     ("publish_corrupt@2", "version"),
                     ("publish_corrupt@vx", "not an integer"),
                     ("learner_transient@x", "burst")]:
        with pytest.raises(ValueError, match=msg):
            FaultPlan.parse(bad)

    # the shared end-of-run check: one structured event per run listing
    # every entry that never fired (serial + replica + async paths all
    # call this same method)
    class Hub:
        def __init__(self):
            self.events = []

        def event(self, name, **kw):
            self.events.append((name, kw))

    hub = Hub()
    un = plan.warn_unfired(hub)
    assert {f"{s.site}@{s.key}" for s in un} == \
        {"watcher_stall@a1:4", "ring_poison@5", "learner_transient@7"}
    assert hub.events[0][0] == "fault_plan_unfired"
    assert hub.events[0][1]["count"] == 3


def test_nan_grads_rolls_back_on_replica_path(tmp_path):
    """train_parallel now wires nan_grads: the poisoned episode is caught
    by the chaos-only host verify, the RollbackGuard restores the last
    verified snapshot, and the run finishes with a finite state."""
    from gsc_tpu.obs import RunObserver

    env, agent, topo, traffic = make_stack()
    driver = make_driver(env, agent, topo, traffic)
    obs = RunObserver(str(tmp_path), run_id="repnan").start()
    t = Trainer(env, driver, agent, seed=0, obs=obs,
                fault_plan=FaultPlan.parse("nan_grads@1"))
    state, buffers = t.train_parallel(episodes=3, num_replicas=2, chunk=2)
    obs.close()
    assert t.completed_episodes == 3
    assert all(np.isfinite(np.asarray(l)).all() for l in
               jax.tree_util.tree_leaves((state.actor_params,
                                          state.critic_params)))
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    recs = [(e["site"], e["action"]) for e in events
            if e["event"] == "recovery"]
    assert ("learner_state", "rollback") in recs
    assert not any(e["event"] == "fault_plan_unfired" for e in events)


def test_call_with_retry_semantics():
    calls = []

    def flaky():
        calls.append(1)
        if len(calls) < 3:
            raise TransientDispatchError("flaky")
        return "ok"

    retries = []
    policy = RetryPolicy(attempts=3, base_s=0.0, cap_s=0.0)
    assert call_with_retry(flaky, policy,
                           on_retry=lambda a, e, d: retries.append(a)) \
        == "ok"
    assert len(calls) == 3 and retries == [1, 2]
    # bounded: persistent transient propagates after `attempts` tries
    calls.clear()
    with pytest.raises(TransientDispatchError):
        call_with_retry(lambda: flaky() if len(calls) < 99 else None,
                        RetryPolicy(attempts=2, base_s=0.0))
    # non-transient errors are never retried
    boom = []

    def hard():
        boom.append(1)
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        call_with_retry(hard, policy)
    assert len(boom) == 1


# ----------------------------------------------------- guard / bit-identity
def test_no_fault_guard_is_bit_identical_and_never_triggers(reference_run):
    """Acceptance bar: with no fault plan the guardrail flag is computed
    (1.0 on every episode) but training output is bit-identical with the
    rollback snapshots disabled entirely — the guard never perturbs the
    math, it only watches it."""
    s_ref, b_ref, h_ref = reference_run
    assert all(row["state_finite"] == 1.0 for row in h_ref)
    t, state, buffer = _train(rollback=False)
    _assert_matches_reference(reference_run, state, buffer, t.history)


def test_nan_poison_rolls_back_and_recovers(tmp_path, reference_run):
    """The nan_grads fault: the poisoned episode drains with a zero
    finite-flag, the trainer restores the last-good snapshot, emits a
    structured recovery event, and the final learner state is finite."""
    from gsc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path), run_id="nan").start()
    t, state, buffer = _train(episodes=5,
                              fault_plan=FaultPlan.parse("nan_grads@2"),
                              obs=obs)
    obs.close()
    assert all(np.isfinite(np.asarray(l)).all() for l in
               jax.tree_util.tree_leaves((state.actor_params,
                                          state.critic_params,
                                          state.actor_opt)))
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    recs = [e for e in events if e["event"] == "recovery"]
    assert [(r["site"], r["action"]) for r in recs] == \
        [("learner_state", "rollback")]
    assert recs[0]["episode"] == 2 and recs[0]["fault"] == \
        "non_finite_state"
    # the poisoned episode's event carries the evidence...
    by_ep = {e["episode"]: e for e in events if e["event"] == "episode"}
    assert by_ep[2]["state_finite"] == 0.0
    # ...and the post-rollback episode ran on a finite state again
    assert max(by_ep) == 4 and by_ep[4]["state_finite"] == 1.0
    assert events[-1]["event"] == "run_end"
    assert events[-1]["recoveries"] == 1.0


def test_dispatch_transient_retries_bit_identical(tmp_path, reference_run):
    """An injected transient dispatch failure is retried with backoff and
    leaves NO trace in the training results — only in the recovery
    timeline."""
    from gsc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path), run_id="retry").start()
    t, state, buffer = _train(
        fault_plan=FaultPlan.parse("dispatch_transient@1"), obs=obs,
        retry_policy=RetryPolicy(attempts=3, base_s=0.01))
    obs.close()
    _assert_matches_reference(reference_run, state, buffer, t.history)
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    recs = [e for e in events if e["event"] == "recovery"]
    assert [(r["site"], r["action"], r["attempt"]) for r in recs] == \
        [("dispatch", "retry", 1)]


def test_prefetcher_death_restarts_bit_identical(reference_run):
    """A dead producer thread surfaces on the consumer's get; the trainer
    restarts the prefetcher from the episode counter and the re-staged
    sequence is bit-identical to an undisturbed run."""
    t, state, buffer = _train(fault_plan=FaultPlan.parse("prefetch_die@2"))
    _assert_matches_reference(reference_run, state, buffer, t.history)


def test_repeated_pipeline_faults_degrade_to_pipeline_off(tmp_path,
                                                          reference_run):
    """Past pipeline_fault_limit faults the run degrades pipeline->off
    (serial sampling, immediate drains) instead of thrashing restarts —
    and still finishes bit-identical (the pipeline is pure scheduling)."""
    from gsc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path), run_id="degrade").start()
    t, state, buffer = _train(
        fault_plan=FaultPlan.parse("prefetch_die@1;prefetch_die@2"),
        obs=obs, pipeline_fault_limit=1)
    obs.close()
    _assert_matches_reference(reference_run, state, buffer, t.history)
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    actions = [(e["site"], e["action"]) for e in events
               if e["event"] == "recovery"]
    assert actions == [("prefetcher", "restart"),
                       ("pipeline", "pipeline_off")]


def test_watchdog_escalation_interrupts_and_restarts(tmp_path,
                                                     reference_run):
    """An artificially slow episode staging trips the watchdog; after the
    escalation budget the watchdog interrupts the prefetcher, the trainer
    restarts it, and the run completes bit-identical."""
    from gsc_tpu.obs import RunObserver

    obs = RunObserver(str(tmp_path), run_id="esc", watchdog_budget_s=0.25,
                      watchdog_escalate=1).start()
    t, state, buffer = _train(
        fault_plan=FaultPlan.parse("slow_episode@2:30"), obs=obs)
    obs.close()
    _assert_matches_reference(reference_run, state, buffer, t.history)
    events = [json.loads(l) for l in open(tmp_path / "events.jsonl")]
    assert [e for e in events if e["event"] == "stall"], \
        "slow staging never tripped the watchdog"
    assert [e for e in events if e["event"] == "escalation"], \
        "watchdog never escalated"
    restarts = [e for e in events if e["event"] == "recovery"
                and e["site"] == "prefetcher"]
    assert restarts and "escalation" in restarts[0]["fault"]


# ------------------------------------------------------- async fleet battery
@pytest.fixture(scope="module")
def astack():
    """One compiled noise-free async stack for the fleet battery (see
    tests/test_async_rl._setup: rings come from a factory because
    replay_ingest donates them; pddpg/state are safely reusable).
    Noise-free (rand_sigma=rand_mu=0) so actor restarts are
    bit-reproducible: scenario and env-reset keys are GLOBAL-episode-
    keyed, and without exploration noise the actor's thread-local rng
    stream is inert."""
    from tests.test_async_rl import _setup
    return _setup(episode_steps=4, rand_sigma=0.0, rand_mu=0.0)


def _collecting(events):
    def on_recovery(episode, site=None, action=None, fault=None,
                    attempt=None, detail=None):
        events.append({"episode": episode, "site": site, "action": action,
                       "fault": fault, "attempt": attempt,
                       "detail": detail})
    return on_recovery


def _ring_finite(buffers):
    return all(np.isfinite(np.asarray(l)).all()
               for l in jax.tree_util.tree_leaves(buffers.data)
               if np.issubdtype(np.asarray(l).dtype, np.inexact))


def test_async_actor_restart_bit_identical(astack):
    """actor_die at episode entry: the supervisor restarts the actor from
    its episode counter and the re-staged ring is BIT-identical to an
    undisturbed run (publishing frozen, noise-free, death at the FIRST
    episode so the restarted actor's fresh scratch matches the control's
    — later-episode blocks carry dead padding lanes from the previous
    chunk, a masked-out residue a re-staged scratch can't replay)."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    cfg = AsyncConfig(actor_threads=1, publish_bursts=10**6)

    ref = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0, cfg=cfg)
    evts = []
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0, cfg=cfg,
                    fault_plan=FaultPlan.parse("actor_die@a0:0"),
                    on_recovery=_collecting(evts))
    assert res.info["actor_restarts"] == 1
    assert res.info["actors_degraded"] == 0
    assert [(e["site"], e["action"]) for e in evts] == \
        [("actor", "restart")]
    assert evts[0]["fault"] == "FaultInjected" and evts[0]["attempt"] == 1
    assert sorted(r["episode"] for r in res.episodes) == [0, 1, 2]
    _assert_trees_equal(ref.buffers.data, res.buffers.data)
    _assert_trees_equal((ref.buffers.pos, ref.buffers.size),
                        (res.buffers.pos, res.buffers.size))


def test_async_ring_poison_quarantined(astack):
    """A NaN-poisoned block is dropped at the learner's drain boundary
    with an evidence row: the ring never holds a NaN, drain accounting
    still balances, and the run completes."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    evts = []
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0,
                    cfg=AsyncConfig(actor_threads=1),
                    fault_plan=FaultPlan.parse("ring_poison@1"),
                    on_recovery=_collecting(evts))
    info = res.info
    assert info["blocks_quarantined"] == 1
    assert info["steps_quarantined"] == 2 * 2   # one [B=2, chunk=2] block
    assert info["produced_steps"] == info["ingested_steps"]
    assert info["transitions_lost"] == 0
    assert info["episodes_drained"] == 3
    assert _ring_finite(res.buffers), "a poisoned block reached the ring"
    quar = [e for e in evts if e["site"] == "replay"]
    assert [(e["action"], e["fault"]) for e in quar] == \
        [("quarantine", "non_finite_block")]


def test_async_rollback_then_continue(astack):
    """Burst-keyed nan_grads poisons the learner state; the deferred
    state_finite verdict restores the RollbackGuard's last-verified
    snapshot and the run CONTINUES to a finite final state (and the
    publish gate never let the poisoned version out)."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    evts = []
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=4,
                    episode_steps=4, chunk=2, seed=0,
                    cfg=AsyncConfig(actor_threads=1), rollback=True,
                    fault_plan=FaultPlan.parse("nan_grads@1"),
                    on_recovery=_collecting(evts))
    assert res.info["rollbacks"] == 1
    rb = [e for e in evts if e["site"] == "learner_state"]
    assert [(e["action"], e["fault"]) for e in rb] == \
        [("rollback", "non_finite_state")]
    assert all(np.isfinite(np.asarray(l)).all() for l in
               jax.tree_util.tree_leaves((res.state.actor_params,
                                          res.state.critic_params)))
    assert _ring_finite(res.buffers)
    assert res.info["episodes_drained"] == 4


def test_async_learner_transient_retried(astack):
    """learner_transient raises the retryable class at learn-burst entry;
    the retry layer backs off, re-dispatches, and the run is otherwise
    undisturbed."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    evts = []
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0,
                    cfg=AsyncConfig(actor_threads=1),
                    fault_plan=FaultPlan.parse("learner_transient@1"),
                    retry_policy=RetryPolicy(attempts=3, base_s=0.01),
                    on_recovery=_collecting(evts))
    retries = [e for e in evts if e["site"] == "learner"]
    assert [(e["action"], e["attempt"]) for e in retries] == \
        [("retry", 1)]
    assert res.info["episodes_drained"] == 3
    assert res.info["transitions_lost"] == 0


def test_async_watcher_stall_skips_adoption(astack):
    """A stalled/failing version poll never kills the actor: the adoption
    is skipped with a recovery row and the episode completes on the
    current weights."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    evts = []
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0,
                    cfg=AsyncConfig(actor_threads=1),
                    fault_plan=FaultPlan.parse("watcher_stall@a0:1"),
                    on_recovery=_collecting(evts))
    stalls = [e for e in evts if e["site"] == "watcher"]
    assert [(e["action"], e["fault"]) for e in stalls] == \
        [("skip_adopt", "FaultInjected")]
    assert res.info["episodes_drained"] == 3
    assert res.info["actor_restarts"] == 0


def test_async_restart_budget_exhaustion_degrades(astack):
    """Past the per-actor restart budget the fleet degrades to fewer
    actors: the dead actor's episodes are reassigned (episode data is
    GLOBAL-index-keyed, so WHO runs them never changes WHAT they train
    on), the staleness cap is re-derived, and every episode still
    drains."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    evts = []
    # two actors, zero budget: actor 0 dies at its episode 2 and is
    # degraded immediately; actor 1 absorbs the orphans
    res = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=4,
                    episode_steps=4, chunk=2, seed=0,
                    cfg=AsyncConfig(actor_threads=2, restart_budget=0),
                    fault_plan=FaultPlan.parse("actor_die@a0:2"),
                    on_recovery=_collecting(evts))
    assert res.info["actors_degraded"] == 1
    assert res.info["actor_restarts"] == 0
    deg = [e for e in evts if e["action"] == "degrade"]
    assert len(deg) == 1 and "degrades to 1 actor" in deg[0]["detail"]
    assert "staleness cap re-derived" in deg[0]["detail"]
    assert sorted(r["episode"] for r in res.episodes) == [0, 1, 2, 3]
    assert res.info["transitions_lost"] == 0


def test_async_whole_fleet_exhausted_raises(astack):
    """Every actor past its budget with episodes unrun: the run RAISES
    (chained to the actor's error) instead of hanging or silently
    under-running."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    with pytest.raises(RuntimeError, match="exhausted"):
        run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                  episode_steps=4, chunk=2, seed=0,
                  cfg=AsyncConfig(actor_threads=1, restart_budget=0),
                  fault_plan=FaultPlan.parse("actor_die@a0:1"))


def test_async_fault_free_guarded_run_bit_identical(astack):
    """Satellite acceptance: with no fault fired, the guarded stack
    (rollback snapshots + per-block quarantine checks) is BIT-identical
    to the guard-free stack — the guards watch the math, never perturb
    it."""
    from gsc_tpu.parallel.async_rl import AsyncConfig, run_async

    pddpg, state, make_buffers, scenario_fn = astack
    cfg = AsyncConfig(actor_threads=1, publish_bursts=10**6)
    off = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                    episode_steps=4, chunk=2, seed=0, cfg=cfg)
    on = run_async(pddpg, scenario_fn, state, make_buffers(), episodes=3,
                   episode_steps=4, chunk=2, seed=0, cfg=cfg,
                   rollback=True)
    assert on.info["rollbacks"] == 0
    assert on.info["blocks_quarantined"] == 0
    # the ring is the deterministic artifact (the learner STATE depends
    # on how ingests interleave with bursts, same as any two fault-free
    # runs — see test_async_rl.test_async_deterministic_replay)
    _assert_trees_equal(off.buffers.data, on.buffers.data)
    _assert_trees_equal((off.buffers.pos, off.buffers.size),
                        (on.buffers.pos, on.buffers.size))


def test_publisher_finite_gate_and_corrupt_publish(tmp_path):
    """Satellite: the in-process zero-copy publish path is finite-gated
    exactly like the file path — an unverified non-finite publish is
    skipped (no version bump, no delivery), and a publish_corrupt'd
    version is parked by the watcher-side gates on BOTH paths."""
    import jax.numpy as jnp
    from gsc_tpu.serve.fleet import VersionWatcher, WeightPublisher

    class Server:
        policy_version = -1

        def apply_weights(self, leaves, version, fingerprint, meta=None):
            self.leaves, self.policy_version = leaves, version

    # 1) unverified non-finite params never publish
    got = []
    pub = WeightPublisher(subscribers=[lambda rec, p: got.append(rec)])
    assert pub.publish({"w": jnp.asarray([1.0, float("nan")])}) is None
    assert pub.version == 0 and not got
    assert pub.publish({"w": jnp.ones(2)})["version"] == 1
    assert got and got[0]["version"] == 1

    # 2) in-process publish_corrupt: the delivered leaves are poisoned,
    # the watcher's finite gate refuses the version (parked, version
    # unchanged) and a later clean publish is adopted normally
    pub2 = WeightPublisher(
        fault_plan=FaultPlan.parse("publish_corrupt@v1"))
    srv = Server()
    w = VersionWatcher(None, srv, publisher=pub2)
    assert pub2.publish({"w": jnp.ones(2)}, verified=True)["version"] == 1
    assert not w.poll_once()           # gate parks the poisoned version
    assert srv.policy_version == -1
    assert pub2.publish({"w": jnp.full(2, 2.0)},
                        verified=True)["version"] == 2
    assert w.poll_once() and srv.policy_version == 2
    np.testing.assert_array_equal(np.asarray(srv.leaves[0]),
                                  np.full(2, 2.0))
    w.stop()

    # 3) file-path publish_corrupt: the blob's flipped byte fails the
    # manifest fingerprint and the directory watcher parks the version
    pub3 = WeightPublisher(str(tmp_path),
                           fault_plan=FaultPlan.parse("publish_corrupt@v1"))
    srv3 = Server()
    w3 = VersionWatcher(str(tmp_path), srv3)
    assert pub3.publish({"w": np.ones(4, np.float32)},
                        verified=True)["version"] == 1
    assert not w3.poll_once()
    assert srv3.policy_version == -1
    w3.stop()


@pytest.mark.slow
@pytest.mark.skipif(not hasattr(signal, "SIGTERM"), reason="POSIX only")
def test_async_sigterm_resume_auto_roundtrip(tmp_path):
    """Tentpole (d): SIGTERM a live `cli train --async` subprocess — the
    fleet stops its actors, drains fully (the exit JSON carries the
    produced==ingested proof), snapshots, exits 0 — then
    `--async --resume auto` continues with a monotone episode counter."""
    from click.testing import CliRunner

    from gsc_tpu.cli import cli as cli_group
    from gsc_tpu.utils.checkpoint import verify_checkpoint
    from tests.test_agent import write_tiny_configs

    args = write_tiny_configs(tmp_path)
    res = str(tmp_path / "res")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"),
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1",
               JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="-1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gsc_tpu.cli", "train", *args,
         "--episodes", "500", "--replicas", "2", "--async",
         "--async-actors", "2", "--chunk", "3", "--ckpt-interval", "50",
         "--result-dir", res],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        deadline = time.time() + 300
        events_path = None
        while time.time() < deadline:
            for root, _, files in os.walk(res):
                if "events.jsonl" in files:
                    p = os.path.join(root, "events.jsonl")
                    if any('"event": "episode"' in l for l in open(p)):
                        events_path = p
                        break
            if events_path or proc.poll() is not None:
                break
            time.sleep(0.25)
        assert proc.poll() is None, proc.communicate()
        assert events_path, "no episode event before deadline"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=180)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, (out, err)
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["status"] == "preempted" and tail["signal"] == "SIGTERM"
    done = tail["episodes_completed"]
    assert done >= 1
    assert verify_checkpoint(tail["checkpoint"]), tail
    # the drain proof rides the exit line: nothing produced was lost
    assert tail["drain"]["produced_steps"] == \
        tail["drain"]["ingested_steps"]
    assert tail["drain"]["transitions_lost"] == 0
    events = [json.loads(l) for l in open(events_path)]
    assert any(e["event"] == "recovery" and e["action"] ==
               "preempt_snapshot" for e in events)

    r = CliRunner().invoke(cli_group, ["train", *args,
                                       "--episodes", str(done + 2),
                                       "--replicas", "2", "--async",
                                       "--async-actors", "2",
                                       "--chunk", "3",
                                       "--resume", "auto",
                                       "--result-dir", res])
    assert r.exit_code == 0, (r.output, r.exception)
    out2 = json.loads(r.output.strip().splitlines()[-1])
    events2 = [json.loads(l) for l in
               open(os.path.join(out2["result_dir"], "events.jsonl"))]
    eps = sorted(e["episode"] for e in events2 if e["event"] == "episode")
    # monotone continuation: exactly the gap episodes, nothing re-run
    # below the snapshot's contiguous drained prefix
    assert eps == [done, done + 1]


# ------------------------------------------------------------- checkpoints
def test_ckpt_meta_tolerates_corrupt_sidecar(tmp_path, caplog):
    """Satellite: a truncated/garbage/non-object .meta.json degrades to {}
    with a warning instead of raising — a half-written sidecar must not
    brick --resume."""
    import logging

    from gsc_tpu.utils.checkpoint import read_checkpoint_meta

    ckpt = str(tmp_path / "ckpt")
    sidecar = ckpt + ".meta.json"
    cases = [b'{"precision": "bf16', b"\xff\xfe\x00garbage", b'"a-string"',
             b"[1, 2]", b""]
    for raw in cases:
        with open(sidecar, "wb") as f:
            f.write(raw)
        with caplog.at_level(logging.WARNING, "gsc_tpu.utils.checkpoint"):
            caplog.clear()
            assert read_checkpoint_meta(ckpt) == {}, raw
        assert any("sidecar" in r.message for r in caplog.records), raw
    os.unlink(sidecar)
    assert read_checkpoint_meta(ckpt) == {}   # absent: silent pre-meta


def test_ckpt_manager_checksum_rotation_and_fallback(tmp_path):
    from gsc_tpu.agents import DDPG
    from gsc_tpu.resilience.ckpt import (CheckpointManager,
                                         corrupt_checkpoint, find_resumable)
    from gsc_tpu.utils.checkpoint import read_checkpoint_meta, \
        verify_checkpoint

    env, agent, topo, traffic = make_stack()
    _, obs0 = env.reset(jax.random.PRNGKey(0), topo, traffic)
    ddpg = DDPG(env, agent)
    state = ddpg.init(jax.random.PRNGKey(1), obs0)
    buf = ddpg.init_buffer(obs0)

    m = CheckpointManager(str(tmp_path / "ckpts"), retain=2,
                          meta={"precision": "f32"})
    for ep in (2, 4, 6):
        path = m.save(state, buf, episode=ep)
        assert path and verify_checkpoint(path)
        assert read_checkpoint_meta(path)["episode"] == ep
    names = {n for n in os.listdir(tmp_path / "ckpts")
             if n.startswith("ep") and not n.endswith(".json")}
    assert names == {"ep00000004", "ep00000006"}   # retention pruned ep2
    pointer = json.load(open(m.pointer_path))
    assert pointer["episode"] == 6

    newest = find_resumable(str(tmp_path))
    assert newest.endswith("ep00000006")
    # resume-auto fallback: a corrupted newest checkpoint fails its
    # checksum and the previous good one wins
    corrupt_checkpoint(newest)
    assert not verify_checkpoint(newest)
    assert find_resumable(str(tmp_path)).endswith("ep00000004")

    # the injected ckpt_corrupt fault is caught by validation and
    # re-saved, with a structured recovery event
    from gsc_tpu.obs import RunObserver
    obs = RunObserver(str(tmp_path / "obs"), run_id="ck").start()
    m2 = CheckpointManager(str(tmp_path / "ckpts2"), retain=2,
                           fault_plan=FaultPlan.parse("ckpt_corrupt@8"),
                           obs=obs)
    path = m2.save(state, buf, episode=8)
    obs.close()
    assert path and verify_checkpoint(path)
    events = [json.loads(l) for l in open(tmp_path / "obs" /
                                          "events.jsonl")]
    recs = [e for e in events if e["event"] == "recovery"]
    assert [(r["site"], r["action"]) for r in recs] == \
        [("checkpoint", "resave")]


def test_cli_periodic_ckpt_and_resume_auto(tmp_path):
    """cli train --ckpt-interval writes checksummed rotating checkpoints;
    a follow-up --resume auto picks the newest valid one and continues
    with a monotone episode counter."""
    from click.testing import CliRunner

    from gsc_tpu.cli import cli as cli_group
    from tests.test_agent import write_tiny_configs

    args = write_tiny_configs(tmp_path)
    res = str(tmp_path / "res")
    r1 = CliRunner().invoke(cli_group, ["train", *args, "--episodes", "4",
                                        "--ckpt-interval", "2",
                                        "--result-dir", res])
    assert r1.exit_code == 0, (r1.output, r1.exception)
    out1 = json.loads(r1.output.strip().splitlines()[-1])
    ckpts = os.path.join(out1["result_dir"], "ckpts")
    assert os.path.exists(os.path.join(ckpts, "last_good.json"))
    assert any(n.startswith("ep") for n in os.listdir(ckpts))

    r2 = CliRunner().invoke(cli_group, ["train", *args, "--episodes", "6",
                                        "--resume", "auto",
                                        "--result-dir", res])
    assert r2.exit_code == 0, (r2.output, r2.exception)
    out2 = json.loads(r2.output.strip().splitlines()[-1])
    events = [json.loads(l) for l in
              open(os.path.join(out2["result_dir"], "events.jsonl"))]
    eps = [e["episode"] for e in events if e["event"] == "episode"]
    # the resumed run continues where the newest valid checkpoint stopped
    assert eps == [4, 5]

    # resume auto with nothing restorable is a clean parameter error
    r3 = CliRunner().invoke(cli_group, ["train", *args, "--episodes", "2",
                                        "--resume", "auto", "--result-dir",
                                        str(tmp_path / "empty")])
    assert r3.exit_code != 0
    assert "resume auto" in r3.output


@pytest.mark.skipif(not hasattr(signal, "SIGTERM"), reason="POSIX only")
def test_sigterm_snapshot_and_resume_auto_roundtrip(tmp_path):
    """Satellite acceptance: SIGTERM a live `cli train` subprocess
    mid-training — the handler drains, writes a checksummed checkpoint,
    exits 0 — then --resume auto continues to completion with the episode
    counter monotone."""
    from click.testing import CliRunner

    from gsc_tpu.cli import cli as cli_group
    from gsc_tpu.utils.checkpoint import verify_checkpoint
    from tests.test_agent import write_tiny_configs

    args = write_tiny_configs(tmp_path)
    res = str(tmp_path / "res")
    env = dict(os.environ)
    env.update(JAX_PLATFORMS="cpu", PYTHONPATH=REPO,
               # share the repo compile cache so the subprocess's
               # episode_step compile is a disk hit, not a minute of XLA
               JAX_COMPILATION_CACHE_DIR=os.path.join(REPO, ".jax_cache"),
               JAX_PERSISTENT_CACHE_MIN_COMPILE_TIME_SECS="1",
               JAX_PERSISTENT_CACHE_MIN_ENTRY_SIZE_BYTES="-1")
    proc = subprocess.Popen(
        [sys.executable, "-m", "gsc_tpu.cli", "train", *args,
         "--episodes", "500", "--ckpt-interval", "50",
         "--result-dir", res],
        cwd=REPO, env=env, stdout=subprocess.PIPE,
        stderr=subprocess.PIPE, text=True)
    try:
        # wait until training demonstrably progresses (first episode
        # event drained), then preempt
        deadline = time.time() + 240
        events_path = None
        while time.time() < deadline:
            for root, _, files in os.walk(res):
                if "events.jsonl" in files:
                    p = os.path.join(root, "events.jsonl")
                    if any('"event": "episode"' in l for l in open(p)):
                        events_path = p
                        break
            if events_path or proc.poll() is not None:
                break
            time.sleep(0.25)
        assert proc.poll() is None, proc.communicate()
        assert events_path, "no episode event before deadline"
        proc.send_signal(signal.SIGTERM)
        out, err = proc.communicate(timeout=120)
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.communicate()
    assert proc.returncode == 0, (out, err)
    tail = json.loads(out.strip().splitlines()[-1])
    assert tail["status"] == "preempted" and tail["signal"] == "SIGTERM"
    done = tail["episodes_completed"]
    assert done >= 1
    assert verify_checkpoint(tail["checkpoint"]), tail
    # events stream of the killed run records the preemption recovery
    events = [json.loads(l) for l in open(events_path)]
    assert any(e["event"] == "recovery" and e["action"] ==
               "preempt_snapshot" for e in events)

    r = CliRunner().invoke(cli_group, ["train", *args,
                                       "--episodes", str(done + 2),
                                       "--resume", "auto",
                                       "--result-dir", res])
    assert r.exit_code == 0, (r.output, r.exception)
    out2 = json.loads(r.output.strip().splitlines()[-1])
    events2 = [json.loads(l) for l in
               open(os.path.join(out2["result_dir"], "events.jsonl"))]
    eps = [e["episode"] for e in events2 if e["event"] == "episode"]
    # monotone continuation: picks up exactly where the snapshot stopped
    assert eps == [done, done + 1]


# -------------------------------------------------------------- preemption
def test_preemption_guard_flag_and_trainer_stop():
    with PreemptionGuard() as g:
        assert not g.triggered
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.time() + 5
        while not g.triggered and time.time() < deadline:
            time.sleep(0.01)
        assert g.triggered and g.signame == "SIGTERM"
        env, agent, topo, traffic = make_stack()
        driver = make_driver(env, agent, topo, traffic)
        t = Trainer(env, driver, agent, seed=0)
        t.train(episodes=3, preempt=g)
        assert t.preempted and t.completed_episodes == 0
    # handlers restored on exit
    assert signal.getsignal(signal.SIGTERM) in (signal.SIG_DFL,
                                                signal.default_int_handler,
                                                signal.Handlers.SIG_DFL)


def test_prefetcher_interrupt_api():
    env, agent, topo, traffic = make_stack()
    driver = make_driver(env, agent, topo, traffic)
    from gsc_tpu.env.driver import PrefetchInterrupted

    pf = driver.prefetcher(0, 5, False)
    try:
        pf.get(0)
        pf.interrupt("test escalation")
        with pytest.raises(PrefetchInterrupted, match="test escalation"):
            pf.get(1)
    finally:
        pf.close()
