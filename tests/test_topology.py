"""Topology compiler tests.

Checks the padded dense compilation against hand-computable graphs and the
reference's weight/delay rules (coordsim/reader/reader.py:114-250).
"""
import numpy as np
import pytest

from gsc_tpu.topology import (INF_DELAY, compile_topology, edge_weight,
                              load_topology, stack_topologies, synthetic)


def test_edge_weight_rules():
    # reader.py:114-126
    assert edge_weight(0.0, 5.0) == float("inf")
    assert edge_weight(10.0, 0.0) == 0.0
    assert edge_weight(10.0, 2.0) == 1.0 / (10.0 + 0.5)


def test_triangle_compiles():
    topo = compile_topology(synthetic.triangle(), max_nodes=8, max_edges=8)
    assert int(topo.n_nodes) == 3 and int(topo.n_edges) == 3
    assert topo.node_mask.sum() == 3 and topo.edge_mask.sum() == 3
    # direct edges exist: path delay 1 between every pair
    pd = np.asarray(topo.path_delay)
    for i in range(3):
        assert pd[i, i] == 0
        for j in range(3):
            if i != j:
                assert pd[i, j] == 1.0
    # padded pairs unreachable
    assert pd[0, 5] == INF_DELAY
    assert int(topo.next_hop[0, 5]) == -1
    assert float(topo.diameter) == 1.0


def test_line_next_hop():
    topo = compile_topology(synthetic.line(4), max_nodes=8, max_edges=8)
    nh = np.asarray(topo.next_hop)
    assert nh[0, 3] == 1 and nh[1, 3] == 2 and nh[2, 3] == 3
    assert nh[3, 0] == 2
    assert float(np.asarray(topo.path_delay)[0, 3]) == 3.0
    assert float(topo.diameter) == 3.0


def test_adj_edge_id_undirected():
    topo = compile_topology(synthetic.two_node(), max_nodes=4, max_edges=4)
    adj = np.asarray(topo.adj_edge_id)
    assert adj[0, 1] == adj[1, 0] == 0
    assert adj[0, 0] == -1


def test_abilene_scale_parity():
    # Benchmark scenario scale: 11 nodes / 14 edges / 4 ingress
    # (reference: configs/networks/abilene/abilene-in4-rand-cap1-2.graphml).
    spec = synthetic.abilene()
    topo = compile_topology(spec)
    assert int(topo.n_nodes) == 11 and int(topo.n_edges) == 14
    assert int(topo.is_ingress.sum()) == 4
    # geo delays: NY-Chicago ~1140km -> ~3ms at 0.77c (reader.py:163-225)
    d = float(np.asarray(topo.edge_delay)[0])
    assert 2 <= d <= 5


def test_graphml_roundtrip(tmp_path):
    spec = synthetic.abilene()
    path = str(tmp_path / "abilene.graphml")
    synthetic.write_graphml(spec, path)
    topo = load_topology(path)
    ref = compile_topology(spec)
    np.testing.assert_allclose(np.asarray(topo.node_cap), np.asarray(ref.node_cap))
    np.testing.assert_allclose(np.asarray(topo.path_delay), np.asarray(ref.path_delay))
    assert int(topo.is_ingress.sum()) == 4


def test_stacking():
    t1 = compile_topology(synthetic.triangle(), max_nodes=8, max_edges=8)
    t2 = compile_topology(synthetic.line(3), max_nodes=8, max_edges=8)
    stacked = stack_topologies([t1, t2])
    assert stacked.node_cap.shape == (2, 8)
    assert stacked.next_hop.shape == (2, 8, 8)


def test_random_network_connected():
    spec = synthetic.random_network(32, seed=3)
    topo = compile_topology(spec, max_nodes=32, max_edges=64)
    pd = np.asarray(topo.path_delay)[:32, :32]
    assert (pd < INF_DELAY).all(), "random network must be connected"


def test_config_loading(tmp_path):
    from gsc_tpu.config import load_agent, load_service, load_sim

    (tmp_path / "svc.yaml").write_text(
        "sfc_list:\n  sfc_1: [a, b, c]\n"
        "sf_list:\n  a: {processing_delay_mean: 5.0, processing_delay_stdev: 0.0}\n"
        "  b: {processing_delay_mean: 5.0, processing_delay_stdev: 0.0}\n"
        "  c: {processing_delay_mean: 5.0, processing_delay_stdev: 0.0}\n")
    svc = load_service(str(tmp_path / "svc.yaml"))
    assert svc.num_sfcs == 1 and svc.max_chain_len == 3
    assert svc.sf_list["a"].processing_delay_mean == 5.0
    assert svc.sf_list["a"].startup_delay == 0.0  # default (reader.py:84)

    (tmp_path / "sim.yaml").write_text(
        "inter_arrival_mean: 10.0\ndeterministic: True\nflow_dr_mean: 1.0\n"
        "flow_dr_stdev: 0.0\nflow_size_shape: 0.001\nrun_duration: 100\n"
        "ttl_choices: [100]\n")
    sim = load_sim(str(tmp_path / "sim.yaml"))
    assert sim.deterministic_arrival and sim.deterministic_size
    assert sim.substeps_per_run == 100

    (tmp_path / "agent.yaml").write_text(
        "graph_mode: True\nepisode_steps: 200\nGNN_features: 22\n"
        "objective: weighted\nflow_weight: 1\n")
    ag = load_agent(str(tmp_path / "agent.yaml"))
    assert ag.gnn_features == 22 and ag.objective == "weighted"

    with pytest.raises(ValueError):
        load_agent(str(tmp_path / "agent.yaml"), objective="nope")


def test_zoo_network_shapes():
    """Claranet/Compuserve (Topology Zoo) match the reference's scenario
    shapes (Claranet-in4-cap1: 15n/18e, Compuserve-in4-cap1: 14n/17e) and
    round-trip through GraphML."""
    import numpy as np

    for spec_fn, n, e in ((synthetic.claranet, 15, 18),
                          (synthetic.compuserve, 14, 17)):
        topo = compile_topology(spec_fn(), max_nodes=24, max_edges=37)
        assert int(np.asarray(topo.node_mask).sum()) == n
        assert int(np.asarray(topo.edge_mask).sum()) == e
        assert int(np.asarray(topo.is_ingress).sum()) == 4
        pd = np.asarray(topo.path_delay)[:n, :n]
        assert np.isfinite(pd).all()


def test_large_zoo_network_shapes():
    """Tinet/Chinanet/Interoute (Topology Zoo) match the reference's
    larger scenario shapes (tinet: 53n/89e, chinanet: 42n/66e,
    interroute: 110n/146 deduped simple edges) with first-N ingress,
    integer caps in {0,1,2}, connected path matrices, and geodesic link
    delays where both endpoints carry coordinates."""
    cases = ((synthetic.tinet, 53, 89, 2, 64, 128),
             (synthetic.chinanet, 42, 66, 2, 64, 128),
             (synthetic.interroute, 110, 146, 4, 128, 192))
    for spec_fn, n, e, ing, max_n, max_e in cases:
        spec = spec_fn()
        assert len(spec.node_caps) == n and len(spec.edges) == e
        assert all(c in (0.0, 1.0, 2.0) for c in spec.node_caps)
        topo = compile_topology(spec, max_nodes=max_n, max_edges=max_e)
        assert int(np.asarray(topo.node_mask).sum()) == n
        assert int(np.asarray(topo.edge_mask).sum()) == e
        assert int(np.asarray(topo.is_ingress).sum()) == ing
        pd = np.asarray(topo.path_delay)[:n, :n]
        assert np.isfinite(pd).all()  # connected
        # geodesic delays: some real spread, none absurd (< 150 ms); short
        # links legitimately round to 0 ms (reader.py:223-225 int rounding)
        delays = [d for (_, _, _, d) in spec.edges]
        assert min(delays) >= 0 and max(delays) < 150.0
        assert len({round(d, 3) for d in delays}) >= 4


def test_dt_quantization_warning():
    """Fractional edge delays at dt=1 warn with a dt suggestion; integer
    delays stay silent (the BT-Europe divergence guard — the fixed-step
    engine quantizes hop timers, tests/test_reference_parity.py)."""
    import pytest

    from gsc_tpu.topology.compiler import NetworkSpec, check_dt_quantization

    frac = compile_topology(NetworkSpec(
        node_caps=[1.0, 1.0], node_types=["Ingress", "Normal"],
        edges=[(0, 1, 10.0, 5.75)]), max_nodes=4, max_edges=4)
    with pytest.warns(UserWarning, match="not integer multiples of dt=1"):
        assert check_dt_quantization(frac, 1.0, name="bt-like")
    # the suggestion names a dt that actually divides the delays
    with pytest.warns(UserWarning, match="dt=0.25"):
        check_dt_quantization(frac, 1.0)

    whole = compile_topology(NetworkSpec(
        node_caps=[1.0, 1.0], node_types=["Ingress", "Normal"],
        edges=[(0, 1, 10.0, 3.0)]), max_nodes=4, max_edges=4)
    import warnings

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert not check_dt_quantization(whole, 1.0)
