"""Serving-observability tests (gsc_tpu.obs.slo + the batcher/server
tracing hooks): SLO-engine arithmetic against hand-computed cases,
span-decomposition identities, rejection/queue-depth visibility, the
live /metrics endpoint under concurrent submit load, trace-validator
acceptance of the serve-request track, bench_diff slo-band verdicts in
both directions, and the tracing-off bit-parity + no-host-sync
contracts on the flush path.

Most tests drive a raw :class:`MicroBatcher` (or a stub-policy
:class:`PolicyServer`) with a numpy backend — no jax compile anywhere —
so the whole group is tier-1 fast."""
import json
import os
import sys
import threading
import time
import urllib.request

import numpy as np
import pytest

from gsc_tpu.obs import (ListSink, MetricsEndpoint, MetricsHub, ServeTracer,
                         SLOEngine, SLOObjectives, parse_slo_spec)
from gsc_tpu.obs.trace import TRACE_TRACKS, build_trace, validate_trace
from gsc_tpu.serve import (MicroBatcher, ObsTemplate, PolicyServer,
                           ServeError)

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

pytestmark = pytest.mark.serve_obs

ANSWER = np.arange(2, dtype=np.float32)


class StubPolicy:
    """Duck-typed fallback tier: a fixed numpy answer per request — the
    full batcher/tracer/SLO path with zero jax involvement."""

    def __init__(self, leaf_dim=3):
        self.template = ObsTemplate(np.zeros(leaf_dim, np.float32))

    def run_batch(self, leaves, n_real, bucket):
        return np.tile(ANSWER[None, :], (bucket, 1))


def _obs():
    return np.zeros(3, np.float32)


def _traced_batcher(hub, sample=1, buckets=(1, 4), deadline_ms=5.0,
                    slo="10", run_batch=None, **kw):
    tracer = ServeTracer(hub=hub, sample=sample)
    tracer.bind_engine(SLOEngine(deadline_ms=deadline_ms,
                                 objectives=parse_slo_spec(slo), hub=hub))
    tracer.start()
    mb = MicroBatcher(run_batch or StubPolicy().run_batch,
                      ObsTemplate(_obs()), buckets=buckets,
                      deadline_ms=deadline_ms, hub=hub, tracer=tracer,
                      **kw).start()
    return mb, tracer


# ------------------------------------------------------------- SLO engine
def test_slo_engine_hand_computed_attainment_and_burn():
    """10 requests against a 10 ms objective at target 0.99: 8 hits + 2
    violations -> attainment 0.8, burn (1-0.8)/(1-0.99) = 20x; deadline
    5 ms -> 2 misses -> miss ratio 0.2."""
    eng = SLOEngine(deadline_ms=5.0, objectives=parse_slo_spec("10"))
    for lat in [4.0] * 8 + [20.0] * 2:
        eng.record_request(lat, bucket=1)
    snap = eng.snapshot()
    assert snap["attainment"] == 0.8
    assert abs(snap["burn_rate"] - 20.0) < 1e-9
    assert snap["deadline_miss_ratio"] == 0.2
    assert snap["deadline_misses"] == 2 and snap["requests"] == 10


def test_slo_engine_per_bucket_objective_overrides_overall():
    """Spec "10,4:50": a 30 ms request in bucket 4 meets ITS objective
    (50) while the same latency in bucket 1 violates the overall 10."""
    eng = SLOEngine(deadline_ms=100.0, objectives=parse_slo_spec("10,4:50"),
                    hub=None)
    eng.record_request(30.0, bucket=4)
    eng.record_request(30.0, bucket=1)
    snap = eng.snapshot()
    assert snap["attainment"] == 0.5
    assert snap["per_bucket"]["4"]["attainment"] == 1.0
    assert snap["per_bucket"]["4"]["objective_ms"] == 50.0
    assert snap["per_bucket"]["1"]["attainment"] == 0.0
    assert snap["per_bucket"]["1"]["objective_ms"] == 10.0
    # deadline generous: no misses either way
    assert snap["deadline_miss_ratio"] == 0.0


def test_slo_engine_no_objective_tracks_misses_but_not_attainment():
    eng = SLOEngine(deadline_ms=5.0)     # objectives off (the default)
    eng.record_request(20.0, bucket=1)
    snap = eng.snapshot()
    assert snap["attainment"] is None and snap["burn_rate"] is None
    assert snap["deadline_miss_ratio"] == 1.0


def test_slo_engine_pad_waste_and_arrival_rate():
    eng = SLOEngine(deadline_ms=5.0)
    eng.record_flush(n_real=1, bucket=4)     # 0.75 wasted
    eng.record_flush(n_real=4, bucket=4)     # 0.0 wasted
    # 10 ms inter-arrival gaps -> EWMA converges onto 100 rps exactly
    for i in range(50):
        eng.note_arrival(100.0 + 0.01 * i)
    snap = eng.snapshot()
    assert snap["pad_waste"] == 0.375
    assert snap["per_bucket"]["4"]["pad_waste"] == 0.375
    assert abs(snap["arrival_rate_rps"] - 100.0) < 1.0


def test_parse_slo_spec_grammar():
    obj = parse_slo_spec("25")
    assert obj.p99_ms == 25.0 and not obj.per_bucket
    obj = parse_slo_spec("25,4:40,8:60")
    assert obj.p99_ms == 25.0
    assert obj.per_bucket == {4: 40.0, 8: 60.0}
    assert obj.objective_for(8) == 60.0 and obj.objective_for(2) == 25.0
    assert parse_slo_spec("4:40").p99_ms is None
    for bad in ("", "abc", "25,30", "4:", "0", "4:-1", "4:40,4:50"):
        with pytest.raises(ValueError):
            parse_slo_spec(bad)


# --------------------------------------------------------- span decomposition
def test_span_decomposition_sums_to_recorded_latency():
    """queue-wait + batch-wait + device == the serve_latency_ms the
    batcher recorded for the same request (shared timestamps, so the
    identity is exact up to float addition); every component and the
    fan-out tail are non-negative."""
    hub = MetricsHub(tags={"run": "spans"})
    sink = ListSink()
    hub.add_sink(sink)
    mb, tracer = _traced_batcher(hub, sample=1, deadline_ms=20.0)
    futs = [mb.submit(_obs()) for _ in range(4)]
    for f in futs:
        np.testing.assert_array_equal(f.result(30), ANSWER)
    mb.submit(_obs()).result(30)      # lone request: deadline flush
    mb.stop()
    tracer.stop()
    spans = sink.of_kind("serve_request_span")
    assert len(spans) == 5            # sample=1 -> every request
    for s in spans:
        assert s["queue_wait_ms"] >= 0 and s["batch_wait_ms"] >= 0
        assert s["device_ms"] >= 0 and s["fanout_ms"] >= 0
        total = s["queue_wait_ms"] + s["batch_wait_ms"] + s["device_ms"]
        assert abs(total - s["latency_ms"]) < 1e-2, s
    lat = hub.histogram_summary("serve_latency_ms")
    assert lat["count"] == 5
    # the recorded end-to-end histogram and the span latencies agree
    assert abs(max(s["latency_ms"] for s in spans) - lat["max"]) < 1e-2
    # decomposition histograms landed per bucket too
    assert hub.histogram_summary("serve_queue_wait_ms", bucket=4)["count"] \
        == 4
    flushes = sink.of_kind("serve_flush")
    assert len(flushes) == 2
    by_bucket = {f["bucket"]: f for f in flushes}
    assert by_bucket[4]["n_real"] == 4 and by_bucket[4]["pad_fraction"] == 0
    assert by_bucket[1]["n_real"] == 1
    # span events reference the flush that answered them
    assert {s["flush_id"] for s in spans} == \
        {f["flush_id"] for f in flushes}


def test_head_sampling_records_every_nth_request():
    hub = MetricsHub()
    sink = ListSink()
    hub.add_sink(sink)
    mb, tracer = _traced_batcher(hub, sample=3, buckets=(1,),
                                 deadline_ms=0.5)
    for _ in range(9):
        mb.submit(_obs()).result(30)
    mb.stop()
    tracer.stop()
    spans = sink.of_kind("serve_request_span")
    assert [s["trace_id"] for s in spans] == [0, 3, 6]
    # flush-level spans are ALWAYS recorded, sampling or not
    assert len(sink.of_kind("serve_flush")) == 9


# ------------------------------------------------- rejections + queue depth
def test_rejections_are_counted_before_the_error_reaches_the_caller():
    hub = MetricsHub()
    t = ObsTemplate(_obs())
    stub = StubPolicy()
    tracer = ServeTracer(hub=hub, sample=0)
    engine = SLOEngine(deadline_ms=5.0, hub=hub)
    tracer.bind_engine(engine)
    mb = MicroBatcher(stub.run_batch, t, buckets=(1,), max_queue=1,
                      hub=hub, tracer=tracer)    # consumer NOT started
    mb.submit(_obs())
    with pytest.raises(ServeError, match="queue full"):
        mb.submit(_obs())
    assert hub.get_counter("serve_rejected_total", reason="queue_full") == 1
    mb._stopping = True
    with pytest.raises(ServeError, match="stopping"):
        mb.submit(_obs())
    assert hub.get_counter("serve_rejected_total", reason="stopping") == 1
    tracer.drain_pending()
    assert engine.snapshot()["rejected"] == {"queue_full": 1,
                                             "stopping": 1}


def test_queue_depth_sampled_on_submit_not_only_at_flush():
    """The gauge used to be written only inside _flush, so it read stale
    between flushes and while idle; submit now samples it too."""
    hub = MetricsHub()
    mb = MicroBatcher(StubPolicy().run_batch, ObsTemplate(_obs()),
                      buckets=(8,), hub=hub)     # consumer NOT started
    assert hub.get_gauge("serve_queue_depth") is None
    mb.submit(_obs())
    assert hub.get_gauge("serve_queue_depth") == 1.0
    mb.submit(_obs())
    assert hub.get_gauge("serve_queue_depth") == 2.0


def test_live_queue_depth_probe_in_snapshot():
    """PolicyServer registers a live probe: a hub snapshot taken at any
    point reads the CURRENT depth, and drop_live_gauge retires it."""
    hub = MetricsHub()
    srv = PolicyServer(fallback=StubPolicy(), buckets=(1,),
                       deadline_ms=1.0, hub=hub).start()
    try:
        assert hub.snapshot().get("gsc_serve_queue_depth") == 0.0
    finally:
        srv.close()
    # after close the probe is dropped and the final static gauge holds
    assert hub.snapshot().get("gsc_serve_queue_depth") == 0.0
    assert ("serve_queue_depth", ()) not in hub._live_gauges


# -------------------------------------------- endpoint under live serving
def test_metrics_endpoint_under_concurrent_submit_load():
    """Concurrent submitters + /metrics scrapes mid-run: every scrape
    parses, SLO gauges + rejection counters appear once drained, and an
    idle-state scrape equals the hub snapshot exactly."""
    hub = MetricsHub(tags={"run": "live"})
    sink = ListSink()
    hub.add_sink(sink)

    class SlowStub(StubPolicy):
        def run_batch(self, leaves, n_real, bucket):
            time.sleep(0.002)     # lets the mid-run scrape see a queue
            return super().run_batch(leaves, n_real, bucket)

    tracer = ServeTracer(hub=hub, sample=0, drain_interval_s=0.01)
    srv = PolicyServer(fallback=SlowStub(), buckets=(1, 4),
                       deadline_ms=1.0, hub=hub, tracer=tracer,
                       slo=parse_slo_spec("5"), max_queue=4096).start()
    ep = MetricsEndpoint(hub, port=0).start()
    errors = []

    def client(n):
        for _ in range(n):
            try:
                np.testing.assert_array_equal(
                    srv.submit(_obs()).result(30), ANSWER)
            except Exception as e:  # noqa: BLE001 - surfaced below
                errors.append(repr(e))

    threads = [threading.Thread(target=client, args=(10,), daemon=True)
               for _ in range(4)]
    for t in threads:
        t.start()
    mid = urllib.request.urlopen(ep.url, timeout=10).read().decode()
    for line in mid.strip().splitlines():    # every line parses
        name, value = line.rsplit(" ", 1)
        float(value)
    for t in threads:
        t.join()
    # force one rejection so the counter is scrapeable
    srv.batcher._stopping = True
    with pytest.raises(ServeError):
        srv.submit(_obs())
    srv.batcher._stopping = False
    tracer.drain_pending()
    body = urllib.request.urlopen(ep.url, timeout=10).read().decode()
    parsed = {}
    for line in body.strip().splitlines():
        name, value = line.rsplit(" ", 1)
        parsed[name] = float(value)
    assert not errors, errors
    assert parsed['gsc_slo_deadline_miss_ratio{run="live"}'] >= 0.0
    assert 'gsc_slo_attainment{run="live"}' in parsed
    assert 'gsc_slo_burn_rate{run="live"}' in parsed
    assert parsed['gsc_serve_rejected_total{reason="stopping",run="live"}'] \
        == 1.0
    assert parsed['gsc_serve_requests_total{run="live"}'] == 40.0
    # idle-state parity: scrape == snapshot, series for series
    snap = {k: float(v) for k, v in hub.snapshot().items()}
    rescrape = {}
    for line in urllib.request.urlopen(
            ep.url, timeout=10).read().decode().strip().splitlines():
        name, value = line.rsplit(" ", 1)
        rescrape[name] = float(value)
    assert rescrape == snap
    ep.stop()
    srv.close()


# ----------------------------------------------------- trace-track contract
def test_trace_validator_accepts_serve_request_track_with_flows():
    events = [
        {"event": "run_start", "ts": 100.0, "run": "t"},
        {"event": "serve_flush", "ts": 100.010, "flush_id": 0,
         "bucket": 4, "n_real": 3, "pad_fraction": 0.25,
         "device_ms": 1.5, "queue_depth": 0},
        {"event": "serve_request_span", "ts": 100.004, "trace_id": 7,
         "flush_id": 0, "bucket": 4, "queue_wait_ms": 1.0,
         "batch_wait_ms": 5.0, "device_ms": 1.5, "fanout_ms": 0.1,
         "latency_ms": 7.5, "deadline_miss": True},
    ]
    trace = build_trace(events)
    assert validate_trace(trace) == []
    evs = trace["traceEvents"]
    req = [e for e in evs if e.get("ph") == "X"
           and e["tid"] == TRACE_TRACKS["serve_request"]]
    fl = [e for e in evs if e.get("ph") == "X"
          and e["tid"] == TRACE_TRACKS["serve"]]
    assert len(req) == 1 and len(fl) == 1
    assert req[0]["args"]["queue_wait_ms"] == 1.0
    assert req[0]["dur"] == 7600.0      # (latency + fanout) in us
    assert fl[0]["dur"] == 1500.0
    starts = [e for e in evs if e.get("ph") == "s"]
    ends = [e for e in evs if e.get("ph") == "f"]
    assert len(starts) == 1 and len(ends) == 1
    assert starts[0]["id"] == ends[0]["id"]
    # the arrow lands on the flush's dispatch timestamp
    assert ends[0]["ts"] == fl[0]["ts"]


def test_trace_span_without_matching_flush_emits_no_dangling_flow():
    events = [
        {"event": "run_start", "ts": 100.0, "run": "t"},
        {"event": "serve_request_span", "ts": 100.004, "trace_id": 7,
         "flush_id": 42, "bucket": 4, "queue_wait_ms": 1.0,
         "batch_wait_ms": 5.0, "device_ms": 1.5, "fanout_ms": 0.1,
         "latency_ms": 7.5},
    ]
    trace = build_trace(events)
    assert validate_trace(trace) == []
    assert not [e for e in trace["traceEvents"]
                if e.get("ph") in ("s", "f")]


def test_real_stream_exports_valid_trace(tmp_path):
    """A real batcher run's event stream (through a JSONL sink on disk)
    builds a validator-clean trace with flow-linked request spans."""
    from gsc_tpu.obs import JsonlSink
    from gsc_tpu.obs.trace import read_events

    hub = MetricsHub(tags={"run": "e2e"})
    hub.add_sink(JsonlSink(str(tmp_path / "events.jsonl")))
    hub.event("run_start", mode="serve")
    mb, tracer = _traced_batcher(hub, sample=1, deadline_ms=2.0)
    for _ in range(6):
        mb.submit(_obs()).result(30)
    mb.stop()
    tracer.stop()
    hub.event("run_end", status="ok")
    trace = build_trace(read_events(str(tmp_path)))
    assert validate_trace(trace) == []
    req = [e for e in trace["traceEvents"] if e.get("ph") == "X"
           and e["tid"] == TRACE_TRACKS["serve_request"]]
    assert len(req) == 6
    assert [e for e in trace["traceEvents"] if e.get("ph") == "s"]


# ------------------------------------------------------- bench_diff bands
def test_bench_diff_slo_bands_both_directions(tmp_path):
    import bench_diff

    base = {"name": "slo_base", "status": "ok", "kind": "slo",
            "metrics": {"slo_deadline_miss_ratio": 0.05,
                        "slo_pad_waste": 0.2, "slo_queue_wait_frac": 0.3,
                        "slo_burn_rate": 1.0, "slo_attainment": 0.99}}
    worse = {"name": "slo_worse", "status": "ok", "kind": "slo",
             "metrics": {"slo_deadline_miss_ratio": 0.4,
                         "slo_pad_waste": 0.6, "slo_queue_wait_frac": 0.7,
                         "slo_burn_rate": 4.0, "slo_attainment": 0.5}}
    d = bench_diff.diff_rows(worse, base)
    assert d["verdict"] == "regression"
    assert set(d["regressions"]) == {
        "slo_deadline_miss_ratio", "slo_pad_waste", "slo_queue_wait_frac",
        "slo_burn_rate", "slo_attainment"}
    d = bench_diff.diff_rows(base, worse)
    assert d["verdict"] == "ok" and not d["regressions"]
    # absolute floors: near-zero jitter is noise, not a regression
    d = bench_diff.diff_rows(
        {"name": "a", "metrics": {"slo_deadline_miss_ratio": 0.015}},
        {"name": "b", "metrics": {"slo_deadline_miss_ratio": 0.0}})
    assert d["verdict"] == "ok"
    # a real slo.json document ingests as a keyed slo_ row
    doc = {"schema_version": 1, "run": "runx", "tier": "spr",
           "deadline_ms": 5.0, "requests": 10,
           "deadline_miss_ratio": 0.1, "pad_waste": 0.25,
           "queue_wait_frac": 0.4, "burn_rate": 2.0, "attainment": 0.98,
           "arrival_rate_rps": 500.0,
           "p50_latency_ms": 1.0, "p99_latency_ms": 4.0}
    p = tmp_path / "slo.json"
    p.write_text(json.dumps(doc))
    row = bench_diff.extract_row(str(p))
    assert row["name"] == "slo_runx" and row["kind"] == "slo"
    assert row["metrics"]["slo_burn_rate"] == 2.0
    assert row["metrics"]["p99_ms"] == 4.0
    # arrival rate must NOT become a gated `_rps` metric
    assert not any("arrival" in m for m in row["metrics"])


# --------------------------------------------- off-switch + sync contracts
def test_answers_and_latency_bit_identical_with_tracing_off():
    """tracer=None is the historic path: same answers, same latency
    series shape, and zero span events/SLO artifacts."""
    sink_on, sink_off = ListSink(), ListSink()
    hub_on = MetricsHub()
    hub_on.add_sink(sink_on)
    hub_off = MetricsHub()
    hub_off.add_sink(sink_off)
    mb_on, tracer = _traced_batcher(hub_on, sample=1)
    mb_off = MicroBatcher(StubPolicy().run_batch, ObsTemplate(_obs()),
                          buckets=(1, 4), deadline_ms=5.0,
                          hub=hub_off).start()
    outs_on = [mb_on.submit(_obs()).result(30) for _ in range(3)]
    outs_off = [mb_off.submit(_obs()).result(30) for _ in range(3)]
    mb_on.stop()
    tracer.stop()
    mb_off.stop()
    for a, b in zip(outs_on, outs_off):
        np.testing.assert_array_equal(a, b)
    assert hub_on.histogram_summary("serve_latency_ms")["count"] == \
        hub_off.histogram_summary("serve_latency_ms")["count"] == 3
    assert sink_on.of_kind("serve_request_span")
    assert not sink_off.of_kind("serve_request_span")
    assert not sink_off.of_kind("serve_flush")
    # tracing off also means no decomposition histograms
    assert hub_off.histogram_summary("serve_queue_wait_ms") is None


def test_flush_path_and_span_drain_add_no_host_syncs():
    """The whole serve interaction — submit, flush, span drain, SLO
    update, event emission — under the host-sync tripwire: the backend
    is pure numpy, so any device->host sync would come from the new
    tracing/SLO code and raise."""
    from gsc_tpu.analysis.sentinels import no_host_sync

    hub = MetricsHub(tags={"run": "sync"})
    sink = ListSink()
    hub.add_sink(sink)
    with no_host_sync("serve flush path with tracing ON"):
        mb, tracer = _traced_batcher(hub, sample=1, deadline_ms=2.0)
        for _ in range(5):
            mb.submit(_obs()).result(30)
        mb.stop()
        tracer.stop()
    assert sink.of_kind("serve_request_span")
    assert tracer.engine.snapshot()["requests"] == 5


def test_slo_json_written_at_server_close(tmp_path):
    slo_path = str(tmp_path / "slo.json")
    hub = MetricsHub(tags={"run": "closer"})
    tracer = ServeTracer(hub=hub, sample=0)
    srv = PolicyServer(fallback=StubPolicy(), buckets=(1, 2),
                       deadline_ms=1.0, hub=hub, tracer=tracer,
                       slo=SLOObjectives(p99_ms=10.0),
                       slo_path=slo_path).start()
    for _ in range(4):
        srv.submit_sync(_obs(), timeout=30)
    srv.close()
    doc = json.load(open(slo_path))
    assert doc["schema_version"] == 1 and doc["tier"] == "spr"
    assert doc["requests"] == 4 and doc["run"] == "closer"
    assert doc["objectives"]["p99_ms"] == 10.0
    assert doc["deadline_miss_ratio"] is not None
    assert doc["attainment"] is not None and doc["burn_rate"] is not None
    assert doc["pad_waste"] is not None
    assert doc["decomposition_ms"], doc
    # the summary the CLI prints matches the document's core fields
    s = srv.slo_summary()
    assert s["deadline_miss_ratio"] == doc["deadline_miss_ratio"]
    assert s["p99_target_ms"] == 10.0


def test_serve_stats_carries_slo_decomposition_and_report_renders(tmp_path):
    """serve_stats -> events.jsonl -> obs_report: the serving section
    surfaces the SLO snapshot, decomposition table and rejections."""
    from obs_report import load_events, summarize

    from gsc_tpu.obs import RunObserver

    rec = RunObserver(str(tmp_path / "run"))
    rec.start(meta={"mode": "serve", "tier": "spr"})
    tracer = ServeTracer(hub=rec.hub, sample=2)
    srv = PolicyServer(fallback=StubPolicy(), buckets=(1, 2),
                       deadline_ms=1.0, hub=rec.hub, tracer=tracer,
                       slo=parse_slo_spec("50"),
                       slo_path=rec.slo_path).start()
    for _ in range(4):
        srv.submit_sync(_obs(), timeout=30)
    # one visible rejection
    srv.batcher._stopping = True
    with pytest.raises(ServeError):
        srv.submit(_obs())
    srv.batcher._stopping = False
    srv.close()
    rec.close(status="ok")
    sv = summarize(load_events(str(tmp_path / "run")))["serving"]
    assert sv["slo"] is not None
    assert sv["slo"]["p99_target_ms"] == 50.0
    assert sv["slo"]["attainment"] is not None
    assert sv["rejected"].get("stopping") == 1
    assert sv["decomposition"], sv
    first = next(iter(sv["decomposition"].values()))
    assert {"queue_ms", "batch_ms", "device_ms"} <= set(first)
    assert os.path.exists(rec.slo_path)


def test_failed_device_calls_burn_the_slo_budget():
    """A run_batch error must degrade attainment / miss ratio, not leave
    the SLO engine reporting perfect health while clients see errors."""
    hub = MetricsHub()
    sink = ListSink()
    hub.add_sink(sink)
    calls = {"n": 0}

    def flaky(leaves, k, bucket):
        calls["n"] += 1
        if calls["n"] % 2 == 0:
            raise RuntimeError("injected device fault")
        return np.tile(ANSWER[None, :], (bucket, 1))

    mb, tracer = _traced_batcher(hub, sample=1, buckets=(1,),
                                 deadline_ms=1000.0, slo="1000",
                                 run_batch=flaky)
    ok = err = 0
    for _ in range(6):
        try:
            mb.submit(_obs()).result(30)
            ok += 1
        except ServeError:
            err += 1
    mb.stop()
    tracer.stop()
    assert ok == 3 and err == 3
    snap = tracer.engine.snapshot()
    # EVERY request is accounted: 3 answered + 3 errored
    assert snap["requests"] == 6 and snap["errored_requests"] == 3
    assert snap["deadline_misses"] == 3      # errored = missed
    assert snap["deadline_miss_ratio"] == 0.5
    assert snap["attainment"] == 0.5         # inf latency fails the 1000
    assert snap["burn_rate"] > 0
    # failed flushes still land as serve_flush slices, carrying the error
    failed = [f for f in sink.of_kind("serve_flush") if f.get("error")]
    assert len(failed) == 3
    assert "injected device fault" in failed[0]["error"]
    # but no request span pretends those requests completed
    assert len(sink.of_kind("serve_request_span")) == 3


def test_flows_never_cross_appended_runs():
    """Two runs in one stream each restart flush ids at 0: a run-1 span
    must not arrow into run-2's flush slice (and with run-1's flush
    absent, no dangling flow at all)."""
    events = [
        {"event": "run_start", "ts": 100.0, "run": "r"},
        # run 1: sampled span whose flush event was lost (rotation, torn
        # tail) — flush_id 0 exists only in run 2
        {"event": "serve_request_span", "ts": 100.001, "trace_id": 1,
         "flush_id": 0, "bucket": 1, "queue_wait_ms": 0.1,
         "batch_wait_ms": 0.1, "device_ms": 0.1, "fanout_ms": 0.0,
         "latency_ms": 0.3},
        {"event": "run_start", "ts": 200.0, "run": "r"},
        {"event": "serve_flush", "ts": 200.005, "flush_id": 0,
         "bucket": 1, "n_real": 1, "pad_fraction": 0.0,
         "device_ms": 0.1},
        {"event": "serve_request_span", "ts": 200.001, "trace_id": 1,
         "flush_id": 0, "bucket": 1, "queue_wait_ms": 0.1,
         "batch_wait_ms": 0.1, "device_ms": 0.1, "fanout_ms": 0.0,
         "latency_ms": 0.3},
    ]
    trace = build_trace(events)
    assert validate_trace(trace) == []
    starts = [e for e in trace["traceEvents"] if e.get("ph") == "s"]
    # exactly ONE flow: run 2's span -> run 2's flush
    assert len(starts) == 1
    assert starts[0]["ts"] >= 100000.0   # run 2 territory (ts_us)


def test_tracer_overflow_drops_oldest_and_counts():
    hub = MetricsHub()
    tracer = ServeTracer(hub=hub, sample=0, max_pending=2)
    for i in range(5):
        tracer.note_rejection("queue_full", float(i))
    assert tracer.spans_dropped == 3
    tracer.drain_pending()
    assert hub.get_counter("serve_spans_dropped_total") == 3
