"""Golden parity vs the ACTUAL reference simulator.

The frozen numbers below were produced by ``tools/run_reference.py`` — the
UNMODIFIED reference coordsim (SimPy process model) running under the
``tools/minisimpy`` shim — via::

    python tools/run_reference.py --mode interface --network <net> \
        --steps 50 --seed 1234

with the reference's own sample_config.yaml (deterministic arrivals every
10 ms per ingress, deterministic size, run_duration 100 ms, TTL 100) and
abc.yaml (3 x 5 ms SFs), driving the same uniform place-everywhere /
uniform-schedule action our ``cli simulate`` uses.

The jax engine must reproduce them within its documented fixed-step
quantization bounds (gsc_tpu/sim/engine.py divergence notes):
- generated flows: exact (deterministic arrival streams)
- processed/dropped: within +-2 flows of the oracle (in-flight flows at
  the horizon land on different sides of the boundary under 1 ms substeps)
- drop-reason split: exact
- avg e2e delay: within 2.5% relative (measured divergence: ~0.0% on
  triangle, ~1.8% on Abilene)

When the reference tree is present, ``test_oracle_numbers_are_current``
re-runs the oracle live and checks the frozen constants themselves, so the
oracle can't silently rot.
"""
import json
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

REFERENCE = os.environ.get("GSC_REFERENCE_DIR", "/root/reference")
REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_TOOLS = os.path.join(REPO, "tools")
if _TOOLS not in sys.path:
    sys.path.insert(0, _TOOLS)
from reward_curve import no_tpu_env  # noqa: E402  (single env-sanitizer)

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE),
    reason="reference tree not available")

SERVICE = "configs/service_functions/abc.yaml"
CONFIG = "configs/config/simulator/sample_config.yaml"

# frozen oracle outputs (reference coordsim, seed 1234, 50 control steps)
ORACLE = {
    "triangle": {
        "network": "configs/networks/triangle/"
                   "triangle-in2-cap10-delay10.graphml",
        "generated": 1000, "processed": 995, "dropped": 0,
        "drop_reasons": {"TTL": 0, "DECISION": 0, "LINK_CAP": 0,
                         "NODE_CAP": 0},
        "avg_e2e": 34.48743718592965,
    },
    "abilene": {
        "network": "configs/networks/abilene/"
                   "abilene-in4-rand-cap1-2.graphml",
        "generated": 2000, "processed": 599, "dropped": 1395,
        "drop_reasons": {"TTL": 0, "DECISION": 0, "LINK_CAP": 0,
                         "NODE_CAP": 1395},
        "avg_e2e": 38.51419031719533,
    },
    # BT-Europe cap1: heavily contended (node cap 1) with FRACTIONAL geo
    # link delays.  At dt=1 the quantization reorders same-substep
    # contenders (398 vs 349 processed); at dt=0.25 — which resolves the
    # fractional event times — the engine reproduces the reference
    # EXACTLY (flow counts equal, avg e2e to 7 significant digits),
    # demonstrating the divergence is pure time quantization, not
    # semantics.
    "bteurope": {
        "network": "configs/networks/BtEurope-in2-cap1.graphml",
        "generated": 1000, "processed": 349, "dropped": 649,
        "drop_reasons": {"TTL": 0, "DECISION": 0, "LINK_CAP": 0,
                         "NODE_CAP": 649},
        "avg_e2e": 22.570200573065904,
        "overrides": {"dt": 0.25, "release_horizon": 1024},
        "exact": True,
    },
    # tinet: the reference's 53-node mid-size real network (rand-cap0-2:
    # integer caps {0,1,2}, so heavy NODE_CAP contention), fractional geo
    # delays -> dt=0.25 like bteurope.  Extends the exact-parity evidence
    # beyond the 24-node padding limit.
    "tinet": {
        "network": "configs/networks/tinet/tinet-in2-rand-cap0-2.graphml",
        "generated": 1000, "processed": 48, "dropped": 946,
        "drop_reasons": {"TTL": 0, "DECISION": 0, "LINK_CAP": 0,
                         "NODE_CAP": 946},
        "avg_e2e": 66.0,
        "overrides": {"dt": 0.25, "release_horizon": 1024},
        "limits": (64, 96),
        "exact": True,
    },
    # line3-linkcap2 (repo asset, absolute paths): LinkFwdCap=2 line with
    # huge node caps, fast arrivals, 20 ms flow durations — the only
    # oracle whose drops are LINK_CAP, pinning the link-admission
    # comparison ordering (engine.py stage 5: prefix <= cap-used headroom
    # vs the reference's used+prefix <= cap; ADVICE r3 flagged that no
    # oracle would catch an admission flip at exact capacity ties).
    "linkcap": {
        "network": os.path.join(REPO, "tests", "assets",
                                "line3-linkcap2.graphml"),
        "config": os.path.join(REPO, "tests", "assets",
                               "linkcap_config.yaml"),
        "generated": 2500, "processed": 151, "dropped": 2348,
        "drop_reasons": {"TTL": 0, "DECISION": 0, "LINK_CAP": 2348,
                         "NODE_CAP": 0},
        "avg_e2e": 22.94701986754967,
        # saturated links make nearly every substep a same-timestamp
        # admission tie, resolved slot-order here vs SimPy-FIFO there
        # (documented divergence, engine.py module docstring) — counts
        # drift ~1% (engine: 169/2329) but a broken admission comparison
        # (e.g. off-by-one-flow headroom) would shift them by >10x this
        # tolerance, and every drop must still be LINK_CAP.
        "atol_flows": 30,
        "e2e_rel": 0.05,
    },
}
STEPS = 50
SEED = 1234

# dt=0.25 oracles (fractional geo delays) cost 4x the substeps —
# the ~2-minute tail of the suite; quick tier skips them
_PARAMS = [pytest.param(k, marks=pytest.mark.slow)
           if ORACLE[k].get("overrides") else k
           for k in sorted(ORACLE)]


def _run_engine(network_rel, overrides=None, max_nodes=24, max_edges=37,
                config=CONFIG):
    """The cli-simulate path, in-process: uniform schedule over real nodes,
    everything placed everywhere, 50 x 100 ms control intervals.  The
    harness itself lives in tools/reward_curve.py (uniform_engine_run) and
    is shared with the reward-curve anchor so the two can't diverge."""
    from gsc_tpu.config.schema import DROP_REASONS

    from reward_curve import uniform_engine_run

    metrics, _, _ = uniform_engine_run(
        os.path.join(REFERENCE, network_rel), STEPS, SEED,
        config=os.path.join(REFERENCE, config), overrides=overrides,
        max_nodes=max_nodes, max_edges=max_edges)
    return {
        "generated": int(metrics.generated),
        "processed": int(metrics.processed),
        "dropped": int(metrics.dropped),
        "drop_reasons": {k: int(v) for k, v in
                         zip(DROP_REASONS, np.asarray(metrics.drop_reasons))},
        "avg_e2e": float(metrics.avg_e2e()),
    }


@pytest.mark.parametrize("name", _PARAMS)
def test_engine_matches_reference(name):
    want = ORACLE[name]
    mn, me = want.get("limits", (24, 37))
    got = _run_engine(want["network"], want.get("overrides"),
                      max_nodes=mn, max_edges=me,
                      config=want.get("config", CONFIG))
    assert got["generated"] == want["generated"]
    if want.get("exact"):
        assert got["processed"] == want["processed"], (got, want)
        assert got["dropped"] == want["dropped"], (got, want)
        assert got["avg_e2e"] == pytest.approx(want["avg_e2e"], rel=1e-5)
        assert got["drop_reasons"] == want["drop_reasons"]
    elif "atol_flows" in want:
        atol = want["atol_flows"]
        assert abs(got["processed"] - want["processed"]) <= atol, (got, want)
        assert abs(got["dropped"] - want["dropped"]) <= atol, (got, want)
        assert got["avg_e2e"] == pytest.approx(want["avg_e2e"],
                                               rel=want["e2e_rel"])
        for reason, n in want["drop_reasons"].items():
            assert abs(got["drop_reasons"][reason] - n) <= atol, (got, want)
            if n == 0:  # no misclassification: unused reasons stay at zero
                assert got["drop_reasons"][reason] == 0, (got, want)
    else:
        assert abs(got["processed"] - want["processed"]) <= 2, (got, want)
        assert abs(got["dropped"] - want["dropped"]) <= 2, (got, want)
        assert got["avg_e2e"] == pytest.approx(want["avg_e2e"], rel=0.025)
        assert got["drop_reasons"] == want["drop_reasons"]


@pytest.mark.parametrize("name", _PARAMS)
def test_oracle_numbers_are_current(name):
    """Re-run the reference itself and verify the frozen constants."""
    want = ORACLE[name]
    env = no_tpu_env()  # skip TPU registration: no jax
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_reference.py"),
         "--mode", "interface", "--network", want["network"],
         "--config", want.get("config", CONFIG),
         "--steps", str(STEPS), "--seed", str(SEED)],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["generated_flows"] == want["generated"]
    assert out["processed_flows"] == want["processed"]
    assert out["dropped_flows"] == want["dropped"]
    assert out["dropped_by_reason"] == want["drop_reasons"]
    assert out["avg_end2end_delay"] == pytest.approx(want["avg_e2e"],
                                                     rel=1e-9)


# ---------------------------------------------------------------- per-flow
# FlowController (per-flow external decisions) parity: local-processing
# policy on the line3-egress asset — place-on-decision, the per-flow
# decision loop, and egress routing, vs the reference's FlowController +
# ExternalDecisionMaker driven by tools/run_reference.py --mode perflow.
# Frozen reference output (duration 2000, seed 1234): generated 201
# (the reference also books the boundary arrival at t == horizon),
# processed 197, dropped 0, avg e2e 35.0 (3 x 5 ms SFs + 20 ms path).
PERFLOW = {
    "network": os.path.join(REPO, "tests", "assets", "line3-egress.graphml"),
    "config": os.path.join(REPO, "tests", "assets", "perflow_config.yaml"),
    "duration": 2000,
    "generated": 201, "processed": 197, "dropped": 0,
    "avg_e2e": 35.0,
}


def test_perflow_engine_matches_reference():
    import jax.numpy as jnp

    from gsc_tpu.config.loader import load_service, load_sim
    from gsc_tpu.config.schema import EnvLimits
    from gsc_tpu.sim.engine import SimEngine
    from gsc_tpu.sim.state import PH_DECIDE
    from gsc_tpu.sim.traffic import generate_traffic
    from gsc_tpu.topology.compiler import load_topology

    svc = load_service(os.path.join(REFERENCE, SERVICE))
    sim_cfg = load_sim(PERFLOW["config"])
    assert sim_cfg.controller == "per_flow"   # loader maps FlowController
    limits = EnvLimits.for_service(svc, max_nodes=8, max_edges=8)
    topo = load_topology(PERFLOW["network"], max_nodes=8, max_edges=8)
    steps = PERFLOW["duration"] // int(sim_cfg.run_duration)
    traffic = generate_traffic(sim_cfg, svc, topo, steps, SEED)
    engine = SimEngine(svc, sim_cfg, limits)

    def decide_local(st):
        return jnp.where(st.flows.phase == PH_DECIDE, st.flows.node, -1)

    state = engine.init(jax.random.PRNGKey(SEED), topo)
    for _ in range(steps):
        state, metrics = engine.apply_per_flow(state, topo, traffic,
                                               decide_local)
    assert abs(int(metrics.generated) - PERFLOW["generated"]) <= 2
    assert int(metrics.processed) == PERFLOW["processed"]
    assert int(metrics.dropped) == PERFLOW["dropped"]
    assert float(metrics.avg_e2e()) == pytest.approx(PERFLOW["avg_e2e"],
                                                     rel=1e-6)


def test_perflow_oracle_numbers_are_current():
    """Re-run the reference FlowController itself and verify the frozen
    constants."""
    env = no_tpu_env()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "run_reference.py"),
         "--mode", "perflow", "--network", PERFLOW["network"],
         "--config", PERFLOW["config"],
         "--duration", str(PERFLOW["duration"]), "--seed", str(SEED)],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout.strip().splitlines()[-1])
    assert out["generated_flows"] == PERFLOW["generated"]
    assert out["processed_flows"] == PERFLOW["processed"]
    assert out["dropped_flows"] == PERFLOW["dropped"]
    assert out["avg_end2end_delay"] == pytest.approx(PERFLOW["avg_e2e"],
                                                     rel=1e-9)


def test_reward_curve_matches_reference():
    """Per-interval REWARD parity on the flagship config-1 scenario
    (BASELINE protocol: "reproduce the reference's reward curve"): both
    simulators' per-step flow metrics fed through the one compute_reward
    implementation must produce near-identical curves.  The residual is
    the documented dt=1 avg-e2e quantization (+1.8% delay -> ~0.05
    constant reward offset through the /15 diameter term); shape must
    match to r > 0.99.  tools/reward_curve.py is the measurement; 25
    steps keeps CI cost at half the 50-step exhibit."""
    env = no_tpu_env()
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "reward_curve.py"),
         "--steps", "25"],
        capture_output=True, text=True, timeout=600, env=env)
    assert r.returncode == 0, r.stderr[-2000:]
    out = json.loads(r.stdout[r.stdout.index("{"):])
    assert out["pearson_r"] > 0.99, out
    assert out["max_abs_diff"] < 0.1, out
