"""Training-quality observability tests (on-device learn ledger, live
/metrics endpoint, learning-curve envelope comparator) — the PR-11 layer.

Covers: ledger arithmetic vs a hand-computed tiny batch, per-topology
TD-error segmentation on a mixed [A, B, A, B] batch, ledger-on vs
ledger-off bit-identity of the training math, the no-host-sync dispatch
contract, the /metrics endpoint scrape roundtrip, curves.json end-to-end
from a tiny run (with the serial path's topology stamping), bench_diff
curve-ingest + envelope-regression verdicts, and the shuffled-write
read_events sort (the hub stamps ts before the sink lock, so concurrent
threads can land out of order in the file).
"""
import json
import os
import sys
import urllib.error
import urllib.request

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gsc_tpu.agents.buffer import buffer_add, buffer_init
from gsc_tpu.agents.ddpg import DDPG
from gsc_tpu.agents.trainer import Trainer
from gsc_tpu.obs import (CURVES_SCHEMA_VERSION, JsonlSink, ListSink,
                         MetricsEndpoint, MetricsHub, RunObserver,
                         extract_curves, prometheus_text)
from gsc_tpu.obs.learning import (LearnLedger, LearnLedgerSpec,
                                  accumulate_signal, layer_norms,
                                  learn_signal, replay_stats,
                                  zero_learn_signal)
from gsc_tpu.obs.trace import build_trace, read_events, validate_trace

from tests.test_agent import make_driver, make_stack

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

import bench_diff
import obs_report

pytestmark = pytest.mark.learn_obs


# ------------------------------------------------------- ledger arithmetic
def test_learn_signal_arithmetic_hand_computed():
    """Ledger pieces vs a hand-computed tiny batch: per-topology |TD|
    segment sums, Q distribution moments, per-layer norms."""
    spec = LearnLedgerSpec(num_topos=3)
    topo_idx = jnp.asarray([0, 1, 0, 2, 7], jnp.int32)   # 7 clips to 2
    td = jnp.asarray([1.0, -2.0, 3.0, -4.0, 0.5])
    q = jnp.asarray([0.5, 1.5, 2.5, 3.5, 4.5])
    params = {"actor": {"params": {"Dense_0": {
                  "kernel": jnp.asarray([[3.0, 4.0]]),
                  "bias": jnp.zeros(2)}}},
              "critic": {"params": {"Dense_0": {
                  "kernel": jnp.asarray([[5.0, 12.0]])}}}}
    grads = jax.tree_util.tree_map(lambda x: 2.0 * x, params)
    sig = learn_signal(spec, topo_idx, td, q, params=params, grads=grads)

    np.testing.assert_allclose(np.asarray(sig["td_abs_sum"]),
                               [4.0, 2.0, 4.5])
    np.testing.assert_allclose(np.asarray(sig["td_count"]),
                               [2.0, 1.0, 2.0])
    np.testing.assert_allclose(float(sig["q_mean"]), np.mean(np.asarray(q)),
                               rtol=1e-6)
    np.testing.assert_allclose(float(sig["q_std"]), np.std(np.asarray(q)),
                               rtol=1e-6)
    assert float(sig["q_min"]) == 0.5 and float(sig["q_max"]) == 4.5
    # per-layer norms group by <tree>/<module> and drop 'params' levels
    assert set(sig["param_norms"]) == {"actor/Dense_0", "critic/Dense_0"}
    np.testing.assert_allclose(float(sig["param_norms"]["actor/Dense_0"]),
                               5.0, rtol=1e-6)
    np.testing.assert_allclose(float(sig["param_norms"]["critic/Dense_0"]),
                               13.0, rtol=1e-6)
    np.testing.assert_allclose(float(sig["grad_norms"]["actor/Dense_0"]),
                               10.0, rtol=1e-6)

    # accumulation: TD segments sum, moments take the newest value
    state_like = type("S", (), {"actor_params": params["actor"],
                                "critic_params": params["critic"]})
    zero = zero_learn_signal(spec, state_like)
    assert jax.tree_util.tree_structure(zero) \
        == jax.tree_util.tree_structure(sig)
    acc = accumulate_signal(accumulate_signal(zero, sig), sig)
    np.testing.assert_allclose(np.asarray(acc["td_abs_sum"]),
                               [8.0, 4.0, 9.0])
    assert float(acc["q_max"]) == 4.5

    # layer_norms standalone agrees with the signal's view
    np.testing.assert_allclose(
        float(layer_norms(params)["critic/Dense_0"]), 13.0, rtol=1e-6)


def test_replay_stats_both_layouts():
    example = {"x": jnp.zeros(3)}
    buf = buffer_init(example, capacity=8)
    for i in range(3):
        buf = buffer_add(buf, {"x": jnp.full(3, i, jnp.float32)})
    stats = replay_stats(buf)
    assert int(stats["size"]) == 3
    np.testing.assert_allclose(float(stats["fill"]), 3 / 8)
    np.testing.assert_allclose(float(stats["age_mean_steps"]), 1.0)

    # replica-sharded layout: [B, capacity, ...] leaves, size [B]
    from gsc_tpu.agents.buffer import ReplayBuffer
    pbuf = ReplayBuffer(data={"x": jnp.zeros((2, 4, 3))},
                        pos=jnp.zeros(2, jnp.int32),
                        size=jnp.asarray([4, 1], jnp.int32), shapes=None)
    pstats = replay_stats(pbuf)
    np.testing.assert_allclose(np.asarray(pstats["fill"]), [1.0, 0.25])
    np.testing.assert_allclose(np.asarray(pstats["age_mean_steps"]),
                               [1.5, 0.0])


# ------------------------------------------------- dispatch-path contracts
def _episode_inputs(env, topo, traffic, ddpg, seed=0):
    env_state, obs = env.reset(jax.random.PRNGKey(seed), topo, traffic)
    state = ddpg.init(jax.random.PRNGKey(1), obs)
    buffer = ddpg.init_buffer(obs)
    return state, buffer, env_state, obs


def test_ledger_on_is_bit_identical_and_emits_signal():
    """The acceptance contract's numeric half: the ledger only CONSUMES
    tensors the update path materialized, so a ledger-on run's learner
    state and replay are BIT-identical to the ledger-off (pre-PR) run —
    while its metrics additionally carry the learn signal."""
    env, agent, topo, traffic = make_stack()
    plain = DDPG(env, agent)
    led = DDPG(env, agent, learn_ledger=LearnLedgerSpec(num_topos=2))

    outs = {}
    for name, ddpg in (("plain", plain), ("ledger", led)):
        state, buffer, env_state, obs = _episode_inputs(env, topo, traffic,
                                                        ddpg)
        for ep in range(2):
            state, buffer, env_state, obs, stats, metrics = \
                ddpg.episode_step(state, buffer, env_state, obs, topo,
                                  traffic,
                                  np.int32(ep * agent.episode_steps),
                                  learn=True)
        outs[name] = (state, buffer, stats, metrics)

    s_p, b_p, st_p, m_p = outs["plain"]
    s_l, b_l, st_l, m_l = outs["ledger"]
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        (s_p, b_p.data), (s_l, b_l.data))
    assert "learn_signal" not in m_p and "replay" not in st_p
    sig = m_l["learn_signal"]
    # every burst sample lands in exactly one TD segment
    n_steps = agent.learn_steps or agent.episode_steps
    assert float(np.asarray(sig["td_count"]).sum()) \
        == n_steps * agent.batch_size
    assert np.isfinite(np.asarray(sig["td_abs_sum"])).all()
    assert set(sig["grad_norms"]) == set(sig["param_norms"])
    assert float(st_l["replay"]["size"]) == int(b_l.size)


def test_ledger_dispatch_is_host_sync_free():
    """The acceptance contract's sync half: with the ledger folded into
    the dispatch outputs, the fused episode dispatch performs ZERO
    device->host syncs — the signal drains with the deferred metrics."""
    from gsc_tpu.analysis.sentinels import no_host_sync

    env, agent, topo, traffic = make_stack()
    ddpg = DDPG(env, agent, learn_ledger=LearnLedgerSpec(num_topos=1))
    state, buffer, env_state, obs = _episode_inputs(env, topo, traffic,
                                                    ddpg)
    # warm the trace outside the guard (compile-time work is not dispatch)
    out = ddpg.episode_step(state, buffer, env_state, obs, topo, traffic,
                            np.int32(0), learn=True)
    jax.block_until_ready(out)
    state, buffer, env_state, obs = out[:4]
    with no_host_sync("learn-ledger dispatch"):
        out = ddpg.episode_step(state, buffer, env_state, obs, topo,
                                traffic, np.int32(agent.episode_steps),
                                learn=True)
    # the deferred drain's sync happens OUTSIDE the guard
    assert np.isfinite(np.asarray(out[4]["episodic_return"]))
    assert np.isfinite(np.asarray(out[5]["learn_signal"]["td_abs_sum"])).all()


def test_mixed_batch_td_segments_by_topology():
    """[A, B, A, B] mixed batch: the burst's TD segments attribute every
    sampled transition to its stored topo_idx — segments 0 and 1 fill,
    the padding segments stay exactly zero."""
    from gsc_tpu.parallel import ParallelDDPG
    from gsc_tpu.sim.traffic import generate_traffic
    from gsc_tpu.topology import stack_topologies
    from gsc_tpu.topology.compiler import compile_topology
    from gsc_tpu.topology.synthetic import line, triangle

    env, agent, _, _ = make_stack()
    tA = compile_topology(triangle(), max_nodes=8, max_edges=8, topo_id=0)
    tB = compile_topology(line(4), max_nodes=8, max_edges=8, topo_id=1)
    steps = agent.episode_steps
    tr = lambda t, s: generate_traffic(env.sim_cfg, env.service, t, steps,
                                       seed=s, capacity=64)
    topo = stack_topologies([tA, tB, tA, tB])
    traffic = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[tr(t, s) for t, s in ((tA, 0), (tB, 10), (tA, 1), (tB, 11))])
    pddpg = ParallelDDPG(env, agent, num_replicas=4,
                         per_replica_topology=True,
                         learn_ledger=LearnLedgerSpec(num_topos=4))
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, stats, metrics = pddpg.chunk_step(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(10 ** 6),
        num_steps=steps, learn=True)
    counts = np.asarray(metrics["learn_signal"]["td_count"])
    n_steps = agent.learn_steps or agent.episode_steps
    assert counts.sum() == n_steps * agent.batch_size
    assert counts[0] > 0 and counts[1] > 0, counts
    np.testing.assert_array_equal(counts[2:], 0.0)
    # replay stats carry the per-replica [B] axis
    assert np.asarray(stats["replay"]["fill"]).shape == (4,)


# --------------------------------------------------------------- endpoint
def test_metrics_endpoint_scrape_roundtrip():
    hub = MetricsHub(tags={"run": "scrape"})
    hub.counter("episodes_drained", 3)
    hub.gauge("sps", 123.5)
    hub.gauge("topology_return", -2.5, topology="abilene.graphml")
    hub.observe("phase_s", 0.25, phase="dispatch")
    ep = MetricsEndpoint(hub, port=0).start()
    try:
        assert ep.port > 0
        body = urllib.request.urlopen(ep.url, timeout=10).read().decode()
        parsed = {}
        for line in body.strip().splitlines():
            name, value = line.rsplit(" ", 1)
            parsed[name] = float(value)
        # the scrape IS the snapshot (same flat exposition names)
        snap = hub.snapshot()
        assert parsed == {k: float(v) for k, v in snap.items()}
        assert parsed['gsc_sps{run="scrape"}'] == 123.5
        assert parsed[
            'gsc_topology_return{run="scrape",topology="abilene.graphml"}'
        ] == -2.5
        assert 'gsc_phase_s_p99{phase="dispatch",run="scrape"}' in parsed
        # a scrape between hub writes sees the newer value (live, not a
        # point-in-time file)
        hub.gauge("sps", 200.0)
        body2 = urllib.request.urlopen(ep.url, timeout=10).read().decode()
        assert 'gsc_sps{run="scrape"} 200.0' in body2
        health = json.loads(urllib.request.urlopen(
            ep.url.replace("/metrics", "/healthz"), timeout=10).read())
        assert health["status"] == "ok" and health["series"] > 0
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(ep.url.replace("/metrics", "/nope"),
                                   timeout=10)
    finally:
        ep.stop()
    assert "gsc_sps" in prometheus_text(hub.snapshot())


# ----------------------------------------------------------------- curves
def test_extract_curves_summary_math():
    base = 1_000_000.0
    events = [{"event": "run_start", "ts": base, "run": "cm"}]
    for ep in range(20):
        events.append({"event": "episode", "ts": base + 1 + ep,
                       "run": "cm", "episode": ep,
                       "episodic_return": float(ep), "critic_loss": 0.5,
                       "actor_loss": -0.5, "sps": 10.0})
        events.append({"event": "learn_signal", "ts": base + 1.5 + ep,
                       "run": "cm", "episode": ep,
                       "td_abs_mean": 2.0 - 0.05 * ep, "q_mean": 0.1,
                       "per_topology_td": {"tri": 2.0 - 0.05 * ep}})
    doc = extract_curves(events)
    assert doc["schema_version"] == CURVES_SCHEMA_VERSION
    assert doc["episodes"] == 20 and doc["run"] == "cm"
    s = doc["summary"]
    assert s["final_window_return"] == pytest.approx(14.5)
    assert s["first_window_return"] == pytest.approx(4.5)
    assert s["auc_return"] == pytest.approx(9.5)
    # threshold = 4.5 + 0.9*(14.5-4.5) = 13.5; trailing-10 mean first
    # reaches it at episode 18 (mean of 9..18)
    assert s["threshold_return"] == pytest.approx(13.5)
    assert s["episodes_to_threshold"] == 18
    assert s["final_window_td_abs"] == pytest.approx(
        sum(2.0 - 0.05 * ep for ep in range(10, 20)) / 10)
    assert doc["per_topology"]["tri"]["episode"] == list(range(20))
    # non-finite values sanitize to null (strict-JSON contract)
    events.append({"event": "episode", "ts": base + 100, "run": "cm",
                   "episode": 20, "episodic_return": float("nan")})
    doc2 = extract_curves(events)
    assert doc2["series"]["episodic_return"][-1] is None
    json.dumps(doc2)   # must be serializable

    # a flat/declining run has no time-to-learn: null, never a fake 0
    flat = [{"event": "episode", "ts": base + ep, "episode": ep,
             "episodic_return": 5.0 - ep} for ep in range(12)]
    assert extract_curves(flat)["summary"]["episodes_to_threshold"] is None


def test_curves_e2e_tiny_run_and_bench_diff_gate(tmp_path):
    """Serial tiny run under RunObserver(learn=True): learn_signal events
    + topology-stamped episode events land in the stream, close() writes
    curves.json, bench_diff ingests it and self-compares clean while an
    injected envelope regression exits 1."""
    env, agent, topo, traffic = make_stack()
    driver = make_driver(env, agent, topo, traffic)
    obs = RunObserver(str(tmp_path / "obs"), run_id="learnrun", learn=True)
    obs.start(meta={"episodes": 3})
    trainer = Trainer(env, driver, agent, seed=0, result_dir=str(tmp_path),
                      obs=obs)
    trainer.train(episodes=3)
    obs.close()

    events = read_events(str(tmp_path / "obs"))
    signals = [e for e in events if e["event"] == "learn_signal"]
    assert [e["episode"] for e in signals] == [0, 1, 2]
    assert signals[-1]["per_topology_td"], "per-topology TD missing"
    assert signals[-1]["replay"]["fill"] > 0
    # serial-path topology identity (the satellite): every episode event
    # carries the scheduled network's name, and the gauge exists
    eps = [e for e in events if e["event"] == "episode"]
    assert all(e.get("topology") == "x" for e in eps)
    snap = json.load(open(tmp_path / "obs" / "metrics.json"))["metrics"]
    assert any(k.startswith("gsc_topology_return") and 'topology="x"' in k
               for k in snap)
    assert any(k.startswith("gsc_td_abs_mean") for k in snap)
    assert any(k.startswith("gsc_grad_norm{") for k in snap)

    curves = json.load(open(tmp_path / "obs" / "curves.json"))
    assert curves["schema_version"] == CURVES_SCHEMA_VERSION
    assert curves["episodes"] == 3
    assert len(curves["series"]["episodic_return"]) == 3
    assert len(curves["series"]["td_abs_mean"]) == 3
    assert curves["per_topology"]["x"]["episode"] == [0, 1, 2]
    assert curves["summary"]["final_window_return"] is not None

    # obs_report renders the stream's learning section
    summary = obs_report.summarize(
        obs_report.load_events(str(tmp_path / "obs")))
    assert summary["learning"]["episodes"] == 3
    assert "x" in summary["learning"]["per_topology_td"]
    assert summary["per_topology"]["x"]["episodes"] == 3
    obs_report.render_text(summary, out=open(os.devnull, "w"))

    # bench_diff: ingest + self-compare clean + injected regression rc 1
    traj = str(tmp_path / "traj.json")
    doc = bench_diff.ingest([str(tmp_path / "obs" / "curves.json")], traj)
    assert "curves_learnrun" in doc["rows"]
    assert bench_diff.main(["diff", "curves_learnrun", "--baseline",
                            "curves_learnrun", "--trajectory", traj]) == 0
    base_final = doc["rows"]["curves_learnrun"]["metrics"][
        "final_window_return"]
    bad = dict(curves)
    bad["summary"] = {**curves["summary"],
                      "final_window_return": base_final
                      - 10 * abs(base_final) - 100.0}
    bad_path = str(tmp_path / "bad_curves.json")
    with open(bad_path, "w") as f:
        json.dump(bad, f)
    assert bench_diff.main(["diff", bad_path, "--baseline",
                            "curves_learnrun", "--trajectory", traj]) == 1


def test_parallel_run_emits_learn_signal_and_topology(tmp_path):
    """train_parallel (homogeneous replicas): the harness emits the
    learn_signal per episode and the episode events stamp the topology
    name — replica runs land in the same report tables as serial ones."""
    env, agent, topo, traffic = make_stack()
    driver = make_driver(env, agent, topo, traffic)
    obs = RunObserver(str(tmp_path / "obs"), run_id="prun", learn=True)
    obs.start(meta={"episodes": 2})
    trainer = Trainer(env, driver, agent, seed=0, result_dir=str(tmp_path),
                      obs=obs)
    trainer.train_parallel(episodes=2, num_replicas=2, chunk=2,
                           device_traffic=False)
    obs.close()
    events = read_events(str(tmp_path / "obs"))
    signals = [e for e in events if e["event"] == "learn_signal"]
    assert [e["episode"] for e in signals] == [0, 1]
    assert signals[-1]["per_topology_td"] == {
        "x": signals[-1]["td_abs_mean"]}
    assert len(signals[-1]["replay"]["size"]) == 2   # per-replica
    eps = [e for e in events if e["event"] == "episode"]
    assert all(e.get("topology") == "x" and e.get("replicas") == 2
               for e in eps)
    curves = json.load(open(tmp_path / "obs" / "curves.json"))
    assert curves["episodes"] == 2
    assert len(curves["series"]["td_abs_mean"]) == 2


# ------------------------------------------------- shuffled-write reading
def test_read_events_sorts_shuffled_writes(tmp_path):
    """The hub stamps ts before taking the sink lock, so concurrent
    threads can interleave out of order in the file (and across rotation
    segments).  read_events must return one ts-sorted stream that the
    strict trace validator accepts."""
    path = str(tmp_path / "events.jsonl")
    base = 1_000_000_000.0
    records = [{"event": "run_start", "ts": base, "run": "shuf"}]
    disp = 0.0
    for ep in range(6):
        disp += 0.01
        records.append({"event": "episode", "ts": base + 1 + ep,
                        "run": "shuf", "episode": ep, "sps": 1.0,
                        "episodic_return": float(ep),
                        "phases": {"dispatch": {"total_s": round(disp, 3),
                                                "count": ep + 1,
                                                "mean_ms": 10.0}}})
        records.append({"event": "learn_signal", "ts": base + 1.25 + ep,
                        "run": "shuf", "episode": ep, "td_abs_mean": 1.0})
    records.append({"event": "run_end", "ts": base + 99, "run": "shuf",
                    "status": "ok"})

    # adversarial write order, split across two rotation segments.
    # run_start stays FIRST in file order — it is emitted before any
    # concurrent writer exists, and the per-run sort keys off it.
    body = [records[i + 1] for i in
            np.random.RandomState(7).permutation(len(records) - 1)]
    shuffled = [records[0]] + body
    cut = len(shuffled) // 2
    with open(path + ".1", "w") as f:
        for r in shuffled[:cut]:
            f.write(json.dumps(r) + "\n")
    with open(path, "w") as f:
        for r in shuffled[cut:]:
            f.write(json.dumps(r) + "\n")

    events = read_events(path)
    ts = [e["ts"] for e in events]
    assert ts == sorted(ts), "read_events did not sort by ts"
    assert [e["episode"] for e in events if e["event"] == "episode"] \
        == list(range(6))
    assert validate_trace(build_trace(events)) == []
    # the report's reader sorts identically, so phase deltas stay sane
    assert obs_report.load_events(path) == events
    deltas = obs_report.phase_deltas(
        [e for e in events if e["event"] == "episode"])
    assert all(d.get("dispatch", 0.0) >= 0.0 for d in deltas)
    # curves extraction sees the ordered series
    doc = extract_curves(events)
    assert doc["series"]["episodic_return"] == [float(e) for e in range(6)]


def test_hub_out_of_order_sink_writes_roundtrip(tmp_path):
    """Regression for the emit race itself: records handed to the sink
    with non-monotone ts (the stamped-before-lock interleaving) come back
    sorted from read_events."""
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path)
    sink.emit({"event": "run_start", "ts": 100.0, "run": "r"})
    sink.emit({"event": "stall", "ts": 103.0, "run": "r"})       # watchdog
    sink.emit({"event": "episode", "ts": 101.0, "run": "r",      # main loop
               "episode": 0})
    sink.emit({"event": "episode", "ts": 102.0, "run": "r", "episode": 1})
    sink.close()
    kinds = [(e["ts"], e["event"]) for e in read_events(path)]
    assert kinds == [(100.0, "run_start"), (101.0, "episode"),
                     (102.0, "episode"), (103.0, "stall")]


def test_read_events_sort_never_crosses_run_boundaries(tmp_path):
    """Appended (--resume) runs whose wall clock stepped BACKWARDS (NTP,
    VM resume) must not interleave: the sort is per run_start-delimited
    slice, so run partitioning and last-run summaries stay correct."""
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path)
    sink.emit({"event": "run_start", "ts": 500.0, "run": "r1"})
    sink.emit({"event": "episode", "ts": 502.0, "run": "r1", "episode": 0,
               "episodic_return": 1.0})
    # second run appends with an EARLIER clock
    sink.emit({"event": "run_start", "ts": 100.0, "run": "r2"})
    sink.emit({"event": "episode", "ts": 103.0, "run": "r2", "episode": 0,
               "episodic_return": 2.0})
    sink.emit({"event": "episode", "ts": 101.0, "run": "r2", "episode": 1,
               "episodic_return": 3.0})
    sink.close()
    events = read_events(path)
    # run 2's records all stay AFTER run 1's, sorted within their run
    assert [(e["run"], e["ts"]) for e in events] == [
        ("r1", 500.0), ("r1", 502.0),
        ("r2", 100.0), ("r2", 101.0), ("r2", 103.0)]
    assert obs_report.load_events(path) == events
    # the report summarizes the LAST run only, with run 2's episodes
    s = obs_report.summarize(events)
    assert s["runs_in_stream"] == 2 and s["episodes"] == 2
    # curves extraction likewise sees only run 2, keyed by episode index
    doc = extract_curves(events)
    assert doc["run"] == "r2"
    assert doc["series"]["episode"] == [0, 1]
    assert doc["series"]["episodic_return"] == [2.0, 3.0]


def test_learn_ledger_emit_without_device(tmp_path):
    """Host-side emitter semantics on plain numpy inputs: segment names
    resolve, empty segments are omitted, gauges land."""
    hub = MetricsHub(tags={"run": "emit"})
    sink = ListSink()
    hub.add_sink(sink)
    led = LearnLedger(hub)
    spec = led.spec(3, names=["tri", "line", "ring"])
    assert spec == LearnLedgerSpec(num_topos=3)
    led.episode(5, signal={
        "td_abs_sum": np.asarray([4.0, 0.0, 1.0]),
        "td_count": np.asarray([2.0, 0.0, 4.0]),
        "q_mean": np.float32(0.5), "q_std": np.float32(0.1),
        "q_min": np.float32(0.0), "q_max": np.float32(1.0),
        "grad_norms": {"actor/MLP_0": np.float32(2.0)},
        "param_norms": {"actor/MLP_0": np.float32(3.0)},
    }, replay={"size": np.asarray([7]), "fill": np.asarray([0.5]),
               "age_mean_steps": np.asarray([3.0])})
    (ev,) = sink.of_kind("learn_signal")
    assert ev["episode"] == 5
    # 'line' has no samples this burst: omitted, never a fake 0.0
    assert ev["per_topology_td"] == {"tri": 2.0, "ring": 0.25}
    assert ev["td_abs_mean"] == pytest.approx(5.0 / 6.0, abs=1e-6)
    assert ev["replay"] == {"size": [7], "fill": 0.5,
                            "age_mean_steps": 3.0}
    assert hub.get_gauge("td_abs_mean", topology="tri") == 2.0
    assert hub.get_gauge("td_abs_mean", topology="line") is None
    assert hub.get_gauge("grad_norm", layer="actor/MLP_0") == 2.0
    assert hub.get_gauge("replay_fill") == 0.5
