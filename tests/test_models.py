"""Model tests: GATv2 implementation parity (dense vs segment vs Pallas),
embedder weight tying, actor/critic shapes and masking.

The reference has no model tests at all; SURVEY.md §4 calls for parity tests
between the Pallas kernel and the XLA reference implementation — these are
them (Pallas runs in interpret mode on CPU).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gsc_tpu.config.schema import AgentConfig
from gsc_tpu.env.observations import GraphObs
from gsc_tpu.models import Actor, GNNEmbedder, QNetwork, dense_adj
from gsc_tpu.models.gnn import GATv2Conv
from gsc_tpu.ops.pallas_gat import gatv2_pallas

N, E, F_IN = 8, 8, 3


def random_graph(key, batch=()):
    """Random connected-ish graph with 5 real nodes / 6 real edges."""
    k1, = jax.random.split(key, 1)
    nodes = jax.random.uniform(k1, batch + (N, F_IN))
    edges = np.array([[0, 1], [1, 2], [2, 3], [3, 4], [4, 0], [1, 3]]).T
    ei = np.zeros((2, 2 * E), np.int32)
    em = np.zeros(2 * E, bool)
    ei[:, :6] = edges
    ei[:, E:E + 6] = edges[::-1]
    em[:6] = em[E:E + 6] = True
    nm = np.zeros(N, bool)
    nm[:5] = True
    bc = lambda x: jnp.broadcast_to(jnp.asarray(x), batch + x.shape)
    return nodes, bc(ei), bc(em), bc(nm)


@pytest.fixture(scope="module")
def graph():
    return random_graph(jax.random.PRNGKey(0))


def test_dense_vs_segment_parity(graph):
    nodes, ei, em, nm = graph
    conv = GATv2Conv(features=16, mean_aggr=True, impl="dense")
    params = conv.init(jax.random.PRNGKey(1), nodes,
                       adj=dense_adj(ei, em, nm))
    out_dense = conv.apply(params, nodes, adj=dense_adj(ei, em, nm))
    seg = GATv2Conv(features=16, mean_aggr=True, impl="segment")
    out_seg = seg.apply(params, nodes, edge_index=ei, edge_mask=em,
                        node_mask=nm)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_seg),
                               rtol=1e-5, atol=1e-6)
    # padded nodes produce exactly zero
    assert not np.asarray(out_dense)[5:].any()


def test_dense_vs_pallas_parity(graph):
    nodes, ei, em, nm = graph
    adj = dense_adj(ei, em, nm)
    conv = GATv2Conv(features=16, mean_aggr=True, impl="dense")
    params = conv.init(jax.random.PRNGKey(1), nodes, adj=adj)
    out_dense = conv.apply(params, nodes, adj=adj)
    p = params["params"]
    xl = nodes @ p["w_l"] + p["b_l"]
    xr = nodes @ p["w_r"] + p["b_r"]
    out_pl = gatv2_pallas(xl, xr, p["att"][:, 0], p["bias"], adj,
                          mean_aggr=True, interpret=True)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_pl),
                               rtol=1e-5, atol=1e-6)


def test_pallas_batched_and_sum_aggr():
    nodes, ei, em, nm = random_graph(jax.random.PRNGKey(2), batch=(5,))
    adj = dense_adj(ei, em, nm)
    conv = GATv2Conv(features=4, mean_aggr=False, impl="dense")
    params = conv.init(jax.random.PRNGKey(1), nodes, adj=adj)
    out_dense = conv.apply(params, nodes, adj=adj)
    p = params["params"]
    xl = nodes @ p["w_l"] + p["b_l"]
    xr = nodes @ p["w_r"] + p["b_r"]
    out_pl = gatv2_pallas(xl, xr, p["att"][:, 0], p["bias"], adj,
                          mean_aggr=False, tile_b=2, interpret=True)
    assert out_pl.shape == (5, N, 4)
    np.testing.assert_allclose(np.asarray(out_dense), np.asarray(out_pl),
                               rtol=1e-5, atol=1e-6)


def test_embedder_weight_tying(graph):
    """num_layers=2, num_iter=2 must create exactly 2 conv parameter sets
    (encoder + one shared process conv), models.py:22-27, 44-53."""
    nodes, ei, em, nm = graph
    emb = GNNEmbedder(hidden=16, num_layers=2, num_iter=2)
    params = emb.init(jax.random.PRNGKey(0), nodes, ei, em, nm)
    names = set(params["params"].keys())
    assert names == {"encoder", "process_0"}
    out = emb.apply(params, nodes, ei, em, nm)
    assert out.shape == (16,)


def test_embedder_batched(graph):
    nodes, ei, em, nm = random_graph(jax.random.PRNGKey(3), batch=(4,))
    emb = GNNEmbedder(hidden=8, num_layers=2, num_iter=2)
    params = emb.init(jax.random.PRNGKey(0), nodes, ei, em, nm)
    out = emb.apply(params, nodes, ei, em, nm)
    assert out.shape == (4, 8)


def make_obs(batch=()):
    nodes, ei, em, nm = random_graph(jax.random.PRNGKey(0), batch=batch)
    a = 5 * 1 * 2 * 5  # 5 real nodes, 1 sfc, 2 sfs... use full padded dims
    mask = jnp.broadcast_to(
        (jnp.arange(N * 1 * 2 * N) % 2 == 0).astype(jnp.float32),
        batch + (N * 1 * 2 * N,))
    return GraphObs(nodes=nodes, node_mask=nm, edge_index=ei, edge_mask=em,
                    mask=mask)


def test_actor_mask_and_shapes():
    agent = AgentConfig(graph_mode=True, gnn_features=8,
                        actor_hidden_layer_nodes=(32,))
    obs = make_obs()
    action_dim = N * 1 * 2 * N
    actor = Actor(agent=agent, action_dim=action_dim)
    params = actor.init(jax.random.PRNGKey(0), obs)
    out = actor.apply(params, obs)
    assert out.shape == (action_dim,)
    # masked entries exactly zero (models.py:151-152)
    np.testing.assert_array_equal(np.asarray(out)[1::2], 0.0)


def test_critic_batched():
    agent = AgentConfig(graph_mode=True, gnn_features=8,
                        critic_hidden_layer_nodes=(16,))
    obs = make_obs(batch=(6,))
    action_dim = N * 1 * 2 * N
    action = jnp.ones((6, action_dim)) * 0.5
    q = QNetwork(agent=agent)
    params = q.init(jax.random.PRNGKey(0), obs, action)
    out = q.apply(params, obs, action)
    assert out.shape == (6, 1)


def test_pallas_gradients_match_dense(graph):
    """The Pallas kernel's custom VJP (backward through the dense math)
    yields parameter gradients equal to the dense path's — gnn_impl=
    'pallas' is usable in the LEARN path, not just for acting."""
    nodes, ei, em, nm = graph
    grads = {}
    params = None
    for impl in ("dense", "pallas"):
        emb = GNNEmbedder(hidden=8, num_layers=2, num_iter=2, impl=impl)
        if params is None:
            params = emb.init(jax.random.PRNGKey(0), nodes, ei, em, nm)
        grads[impl] = jax.grad(
            lambda p: (emb.apply(p, nodes, ei, em, nm) ** 2).sum())(params)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6),
        grads["dense"], grads["pallas"])


def test_factored_actor_mask_shapes_and_param_scaling():
    """Factored head: same output contract as the monolithic head (shape,
    exact zeros at masked entries, batch dims) with parameters independent
    of the N x N' output plane (VERDICT r3 #4: the rung-5 monolithic head
    is a ~100M-param matrix that OOMs one chip)."""
    agent = AgentConfig(graph_mode=True, gnn_features=8,
                        actor_hidden_layer_nodes=(32,), factored_head=True,
                        factored_key_dim=4)
    obs = make_obs()
    action_dim = N * 1 * 2 * N
    actor = Actor(agent=agent, action_dim=action_dim,
                  sched_shape=(N, 1, 2, N))
    params = actor.init(jax.random.PRNGKey(0), obs)
    out = actor.apply(params, obs)
    assert out.shape == (action_dim,)
    np.testing.assert_array_equal(np.asarray(out)[1::2], 0.0)
    # batched
    obs_b = make_obs(batch=(3,))
    assert actor.apply(params, obs_b).shape == (3, action_dim)

    count = lambda p: sum(x.size for x in jax.tree_util.tree_leaves(p))
    mono = Actor(agent=AgentConfig(graph_mode=True, gnn_features=8,
                                   actor_hidden_layer_nodes=(32,),
                                   factored_head=False),
                 action_dim=action_dim, sched_shape=(N, 1, 2, N))
    n_fact = count(params)
    n_mono = count(mono.init(jax.random.PRNGKey(0), obs))
    # even at this toy size the factored head is smaller; at rung-5
    # padding the ratio is ~2000x
    assert n_fact < n_mono


def test_factored_critic_batched_and_action_sensitivity():
    agent = AgentConfig(graph_mode=True, gnn_features=8,
                        critic_hidden_layer_nodes=(16,), factored_head=True,
                        factored_key_dim=4)
    obs = make_obs(batch=(6,))
    action_dim = N * 1 * 2 * N
    q = QNetwork(agent=agent, action_dim=action_dim,
                 sched_shape=(N, 1, 2, N))
    action = jnp.ones((6, action_dim)) * 0.5
    params = q.init(jax.random.PRNGKey(0), obs, action)
    out = q.apply(params, obs, action)
    assert out.shape == (6, 1)
    out2 = q.apply(params, obs, action * 0.0)
    assert not np.allclose(np.asarray(out), np.asarray(out2))


def test_factored_head_auto_threshold():
    from gsc_tpu.models.nets import (FACTORED_HEAD_THRESHOLD,
                                     use_factored_head)
    g = AgentConfig(graph_mode=True)
    assert not use_factored_head(g, 1728)              # flagship: monolithic
    assert use_factored_head(g, FACTORED_HEAD_THRESHOLD)   # rung-5 scale
    assert use_factored_head(
        AgentConfig(graph_mode=True, factored_head=True), 16)
    assert not use_factored_head(
        AgentConfig(graph_mode=True, factored_head=False), 10 ** 6)
    assert not use_factored_head(AgentConfig(graph_mode=False), 10 ** 6)


def test_flat_mode_networks():
    agent = AgentConfig(graph_mode=False)
    obs = jnp.ones((4, 24))
    actor = Actor(agent=agent, action_dim=10)
    params = actor.init(jax.random.PRNGKey(0), obs)
    assert actor.apply(params, obs).shape == (4, 10)
    q = QNetwork(agent=agent)
    qp = q.init(jax.random.PRNGKey(0), obs, jnp.ones((4, 10)))
    assert q.apply(qp, obs, jnp.ones((4, 10))).shape == (4, 1)
