"""Engine semantics tests on hand-computable deterministic scenarios.

The reference's own tests only cover the interface contract
(src/tests/test_simulatorInterface.py); these go further and pin the
simulator's *semantics* — per-flow timelines, drop taxonomy, WRR splits —
on scenarios small enough to verify by hand against the reference's rules
(coordsim/simulation/flowsimulator.py:72-128 and its components).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gsc_tpu.config.schema import (
    EnvLimits,
    ServiceConfig,
    ServiceFunction,
    SimConfig,
)
from gsc_tpu.sim import SimEngine, generate_traffic
from gsc_tpu.topology.compiler import NetworkSpec, compile_topology

N, E = 8, 8  # small padded dims for fast tests


def make_service():
    sf = lambda n: ServiceFunction(name=n, processing_delay_mean=5.0,
                                   processing_delay_stdev=0.0)
    return ServiceConfig(sfc_list={"sfc_1": ("a", "b", "c")},
                         sf_list={n: sf(n) for n in "abc"})


def line_topo(node_cap=10.0, link_cap=100.0, link_delay=3.0):
    """0(Ingress) -- 1 -- 2, integer link delays."""
    spec = NetworkSpec(
        node_caps=[node_cap] * 3,
        node_types=["Ingress", "Normal", "Normal"],
        edges=[(0, 1, link_cap, link_delay), (1, 2, link_cap, link_delay)],
    )
    return compile_topology(spec, max_nodes=N, max_edges=E)


def make_cfg(**kw):
    kw.setdefault("ttl_choices", (100.0,))
    return SimConfig(**kw)


def schedule_all_to(limits, dst):
    """Every (node, sfc, sf) row sends everything to dst."""
    sched = np.zeros(limits.scheduling_shape, np.float32)
    sched[:, :, :, dst] = 1.0
    return jnp.asarray(sched)


def placement_at(limits, nodes_sfs):
    p = np.zeros((limits.max_nodes, limits.max_sfs), bool)
    for n, s in nodes_sfs:
        p[n, s] = True
    return jnp.asarray(p)


def run_intervals(engine, topo, traffic, schedule, placement, k, seed=0):
    state = engine.init(jax.random.PRNGKey(seed), topo)
    out = []
    for _ in range(k):
        state, metrics = engine.apply(state, topo, traffic, schedule, placement)
        out.append(metrics)
    return state, out


@pytest.fixture(scope="module")
def base():
    service = make_service()
    limits = EnvLimits(max_nodes=N, max_edges=E, num_sfcs=1, max_sfs=3)
    return service, limits


def test_single_flow_timeline(base):
    """Flow: ingress 0 -> all SFs at node 1 -> departs at node 1.

    e2e = path_delay(0,1) + 3 * 5ms processing = 3 + 15 = 18 ms
    (default_forwarder.py:83-86 path credit + base_processor.py:37-49).
    """
    service, limits = base
    cfg = make_cfg()
    topo = line_topo()
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=4, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2)])

    _, out = run_intervals(engine, topo, traffic, sched, place, 2)
    m1, m2 = out
    # interval 1: arrivals at 0,10,...,90; flow k departs at 10k+18
    assert int(m1.run_generated) == 10
    assert int(m1.run_processed) == 9          # arrival@90 departs at 108
    assert int(m1.run_dropped) == 0
    assert int(m1.active) == 1
    assert float(m1.run_avg_e2e()) == pytest.approx(18.0)
    assert float(m1.run_e2e_max) == pytest.approx(18.0)
    # interval 2: 10 new arrivals, 10 departures (the straggler + 9 own)
    assert int(m2.run_generated) == 10
    assert int(m2.run_processed) == 10
    assert int(m2.generated) == 20
    assert int(m2.processed) == 19
    # requested traffic: every decision at node 0 (sf a) and node 1 (sf b, c)
    req = np.asarray(m2.run_requested)
    assert req[0, 0, 0] == pytest.approx(10.0)   # 10 flows x dr 1.0 at sf a
    assert req[1, 0, 1] == pytest.approx(10.0)
    assert req[1, 0, 2] == pytest.approx(10.0)
    # processed traffic at node 1 for all three SFs
    proc = np.asarray(m2.run_processed_traffic)
    assert proc[1].sum() == pytest.approx(30.0)


def test_node_cap_drop(base):
    """Node capacity below demand -> NODE_CAP drops
    (base_processor.py:98-101, metrics.py:144-164)."""
    service, limits = base
    cfg = make_cfg()
    topo = line_topo(node_cap=0.5)
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2)])
    _, out = run_intervals(engine, topo, traffic, sched, place, 1)
    (m,) = out
    assert int(m.run_dropped) == 10
    assert int(m.drop_reasons[3]) == 10        # NODE_CAP
    assert int(m.run_processed) == 0
    # drops recorded at the processing node (metrics.py:150-157)
    assert int(m.run_dropped_per_node[1]) == 10


def test_unplaced_sf_drop(base):
    """SF missing from placement -> NODE_CAP drop (default_processor.py:48-50)."""
    service, limits = base
    cfg = make_cfg()
    topo = line_topo()
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1)])  # no SF c
    _, out = run_intervals(engine, topo, traffic, sched, place, 1)
    (m,) = out
    assert int(m.drop_reasons[3]) >= 8
    assert int(m.run_processed) == 0


def test_link_cap_drop(base):
    """Link capacity below demand -> LINK_CAP drops
    (default_forwarder.py:95-111)."""
    service, limits = base
    cfg = make_cfg()
    topo = line_topo(link_cap=0.5)
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2)])
    _, out = run_intervals(engine, topo, traffic, sched, place, 1)
    (m,) = out
    assert int(m.run_dropped) == 10
    assert int(m.drop_reasons[2]) == 10        # LINK_CAP


def test_ttl_drop(base):
    """TTL shorter than the service time -> TTL drops; a drop with ttl<=0 is
    always recorded as TTL (metrics.py:158-160)."""
    service, limits = base
    cfg = make_cfg(ttl_choices=(10.0,))
    topo = line_topo()
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2)])
    _, out = run_intervals(engine, topo, traffic, sched, place, 1)
    (m,) = out
    assert int(m.run_dropped) == 10
    assert int(m.drop_reasons[0]) == 10        # TTL
    assert int(m.run_processed) == 0


def test_wrr_split(base):
    """50/50 schedule row -> weighted round robin alternates destinations
    (default_decision_maker.py:42-66)."""
    service, limits = base
    cfg = make_cfg()
    # triangle so both destinations are adjacent to the ingress
    spec = NetworkSpec(
        node_caps=[20.0, 20.0, 20.0],
        node_types=["Ingress", "Normal", "Normal"],
        edges=[(0, 1, 100.0, 1.0), (0, 2, 100.0, 1.0), (1, 2, 100.0, 1.0)],
    )
    topo = compile_topology(spec, max_nodes=N, max_edges=E)
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = np.zeros(limits.scheduling_shape, np.float32)
    sched[0, 0, 0, 1] = 0.5   # sf a from ingress: split 1 / 2
    sched[0, 0, 0, 2] = 0.5
    for n in (1, 2):          # later SFs stay put
        sched[n, 0, 1, n] = 1.0
        sched[n, 0, 2, n] = 1.0
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2),
                                  (2, 0), (2, 1), (2, 2)])
    _, out = run_intervals(engine, topo, traffic, jnp.asarray(sched), place, 1)
    (m,) = out
    counts = np.asarray(m.run_flow_counts)[0, 0, 0]
    assert counts[1] == 5 and counts[2] == 5
    assert int(m.run_dropped) == 0


def test_empty_schedule_quirk(base):
    """All-zero schedule row: the reference's argmax over all -1 diffs picks
    the first node (default_decision_maker.py:55-61) — flows go to node 0 and
    drop there because nothing is placed."""
    service, limits = base
    cfg = make_cfg()
    topo = line_topo()
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = jnp.zeros(limits.scheduling_shape, jnp.float32)
    place = placement_at(limits, [])
    _, out = run_intervals(engine, topo, traffic, sched, place, 1)
    (m,) = out
    assert int(m.run_dropped) == 10
    assert int(m.drop_reasons[3]) == 10        # NODE_CAP at node 0
    assert int(m.run_dropped_per_node[0]) == 10


def test_load_and_release(base):
    """Node load rises while flows process and releases duration ms after
    processing ends (base_processor.py:103-112)."""
    service, limits = base
    cfg = make_cfg()
    topo = line_topo()
    engine = SimEngine(service, cfg, limits)
    traffic = generate_traffic(cfg, service, topo, episode_steps=2, seed=0)
    sched = schedule_all_to(limits, 1)
    place = placement_at(limits, [(1, 0), (1, 1), (1, 2)])
    state, out = run_intervals(engine, topo, traffic, sched, place, 1)
    # traffic covers 2 intervals; after a 3rd (drain) interval every flow has
    # departed and all held capacity is back
    state2, _ = engine.apply(state, topo, traffic, sched, place)
    state2, _ = engine.apply(state2, topo, traffic, sched, place)
    assert float(jnp.abs(state2.node_load).max()) < 1e-3
    assert float(jnp.abs(state2.edge_used).max()) < 1e-3
    # max node usage observed during interval 1 should be >= 1 flow's demand
    assert float(out[0].run_max_node_usage[1]) >= 1.0


def test_onehot_helpers_match_native_indexing():
    """_onehot/_take/_pick (the TPU one-hot data-movement primitives)
    reproduce native gather semantics exactly — f32/i32/bool tables,
    out-of-range drop rows, and permutation transpose-scatter."""

    from gsc_tpu.sim.engine import _onehot, _pick, _take

    rng = np.random.default_rng(0)
    M, N, P = 37, 11, 5
    idx = jnp.asarray(rng.integers(0, N, M), jnp.int32)
    ftab = jnp.asarray(rng.normal(size=(N, P)), jnp.float32)
    itab = jnp.asarray(rng.integers(-3, 99, (N, P)), jnp.int32)
    btab = jnp.asarray(rng.integers(0, 2, (N, P)).astype(bool))
    oh = _onehot(idx, N)
    for tab in (ftab, itab, btab):
        got = np.asarray(_take(tab, oh))
        want = np.asarray(tab)[np.asarray(idx)]
        assert got.dtype == want.dtype
        np.testing.assert_array_equal(got, want)
    # out-of-range index -> all-zero row (mode="drop" analogue)
    oh_drop = _onehot(jnp.full((3,), N, jnp.int32), N)
    np.testing.assert_array_equal(np.asarray(_take(ftab, oh_drop)), 0.0)
    # _pick: per-row column select
    cols = jnp.asarray(rng.integers(0, P, M), jnp.int32)
    rows = _take(ftab, oh)                       # [M, P]
    got = np.asarray(_pick(rows, _onehot(cols, P)))
    want = np.asarray(rows)[np.arange(M), np.asarray(cols)]
    np.testing.assert_array_equal(got, want)
    # permutation: P @ v sorts, v^T @ P inverse-scatters back
    perm = jnp.asarray(rng.permutation(M), jnp.int32)
    pm = _onehot(perm, M)
    v = jnp.asarray(rng.normal(size=M), jnp.float32)
    sorted_v = jnp.dot(pm, v, precision=jax.lax.Precision.HIGHEST)
    np.testing.assert_array_equal(np.asarray(sorted_v),
                                  np.asarray(v)[np.asarray(perm)])
    back = jnp.dot(sorted_v, pm, precision=jax.lax.Precision.HIGHEST)
    np.testing.assert_array_equal(np.asarray(back), np.asarray(v))
