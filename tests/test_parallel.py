"""Scale-out tests on the virtual 8-device CPU mesh: sharded data-parallel
rollout + learn, and the driver-facing __graft_entry__ contract."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gsc_tpu.parallel import ParallelDDPG, make_mesh, put_replicated, put_sharded


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.shape == (8,)


def test_graft_entry_forward():
    import __graft_entry__ as ge
    fn, (params, obs) = ge.entry()
    out = jax.jit(fn)(params, obs)
    assert out.shape == (24 * 1 * 3 * 24,)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)  # raises on any sharding/compile failure


def _deterministic_setup(episode_steps=2, B=2):
    """Flagship small env with zero exploration noise + identical traffic on
    every replica: post-warmup the policy is deterministic, so per-replica
    trajectories must match bitwise."""
    import dataclasses

    import __graft_entry__ as ge
    env, agent, topo, traffic0 = ge._flagship(
        max_nodes=8, max_edges=8, episode_steps=episode_steps, max_flows=32)
    agent = dataclasses.replace(agent, rand_sigma=0.0, rand_mu=0.0)
    env.agent = agent
    traffic = jax.tree_util.tree_map(
        lambda x: jnp.stack([x] * B), traffic0)
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    return pddpg, state, buffers, env_states, obs, topo, traffic


def test_parallel_matches_manual_replica():
    """B=2 with identical traffic and a deterministic post-warmup policy:
    the per-replica transition streams (obs, action, reward, done) must be
    identical across the vmap axis — real cross-replica determinism, not
    just finiteness."""
    pddpg, state, buffers, env_states, obs, topo, traffic = \
        _deterministic_setup(episode_steps=2)
    state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(10**6))
    assert int(buffers.size[0]) == 2 and int(buffers.size[1]) == 2
    jax.tree_util.tree_map(
        lambda x: np.testing.assert_array_equal(np.asarray(x[0]),
                                                np.asarray(x[1])),
        buffers.data)
    assert np.isfinite(float(stats["episodic_return"]))


def test_rollout_chunked_equals_straight():
    """A 4-step episode run as 2x 2-step chunked device calls (the bench /
    TPU operating mode — long single scans fault the chip) reproduces the
    one-call rollout exactly: same replay contents, same final obs."""
    pddpg, state, buffers, env_states, obs, topo, traffic = \
        _deterministic_setup(episode_steps=4)
    start = 10**6  # far past warmup: policy branch, zero noise
    _, b1, es1, ob1, _ = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(start))
    s2, b2, es2, ob2, _ = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(start), 2)
    s2, b2, es2, ob2, _ = pddpg.rollout_episodes(
        s2, b2, es2, ob2, topo, traffic, jnp.int32(start + 2), 2)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        b1.data, b2.data)
    np.testing.assert_array_equal(np.asarray(b1.size), np.asarray(b2.size))
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a),
                                                   np.asarray(b)),
        ob1, ob2)


def test_parallel_shuffle_nodes_smoke():
    """shuffle_nodes works through the parallel rollout path too."""
    import dataclasses

    import __graft_entry__ as ge
    env, agent, topo, traffic0 = ge._flagship(max_nodes=8, max_edges=8,
                                              episode_steps=2, max_flows=32)
    agent = dataclasses.replace(agent, shuffle_nodes=True)
    env.agent = agent
    B = 2
    traffic = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), traffic0)
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(0))
    assert int(buffers.size[0]) == 2
    assert np.isfinite(float(stats["episodic_return"]))


def test_per_replica_topology_diversity():
    """Two replicas train on DIFFERENT topologies inside one rollout scan
    (stack_topologies + per_replica_topology=True) — beyond the reference's
    serial per-episode topology swapping (gym_env.py:103-128)."""
    import __graft_entry__ as ge
    from gsc_tpu.sim.traffic import generate_traffic
    from gsc_tpu.topology import stack_topologies
    from gsc_tpu.topology.compiler import compile_topology
    from gsc_tpu.topology.synthetic import line, triangle

    env, agent, _, _ = ge._flagship(max_nodes=8, max_edges=8,
                                    episode_steps=3, max_flows=32)
    t1 = compile_topology(triangle(), max_nodes=8, max_edges=8)
    t2 = compile_topology(line(4), max_nodes=8, max_edges=8)
    topos = stack_topologies([t1, t2])
    traffic = jax.tree_util.tree_map(
        lambda *xs: jnp.stack(xs),
        *[generate_traffic(env.sim_cfg, env.service, t, 3, seed=0)
          for t in (t1, t2)])
    pddpg = ParallelDDPG(env, agent, num_replicas=2,
                         per_replica_topology=True)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topos, traffic)
    # each replica observes its own network from the start
    assert not np.array_equal(np.asarray(obs.node_mask[0]),
                              np.asarray(obs.node_mask[1]))
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topos, traffic, jnp.int32(0))
    assert int(buffers.size[0]) == 3 and int(buffers.size[1]) == 3
    assert np.isfinite(float(stats["episodic_return"]))
    # the stored transitions reflect two different networks
    r0 = np.asarray(buffers.data["obs"].node_mask[0])
    r1 = np.asarray(buffers.data["obs"].node_mask[1])
    assert not np.array_equal(r0, r1)
    state, metrics = pddpg.learn_burst(state, buffers)
    assert np.isfinite(float(metrics["critic_loss"]))


def test_local_sampling_learn_burst():
    """sample_mode='local' draws each replica's contribution from its own
    shard (no cross-shard gather in the learning loop) and still learns:
    finite losses, params move."""
    import __graft_entry__ as ge
    from gsc_tpu.sim.traffic import generate_traffic

    env, agent, topo, traffic0 = ge._flagship(max_nodes=8, max_edges=8,
                                              episode_steps=2, max_flows=32)
    B = 2
    traffic = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), traffic0)
    pddpg = ParallelDDPG(env, agent, num_replicas=B, sample_mode="local")
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, _ = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(0))
    new_state, metrics = pddpg.learn_burst(state, buffers)
    assert np.isfinite(float(metrics["critic_loss"]))
    diff = jax.tree_util.tree_map(
        lambda a, b: float(jnp.abs(a - b).max()),
        state.critic_params, new_state.critic_params)
    assert max(jax.tree_util.tree_leaves(diff)) > 0


def test_pallas_gnn_selectable_from_config():
    """gnn_impl='pallas' flows from AgentConfig into the embedder and the
    forward runs (interpret mode on CPU)."""
    import dataclasses

    import __graft_entry__ as ge
    from gsc_tpu.models.nets import Actor

    env, agent, topo, traffic = ge._flagship(max_nodes=8, max_edges=8,
                                             episode_steps=2, max_flows=32)
    agent = dataclasses.replace(agent, gnn_impl="pallas")
    _, obs = env.reset(jax.random.PRNGKey(0), topo, traffic)
    actor = Actor(agent=agent, action_dim=env.limits.action_dim,
                  gnn_impl=agent.gnn_impl)
    params = actor.init(jax.random.PRNGKey(1), obs)
    out = jax.jit(actor.apply)(params, obs)
    assert np.isfinite(np.asarray(out)).all()


def test_harness_global_step_offsets():
    """run_chunked_episodes threads the GLOBAL step into every rollout
    call: chunks advance within an episode, episodes advance within a
    call, and step_offset shifts the whole call — so per-episode drivers
    (Trainer.train_parallel) keep the agent's warmup schedule continuous
    instead of restarting it at 0 each episode."""
    import jax.numpy as jnp

    from gsc_tpu.parallel.harness import run_chunked_episodes

    class Spy:
        def __init__(self):
            self.starts = []
            self.learns = []

        def reset_all(self, rng, topo, traffic):
            return None, None

        def chunk_step(self, state, buffers, es, obs, topo, traffic,
                       start, chunk, learn=False):
            self.starts.append(int(start))
            self.learns.append(learn)
            stats = {"episodic_return": jnp.float32(1.0),
                     "mean_succ_ratio": jnp.float32(0.5),
                     "final_succ_ratio": jnp.float32(0.5)}
            metrics = {"critic_loss": jnp.float32(0.0)} if learn else None
            return state, buffers, es, obs, stats, metrics

    spy = Spy()
    run_chunked_episodes(spy, None, lambda ep: None, None, None,
                         episodes=2, episode_steps=4, chunk=2, seed=0)
    assert spy.starts == [0, 2, 4, 6]
    # the learn burst fuses into the LAST chunk of each episode only
    assert spy.learns == [False, True, False, True]
    spy.starts.clear()
    run_chunked_episodes(spy, None, lambda ep: None, None, None,
                         episodes=1, episode_steps=4, chunk=2, seed=0,
                         step_offset=8)
    assert spy.starts == [8, 10]


def test_chunked_rollout_rejects_shuffle():
    """Chunked rollouts open a fresh permutation frame per device call —
    only correct at episode boundaries — so combining num_steps <
    episode_steps with shuffle_nodes must raise instead of silently
    corrupting the obs<->action frame alignment."""
    import dataclasses

    pddpg, state, buffers, env_states, obs, topo, traffic = \
        _deterministic_setup(episode_steps=4)
    pddpg.agent = dataclasses.replace(pddpg.agent, shuffle_nodes=True)
    with pytest.raises(ValueError, match="shuffle_nodes"):
        pddpg.rollout_episodes(state, buffers, env_states, obs, topo,
                               traffic, jnp.int32(0), 2)
    # whole-episode calls with shuffling stay allowed
    pddpg.rollout_episodes(state, buffers, env_states, obs, topo, traffic,
                           jnp.int32(0), 4)
