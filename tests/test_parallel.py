"""Scale-out tests on the virtual 8-device CPU mesh: sharded data-parallel
rollout + learn, and the driver-facing __graft_entry__ contract."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from gsc_tpu.parallel import ParallelDDPG, make_mesh, put_replicated, put_sharded


def test_mesh_has_8_devices():
    assert len(jax.devices()) == 8
    mesh = make_mesh()
    assert mesh.devices.shape == (8,)


def test_graft_entry_forward():
    import __graft_entry__ as ge
    fn, (params, obs) = ge.entry()
    out = jax.jit(fn)(params, obs)
    assert out.shape == (24 * 1 * 3 * 24,)
    assert np.isfinite(np.asarray(out)).all()


def test_dryrun_multichip_8():
    import __graft_entry__ as ge
    ge.dryrun_multichip(8)  # raises on any sharding/compile failure


def test_parallel_matches_manual_replica(monkeypatch):
    """B=2 parallel rollout produces per-replica rewards identical to two
    equal-traffic replicas (determinism across the vmap axis)."""
    import __graft_entry__ as ge
    env, agent, topo, traffic0 = ge._flagship(max_nodes=8, max_edges=8,
                                              episode_steps=2, max_flows=32)
    B = 2
    traffic = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), traffic0)
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(10**6))
    # both replicas saw identical traffic and (post-warmup) the same policy;
    # nothing should diverge except exploration noise — which is per-replica,
    # so just check both produced finite, populated buffers
    assert int(buffers.size[0]) == 2 and int(buffers.size[1]) == 2
    assert np.isfinite(float(stats["episodic_return"]))


def test_parallel_shuffle_nodes_smoke():
    """shuffle_nodes works through the parallel rollout path too."""
    import dataclasses

    import __graft_entry__ as ge
    env, agent, topo, traffic0 = ge._flagship(max_nodes=8, max_edges=8,
                                              episode_steps=2, max_flows=32)
    agent = dataclasses.replace(agent, shuffle_nodes=True)
    env.agent = agent
    B = 2
    traffic = jax.tree_util.tree_map(lambda x: jnp.stack([x, x]), traffic0)
    pddpg = ParallelDDPG(env, agent, num_replicas=B)
    env_states, obs = pddpg.reset_all(jax.random.PRNGKey(0), topo, traffic)
    one_obs = jax.tree_util.tree_map(lambda x: x[0], obs)
    state = pddpg.init(jax.random.PRNGKey(1), one_obs)
    buffers = pddpg.init_buffers(one_obs)
    state, buffers, env_states, obs, stats = pddpg.rollout_episodes(
        state, buffers, env_states, obs, topo, traffic, jnp.int32(0))
    assert int(buffers.size[0]) == 2
    assert np.isfinite(float(stats["episodic_return"]))
